"""Serving fleet (serving/fleet/, ISSUE 17).

Pinned contracts:

- the retryable-shed WIRE contract round-trips: ``to_wire``/
  ``from_wire`` reconstruct the concrete error class with its
  ``retry_after_s`` hint intact, unknown kinds degrade to the base
  class without losing the hint;
- ``health_snapshot`` merges provider ``load`` sub-dicts and the
  replica scrape reads them — over HTTP ``/readyz`` when the server
  runs a TelemetryServer, in-process otherwise, same fields either way;
- routing: least-loaded among ready; prefix affinity keeps a repeated
  prefix on ONE replica (asserted via that replica's prefix-cache hit
  counter) and spills off an overloaded home; a typed shed is retried
  honoring its ``retry_after_s`` and re-raises typed once the budget is
  spent; permanent ``ValueError`` is NEVER retried; a dead replica is
  failed over immediately (no sleep);
- rolling deploys drain before reload (zero queued + in-flight work at
  ``update_model`` time), keep the rest of the fleet serving
  throughout, and roll BACK the canary's parameters on a failed gate;
- the autoscaler needs ``hysteresis`` consecutive signals + an elapsed
  cooldown before acting, and respects min/max bounds;
- chaos: killing a replica mid-traffic fails ZERO healthy requests —
  the router retries onto survivors (slow-marked drill).
"""
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from deeplearning4j_tpu.serving.fleet import (FleetAutoscaler, FleetMetrics,
                                              FleetReplica, FleetRouter,
                                              FleetUnavailableError,
                                              ReplicaLoad, RollingDeploy)
from deeplearning4j_tpu.serving.paged import (PagedGenerativeServer,
                                              PoolExhaustedError)
from deeplearning4j_tpu.serving.queue import (RequestTimeoutError,
                                              ServerClosedError,
                                              ServerOverloadedError,
                                              ServingError)
from deeplearning4j_tpu.serving.resilience import (PoisonedRequestError,
                                                   RetryableServingError)
from deeplearning4j_tpu.zoo.gpt import GPTConfig, build_gpt, gpt_paged_spec

CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                intermediate_size=64, max_seq_len=32)
MSL = 32
BS = 8


@pytest.fixture(scope="module")
def gpt_sd():
    return build_gpt(CFG, batch=2, seq_len=8, seed=0)


@pytest.fixture(scope="module")
def spec(gpt_sd):
    # one spec for the whole module: the jitted programs are memoized
    # per (spec, geometry), so every replica below shares one compile set
    return gpt_paged_spec(gpt_sd, CFG)


def make_server(spec, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", MSL)
    kw.setdefault("block_size", BS)
    kw.setdefault("warmup", False)
    kw.setdefault("debug_leaks", True)
    return PagedGenerativeServer(spec, **kw)


def make_fleet(spec, n=3, router_kw=None, **server_kw):
    """n paged replicas (shared spec -> shared compile set) + a router."""
    replicas = [FleetReplica(f"r{i}", server=make_server(spec, **server_kw))
                for i in range(n)]
    router = FleetRouter(replicas, **(router_kw or {}))
    return router, replicas


def stop_fleet(replicas):
    for r in replicas:
        try:
            r.stop(drain=False)
        except Exception:   # noqa: BLE001 — already dead is fine here
            pass


# ----------------------------------------------------------------------
# stub surface: just enough GenerativeServer for router-logic tests
# (placement/retry semantics are host-side — no model needed)

class StubHandle:
    def __init__(self, tokens, fail=None):
        self._tokens = tokens
        self._fail = fail

    def result(self, timeout=None):
        if self._fail is not None:
            raise self._fail
        return self._tokens


class StubServer:
    def __init__(self, queue_depth=0, occupancy=0.0, step_ms=1.0,
                 ready=True, submit_errors=(), result_errors=()):
        self.block_size = BS
        self.telemetry = None
        self.queue_depth = queue_depth
        self.occupancy = occupancy
        self.step_ms = step_ms
        self.ready = ready
        self.submit_errors = list(submit_errors)
        self.result_errors = list(result_errors)
        self.submitted = []
        self.reloads = 0
        self.params = {"w": 0}
        self.metrics = SimpleNamespace(counters={})
        self._queue = SimpleNamespace(pending=lambda: 0)

    def _n_active(self):
        return 0

    def _telemetry_health(self):
        return {"ready": self.ready, "healthy": self.ready,
                "load": {"queue_depth": self.queue_depth,
                         "slot_occupancy": self.occupancy,
                         "p99_decode_step_ms": self.step_ms}}

    def submit(self, prompt, max_new_tokens=16, timeout_ms=None,
               on_token=None, **kw):
        if self.submit_errors:
            raise self.submit_errors.pop(0)
        self.submitted.append(list(np.asarray(prompt).tolist()))
        toks = list(range(max_new_tokens))
        if self.result_errors:
            return StubHandle(toks, fail=self.result_errors.pop(0))
        if on_token is not None:
            for t in toks:
                on_token(t)
        return StubHandle(toks)

    def shutdown(self, drain=True, timeout=None):
        self.ready = False

    def update_model(self):
        self.reloads += 1

    def params_snapshot(self):
        return dict(self.params)

    def restore_params(self, params):
        self.params = dict(params)


def stub_fleet(loads, **router_kw):
    """{name: queue_depth} -> (router, {name: FleetReplica})."""
    replicas = {name: FleetReplica(name, server=StubServer(queue_depth=d))
                for name, d in loads.items()}
    router_kw.setdefault("poll_interval_s", 0.0)   # always fresh loads
    router = FleetRouter(replicas.values(), **router_kw)
    return router, replicas


# ----------------------------------------------------------------------
class TestWireContract:
    def test_round_trip_preserves_kind_and_hint(self):
        e = ServerOverloadedError("queue full", retry_after_s=0.5)
        wire = e.to_wire()
        assert wire == {"kind": "ServerOverloadedError",
                        "message": "queue full", "retry_after_s": 0.5}
        back = RetryableServingError.from_wire(wire)
        assert type(back) is ServerOverloadedError
        assert isinstance(back, RetryableServingError)
        assert isinstance(back, ServingError)
        assert back.retry_after_s == 0.5 and str(back) == "queue full"

    def test_subclasses_auto_register(self):
        # PoolExhaustedError lives in serving/paged — registered by
        # __init_subclass__, not by an import-order side table
        e = PoolExhaustedError("no blocks", retry_after_s=0.25)
        back = RetryableServingError.from_wire(e.to_wire())
        assert type(back) is PoolExhaustedError
        assert back.retry_after_s == 0.25

    def test_unknown_kind_degrades_to_base(self):
        back = RetryableServingError.from_wire(
            {"kind": "FutureShedKind", "message": "m",
             "retry_after_s": 1.5})
        assert type(back) is RetryableServingError
        assert back.retry_after_s == 1.5    # the hint survives anyway

    def test_none_hint_round_trips(self):
        back = RetryableServingError.from_wire(
            RetryableServingError("m").to_wire())
        assert back.retry_after_s is None


# ----------------------------------------------------------------------
class TestLoadTelemetry:
    def test_health_snapshot_merges_load_subdicts(self):
        from deeplearning4j_tpu.monitor.server import health_snapshot
        snap = health_snapshot(providers={
            "a": lambda: {"ready": True,
                          "load": {"queue_depth": 3}},
            "b": lambda: {"ready": True,
                          "load": {"slot_occupancy": 0.5}}})
        assert snap["load"] == {"queue_depth": 3, "slot_occupancy": 0.5}

    def test_replica_scrape_in_process(self):
        r = FleetReplica("s", server=StubServer(queue_depth=2,
                                                occupancy=0.25,
                                                step_ms=7.0))
        load = r.scrape()
        assert load.ready and load.healthy
        assert load.queue_depth == 2
        assert load.occupancy == 0.25
        assert load.p99_decode_step_ms == 7.0
        assert r.last_load is load

    def test_replica_scrape_over_http_readyz(self, spec):
        # the real cross-process path: TelemetryServer on an ephemeral
        # port, load fields travel through GET /readyz JSON
        srv = make_server(spec, telemetry_port=0)
        try:
            r = FleetReplica("net", server=srv)
            load = r.scrape()
            assert load.ready and load.healthy
            assert load.queue_depth == 0
            assert 0.0 <= load.occupancy <= 1.0
        finally:
            srv.shutdown(drain=False)

    def test_scrape_failure_means_unready(self):
        r = FleetReplica("b", server=StubServer())
        r.server._telemetry_health = lambda: 1 / 0
        load = r.scrape()
        assert not load.ready and not load.healthy

    def test_dead_replica_scrapes_unready(self):
        r = FleetReplica("d", server=StubServer())
        r.kill()
        assert r.state == "dead"
        assert not r.scrape().ready


# ----------------------------------------------------------------------
class TestRouting:
    def test_least_loaded_among_ready(self):
        router, reps = stub_fleet({"a": 5, "b": 0, "c": 2},
                                  affinity=False)
        for _ in range(4):
            res = router.generate(np.arange(3), max_new_tokens=2)
            assert res.replica == "b" and res.routed == "least_loaded"
        assert len(reps["b"].server.submitted) == 4

    def test_unready_replicas_are_skipped(self):
        router, reps = stub_fleet({"a": 0, "b": 9}, affinity=False)
        reps["a"].server.ready = False
        res = router.generate(np.arange(3), max_new_tokens=2)
        assert res.replica == "b"   # worst load but the only ready one

    def test_empty_ready_set_raises_typed(self):
        router, reps = stub_fleet({"a": 0}, affinity=False,
                                  retry_budget=0)
        reps["a"].server.ready = False
        with pytest.raises(FleetUnavailableError) as ei:
            router.generate(np.arange(3), max_new_tokens=2)
        assert ei.value.retry_after_s is not None

    def test_affinity_stable_and_spills_under_load(self):
        router, reps = stub_fleet({"a": 0, "b": 0, "c": 0})
        prompt = np.arange(BS + 3)      # one full block -> affinity key
        homes = {router.generate(prompt, max_new_tokens=2).replica
                 for _ in range(6)}
        assert len(homes) == 1          # rendezvous: one home per key
        home = homes.pop()
        assert router.metrics.counters["routed_affinity"] == 6
        # overload the home past spill_queue_depth -> least-loaded wins
        reps[home].server.queue_depth = router.spill_queue_depth
        res = router.generate(prompt, max_new_tokens=2)
        assert res.replica != home and res.routed == "spill"
        assert router.metrics.counters["routed_spill"] == 1
        assert 0 < router.metrics.affinity_hit_rate() < 1

    def test_short_prompt_has_no_affinity_key(self):
        router, _ = stub_fleet({"a": 0, "b": 0})
        res = router.generate(np.arange(BS - 1), max_new_tokens=2)
        assert res.routed == "least_loaded"

    def test_membership_change_rehomes_only_lost_keys(self):
        router, _ = stub_fleet({"a": 0, "b": 0, "c": 0})
        prompts = [np.concatenate([np.full(BS, i), np.arange(2)])
                   for i in range(8)]
        before = {i: router.route(p)[0].name
                  for i, p in enumerate(prompts)}
        gone = before[0]
        router.remove_replica(gone)
        after = {i: router.route(p)[0].name
                 for i, p in enumerate(prompts)}
        for i, name in before.items():
            if name != gone:
                assert after[i] == name     # survivors keep their keys


class TestRetrySemantics:
    def test_shed_retry_honors_retry_after_s(self):
        sleeps = []
        router, reps = stub_fleet({"a": 0}, sleep=sleeps.append,
                                  affinity=False, retry_budget=3)
        reps["a"].server.submit_errors = [
            ServerOverloadedError("shed", retry_after_s=0.03),
            ServerOverloadedError("shed", retry_after_s=0.07)]
        res = router.generate(np.arange(3), max_new_tokens=2)
        assert res.retries == 2
        assert sleeps == [0.03, 0.07]   # the error's OWN hint, per shed
        assert router.metrics.counters["sheds_seen"] == 2
        assert router.metrics.counters["retries"] == 2

    def test_budget_exhausted_reraises_typed(self):
        sleeps = []
        router, reps = stub_fleet({"a": 0}, sleep=sleeps.append,
                                  affinity=False, retry_budget=2)
        reps["a"].server.submit_errors = [
            ServerOverloadedError("shed", retry_after_s=0.01)
            for _ in range(5)]
        with pytest.raises(ServerOverloadedError):
            router.generate(np.arange(3), max_new_tokens=2)
        assert len(sleeps) == 2         # budget sleeps only, then raise
        assert router.metrics.counters["retry_giveups"] == 1
        assert router.metrics.counters["requests_failed"] == 1

    def test_backoff_is_bounded(self):
        sleeps = []
        router, reps = stub_fleet({"a": 0}, sleep=sleeps.append,
                                  affinity=False, max_backoff_s=0.05)
        reps["a"].server.submit_errors = [
            ServerOverloadedError("shed", retry_after_s=60.0)]
        router.generate(np.arange(3), max_new_tokens=2)
        assert sleeps == [0.05]

    def test_permanent_error_never_retried(self):
        sleeps = []
        router, reps = stub_fleet({"a": 0, "b": 0},
                                  sleep=sleeps.append, affinity=False)
        reps["a"].server.submit_errors = [ValueError("bad prompt")]
        with pytest.raises(ValueError):
            router.generate(np.arange(3), max_new_tokens=2)
        assert sleeps == []             # no backoff, no second replica
        assert reps["b"].server.submitted == []
        assert router.metrics.counters["requests_failed"] == 1
        assert router.metrics.counters["retries"] == 0

    def test_poisoned_request_never_retried(self):
        router, reps = stub_fleet({"a": 0, "b": 0}, affinity=False)
        reps["a"].server.submit_errors = [PoisonedRequestError("poison")]
        with pytest.raises(PoisonedRequestError):
            router.generate(np.arange(3), max_new_tokens=2)
        assert reps["b"].server.submitted == []

    def test_deadline_miss_never_retried(self):
        router, reps = stub_fleet({"a": 0, "b": 0}, affinity=False)
        reps["a"].server.result_errors = [RequestTimeoutError("late")]
        with pytest.raises(RequestTimeoutError):
            router.generate(np.arange(3), max_new_tokens=2)
        assert router.metrics.counters["requests_timed_out"] == 1
        assert reps["b"].server.submitted == []

    def test_replica_death_fails_over_immediately(self):
        sleeps = []
        router, reps = stub_fleet({"a": 0, "b": 1},
                                  sleep=sleeps.append, affinity=False)
        reps["a"].server.submit_errors = [ServerClosedError("gone")]
        res = router.generate(np.arange(3), max_new_tokens=2)
        assert res.replica == "b" and res.retries == 1
        assert sleeps == []             # death -> no sleep, next replica
        assert reps["a"].state == "dead"
        assert router.metrics.counters["replica_deaths_seen"] == 1

    def test_mid_generation_death_fails_over(self):
        router, reps = stub_fleet({"a": 0, "b": 1}, affinity=False)
        reps["a"].server.result_errors = [ServerClosedError("gone")]
        res = router.generate(np.arange(3), max_new_tokens=2)
        assert res.replica == "b" and res.retries == 1

    def test_all_dead_raises_fleet_unavailable(self):
        router, reps = stub_fleet({"a": 0}, affinity=False,
                                  retry_budget=1)
        reps["a"].server.submit_errors = [ServerClosedError("gone"),
                                          ServerClosedError("gone")]
        with pytest.raises(FleetUnavailableError):
            router.generate(np.arange(3), max_new_tokens=2)


# ----------------------------------------------------------------------
class TestAutoscaler:
    @staticmethod
    def synth_loads(queues, step_ms=10.0, t=0.0):
        return {f"r{i}": ReplicaLoad(t=t, ready=True, healthy=True,
                                     queue_depth=q,
                                     p99_decode_step_ms=step_ms)
                for i, q in enumerate(queues)}

    @staticmethod
    def make(router, clock, **kw):
        built = []

        def factory(name):
            rep = FleetReplica(name, server=StubServer())
            built.append(rep)
            return rep
        kw.setdefault("ttft_slo_ms", 500.0)
        kw.setdefault("hysteresis", 2)
        kw.setdefault("cooldown_s", 10.0)
        sc = FleetAutoscaler(router, factory, clock=clock, **kw)
        return sc, built

    def test_hysteresis_delays_action(self):
        router, _ = stub_fleet({"a": 0})
        now = [0.0]
        sc, built = self.make(router, lambda: now[0], max_replicas=4)
        hot = self.synth_loads([8], step_ms=100.0)     # est 900 > 350
        out1 = sc.step(dict(hot))
        assert out1["signal"] == "scale_up" and not out1["acted"]
        out2 = sc.step(dict(hot))
        assert out2["acted"] and len(built) == 1
        assert "scaled-0" in router.replicas
        assert router.metrics.counters["scale_up_events"] == 1

    def test_cooldown_blocks_back_to_back_actions(self):
        router, _ = stub_fleet({"a": 0})
        now = [0.0]
        sc, built = self.make(router, lambda: now[0], max_replicas=8)
        hot = self.synth_loads([8], step_ms=100.0)
        sc.step(dict(hot)); sc.step(dict(hot))        # acts once
        out = sc.step(dict(hot)); out = sc.step(dict(hot))
        assert not out["acted"] and out.get("reason") == "cooldown"
        now[0] = 60.0                                  # cooldown elapsed
        out = sc.step(dict(hot))    # streak already past hysteresis
        assert out["acted"] and len(built) == 2

    def test_bounds_are_hard(self):
        router, _ = stub_fleet({"a": 0})
        now = [0.0]
        sc, _ = self.make(router, lambda: now[0],
                          min_replicas=1, max_replicas=1)
        hot = self.synth_loads([9], step_ms=100.0)
        sc.step(dict(hot))
        out = sc.step(dict(hot))
        assert not out["acted"] and out["reason"] == "at max_replicas"
        idle = self.synth_loads([0], step_ms=1.0)      # est 1 << 100
        sc.step(dict(idle))
        out = sc.step(dict(idle))
        assert not out["acted"] and out["reason"] == "at min_replicas"

    def test_scale_down_drains_least_loaded(self):
        router, reps = stub_fleet({"a": 0, "b": 0})
        now = [0.0]
        sc, _ = self.make(router, lambda: now[0],
                          min_replicas=1, max_replicas=4)
        # scale-down wants provably idle capacity: zero queues, low est;
        # occupancy breaks the victim tie toward b
        idle = {"a": ReplicaLoad(t=0.0, ready=True, healthy=True,
                                 occupancy=0.5, p99_decode_step_ms=1.0),
                "b": ReplicaLoad(t=0.0, ready=True, healthy=True,
                                 occupancy=0.0, p99_decode_step_ms=1.0)}
        sc.step(dict(idle))
        out = sc.step(dict(idle))
        assert out["acted"] and out["replica"] == "b"  # least loaded
        assert "b" not in router.replicas
        assert reps["b"].state == "stopped"
        assert router.metrics.counters["scale_down_events"] == 1

    def test_queue_trend_rising_signals_up(self):
        router, _ = stub_fleet({"a": 0})
        sc, _ = self.make(router, time.monotonic)
        assert sc.evaluate(self.synth_loads([1], step_ms=1.0)) == "hold"
        assert sc.evaluate(self.synth_loads([3], step_ms=1.0)) \
            == "scale_up"                              # 1 -> 3 rising

    def test_no_ready_replicas_signals_up(self):
        router, _ = stub_fleet({"a": 0})
        sc, _ = self.make(router, time.monotonic)
        assert sc.evaluate({}) == "scale_up"


# ----------------------------------------------------------------------
class TestRollingDeployStubs:
    def test_drains_before_reload_and_rolls_all(self):
        router, reps = stub_fleet({"a": 0, "b": 0, "c": 0})
        seen_idle = []
        for r in reps.values():
            orig, rep = r.server.update_model, r

            def wrapped(orig=orig, rep=rep):
                seen_idle.append((rep.name, rep.idle,
                                  rep.state == "draining"))
                orig()
            r.server.update_model = wrapped
        report = RollingDeploy(router, probes=[(np.arange(4), 3, None)],
                               drain_timeout_s=2.0).run(canary="b")
        assert report["ok"] and report["canary"] == "b"
        assert report["rolled"] == ["b", "a", "c"]     # canary first
        for name, idle, draining in seen_idle:
            assert idle and draining, name
        assert all(r.server.reloads == 1 for r in reps.values())
        assert all(r.state == "ready" for r in reps.values())
        assert all(r.model_version == 1 for r in reps.values())
        assert router.metrics.counters["deploys"] == 1

    def test_failed_gate_rolls_back_canary(self):
        router, reps = stub_fleet({"a": 0, "b": 0})
        # expected tokens the stub can never produce -> canary gate fails
        report = RollingDeploy(
            router, probes=[(np.arange(4), 3, [61, 62, 63])],
            drain_timeout_s=2.0).run(canary="a")
        assert not report["ok"] and report["failed_at"] == "a"
        assert report.get("rolled_back")
        assert "mismatch" in report["reason"]
        assert report["rolled"] == []
        assert reps["b"].server.reloads == 0           # roll never started
        assert reps["a"].state == "ready"              # resumed serving
        assert router.metrics.counters["deploy_rollbacks"] == 1

    def test_canary_defines_reference_for_the_roll(self):
        router, reps = stub_fleet({"a": 0, "b": 0})
        # b's stub output diverges from a's -> the roll must fail at b
        reps["b"].server.submit = (
            lambda *a, **kw: StubHandle([9, 9, 9]))
        report = RollingDeploy(router,
                               probes=[(np.arange(4), 3, None)],
                               drain_timeout_s=2.0).run(canary="a")
        assert not report["ok"] and report["failed_at"] == "b"
        assert report["rolled"] == ["a"]

    def test_drain_timeout_aborts_with_nothing_reloaded(self):
        router, reps = stub_fleet({"a": 0})
        reps["a"].server._queue = SimpleNamespace(pending=lambda: 1)
        report = RollingDeploy(router, drain_timeout_s=0.05).run()
        assert not report["ok"] and "drain timed out" in report["reason"]
        assert reps["a"].server.reloads == 0
        assert reps["a"].state == "ready"              # resumed


# ----------------------------------------------------------------------
class TestFleetMetrics:
    def seed(self):
        m = FleetMetrics()
        m.on_routed("affinity", "r0")
        m.on_routed("affinity", "r0")
        m.on_routed("spill", "r1")
        m.on_routed("least_loaded", "r1")
        m.inc("requests_ok", 4)
        m.inc("retries")
        m.observe_replica("r0", ReplicaLoad(
            t=0.0, ready=True, healthy=True, queue_depth=2,
            occupancy=0.4, p99_decode_step_ms=12.0))
        m.observe_replica("r1", ReplicaLoad(
            t=0.0, ready=False, healthy=False))
        return m

    def test_record_shape(self):
        rec = self.seed().to_record(now=123.0)
        assert rec["type"] == "fleet" and rec["t"] == 123.0
        assert rec["fleet"]["n_replicas"] == 2
        assert rec["fleet"]["n_ready"] == 1
        assert rec["fleet"]["affinity_hit_rate"] == round(2 / 3, 4)
        assert rec["fleet"]["retries_per_request"] == 0.25
        assert rec["replicas"]["r0"]["routed"] == 2
        assert rec["counters"]["requests_routed"] == 4

    def test_registry_folds_fleet_gauges(self):
        from deeplearning4j_tpu.monitor.registry import MetricsRegistry
        reg = MetricsRegistry()
        reg.fold_fleet(self.seed().to_record(now=1.0))
        text = reg.to_prometheus_text()
        for needle in ("dl4j_fleet_requests_routed_total",
                       "dl4j_fleet_affinity_hit_rate",
                       "dl4j_fleet_replicas_ready",
                       'dl4j_fleet_replica_queue_depth{replica="r0"}'):
            assert needle in text, needle
        assert "nan" not in text.lower()

    def test_report_renders_fleet_panel(self):
        from deeplearning4j_tpu.ui.report import render_report
        from deeplearning4j_tpu.ui.stats import StatsStorage
        storage = StatsStorage()
        storage.put(self.seed().to_record(now=1.0))
        html = render_report(storage)
        assert "Fleet (1/2 replicas ready)" in html
        assert "affinity hit rate" in html


# ----------------------------------------------------------------------
# real servers: affinity hits a prefix cache, deploys serve throughout,
# chaos kills lose nothing

class TestFleetIntegration:
    def test_affinity_lands_prefix_cache_hits(self, spec):
        router, replicas = make_fleet(spec, n=3)
        try:
            shared = np.arange(BS, dtype=np.int32)     # one full block
            prompts = [np.concatenate([shared,
                                       np.full(2, i, dtype=np.int32)])
                       for i in range(5)]
            results = [router.generate(p, max_new_tokens=2)
                       for p in prompts]
            homes = {r.replica for r in results}
            assert homes == {results[0].replica}       # one home replica
            assert all(r.routed == "affinity" for r in results)
            hits = {r.name: r.prefix_hits() for r in replicas}
            home = results[0].replica
            # every post-first request hit the home's prefix cache; the
            # other replicas never even saw the prefix
            assert hits[home] >= len(prompts) - 1
            assert all(h == 0 for n, h in hits.items() if n != home)
        finally:
            stop_fleet(replicas)

    def test_deploy_serves_throughout(self, spec):
        router, replicas = make_fleet(spec, n=2)
        failures, done = [], []

        def traffic():
            rng = np.random.default_rng(7)
            for _ in range(6):
                prompt = rng.integers(0, CFG.vocab_size, 5,
                                      dtype=np.int64).astype(np.int32)
                try:
                    res = router.generate(prompt, max_new_tokens=2)
                    done.append(res)
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append(e)
        try:
            t = threading.Thread(target=traffic)
            t.start()
            report = RollingDeploy(
                router, probes=[(np.arange(6, dtype=np.int32), 3, None)],
                drain_timeout_s=30.0).run()
            t.join(timeout=120)
            assert not t.is_alive()
            assert report["ok"], report
            assert sorted(report["rolled"]) == ["r0", "r1"]
            assert failures == []                       # zero failed
            assert len(done) == 6
            assert all(r.model_version == 1 for r in replicas)
        finally:
            stop_fleet(replicas)

    @pytest.mark.slow
    @pytest.mark.chaos
    def test_kill_replica_chaos_drill(self, spec):
        """The acceptance bar: kill one of three replicas mid-traffic;
        every healthy request still completes (retried onto survivors),
        zero failures."""
        router, replicas = make_fleet(
            spec, n=3, router_kw={"retry_budget": 4,
                                  "poll_interval_s": 0.05})
        failures, done = [], []
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, CFG.vocab_size, 5).astype(np.int32)
                   for _ in range(18)]

        def one(p):
            try:
                done.append(router.generate(p, max_new_tokens=3))
            except Exception as e:      # noqa: BLE001 — the assertion
                failures.append(e)
        try:
            threads = []
            for i, p in enumerate(prompts):
                t = threading.Thread(target=one, args=(p,))
                t.start()
                threads.append(t)
                if i == 5:
                    replicas[0].kill()  # mid-traffic, no drain
                time.sleep(0.01)
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads)
            assert failures == [], failures
            assert len(done) == len(prompts)
            survivors = {r.replica for r in done}
            assert survivors <= {"r0", "r1", "r2"}
            # post-kill requests all landed on survivors
            late = {r.replica for r in done[-6:]}
            assert "r0" not in late
        finally:
            stop_fleet(replicas)


# ----------------------------------------------------------------------
# request tracing on the real stack (ISSUE 20): the serving spans carry
# trace_id tags, batch decodes carry the slot->trace occupancy map, and
# the rail never changes the tokens

class TestFleetTracing:
    def test_spans_tagged_and_tracing_never_changes_tokens(self, spec):
        from deeplearning4j_tpu.monitor.trace import (TRACER,
                                                      disable_tracing,
                                                      enable_tracing)
        prompt = np.arange(5, dtype=np.int32)

        def run(traced):
            if traced:
                enable_tracing(reset=True)
            else:
                disable_tracing()
            router, replicas = make_fleet(
                spec, n=1, router_kw=(
                    {} if traced else {"slo": False, "reqtrace": False}))
            try:
                return router, [router.generate(p, max_new_tokens=3)
                                for p in (prompt, prompt + 1)]
            finally:
                stop_fleet(replicas)

        try:
            _, plain = run(False)
            router, traced = run(True)
            # bit-identity: seeds pin to the request id, which both legs
            # mint identically — tracing on MUST NOT move a single token
            assert [r.tokens for r in traced] == \
                [r.tokens for r in plain]
            ids = {r.trace_id for r in traced}
            assert len(ids) == 2 and None not in ids
            spans = TRACER.spans()
            tagged = {s.name for s in spans
                      if s.args.get("trace_id") in ids}
            assert {"fleet.attempt", "serving.enqueue",
                    "serving.prefill", "serving.reply"} <= tagged
            # batch-level decode spans record slot->trace occupancy
            decodes = [s for s in spans if s.name == "serving.decode"
                       and s.args.get("slots")]
            assert decodes
            occupants = set()
            for d in decodes:
                occupants |= set(d.args["slots"].values())
            assert ids <= occupants
            # ...which is what makes the per-request waterfall add up
            for r in traced:
                wf = router.reqtrace.get(r.trace_id)
                assert wf is not None
                assert wf["phases"]["prefill_ms"] > 0.0
                assert wf["phases"]["decode_rounds"] >= 1
                assert r.ttft_breakdown is not None
                assert r.ttft_breakdown["prefill_ms"] > 0.0
        finally:
            disable_tracing()
