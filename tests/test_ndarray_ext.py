"""INDArray surface wave (round-4 Weak #9): boolean-indexing
conditionals, row/column vector ops, tensors-along-dimension, scalar
reducers, distances, exporters.

Reference parity: INDArray.java's replaceWhere/getWhere/addRowVector/
tensorAlongDimension/maxNumber/distance2/toIntVector families +
indexing/conditions/Conditions.java and BooleanIndexing.java.
"""
import numpy as np
import pytest

from deeplearning4j_tpu import nd
from deeplearning4j_tpu.ndarray.conditions import Conditions


def arr(vals):
    return nd.create(np.asarray(vals, np.float32))


class TestConditionals:
    def test_replace_where_scalar(self):
        x = arr([[1.0, -2.0], [-3.0, 4.0]])
        x.replace_where(0.0, Conditions.less_than(0))
        np.testing.assert_allclose(np.asarray(x), [[1, 0], [0, 4]])

    def test_replace_where_nan(self):
        x = arr([1.0, np.nan, 3.0])
        x.replace_where(-1.0, Conditions.is_nan())
        np.testing.assert_allclose(np.asarray(x), [1, -1, 3])

    def test_put_where(self):
        x = arr([1.0, 5.0, 2.0])
        x.put_where(Conditions.greater_than(1.5), arr([10.0, 20.0, 30.0]))
        np.testing.assert_allclose(np.asarray(x), [1, 20, 30])

    def test_get_where(self):
        x = arr([1.0, 5.0, 2.0, 7.0])
        got = x.get_where(None, Conditions.greater_than(2))
        np.testing.assert_allclose(np.asarray(got), [5, 7])

    def test_match_condition_and_count(self):
        x = arr([1.0, -1.0, 2.0])
        mask = x.match_condition(Conditions.greater_than(0))
        np.testing.assert_array_equal(np.asarray(mask),
                                      [True, False, True])
        assert x.condition_count(Conditions.greater_than(0)) == 2

    def test_callable_condition(self):
        x = arr([1.0, 4.0, 9.0])
        x.replace_where(0.0, lambda v: v > 5)
        np.testing.assert_allclose(np.asarray(x), [1, 4, 0])

    def test_camel_aliases(self):
        x = arr([[1.0, -1.0]])
        x.replaceWhere(9.0, Conditions.lessThan(0))
        np.testing.assert_allclose(np.asarray(x), [[1, 9]])


class TestRowColumnVectors:
    def setup_method(self):
        self.m = arr([[1.0, 2.0], [3.0, 4.0]])

    def test_add_row_vector(self):
        out = self.m.add_row_vector([10.0, 20.0])
        np.testing.assert_allclose(np.asarray(out), [[11, 22], [13, 24]])
        # original untouched (copy semantics, like the reference's add*)
        np.testing.assert_allclose(np.asarray(self.m), [[1, 2], [3, 4]])

    def test_addi_column_vector_in_place(self):
        self.m.addi_column_vector([10.0, 20.0])
        np.testing.assert_allclose(np.asarray(self.m), [[11, 12], [23, 24]])

    def test_mul_div_sub(self):
        np.testing.assert_allclose(
            np.asarray(self.m.mul_row_vector([2.0, 3.0])),
            [[2, 6], [6, 12]])
        np.testing.assert_allclose(
            np.asarray(self.m.div_column_vector([1.0, 2.0])),
            [[1, 2], [1.5, 2]])
        np.testing.assert_allclose(
            np.asarray(self.m.sub_row_vector([1.0, 1.0])),
            [[0, 1], [2, 3]])


class TestTensorAlongDimension:
    def test_tad_matches_reference_semantics(self):
        x = nd.create(np.arange(24).reshape(2, 3, 4).astype(np.float32))
        # TADs along dim 2: rows of length 4; there are 6 of them
        assert x.num_tensors_along_dimension(2) == 6
        t0 = x.tensor_along_dimension(0, 2)
        np.testing.assert_allclose(np.asarray(t0), [0, 1, 2, 3])
        # along dims (1, 2): the 2 matrices
        assert x.num_tensors_along_dimension(1, 2) == 2
        np.testing.assert_allclose(
            np.asarray(x.tensor_along_dimension(1, 1, 2)),
            np.arange(12, 24).reshape(3, 4))

    def test_slice_and_put_slice(self):
        x = nd.create(np.zeros((3, 2), np.float32))
        x.put_slice(1, [5.0, 6.0])
        np.testing.assert_allclose(np.asarray(x.slice_at(1)), [5, 6])
        np.testing.assert_allclose(np.asarray(x)[0], [0, 0])

    def test_slice_at_is_view(self):
        x = nd.create(np.zeros((3, 2), np.float32))
        x.slice_at(2).addi(7.0)
        np.testing.assert_allclose(np.asarray(x)[2], [7, 7])


class TestScalarReducers:
    def setup_method(self):
        self.x = arr([[1.0, -2.0], [3.0, -4.0]])

    def test_numbers(self):
        assert self.x.max_number() == 3.0
        assert self.x.min_number() == -4.0
        assert self.x.sum_number() == -2.0
        assert self.x.mean_number() == -0.5
        np.testing.assert_allclose(self.x.norm1_number(), 10.0)
        np.testing.assert_allclose(self.x.norm2_number(),
                                   np.sqrt(30.0), rtol=1e-6)
        np.testing.assert_allclose(self.x.ammean(), 2.5)
        np.testing.assert_allclose(self.x.median_number(), -0.5)
        np.testing.assert_allclose(self.x.percentile_number(50), -0.5)

    def test_std_bias_correction(self):
        v = np.asarray(self.x).reshape(-1)
        np.testing.assert_allclose(self.x.std_number(True),
                                   np.std(v, ddof=1), rtol=1e-6)
        np.testing.assert_allclose(self.x.var_number(False),
                                   np.var(v), rtol=1e-6)


class TestDistances:
    def test_distance_family(self):
        a = arr([1.0, 2.0, 3.0])
        b = arr([2.0, 4.0, 6.0])
        np.testing.assert_allclose(a.distance1(b), 6.0)
        np.testing.assert_allclose(a.distance2(b), np.sqrt(14.0),
                                   rtol=1e-6)
        np.testing.assert_allclose(a.squared_distance(b), 14.0)
        np.testing.assert_allclose(a.cosine_similarity(b), 1.0, rtol=1e-6)


class TestExportersAndPredicates:
    def test_exporters(self):
        x = arr([[1.7, 2.2], [3.0, 4.9]])
        assert x.to_int_vector() == [1, 2, 3, 4]
        assert x.to_int_matrix() == [[1, 2], [3, 4]]
        assert x.to_float_vector() == pytest.approx([1.7, 2.2, 3.0, 4.9],
                                                    rel=1e-6)
        assert x.toDoubleMatrix()[1] == pytest.approx([3.0, 4.9])

    def test_predicates(self):
        assert arr([[1.0, 2.0]]).is_row_vector
        assert arr([[1.0], [2.0]]).is_column_vector
        assert arr([[1.0, 2.0], [3.0, 4.0]]).is_square
        assert not arr([[1.0, 2.0]]).is_square

    def test_repmat_broadcast(self):
        x = arr([[1.0, 2.0]])
        assert x.repmat(2, 3).shape == (2, 6)
        assert x.broadcast(4, 2).shape == (4, 2)


# ---- round-5 tail: entropy family, eps, take, where family ----------------

def test_entropy_family_and_prod():
    from deeplearning4j_tpu import nd
    p = nd.create([0.5, 0.25, 0.25])
    assert p.shannon_entropy().item() == pytest.approx(1.5)
    assert p.log_entropy().item() == pytest.approx(
        np.log(-(0.5 * np.log(0.5) + 0.5 * np.log(0.25))))
    assert nd.create([2.0, 3.0, 4.0]).prod_number() == pytest.approx(24.0)


def test_eps_take_where_family():
    from deeplearning4j_tpu import nd
    from deeplearning4j_tpu.ndarray.conditions import Conditions
    a = nd.create([[1.0, 2.0], [3.0, 4.0]])
    assert a.eps(nd.create([[1.0, 2.000001], [3.1, 4.0]])
                 ).to_numpy().tolist() == [[True, True], [False, True]]
    np.testing.assert_array_equal(
        a.take([1, 0]).to_numpy(), [[3.0, 4.0], [1.0, 2.0]])
    np.testing.assert_array_equal(
        a.take([1], axis=1).to_numpy(), [[2.0], [4.0]])
    got = a.get_where(None, Conditions.greater_than(2.5))
    np.testing.assert_array_equal(np.sort(got.to_numpy()), [3.0, 4.0])
    rep = a.dup().replace_where(0.0, Conditions.greater_than(2.5))
    np.testing.assert_array_equal(rep.to_numpy(), [[1.0, 2.0], [0.0, 0.0]])


def test_entropy_zero_probability_and_camel_aliases():
    """Regression: zero-probability entries contribute 0 to both entropy
    variants (no NaN), and the new methods have camelCase aliases."""
    from deeplearning4j_tpu import nd
    p = nd.create([1.0, 0.0])
    assert p.entropy().item() == pytest.approx(0.0)
    assert p.shannon_entropy().item() == pytest.approx(0.0)
    assert np.isfinite(p.log_entropy().item()) or \
        p.log_entropy().item() == -np.inf    # log(0) of zero entropy
    q = nd.create([0.5, 0.25, 0.25])
    assert q.shannonEntropy().item() == pytest.approx(1.5)
    assert q.prodNumber() == pytest.approx(0.03125)
    assert np.isfinite(q.logEntropy().item())
