"""Mixed-precision policy, scanned whole-epoch fit, gradient
normalization modes.

Reference test model: the reference has no mixed-precision analogue (its
DataType plumbing switches whole-net dtype); the policy here is validated
the way the reference validates training changes — numerics against a
known-good configuration (IntegrationTestRunner.java:84 golden-comparison
style): f32-master mixed-precision training must track pure-f32 training
on the same data/seed.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import MixedPrecision, SameDiff, TrainingConfig
from deeplearning4j_tpu.dataset import DeviceCachedIterator
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.learning.updaters import Adam, Sgd


def _mlp_sd(mp=None, updater=None, **tc_kw):
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 20))
    w = sd.var("w", value=rng.normal(0, 0.1, (20, 16)).astype(np.float32))
    b = sd.var("b", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w).add(b))
    w2 = sd.var("w2", value=rng.normal(0, 0.1, (16, 4)).astype(np.float32))
    logits = h.mmul(w2, name="logits")
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=updater or Adam(learning_rate=1e-2),
        data_set_feature_mapping=["x"], data_set_label_mapping=["labels"],
        mixed_precision=mp, **tc_kw)
    return sd


def _data(n=256, din=20, k=4, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, din)).astype(np.float32)
    Y = np.eye(k, dtype=np.float32)[rng.integers(0, k, n)]
    return X, Y


# ----------------------------------------------------------------------
# scanned whole-epoch fit
def test_scan_fit_matches_loop_fit_exactly():
    """DeviceCachedIterator (scan path) must produce identical losses and
    params to the per-step loop path — same batches, same key schedule."""
    X, Y = _data()
    sd_loop, sd_scan = _mlp_sd(), _mlp_sd()
    sd_loop._seed = sd_scan._seed = 99
    h_loop = sd_loop.fit(ArrayDataSetIterator(X, Y, 32), epochs=3)
    h_scan = sd_scan.fit(DeviceCachedIterator(X, Y, 32), epochs=3)
    np.testing.assert_allclose(h_loop.loss_curve.losses,
                               h_scan.loss_curve.losses, rtol=1e-6)
    for n in sd_loop.trainable_params():
        np.testing.assert_allclose(np.asarray(sd_loop._arrays[n]),
                                   np.asarray(sd_scan._arrays[n]), atol=1e-6)


def test_scan_fit_resumes_iteration_count():
    X, Y = _data()
    sd = _mlp_sd()
    sd.fit(DeviceCachedIterator(X, Y, 32), epochs=2)
    assert sd.training_config.iteration_count == 2 * (256 // 32)


# ----------------------------------------------------------------------
# mixed precision
def test_mixed_precision_converges_like_f32():
    """f32-master mixed precision must track pure-f32 convergence on the
    same data (bf16 compute noise, not divergence)."""
    X, Y = _data()
    sd32, sdmp = _mlp_sd(), _mlp_sd(MixedPrecision())
    sd32._seed = sdmp._seed = 5
    h32 = sd32.fit(DeviceCachedIterator(X, Y, 32), epochs=12)
    hmp = sdmp.fit(DeviceCachedIterator(X, Y, 32), epochs=12)
    f32_first, f32_last = h32.loss_curve.losses[0], h32.loss_curve.losses[-1]
    mp_last = hmp.loss_curve.losses[-1]
    assert f32_last < f32_first          # sanity: f32 run converges
    assert mp_last < f32_first           # mp run converges too
    assert abs(mp_last - f32_last) < 0.1 * max(f32_first - f32_last, 1e-3) + 0.05


def test_mixed_precision_keeps_f32_master_params_and_state():
    X, Y = _data()
    sd = _mlp_sd(MixedPrecision())
    sd.fit(DeviceCachedIterator(X, Y, 32), epochs=2)
    for n, a in sd.trainable_params().items():
        assert a.dtype == jnp.float32, (n, a.dtype)
    for leaf in jax.tree_util.tree_leaves(sd._updater_state):
        assert leaf.dtype == jnp.float32


def test_loss_scaling_matches_unscaled():
    """Static loss scaling must be numerics-neutral (scale applied to the
    loss, unapplied on the gradients)."""
    X, Y = _data()
    sd_s = _mlp_sd(MixedPrecision(loss_scale=1024.0))
    sd_n = _mlp_sd(MixedPrecision())
    sd_s._seed = sd_n._seed = 7
    h_s = sd_s.fit(DeviceCachedIterator(X, Y, 32), epochs=3)
    h_n = sd_n.fit(DeviceCachedIterator(X, Y, 32), epochs=3)
    assert abs(h_s.loss_curve.losses[-1] - h_n.loss_curve.losses[-1]) < 5e-2


def test_mixed_precision_layer_api_lenet_smoke():
    """Layer-API plumbing: builder().mixed_precision() reaches the train
    step; a small CNN still learns and BN running stats stay float32."""
    from deeplearning4j_tpu.nn import (BatchNormalization, ConvolutionLayer,
                                       DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer,
                                       SubsamplingLayer)
    conf = (NeuralNetConfiguration.builder()
            .seed(0).updater(Adam(learning_rate=1e-2))
            .mixed_precision()
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation="relu",
                                    convolution_mode="SAME"))
            .layer(BatchNormalization())
            .layer(SubsamplingLayer(pooling_type="MAX", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=4, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(3)
    X = rng.normal(size=(128, 1, 8, 8)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 128)]
    h = net.fit(DeviceCachedIterator(X, Y, 32), epochs=8)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
    for n, a in net._sd_train.state_vars_map().items():
        assert a.dtype == jnp.float32, (n, a.dtype)
    # serde round-trip carries the policy
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.mixed_precision is not None
    assert conf2.mixed_precision.compute_dtype == "bfloat16"


# ----------------------------------------------------------------------
# gradient normalization modes (reference: BaseMultiLayerUpdater.preApply
# :395, GradientNormalization enum)
def _one_step_grads_applied(tc_kw, lr=1.0):
    """Run one SGD step; param delta = -lr * (clipped grad)."""
    X, Y = _data(n=32)
    sd = _mlp_sd(updater=Sgd(learning_rate=lr), **tc_kw)
    before = {n: np.asarray(a) for n, a in sd.trainable_params().items()}
    sd.fit(DeviceCachedIterator(X, Y, 32), epochs=1)
    after = {n: np.asarray(sd._arrays[n]) for n in before}
    return {n: (before[n] - after[n]) / lr for n in before}


def test_clip_l2_global_norm():
    t = 1e-3
    deltas = _one_step_grads_applied(
        {"gradient_normalization": "clip_l2_global",
         "gradient_normalization_threshold": t})
    gn = np.sqrt(sum(float(np.sum(d ** 2)) for d in deltas.values()))
    assert gn <= t * 1.01


def test_clip_l2_per_layer():
    t = 1e-3
    deltas = _one_step_grads_applied(
        {"gradient_normalization": "clip_l2_per_layer",
         "gradient_normalization_threshold": t})
    for n, d in deltas.items():
        assert np.sqrt(float(np.sum(d ** 2))) <= t * 1.01, n


def test_renormalize_l2_per_layer():
    deltas = _one_step_grads_applied(
        {"gradient_normalization": "renormalize_l2_per_layer"})
    for n, d in deltas.items():
        np.testing.assert_allclose(np.sqrt(float(np.sum(d ** 2))), 1.0,
                                   rtol=1e-3, err_msg=n)


def test_unknown_gradient_normalization_raises():
    X, Y = _data(n=32)
    sd = _mlp_sd(gradient_normalization="bogus")
    with pytest.raises(ValueError, match="bogus"):
        sd.fit(DeviceCachedIterator(X, Y, 32), epochs=1)


# ----------------------------------------------------------------------
# CE-tail precision policy (MixedPrecision.softmax_dtype / ce_tail_dtype)

def _fit_params_losses(mp):
    X, Y = _data(n=64)
    sd = _mlp_sd(mp=mp)
    h = sd.fit(DeviceCachedIterator(X, Y, 32), epochs=3)
    return ({n: np.asarray(a) for n, a in sd.trainable_params().items()},
            h.loss_curve.losses)


def test_ce_tail_default_stays_f32_bit_exact():
    """softmax_dtype=None and an explicit "float32" are the SAME
    program: the knob's default must not perturb existing runs."""
    p_none, l_none = _fit_params_losses(MixedPrecision())
    p_f32, l_f32 = _fit_params_losses(
        MixedPrecision(softmax_dtype="float32"))
    assert l_none == l_f32
    for n in p_none:
        assert np.array_equal(p_none[n], p_f32[n]), n


def test_ce_tail_bf16_trains_close_to_f32():
    """The bf16 log-softmax tail changes rounding, not training: losses
    track the f32 tail closely and keep decreasing."""
    _, l_f32 = _fit_params_losses(MixedPrecision())
    _, l_bf16 = _fit_params_losses(
        MixedPrecision(softmax_dtype="bfloat16"))
    np.testing.assert_allclose(l_bf16, l_f32, rtol=3e-2)
    assert l_bf16[-1] < l_bf16[0]


def test_ce_tail_alias_and_serde_roundtrip():
    mp = MixedPrecision(softmax_dtype="bfloat16")
    assert mp.ce_tail_dtype == "bfloat16"
    rt = MixedPrecision.from_json(mp.to_json())
    assert rt.softmax_dtype == "bfloat16"
    # legacy/alias key accepted on the way in
    assert MixedPrecision.from_json(
        {"ce_tail_dtype": "bfloat16"}).softmax_dtype == "bfloat16"
    assert MixedPrecision.from_json({"compute_dtype": "bfloat16"}) \
        .softmax_dtype is None


def test_ce_tail_scope_composes_with_fused_windows():
    """The policy is traced into the fused-window program too (the
    scope wraps the step body the scan re-uses)."""
    X, Y = _data(n=64)
    sd = _mlp_sd(mp=MixedPrecision(softmax_dtype="bfloat16"),
                 fused_steps=4)
    h = sd.fit(DeviceCachedIterator(X, Y, 16), epochs=2)
    assert all(np.isfinite(v) for v in h.loss_curve.losses)
    assert h.loss_curve.losses[-1] < h.loss_curve.losses[0]
