"""Observability + safety rails: NaN panic, per-op localization, fault
injection (reference: DefaultOpExecutioner.java:397-437 NAN_PANIC,
FailureTestingListener.java:19)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.autodiff.samediff import NumericsException
from deeplearning4j_tpu.autodiff.training import FailureTestingListener
from deeplearning4j_tpu.learning.updaters import Adam, Sgd


def _nan_model():
    """log(x - 2) goes NaN for x < 2 — the 'log' node is the producer."""
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 4))
    w = sd.var("w", value=np.ones((4, 4), np.float32))
    h = x.mmul(w, name="h")
    shifted = h.sub(2.0, name="shifted")
    bad = sd.invoke("log", [shifted], {}, name="badlog")
    loss = bad.sum()
    loss.mark_as_loss()
    return sd, loss


def test_exec_debug_names_the_producing_op():
    sd, _ = _nan_model()
    X = np.zeros((2, 4), np.float32)      # h=0 -> shifted=-2 -> log=NaN
    with pytest.raises(NumericsException) as ei:
        sd.exec_debug({"x": X})
    msg = str(ei.value)
    assert "badlog" in msg and "'log'" in msg
    assert "range" in msg                  # input stats included


def test_exec_debug_clean_graph_matches_output():
    sd, loss = _nan_model()
    X = np.full((2, 4), 2.0, np.float32)  # h=8 -> shifted=6 -> fine
    dbg = sd.exec_debug({"x": X}, outputs=[loss.name])
    ref = sd.output({"x": X}, [loss.name])
    np.testing.assert_allclose(np.asarray(dbg[loss.name].data),
                               np.asarray(ref[loss.name].data), rtol=1e-6)


def test_exec_debug_flags_bad_parameter():
    sd, _ = _nan_model()
    sd.set_arr_for_var("w", np.full((4, 4), np.nan, np.float32))
    with pytest.raises(NumericsException, match="parameter 'w'"):
        sd.exec_debug({"x": np.ones((2, 4), np.float32)})


def test_nan_panic_raises_during_fit():
    sd, _ = _nan_model()
    sd.training_config = TrainingConfig(
        updater=Sgd(0.1), data_set_feature_mapping=["x"],
        data_set_label_mapping=[], nan_panic=True)
    X = np.zeros((4, 4), np.float32)
    with pytest.raises(NumericsException, match="non-finite"):
        sd.fit([{"x": X}] * 3, epochs=2)


def test_nan_panic_off_does_not_raise():
    sd, _ = _nan_model()
    sd.training_config = TrainingConfig(
        updater=Sgd(0.1), data_set_feature_mapping=["x"],
        data_set_label_mapping=[], nan_panic=False)
    X = np.zeros((4, 4), np.float32)
    h = sd.fit([{"x": X}] * 3, epochs=1)    # NaN flows, no crash
    assert np.isnan(h.loss_curve.losses).any()


def _clean_fit(listeners):
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 3))
    y = sd.placeholder("y", shape=(-1, 1))
    w = sd.var("w", value=np.zeros((3, 1), np.float32))
    loss = ((x.mmul(w) - y).square()).mean()
    loss.mark_as_loss()
    sd.training_config = TrainingConfig(
        updater=Adam(0.01), data_set_feature_mapping=["x"],
        data_set_label_mapping=["y"])
    rng = np.random.RandomState(0)
    batches = [(rng.randn(8, 3).astype(np.float32),
                rng.randn(8, 1).astype(np.float32)) for _ in range(4)]
    return sd.fit(batches, epochs=3, listeners=listeners)


def test_failure_injection_at_iteration():
    l = FailureTestingListener(trigger="iteration", at=5)
    with pytest.raises(FailureTestingListener.InjectedFailure,
                       match="iteration 5"):
        _clean_fit([l])
    assert l.fired


def test_failure_injection_epoch_end_illegal_state():
    l = FailureTestingListener(failure_mode="illegal_state",
                               trigger="epoch_end", at=1)
    with pytest.raises(RuntimeError, match="illegal state at epoch 1"):
        _clean_fit([l])


def test_failure_injection_sleep_is_nonfatal():
    l = FailureTestingListener(failure_mode="sleep", trigger="epoch_start",
                               at=0, sleep_seconds=0.01)
    h = _clean_fit([l])
    assert l.fired and len(h.loss_curve.losses) == 3
