"""NLP subsystem: tokenizers, vocab, word2vec (skipgram+CBOW), fastText,
ParagraphVectors, GloVe, DeepWalk/node2vec, serialization.

Reference test strategy parity: the reference's Word2VecTests train on a
small corpus and assert neighbor/similarity sanity (deeplearning4j-nlp
src/test .../Word2VecTests.java); same here with a synthetic clustered
corpus whose co-occurrence structure is known by construction.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CommonPreprocessor, DeepWalk, DefaultTokenizerFactory, FastText, Glove,
    Graph, NGramTokenizerFactory, Node2Vec, ParagraphVectors, VocabCache,
    Word2Vec, WordVectorSerializer)


def clustered_corpus(n_sent=300, seed=0):
    """Two topic clusters; words inside a cluster co-occur, across don't.
    Any embedding with signal puts same-cluster words nearer."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tech = ["cpu", "gpu", "ram", "disk", "cache", "bus"]
    out = []
    for _ in range(n_sent):
        group = animals if rng.random() < 0.5 else tech
        out.append(" ".join(rng.choice(group, size=6)))
    return out


class TestTokenization:
    def test_default_tokenizer_with_preprocessor(self):
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        toks = fac.create("The Cat, sat; on 42 mats!").get_tokens()
        assert toks == ["the", "cat", "sat", "on", "mats"]

    def test_ngram_tokenizer(self):
        fac = NGramTokenizerFactory(DefaultTokenizerFactory(), 1, 2)
        toks = fac.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestVocab:
    def test_min_frequency_and_indexing(self):
        vc = VocabCache(min_word_frequency=2)
        vc.fit([["a", "a", "b", "c"], ["a", "b"]])
        assert vc.contains_word("a") and vc.contains_word("b")
        assert not vc.contains_word("c")          # freq 1 < 2
        assert vc.index_of("zzz") == 0            # unk
        assert vc.word_frequency("a") == 3

    def test_unigram_table_prefers_frequent(self):
        vc = VocabCache()
        vc.fit([["a"] * 50 + ["b"] * 2])
        tbl = vc.unigram_table()
        assert tbl[vc.index_of("a")] > tbl[vc.index_of("b")]
        np.testing.assert_allclose(tbl.sum(), 1.0)


class TestWord2Vec:
    @pytest.mark.parametrize("algorithm", ["skipgram", "cbow"])
    def test_clusters_separate(self, algorithm):
        w2v = Word2Vec(vector_size=24, window_size=3, negative=4,
                       epochs=10, learning_rate=0.05, seed=1,
                       algorithm=algorithm,
                       batch_size=512).fit(clustered_corpus())
        sim_in = w2v.similarity("cat", "dog")
        sim_out = w2v.similarity("cat", "gpu")
        assert sim_in > sim_out + 0.2, (sim_in, sim_out)

    def test_words_nearest_same_cluster(self):
        w2v = Word2Vec(vector_size=24, window_size=3, epochs=10,
                       learning_rate=0.05, seed=1,
                       batch_size=512).fit(clustered_corpus())
        near = w2v.words_nearest("cpu", top_n=3)
        assert set(near) <= {"gpu", "ram", "disk", "cache", "bus"}, near

    def test_loss_decreases(self):
        w2v = Word2Vec(vector_size=16, epochs=4, seed=0,
                       batch_size=512).fit(clustered_corpus(150))
        h = w2v.loss_history
        assert len(h) > 4
        assert np.mean(h[-3:]) < np.mean(h[:3])

    def test_builder_api(self):
        w2v = (Word2Vec.builder().layer_size(12).window_size(2)
               .min_word_frequency(1).seed(7).build())
        assert w2v.trainer.vector_size == 12
        assert w2v.trainer.window_size == 2

    def test_serialization_roundtrip(self, tmp_path):
        w2v = Word2Vec(vector_size=12, epochs=1, seed=0,
                       batch_size=256).fit(clustered_corpus(50))
        p = tmp_path / "vecs.txt"
        WordVectorSerializer.write_word_vectors(w2v, str(p))
        loaded = WordVectorSerializer.read_word_vectors(str(p))
        for w in ("cat", "gpu"):
            np.testing.assert_allclose(loaded.get_word_vector(w),
                                       w2v.get_word_vector(w), atol=1e-5)
        assert loaded.words_nearest("cat", 2) == w2v.words_nearest("cat", 2)


class TestFastText:
    def test_subword_oov_vector(self):
        ft = FastText(vector_size=16, epochs=2, seed=0,
                      batch_size=256).fit(clustered_corpus(100))
        v = ft.get_word_vector("caat")      # OOV: composed from n-grams
        assert v.shape == (16,)
        assert np.abs(v).sum() > 0

    def test_clusters_separate(self):
        ft = FastText(vector_size=24, epochs=3, seed=1,
                      batch_size=512).fit(clustered_corpus())
        def cos(a, b):
            va, vb = ft.compose(a), ft.compose(b)
            return float(va @ vb /
                         (np.linalg.norm(va) * np.linalg.norm(vb)))
        assert cos("cat", "dog") > cos("cat", "gpu")


class TestParagraphVectors:
    def test_doc_clusters(self):
        docs, labels = [], []
        rng = np.random.default_rng(0)
        animals = ["cat", "dog", "horse", "cow"]
        tech = ["cpu", "gpu", "ram", "disk"]
        for i in range(30):
            grp = animals if i % 2 == 0 else tech
            docs.append(" ".join(rng.choice(grp, size=8)))
            labels.append(f"{'A' if i % 2 == 0 else 'T'}{i}")
        pv = ParagraphVectors(vector_size=16, epochs=8, seed=0,
                              batch_size=256).fit(docs, labels)
        sim_same = pv.similarity("A0", "A2")
        sim_diff = pv.similarity("A0", "T1")
        assert sim_same > sim_diff

    def test_infer_vector_lands_near_cluster(self):
        docs = ["cat dog cat dog horse", "gpu ram cpu disk gpu"] * 10
        labels = [f"D{i}" for i in range(20)]
        pv = ParagraphVectors(vector_size=16, epochs=10, seed=0,
                              batch_size=256).fit(docs, labels)
        v = pv.infer_vector("dog horse cat")
        sims = (pv.doc_vectors @ v) / (
            np.linalg.norm(pv.doc_vectors, axis=1) * np.linalg.norm(v)
            + 1e-9)
        # the animal-doc cluster (even indices) should be nearer on
        # average than the tech cluster
        assert sims[0::2].mean() > sims[1::2].mean()


class TestGlove:
    def test_clusters_separate(self):
        gl = Glove(vector_size=16, window_size=3, epochs=30,
                   seed=0).fit(clustered_corpus(200))
        assert gl.similarity("cat", "dog") > gl.similarity("cat", "gpu")


def two_cliques(k=6):
    """Two k-cliques joined by one bridge edge — the standard embedding
    sanity graph."""
    edges = []
    for a in range(k):
        for b in range(a + 1, k):
            edges.append((a, b))
            edges.append((k + a, k + b))
    edges.append((0, k))
    return Graph(2 * k, edges)


class TestDeepWalk:
    def test_cliques_cluster(self):
        g = two_cliques()
        dw = DeepWalk(vector_size=16, walk_length=12, walks_per_vertex=8,
                      epochs=3, seed=0, batch_size=512).fit(g)
        sim_in = dw.similarity_vertex(1, 2)       # same clique
        sim_out = dw.similarity_vertex(1, 8)      # across cliques
        assert sim_in > sim_out

    def test_vertex_vector_shape(self):
        dw = DeepWalk(vector_size=8, walk_length=6, walks_per_vertex=2,
                      epochs=1, seed=0).fit(two_cliques(4))
        assert dw.vertex_vector(0).shape == (8,)

    def test_node2vec_biased_walks_run(self):
        n2v = Node2Vec(vector_size=8, walk_length=6, walks_per_vertex=2,
                       epochs=1, seed=0, q=0.25).fit(two_cliques(4))
        assert n2v.vectors.shape == (9, 8)


class TestNlpOpsLedger:
    """Direct op-registry exercises (ledger pointers)."""

    def test_skipgram_ns_loss_matches_numpy(self):
        from deeplearning4j_tpu.ops import registry
        rng = np.random.default_rng(0)
        V, D, B, K = 10, 4, 6, 3
        syn0 = rng.standard_normal((V, D)).astype(np.float32)
        syn1 = rng.standard_normal((V, D)).astype(np.float32)
        c = rng.integers(0, V, B).astype(np.int32)
        o = rng.integers(0, V, B).astype(np.int32)
        n = rng.integers(0, V, (B, K)).astype(np.int32)
        got = float(registry.exec_op("skipgram_ns_loss", syn0, syn1,
                                     c, o, n).data)
        sig = lambda x: 1.0 / (1.0 + np.exp(-x))
        pos = np.einsum("bd,bd->b", syn0[c], syn1[o])
        neg = np.einsum("bd,bkd->bk", syn0[c], syn1[n])
        want = np.mean(-np.log(sig(pos)) - np.log(sig(-neg)).sum(-1))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_cbow_ns_loss_mask(self):
        from deeplearning4j_tpu.ops import registry
        rng = np.random.default_rng(0)
        V, D, B, W, K = 8, 4, 3, 4, 2
        syn0 = rng.standard_normal((V, D)).astype(np.float32)
        syn1 = rng.standard_normal((V, D)).astype(np.float32)
        wins = rng.integers(0, V, (B, W)).astype(np.int32)
        mask = np.ones((B, W), np.float32)
        mask[:, 2:] = 0
        t = rng.integers(0, V, B).astype(np.int32)
        n = rng.integers(0, V, (B, K)).astype(np.int32)
        loss = float(registry.exec_op("cbow_ns_loss", syn0, syn1, wins,
                                      t, n, mask=mask).data)
        assert np.isfinite(loss) and loss > 0

    def test_glove_loss_zero_at_exact_fit(self):
        from deeplearning4j_tpu.ops import registry
        V, D = 4, 3
        w = np.zeros((V, D), np.float32)
        b = np.log(np.full(V, 2.0, np.float32)) / 2
        rows = np.array([0, 1], np.int32)
        cols = np.array([2, 3], np.int32)
        counts = np.full(2, 2.0, np.float32)
        # pred = 0 + log2/2 + log2/2 = log2 = log(count) -> loss 0
        loss = float(registry.exec_op("glove_loss", w, w, b, b,
                                      rows, cols, counts).data)
        np.testing.assert_allclose(loss, 0.0, atol=1e-10)


# ---- BERT WordPiece -------------------------------------------------------

def test_wordpiece_greedy_longest_match():
    from deeplearning4j_tpu.nlp import BertWordPieceTokenizerFactory
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "un", "##aff", "##able",
             "##ed", "run", "##ning", "!", "the"]
    f = BertWordPieceTokenizerFactory(vocab=vocab)
    assert f.create("unaffable").get_tokens() == ["un", "##aff", "##able"]
    assert f.create("running").get_tokens() == ["run", "##ning"]
    assert f.create("The running!").get_tokens() == \
        ["the", "run", "##ning", "!"]
    # unknown word falls back whole to [UNK]
    assert f.create("xyzzy").get_tokens() == ["[UNK]"]


def test_wordpiece_encode_with_specials_and_padding():
    from deeplearning4j_tpu.nlp import BertWordPieceTokenizerFactory
    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "run", "##ning"])}
    f = BertWordPieceTokenizerFactory(vocab=vocab)
    ids = f.encode("running", max_len=8)
    assert ids == [2, 4, 5, 3, 0, 0, 0, 0]   # CLS run ##ning SEP PAD...
    assert f.encode("running", add_special_tokens=False) == [4, 5]


def test_wordpiece_vocab_file(tmp_path):
    from deeplearning4j_tpu.nlp import BertWordPieceTokenizerFactory
    p = tmp_path / "vocab.txt"
    p.write_text("\n".join(["[PAD]", "[UNK]", "hello", "##!"]))
    f = BertWordPieceTokenizerFactory(vocab_path=str(p))
    assert f.create("hello").get_tokens() == ["hello"]
    assert f.vocab["hello"] == 2


def test_wordpiece_contractions_and_sep_truncation():
    """Regression: punctuation (incl. apostrophes) splits like BERT's
    BasicTokenizer, and max_len truncation preserves [SEP]."""
    from deeplearning4j_tpu.nlp import BertWordPieceTokenizerFactory
    vocab = {t: i for i, t in enumerate(
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "don", "'", "t", "go"])}
    f = BertWordPieceTokenizerFactory(vocab=vocab)
    assert f.create("don't go").get_tokens() == ["don", "'", "t", "go"]
    ids = f.encode("don't go", max_len=4)
    assert ids[0] == vocab["[CLS]"] and ids[-1] == vocab["[SEP]"]
    assert len(ids) == 4
