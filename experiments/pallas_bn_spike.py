"""Spike: Pallas fused BN(+ReLU) backward vs XLA's jax.grad fusions.

PROFILE.md round-4 named "a Pallas fused conv-epilogue/BN kernel" as the
next lever for ResNet-50. This measures whether a hand-written two-phase
Pallas backward (the pass-count-optimal schedule: reduction pass over
(x, dy) then dx pass over (x, dy)) beats the fusions XLA derives from
jax.grad of the same chain, on the real chip at ResNet stage shapes.
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl


def bn_relu_ref(x, gamma, beta, eps=1e-5):
    """The exact forward the framework runs (batchnorm_train + relu),
    NHWC, f32 stats, bf16 tensor math."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    m2 = jnp.mean(xf * xf, axis=(0, 1, 2))
    var = jnp.maximum(m2 - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    a = (gamma * inv).astype(x.dtype)
    b = (beta - gamma * inv * mean).astype(x.dtype)
    return jax.nn.relu(x * a + b)


def loss_ref(x, gamma, beta, dy):
    return jnp.sum(bn_relu_ref(x, gamma, beta) * dy)


# ---------------------------------------------------------------------------
# Pallas two-phase backward
# ---------------------------------------------------------------------------

def _phase1_kernel(x_ref, dy_ref, a_ref, b_ref, s1_ref, s2_ref):
    """Partial sums per row-tile: s1 = sum(dz), s2 = sum(dz * x) with
    dz = dy * (a*x+b > 0). (Reduction over x directly; the xhat algebra
    folds into the combine step on the host side.)"""
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    z = x * a_ref[:] + b_ref[:]
    dz = jnp.where(z > 0, dy, 0.0)
    # (8, C) output block to satisfy TPU tiling; row 0 carries the sum
    s1_ref[:] = jnp.broadcast_to(jnp.sum(dz, axis=0, keepdims=True),
                                 s1_ref.shape)
    s2_ref[:] = jnp.broadcast_to(jnp.sum(dz * x, axis=0, keepdims=True),
                                 s2_ref.shape)


def _phase2_kernel(x_ref, dy_ref, a_ref, b_ref, c1_ref, c2_ref, g_ref,
                   dx_ref):
    """dx = g * (dz - c1 - x * c2) per row-tile (c1/c2 precombined)."""
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    z = x * a_ref[:] + b_ref[:]
    dz = jnp.where(z > 0, dy, 0.0)
    dx_ref[:] = (g_ref[:] * (dz - c1_ref[:] - x * c2_ref[:])
                 ).astype(dx_ref.dtype)


def bn_relu_bwd_pallas(x2d, dy2d, gamma, beta, mean, inv, eps=1e-5,
                       row_tile=2048):
    """x2d, dy2d: (R, C) bf16 flattened NHWC. Returns (dx, dgamma, dbeta).

    Derivation: with xhat=(x-mean)*inv, dgamma=sum(dz*xhat),
    dbeta=sum(dz), dx = gamma*inv*(dz - E[dz] - xhat*E[dz*xhat]).
    Rewriting sums over x (not xhat): sum(dz*xhat) = inv*(sum(dz*x) -
    mean*sum(dz)), and dx = g*(dz - c1 - x*c2) with
    g = gamma*inv, c2 = inv^2 * E[dz*xhat-ish] ... expanded below.
    """
    R, C = x2d.shape
    # tile must divide R exactly — a floor division would silently drop
    # tail rows from the reductions and leave dx's tail uninitialized
    while R % row_tile and row_tile > 8:
        row_tile //= 2
    if R % row_tile:
        raise ValueError(f"R={R} has no power-of-two row tile >= 8")
    n_tiles = R // row_tile
    a = (gamma * inv).astype(jnp.float32)[None, :]
    b = (beta - gamma * inv * mean).astype(jnp.float32)[None, :]

    s1, s2 = pl.pallas_call(
        _phase1_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((8, C), lambda i: (i, 0)),
            pl.BlockSpec((8, C), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles * 8, C), jnp.float32),
            jax.ShapeDtypeStruct((n_tiles * 8, C), jnp.float32),
        ],
    )(x2d, dy2d, a, b)
    sum_dz = s1[::8].sum(0)                     # (C,)
    sum_dzx = s2[::8].sum(0)
    sum_dzxhat = inv * (sum_dzx - mean * sum_dz)
    dgamma = sum_dzxhat
    dbeta = sum_dz
    # dx = gamma*inv*(dz - sum_dz/R - xhat * sum_dzxhat/R)
    #    = g*dz - g*(sum_dz/R - mean*inv*sum_dzxhat/R) - g*inv*sum_dzxhat/R * x
    g = (gamma * inv).astype(jnp.float32)
    c2 = (inv * sum_dzxhat / R)
    c1 = (sum_dz / R - mean * c2)
    dx = pl.pallas_call(
        _phase2_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
            pl.BlockSpec((1, C), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((R, C), x2d.dtype),
    )(x2d, dy2d, a, b, c1[None, :], c2[None, :], g[None, :])
    return dx, dgamma, dbeta


def main():
    shapes = [
        (128, 56, 56, 256),
        (128, 28, 28, 512),
        (128, 56, 56, 64),
    ]
    for (N, H, W, C) in shapes:
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(N, H, W, C)), jnp.bfloat16)
        dy = jnp.asarray(rng.normal(size=(N, H, W, C)), jnp.bfloat16)
        gamma = jnp.asarray(rng.normal(size=(C,)) * 0.1 + 1.0, jnp.float32)
        beta = jnp.asarray(rng.normal(size=(C,)) * 0.1, jnp.float32)

        # XLA backward-only via vjp (residuals precomputed)
        @jax.jit
        def xla_bwd(x, gamma, beta, dy):
            _, f_vjp = jax.vjp(lambda xx, g, b: bn_relu_ref(xx, g, b),
                               x, gamma, beta)
            return f_vjp(dy)
        grad_fn = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))
        dx_ref, dg_ref, db_ref = grad_fn(x, gamma, beta, dy)
        jax.block_until_ready(dx_ref)

        # pallas
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.maximum(jnp.mean(xf * xf, (0, 1, 2)) - mean ** 2, 0.0)
        inv = lax.rsqrt(var + 1e-5)
        R = N * H * W
        x2d = x.reshape(R, C)
        dy2d = dy.reshape(R, C)
        pal = jax.jit(functools.partial(bn_relu_bwd_pallas))
        dx_p, dg_p, db_p = pal(x2d, dy2d, gamma, beta, mean, inv)
        jax.block_until_ready(dx_p)

        d_ref = np.asarray(dx_ref, np.float32).reshape(-1)
        d_pal = np.asarray(dx_p, np.float32).reshape(-1)
        mismatch = np.mean(np.abs(d_ref - d_pal) > 0.05)
        err_g = np.max(np.abs(np.asarray(dg_p) - np.asarray(dg_ref))
                       / (np.abs(np.asarray(dg_ref)) + 1.0))
        print(f"shape {N}x{H}x{W}x{C}: dx mismatch frac={mismatch:.5f} "
              f"(bf16 relu-mask edges) rel|dgamma err|={err_g:.4f}")

        def t(f, *args):
            jax.block_until_ready(f(*args))
            best = 1e9
            for _ in range(5):
                t0 = time.perf_counter()
                r = f(*args)
                jax.block_until_ready(r)
                best = min(best, time.perf_counter() - t0)
            return best * 1000

        ms_full = t(grad_fn, x, gamma, beta, dy)
        ms_xla_bwd = t(xla_bwd, x, gamma, beta, dy)
        ms_pal = t(pal, x2d, dy2d, gamma, beta, mean, inv)
        gb = (5 * R * C * 2) / 1e9        # 4 reads + 1 write, bf16
        print(f"  XLA fwd+bwd: {ms_full:.2f} ms | XLA bwd-only: "
              f"{ms_xla_bwd:.2f} ms | pallas bwd-only: {ms_pal:.2f} ms | "
              f"bwd roofline {1000*gb/819:.2f} ms ({gb:.2f} GB @819GB/s)")


if __name__ == "__main__":
    main()
