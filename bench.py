"""Benchmark: flagship LeNet-class CNN training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric = steady-state training samples/sec (PerformanceListener definition,
reference optimize/listeners/PerformanceListener.java:46-118) for
MultiLayerNetwork.fit() on MNIST-shaped synthetic data, batch 128 —
BASELINE.md target config 1 (LeNet MNIST fit()). The reference publishes no
numbers (BASELINE.json "published": {}), so vs_baseline is reported as 1.0
(parity placeholder) until a measured reference baseline exists.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    from __graft_entry__ import _flagship
    from deeplearning4j_tpu.dataset import DeviceCachedIterator, load_mnist

    batch = 128
    X, y = load_mnist(train=True, n_synthetic=2048)
    Y = np.eye(10, dtype=np.float32)[y]
    n = (len(X) // batch) * batch

    net = _flagship()
    # device-cached feed: the dataset is uploaded to HBM once; the training
    # loop's only host traffic is the dispatch stream
    it = DeviceCachedIterator(X, Y, batch_size=batch)

    # warmup epochs (compile incl. per-slice programs), then median of 3
    # timed trials (the tunnel to the chip adds run-to-run jitter)
    net.fit(it, epochs=2)
    timed_epochs = 6
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        net.fit(it, epochs=timed_epochs)
        rates.append(timed_epochs * n / (time.perf_counter() - t0))
    samples_per_sec = sorted(rates)[1]
    print(json.dumps({
        "metric": "lenet_mnist_train_throughput",
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
