"""Benchmarks: BASELINE.md target configs on one TPU chip.

Prints ONE JSON line (driver contract): the headline metric
{"metric", "value", "unit", "vs_baseline"} plus a "configs" dict with all
measured configs (step-time ms, samples/sec, MFU estimate each).

Configs (BASELINE.md):
1. lenet_mnist      — MultiLayerNetwork.fit(), batch 128 (zoo LeNet)
2. samediff_mlp     — SameDiff graph-autodiff MLP train step, batch 128
3. resnet50         — zoo ResNet-50, 224x224 ImageNet shapes, batch 128,
                      bf16 mixed precision (f32 master params)

All base configs train through the scanned whole-epoch step (one device
dispatch per epoch) with device-cached data — the same code path fit()
takes for any listener-free DeviceCachedIterator run. The *_listener
configs attach a ScoreIterationListener and run the fused-window tier
(fused_steps=8, docs/training_performance.md) — the production path —
and additionally report dispatches_per_epoch.

The reference publishes no benchmark numbers (BASELINE.json
"published": {}), so vs_baseline is null — an honest "no measured
reference baseline exists", not a self-granted parity.

Throughput = steady-state training samples/sec (PerformanceListener
definition, reference optimize/listeners/PerformanceListener.java:46-118).
MFU estimate = achieved matmul+conv FLOPs (3x forward for fwd+bwd) over
the v5e bf16 peak (197 TFLOP/s); forward FLOPs counted analytically.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

V5E_PEAK_FLOPS = 197e12  # bf16; f32 runs lower — MFU is an estimate


def _median_rate(fit_fn, n_samples, trials=3):
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fit_fn()
        rates.append(n_samples / (time.perf_counter() - t0))
    return sorted(rates)[trials // 2]


def _dispatch_stats(sd):
    """dispatches_per_epoch + tier from the fit dispatch accounting."""
    st = getattr(sd, "last_fit_stats", None) or {}
    out = {}
    if "dispatches_per_epoch" in st:
        out["dispatches_per_epoch"] = st["dispatches_per_epoch"]
        out["tier"] = st.get("tier")
    return out


def _memory_stats():
    """Per-model memory trajectory for BENCH_r08+: HBM peak after the
    run (the watermark the run needed) plus the active compiled
    program's plan bytes/flops when one was captured
    (monitor/memstats.py) — so BENCH tracks memory next to throughput."""
    from deeplearning4j_tpu import memory
    from deeplearning4j_tpu.monitor import memstats
    out = {}
    try:
        snap = memory.snapshot()
        out["hbm_peak_bytes"] = max(
            (s.peak_bytes or s.bytes_in_use) for s in snap) if snap else 0
        head = memstats.projected_headroom(snap)
        if head is not None:
            out["hbm_headroom_bytes"] = int(head)
    except Exception:
        pass
    plan = memstats.PLANS.active_plan()
    if plan is not None:
        out["plan_program"] = plan.label
        out["plan_total_bytes"] = int(plan.total_bytes)
        if plan.temp_bytes is not None:
            out["plan_temp_bytes"] = int(plan.temp_bytes)
        if plan.flops_per_step is not None:
            out["plan_gflops_per_step"] = round(
                plan.flops_per_step / 1e9, 3)
    return out


def bench_lenet(batch=128, listener=False, fused_steps=1):
    """BASELINE config 1 — plus the ``lenet_listener`` variant: a
    ScoreIterationListener attached (forcing off the scanned tier, as
    any production run with score/checkpoint listeners is) and
    ``fused_steps=8`` fused windows, tracking the listener-path
    throughput that BENCH_r05 showed dispatch-bound at ~1.8% MFU."""
    from deeplearning4j_tpu.autodiff import ScoreIterationListener
    from deeplearning4j_tpu.dataset import DeviceCachedIterator, load_mnist
    from deeplearning4j_tpu.zoo import LeNet

    X, y = load_mnist(train=True, n_synthetic=2048)
    Y = np.eye(10, dtype=np.float32)[y]
    n = (len(X) // batch) * batch
    net = LeNet(height=28, width=28, channels=1).build()
    it = DeviceCachedIterator(X, Y, batch_size=batch)
    listeners = [ScoreIterationListener(print_every=10 ** 9,
                                        print_fn=lambda *a: None)] \
        if listener else []
    fit = lambda epochs: net.fit(it, epochs=epochs, listeners=listeners,
                                 fused_steps=fused_steps)
    fit(2)                                      # warmup/compile
    epochs = 6
    sps = _median_rate(lambda: fit(epochs), epochs * n)
    # fwd conv+matmul FLOPs per image (LeNet 28x28: conv1 20x5x5 @28x28,
    # conv2 50x20x5x5 @14x14, fc 2450x500, out 500x10)
    fwd_flops = 2 * (20 * 5 * 5 * 1 * 28 * 28 + 50 * 5 * 5 * 20 * 14 * 14
                     + 2450 * 500 + 500 * 10)
    return {"samples_per_sec": round(sps, 1),
            "step_time_ms": round(1000.0 * batch / sps, 3),
            "mfu_est": round(3 * fwd_flops * sps / V5E_PEAK_FLOPS, 5),
            "batch": batch, **_dispatch_stats(net.samediff),
            **_memory_stats()}


def _build_mlp_sd(hidden=(512, 256), fused_steps=1, sentinel=False,
                  seed=0, tensorstats=None, analyze=True,
                  fingerprints=False):
    """The BASELINE config-2 MLP graph (784 -> hidden -> 10, softmax CE,
    Adam 1e-3) — shared by bench_samediff_mlp and the cold-start child
    probe so the restart metric measures the same program the throughput
    benchmark does."""
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
    from deeplearning4j_tpu.learning.updaters import Adam

    rng = np.random.default_rng(seed)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 784))
    cur, n_in = x, 784
    for i, h in enumerate(hidden):
        w = sd.var(f"w{i}", value=rng.normal(0, 0.05, (n_in, h)).astype(np.float32))
        b = sd.var(f"b{i}", value=np.zeros(h, np.float32))
        cur = sd.nn.relu(cur.mmul(w).add(b), name=f"h{i}")
        n_in = h
    w = sd.var("w_out", value=rng.normal(0, 0.05, (n_in, 10)).astype(np.float32))
    b = sd.var("b_out", value=np.zeros(10, np.float32))
    logits = cur.mmul(w).add(b, name="logits")
    labels = sd.placeholder("labels", shape=(-1, 10))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    builder = (TrainingConfig.builder()
               .updater(Adam(learning_rate=1e-3))
               .data_set_feature_mapping("x")
               .data_set_label_mapping("labels")
               .fused_steps(fused_steps)
               .sentinel(sentinel)
               .analyze(analyze))
    if tensorstats is not None:
        builder.tensorstats(tensorstats)
    if fingerprints:
        builder.fingerprints(True)
    sd.training_config = builder.build()
    return sd


def bench_samediff_mlp(batch=128, hidden=(512, 256), listener=False,
                       fused_steps=1, sentinel=False,
                       monitor_storage=None, tensorstats=None,
                       monitor_memory=True, analyze=True,
                       fingerprints=False):
    """BASELINE config 2: SameDiff MLP via the graph-autodiff train path
    (reference TrainingSession.java:74). ``listener``/``fused_steps``
    give the listener-path variant (see bench_lenet); ``sentinel`` arms
    the device-side divergence sentinel (docs/fault_tolerance.md);
    ``monitor_storage`` attaches a monitor.MonitorListener publishing
    steptime/metrics records into it; ``tensorstats`` (True or a
    TensorStatsConfig) arms the in-graph per-layer statistics
    (docs/observability.md)."""
    from deeplearning4j_tpu.autodiff import ScoreIterationListener

    rng = np.random.default_rng(0)
    sd = _build_mlp_sd(hidden=hidden, fused_steps=fused_steps,
                       sentinel=sentinel, tensorstats=tensorstats,
                       analyze=analyze, fingerprints=fingerprints)

    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    n = 2048
    X = rng.normal(size=(n, 784)).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    it = DeviceCachedIterator(X, Y, batch_size=batch)

    listeners = [ScoreIterationListener(print_every=10 ** 9,
                                        print_fn=lambda *a: None)] \
        if listener else []
    sd.fit(it, epochs=2, listeners=listeners)   # warmup/compile
    if monitor_storage is not None:
        # attached AFTER warmup so the steptime records describe
        # steady state — the one-time XLA compile happens inside the
        # warmup windows' dispatch spans and must not inflate the
        # published dispatch share
        from deeplearning4j_tpu.monitor import MonitorListener
        listeners = listeners + [MonitorListener(monitor_storage,
                                                 memory=monitor_memory)]
    epochs = 6
    sps = _median_rate(lambda: sd.fit(it, epochs=epochs,
                                      listeners=listeners), epochs * n)
    fwd_flops = 2 * (784 * hidden[0] + hidden[0] * hidden[1]
                     + hidden[1] * 10)
    return {"samples_per_sec": round(sps, 1),
            "step_time_ms": round(1000.0 * batch / sps, 3),
            "mfu_est": round(3 * fwd_flops * sps / V5E_PEAK_FLOPS, 5),
            "batch": batch, **_dispatch_stats(sd), **_memory_stats()}


def bench_sentinel_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the divergence rail (faults/, docs/fault_tolerance.md):
    the fused-window listener config with the device sentinel off vs on.
    The sentinel adds one finiteness reduction per step inside the scan
    and one int32 per window — the acceptance bar is ≤5% steps/s.

    Run-to-run jitter on a tunneled chip easily exceeds the effect
    size, so each flag is measured ``repeats`` times interleaved and
    the best rate per flag is compared (the min-overhead estimator for
    a one-sided cost)."""
    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            r = bench_samediff_mlp(batch=batch, listener=True,
                                   fused_steps=fused_steps, sentinel=flag)
            best[flag] = max(best[flag], r["samples_per_sec"])
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    return {"samples_per_sec": best[True],
            "samples_per_sec_sentinel_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "sentinel_overhead_pct": round(overhead, 2),
            "batch": batch, "fused_steps": fused_steps}


def bench_tensorstats_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the in-graph tensor-statistics rail (monitor/
    tensorstats.py, docs/observability.md): the fused-window listener
    config with per-layer grad/update/param summaries off vs on at the
    default sampling cadence. The stats compute under a lax.cond only
    on sampled steps (1-in-every_n), plus two small extra carry
    outputs per window and their share of the flush's device_get —
    the acceptance bar is ≤3% steps/s. Same best-of-``repeats``
    interleaved estimator as sentinel_overhead (run-to-run tunnel
    jitter exceeds the effect size)."""
    from deeplearning4j_tpu.monitor import TensorStatsConfig

    cfg = TensorStatsConfig()          # the default cadence under test
    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            r = bench_samediff_mlp(batch=batch, listener=True,
                                   fused_steps=fused_steps,
                                   tensorstats=cfg if flag else None)
            best[flag] = max(best[flag], r["samples_per_sec"])
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    return {"samples_per_sec": best[True],
            "samples_per_sec_tensorstats_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "tensorstats_overhead_pct": round(overhead, 2),
            "every_n": cfg.every_n, "families": list(cfg.families),
            "batch": batch, "fused_steps": fused_steps}


def bench_integrity_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the integrity rail (integrity/, docs/fault_tolerance.md
    "Non-raising failures"): the fused-window K=8 listener config with
    state fingerprints + an armed StallWatchdog on vs both off. The
    fingerprint adds ONE uint32 word-sum of params/optimizer state per
    window (computed once on the post-scan carry) and its share of the
    flush's device_get; the watchdog adds one guard (a deadline
    register/unregister under a lock) around every dispatch and flush.
    Replay probes / replica checks are cadence knobs benchmarked as
    off (their cost is 1/N redispatches by construction). Acceptance
    bar ≤2% steps/s; same best-of-``repeats`` interleaved estimator as
    sentinel_overhead (tunnel jitter exceeds the effect size)."""
    from deeplearning4j_tpu.integrity import StallWatchdog

    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            if flag:
                wd = StallWatchdog(k=8.0, floor_s=5.0, grace_s=120.0)
                with wd:
                    r = bench_samediff_mlp(batch=batch, listener=True,
                                           fused_steps=fused_steps,
                                           fingerprints=True)
            else:
                r = bench_samediff_mlp(batch=batch, listener=True,
                                       fused_steps=fused_steps)
            best[flag] = max(best[flag], r["samples_per_sec"])
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    return {"samples_per_sec": best[True],
            "samples_per_sec_integrity_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "integrity_overhead_pct": round(overhead, 2),
            "batch": batch, "fused_steps": fused_steps}


def bench_analyze_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the pre-compile static analyzer (analyze/,
    docs/static_analysis.md) on the warm dispatch path: the
    fused-window listener config with TrainingConfig.analyze on vs
    off. The analysis runs ONCE per graph version, before the first
    compile — warm fits pay a cache-key dict lookup — so the bar is
    ~0% (noise). The one-time analysis wall cost is reported
    separately (analysis_seconds). Same best-of-``repeats``
    interleaved estimator as the other rail probes."""
    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            r = bench_samediff_mlp(batch=batch, listener=True,
                                   fused_steps=fused_steps,
                                   analyze=flag)
            best[flag] = max(best[flag], r["samples_per_sec"])
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    # the one-time pre-compile cost, measured directly
    from deeplearning4j_tpu.analyze import analyze_training
    sd = _build_mlp_sd(fused_steps=fused_steps)
    rep = analyze_training(sd, has_listeners=True)
    return {"samples_per_sec": best[True],
            "samples_per_sec_analyze_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "analyze_overhead_pct": round(overhead, 2),
            "analysis_seconds": round(rep.seconds, 4),
            "rules_run": rep.rules_run,
            "findings": sum(rep.counts().values()),
            "batch": batch, "fused_steps": fused_steps}


def bench_memory_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the HBM telemetry rail (monitor/memstats.py,
    docs/observability.md "Memory observability"): the fused-window
    K=8 listener path with a MonitorListener whose memory telemetry
    (per-flush {"type": "memory"} records + plan capture + the MFU
    gauge) is on vs off. The on-path additions are pure host work at
    flush boundaries the host already syncs on — one PJRT counter read
    per device (or a live-array walk on CPU), a dict of tagged totals,
    and one registry gauge set — the acceptance bar is ≤2% steps/s.
    Same best-of-``repeats`` interleaved estimator as
    sentinel_overhead (run-to-run tunnel jitter exceeds the effect
    size). Clean runs are bit-identical on vs off
    (tests/test_memory_obs.py)."""
    from deeplearning4j_tpu.monitor import memstats
    from deeplearning4j_tpu.ui.stats import StatsStorage

    # the capture switch is process-global (main() arms it for the
    # whole run; MonitorListener arms it too): the off leg must really
    # run without it, and the ENTRY state must be restored afterwards —
    # leaving it off would strip plan capture (and misattribute stale
    # plans) from every config that runs after this one
    was_enabled = memstats.plan_capture_enabled()
    best = {False: 0.0, True: 0.0}
    try:
        for _ in range(repeats):
            for flag in (False, True):
                if flag:
                    memstats.enable_plan_capture()
                else:
                    memstats.disable_plan_capture()
                r = bench_samediff_mlp(batch=batch, listener=True,
                                       fused_steps=fused_steps,
                                       monitor_storage=StatsStorage(),
                                       monitor_memory=flag)
                best[flag] = max(best[flag], r["samples_per_sec"])
    finally:
        if was_enabled:
            memstats.enable_plan_capture()
        else:
            memstats.disable_plan_capture()
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    return {"samples_per_sec": best[True],
            "samples_per_sec_memory_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "memory_overhead_pct": round(overhead, 2),
            "batch": batch, "fused_steps": fused_steps,
            **_memory_stats()}


def bench_tracer_overhead(batch=128, fused_steps=8, repeats=2):
    """Cost of the observability rail (monitor/, docs/observability.md):
    the fused-window listener config with span tracing off vs on. The
    disabled path adds one no-op attribute check per span site (bar:
    unmeasurable, guarded ≤1% analytically in tests/test_monitor.py);
    enabled tracing adds two clock reads + a locked ring append per
    span, ~5 spans per K-step window — the acceptance bar is ≤3%
    steps/s. Same best-of-``repeats`` interleaved estimator as
    sentinel_overhead (run-to-run tunnel jitter exceeds the effect
    size).

    Also reports the measured step-time breakdown — the aggregate of
    the monitored run's {"type": "steptime"} records: where the wall
    time of a fused listener-path step actually goes (data-wait vs
    dispatch vs flush), the number BENCH_r05 had to hand-derive."""
    from deeplearning4j_tpu.monitor import disable_tracing, enable_tracing
    from deeplearning4j_tpu.ui.stats import StatsStorage

    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            if flag:
                enable_tracing(reset=True)
            else:
                disable_tracing()
            try:
                r = bench_samediff_mlp(batch=batch, listener=True,
                                       fused_steps=fused_steps)
            finally:
                disable_tracing()
            best[flag] = max(best[flag], r["samples_per_sec"])
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    # one monitored (traced + MonitorListener) run for the breakdown —
    # not part of the timed comparison
    storage = StatsStorage()
    enable_tracing(reset=True)
    try:
        bench_samediff_mlp(batch=batch, listener=True,
                           fused_steps=fused_steps,
                           monitor_storage=storage)
    finally:
        disable_tracing()
    recs = [r for r in storage.of_type("steptime")
            if r.get("event") != "straggler"]
    wall = sum(r.get("wall_s", 0.0) for r in recs) or 1.0
    breakdown = {f"{stage}_pct": round(
        100.0 * sum(r.get(f"{stage}_s", 0.0) for r in recs) / wall, 2)
        for stage in ("data_wait", "dispatch", "flush", "other")}
    breakdown["step_ms_p50"] = recs[-1].get("step_ms_p50") if recs else None
    breakdown["steps"] = sum(r.get("steps", 0) for r in recs)
    return {"samples_per_sec": best[True],
            "samples_per_sec_tracing_off": best[False],
            "step_time_ms": round(1000.0 * batch / best[True], 3)
            if best[True] else 0.0,
            "tracer_overhead_pct": round(overhead, 2),
            "steptime_breakdown": breakdown,
            "batch": batch, "fused_steps": fused_steps}


def bench_serving_resilience_overhead(n_requests=768, concurrency=8,
                                      repeats=2):
    """Cost of the serving resilience rail (serving/resilience.py,
    docs/serving.md "Resilience"): closed-loop throughput through the
    BATCHED path with admission control + circuit breaker + supervised
    workers on vs off. The healthy-path additions are one breaker
    acquire per batch, one rolling-percentile insert per exec, one
    admission estimate per submit, and the per-request finite-output
    scan — the acceptance bar is ≤3% req/s. Same best-of-``repeats``
    interleaved estimator as sentinel_overhead (run-to-run jitter
    exceeds the effect size)."""
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                       MultiLayerNetwork,
                                       NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import (InferenceMode, LoadGenerator,
                                            ParallelInference)

    n_in = 64

    def build_server(flag):
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer(n_out=256, activation="tanh"))
                .layer(OutputLayer(n_out=10, loss_function="MCXENT"))
                .set_input_type(InputType.feed_forward(n_in))
                .build())
        net = MultiLayerNetwork(conf).init()
        return ParallelInference(net, mode=InferenceMode.BATCHED,
                                 workers=2, max_batch_size=32,
                                 max_delay_ms=1.0, max_queue_len=1024,
                                 resilience=flag)

    best = {False: 0.0, True: 0.0}
    for _ in range(repeats):
        for flag in (False, True):
            pi = build_server(flag)
            try:
                lg = LoadGenerator(
                    pi, lambda rng, i: rng.normal(size=(2, n_in))
                    .astype(np.float32), seed=3)
                lg.run_closed(n_requests=max(64, n_requests // 4),
                              concurrency=concurrency)   # warmup/compile
                res = lg.run_closed(n_requests=n_requests,
                                    concurrency=concurrency)
            finally:
                pi.shutdown()
            best[flag] = max(best[flag], res.throughput_rps)
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    return {"throughput_rps": round(best[True], 1),
            "throughput_rps_resilience_off": round(best[False], 1),
            "resilience_overhead_pct": round(overhead, 2),
            "n_requests": n_requests, "concurrency": concurrency}


def bench_generative(n_requests=32, max_slots=8, max_seq_len=160,
                     prompt_len=(2, 16), new_tokens=None,
                     concurrency=32, seed=11):
    """Continuous-batching generative serving (serving/generative.py,
    ROADMAP item 1, BENCH_r10): a seeded mixed prompt/output-length
    trace driven through a GPT decode server twice — ``admit=
    "continuous"`` (step-boundary admission into free KV slots) vs
    ``admit="static"`` (the wait-for-full-batch baseline, a new wave
    only when every slot is free). Same trace, same compiled programs;
    the acceptance bar is continuous ≥ 2x static tokens/sec on mixed
    lengths. Reports tokens/sec/chip, p50/p99 TTFT, p50 inter-token
    latency and slot occupancy, all from the shared
    ``GenerativeLoadGenerator`` driver."""
    from deeplearning4j_tpu.serving.generative import GenerativeServer
    from deeplearning4j_tpu.serving.loadgen import GenerativeLoadGenerator
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_generative_spec)

    # big enough that decode compute (not host scheduling) dominates
    # the CPU smoke wall clock; on-chip the step ratio is the binding
    # quantity and it runs 2.5-3x (decode_steps in the sub-dicts)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                    num_heads=8, intermediate_size=512,
                    max_seq_len=max_seq_len)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    spec = gpt_generative_spec(sd, cfg)
    if new_tokens is None:
        # long-tailed output lengths (the distribution continuous
        # batching exists for): mostly short answers, a 20% tail of
        # long generations that would hold a static batch hostage
        def new_tokens(rng):
            return int(rng.integers(2, 9)) if rng.random() < 0.8 \
                else int(rng.integers(80, 129))
    out = {}
    for mode in ("continuous", "static"):
        srv = GenerativeServer(spec, max_slots=max_slots,
                               max_seq_len=max_seq_len, admit=mode,
                               warmup=True)
        try:
            lg = GenerativeLoadGenerator(srv, seed=seed,
                                         prompt_len=prompt_len,
                                         new_tokens=new_tokens)
            res = lg.run_closed(n_requests=n_requests,
                                concurrency=concurrency)
        finally:
            srv.shutdown()
        rec = srv.metrics.to_record()
        out[mode] = {
            "tokens_per_sec": round(res.tokens_per_sec, 1),
            "ttft_p50_ms": round(res.ttft_percentile(50), 3),
            "ttft_p99_ms": round(res.ttft_percentile(99), 3),
            "intertoken_p50_ms": round(res.intertoken_percentile(50), 3),
            "slot_occupancy": rec["generative"]["slot_occupancy"],
            "decode_steps": rec["generative"]["decode_steps"],
            "n_ok": res.n_ok,
            "compiles": rec["counters"]["compiles"],
            "warmup_compiles": rec["counters"]["warmup_compiles"]}
    cont, stat = out["continuous"], out["static"]
    speedup = cont["tokens_per_sec"] / stat["tokens_per_sec"] \
        if stat["tokens_per_sec"] else 0.0
    return {"samples_per_sec": cont["tokens_per_sec"],   # tokens/sec/chip
            "tokens_per_sec": cont["tokens_per_sec"],
            "ttft_p50_ms": cont["ttft_p50_ms"],
            "ttft_p99_ms": cont["ttft_p99_ms"],
            "intertoken_p50_ms": cont["intertoken_p50_ms"],
            "slot_occupancy": cont["slot_occupancy"],
            "static_tokens_per_sec": stat["tokens_per_sec"],
            "static_slot_occupancy": stat["slot_occupancy"],
            "continuous_vs_static_speedup": round(speedup, 2),
            "max_slots": max_slots, "n_requests": n_requests,
            "continuous": cont, "static": stat}


def bench_serving_paged(n_requests=32, dense_slots=4, max_seq_len=256,
                        block_size=16, prompt_len=(2, 16),
                        concurrency=16, seed=13):
    """Paged KV vs dense slabs at EQUAL HBM (serving/paged/, ISSUE 16).

    The dense server preallocates ``max_seq`` KV rows per slot, so its
    concurrent capacity at a fixed HBM budget is ``budget /
    (max_seq_row_bytes)`` regardless of how short requests actually
    are. The paged server spends the SAME budget as a block pool and
    reserves each request's own worst case, so a mixed-length trace
    (mostly short chats, a 20% long tail) fits several times the
    concurrent requests — the acceptance bar is >= 4x. Also records
    the prefix-caching TTFT win (a repeated prompt prefills only its
    suffix: hit TTFT ~ one decode step, vs the cold full-prompt
    prefill) and the tp=2 greedy bit-identity bit."""
    import jax

    from deeplearning4j_tpu.serving.generative import (GenerativeServer,
                                                       greedy_decode)
    from deeplearning4j_tpu.serving.loadgen import GenerativeLoadGenerator
    from deeplearning4j_tpu.serving.paged import (PagedGenerativeServer,
                                                  blocks_for_tokens)
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_generative_spec,
                                            gpt_paged_spec)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                    num_heads=8, intermediate_size=512,
                    max_seq_len=max_seq_len)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    dense_spec = gpt_generative_spec(sd, cfg)
    paged_spec = gpt_paged_spec(sd, cfg)
    # the shared budget: what the SMALL dense deployment preallocates
    dense_bytes = 2 * int(np.prod(
        dense_spec.kv_shape(dense_slots, max_seq_len))) * 4

    def new_tokens(rng):
        # mostly short answers, a 20% long tail (same shape as the
        # continuous-batching bench, scaled into this max_seq)
        return int(rng.integers(2, 9)) if rng.random() < 0.8 \
            else int(rng.integers(64, 97))

    # -- concurrent capacity at equal HBM (worst-case commitment) ------
    rng = np.random.default_rng(seed)
    trace = [(int(rng.integers(prompt_len[0], prompt_len[1] + 1)),
              new_tokens(rng)) for _ in range(max(n_requests, 64))]
    bytes_per_block = 2 * int(np.prod(
        paged_spec.kv_shape(1, block_size))) * 4
    pool_capacity = dense_bytes // bytes_per_block - 1   # null block
    committed = admitted = 0
    for p, n in trace:
        need = blocks_for_tokens(min(p + n, max_seq_len), block_size)
        if committed + need > pool_capacity:
            break
        committed += need
        admitted += 1
    capacity_ratio = admitted / dense_slots if dense_slots else 0.0

    # -- same trace through both servers at the same HBM budget --------
    out = {}
    servers = {
        "dense": lambda: GenerativeServer(
            dense_spec, max_slots=dense_slots, max_seq_len=max_seq_len,
            warmup=True),
        "paged": lambda: PagedGenerativeServer(
            paged_spec, max_slots=concurrency, max_seq_len=max_seq_len,
            block_size=block_size, kv_hbm_bytes=dense_bytes,
            warmup=True)}
    for name, build in servers.items():
        srv = build()
        try:
            lg = GenerativeLoadGenerator(srv, seed=seed,
                                         prompt_len=prompt_len,
                                         new_tokens=new_tokens)
            res = lg.run_closed(n_requests=n_requests,
                                concurrency=concurrency)
        finally:
            srv.shutdown()
        rec = srv.metrics.to_record()
        out[name] = {
            "tokens_per_sec": round(res.tokens_per_sec, 1),
            "ttft_p50_ms": round(res.ttft_percentile(50), 3),
            "n_ok": res.n_ok, "n_rejected": res.n_rejected,
            "kv_bytes": srv.kv_slab_bytes,
            "compiles": rec["counters"]["compiles"]}
        if name == "paged":
            out[name]["pool_occupancy"] = rec["paged"]["pool_occupancy"]
            out[name]["blocks_per_request"] = \
                rec["paged"]["blocks_per_request"]

    # -- prefix-hit TTFT: repeat prompt prefills only its suffix -------
    prompt = (np.arange(64, dtype=np.int32) * 5) % cfg.vocab_size
    srv = PagedGenerativeServer(paged_spec, max_slots=4,
                                max_seq_len=max_seq_len,
                                block_size=block_size,
                                kv_hbm_bytes=dense_bytes, warmup=True)
    try:
        def ttft(h):
            t0 = time.perf_counter()
            next(iter(h.tokens(timeout=60)))
            dt = (time.perf_counter() - t0) * 1000.0
            h.result(timeout=60)
            return dt
        ttft_cold = ttft(srv.submit(prompt, max_new_tokens=8))
        ttft_hit = ttft(srv.submit(prompt, max_new_tokens=8))
        step_p50 = srv.metrics.exec_ms.summary()["p50"]
        hit_rate = srv.metrics.to_record()["paged"]["prefix_hit_rate"]
    finally:
        srv.shutdown()

    # -- tp=2 greedy bit-identity (the mesh exists on 2+ devices) ------
    tp_match = None
    if len(jax.devices()) >= 2:
        tp_srv = PagedGenerativeServer(paged_spec, max_slots=4,
                                       max_seq_len=max_seq_len,
                                       block_size=block_size,
                                       kv_hbm_bytes=dense_bytes,
                                       tp=2, warmup=True)
        try:
            probes = [(np.arange(L, dtype=np.int32) * 3) % cfg.vocab_size
                      for L in (3, 17, 40)]
            got = [tp_srv.submit(p, max_new_tokens=8).result(timeout=120)
                   for p in probes]
        finally:
            tp_srv.shutdown()
        tp_match = got == [greedy_decode(dense_spec, p, 8,
                                         max_seq_len=max_seq_len)
                           for p in probes]

    return {"samples_per_sec": out["paged"]["tokens_per_sec"],
            "tokens_per_sec": out["paged"]["tokens_per_sec"],
            "dense_tokens_per_sec": out["dense"]["tokens_per_sec"],
            "kv_budget_bytes": dense_bytes,
            "dense_concurrent_capacity": dense_slots,
            "paged_concurrent_capacity": admitted,
            "capacity_ratio_equal_hbm": round(capacity_ratio, 2),
            "pool_blocks": pool_capacity,
            "block_size": block_size,
            "ttft_cold_ms": round(ttft_cold, 3),
            "ttft_prefix_hit_ms": round(ttft_hit, 3),
            "decode_step_p50_ms": round(step_p50, 3),
            "ttft_hit_vs_step": round(ttft_hit / step_p50, 2)
            if step_p50 else None,
            "prefix_hit_rate": hit_rate,
            "tp2_greedy_match": tp_match,
            "n_requests": n_requests,
            "dense": out["dense"], "paged": out["paged"]}


def bench_serving_speculative(n_requests=24, max_slots=4, max_seq_len=256,
                              speculate_k=8, n_draft_layers=1,
                              prompt_len=(2, 16), concurrency=8, seed=19):
    """Speculative decoding vs plain decode on one seeded skewed trace
    (serving/generative.py ``draft_spec=``, ISSUE 18).

    Self-speculative pairing: the target's DEEP layers get their
    residual-out projections (``attn/proj``, ``mlp/proj``) zeroed, so
    those blocks are identity on the residual stream and the
    ``n_draft_layers``-deep draft computes the target's exact logits.
    Acceptance then sits at ~1.0, measuring the mechanism's ceiling —
    every drafted token rides the ONE batched verify dispatch — rather
    than any particular draft model's quality; the acceptance bar is
    speculative >= 1.5x plain tokens/sec on the mixed-length trace.
    The geometry matters: with a 1-of-8-layers draft and K=8, a round
    costs ~2 target-step-equivalents (8 cheap drafts + one verify,
    whose window rides the weight bytes one decode step already moves)
    and lands ~K tokens — the plain path pays K full steps. Also
    records the temp-0 bit-identity bit (speculation must emit EXACTLY
    the non-speculative greedy tokens) and both servers'
    traffic-compile counts (0 after warmup)."""
    import dataclasses as _dc

    from deeplearning4j_tpu.serving.generative import (GenerativeServer,
                                                       greedy_decode)
    from deeplearning4j_tpu.serving.loadgen import GenerativeLoadGenerator
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_generative_spec)

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=8,
                    num_heads=8, intermediate_size=512,
                    max_seq_len=max_seq_len)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    for i in range(int(n_draft_layers), cfg.num_layers):
        for part in ("attn/proj", "mlp/proj"):
            for leaf in ("kernel", "bias"):
                n = f"h{i}/{part}/{leaf}"
                sd._arrays[n] = np.zeros_like(np.asarray(sd._arrays[n]))
    spec = gpt_generative_spec(sd, cfg)
    draft = gpt_generative_spec(
        sd, _dc.replace(cfg, num_layers=int(n_draft_layers)))

    def new_tokens(rng):
        # the skewed trace continuous batching + speculation both live
        # for: mostly short answers, a 20% tail of long generations
        return int(rng.integers(2, 9)) if rng.random() < 0.8 \
            else int(rng.integers(80, 129))

    out = {}
    builds = {
        "plain": lambda: GenerativeServer(
            spec, max_slots=max_slots, max_seq_len=max_seq_len,
            warmup=True),
        "speculative": lambda: GenerativeServer(
            spec, max_slots=max_slots, max_seq_len=max_seq_len,
            draft_spec=draft, speculate_k=speculate_k, warmup=True)}
    for name, build in builds.items():
        srv = build()
        try:
            lg = GenerativeLoadGenerator(srv, seed=seed,
                                         prompt_len=prompt_len,
                                         new_tokens=new_tokens)
            res = lg.run_closed(n_requests=n_requests,
                                concurrency=concurrency)
        finally:
            srv.shutdown()
        rec = srv.metrics.to_record()
        gen = rec["generative"]
        out[name] = {
            "tokens_per_sec": round(res.tokens_per_sec, 1),
            "intertoken_p50_ms": round(res.intertoken_percentile(50), 3),
            "decode_steps": gen["decode_steps"],
            "n_ok": res.n_ok,
            "compiles": rec["counters"]["compiles"],
            "warmup_compiles": rec["counters"]["warmup_compiles"]}
        if name == "speculative":
            out[name]["acceptance_rate"] = gen["draft_acceptance_rate"]
            out[name]["spec_rounds"] = gen["spec_rounds"]
            out[name]["draft_rejected"] = gen["draft_rejected"]

    # temp-0 bit-identity: the acceptance criterion of the change
    probes = [(np.arange(L, dtype=np.int32) * 7) % cfg.vocab_size
              for L in (3, 11, 29)]
    srv = GenerativeServer(spec, max_slots=2, max_seq_len=max_seq_len,
                           draft_spec=draft, speculate_k=speculate_k,
                           warmup=True)
    try:
        got = [srv.submit(p, max_new_tokens=12).result(timeout=120)
               for p in probes]
    finally:
        srv.shutdown()
    greedy_match = got == [greedy_decode(spec, p, 12,
                                         max_seq_len=max_seq_len)
                           for p in probes]

    speedup = (out["speculative"]["tokens_per_sec"]
               / out["plain"]["tokens_per_sec"]) \
        if out["plain"]["tokens_per_sec"] else 0.0
    return {"samples_per_sec": out["speculative"]["tokens_per_sec"],
            "tokens_per_sec": out["speculative"]["tokens_per_sec"],
            "plain_tokens_per_sec": out["plain"]["tokens_per_sec"],
            "speculative_speedup": round(speedup, 2),
            "acceptance_rate": out["speculative"]["acceptance_rate"],
            "speculate_k": speculate_k,
            "draft_layers": int(n_draft_layers),
            "greedy_bit_identical": greedy_match,
            "n_requests": n_requests,
            "plain": out["plain"], "speculative": out["speculative"]}


def bench_serving_quant(n_requests=24, max_slots=8, max_seq_len=256,
                        block_size=16, prompt_len=(2, 16),
                        concurrency=8, seed=23):
    """int8 weight + KV quantization at equal slab bytes (zoo/gpt.py
    ``quantize_weights``/``quantize_kv``, ISSUE 18).

    The paged pool is sized in BYTES, and with ISSUE 18 the server
    derives bytes-per-block from the spec's ``kv_dtype`` itemsize —
    int8 KV quarters the bytes per block, so the SAME ``kv_hbm_bytes``
    budget holds ~4x the f32 token capacity (acceptance bar >= 1.9x,
    read from the live servers' pool sizes, not arithmetic). Also
    reports f32-vs-int8 decode throughput on one seeded trace and the
    greedy-token agreement between the two servers on probe prompts
    (quantization is lossy; the delta is published, not gated)."""
    from deeplearning4j_tpu.serving.loadgen import GenerativeLoadGenerator
    from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_paged_spec)

    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                    num_heads=8, intermediate_size=512,
                    max_seq_len=max_seq_len)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    specs = {"f32": gpt_paged_spec(sd, cfg),
             "int8": gpt_paged_spec(sd, cfg, quantize_weights=True,
                                    quantize_kv=True)}
    # one fixed byte budget for both servers: 49 f32 blocks' worth
    # (48 usable + the null block), so the int8 pool's size shows the
    # dtype-aware sizing rather than a bigger grant
    f32_block_bytes = 2 * int(np.prod(
        specs["f32"].kv_shape(1, block_size))) * 4
    kv_budget = 49 * f32_block_bytes

    out = {}
    toks = {}
    probes = [(np.arange(L, dtype=np.int32) * 7) % cfg.vocab_size
              for L in (3, 11, 29)]
    for name, spec in specs.items():
        srv = PagedGenerativeServer(spec, max_slots=max_slots,
                                    max_seq_len=max_seq_len,
                                    block_size=block_size,
                                    kv_hbm_bytes=kv_budget, warmup=True)
        try:
            toks[name] = [srv.submit(p, max_new_tokens=10)
                          .result(timeout=120) for p in probes]
            lg = GenerativeLoadGenerator(srv, seed=seed,
                                         prompt_len=prompt_len,
                                         new_tokens=(4, 24))
            res = lg.run_closed(n_requests=n_requests,
                                concurrency=concurrency)
        finally:
            srv.shutdown()
        rec = srv.metrics.to_record()
        out[name] = {
            "tokens_per_sec": round(res.tokens_per_sec, 1),
            "pool_blocks": rec["paged"]["num_blocks"],
            "token_capacity": rec["paged"]["num_blocks"] * block_size,
            "kv_bytes": srv.kv_slab_bytes,
            "n_ok": res.n_ok,
            "compiles": rec["counters"]["compiles"]}
    agree = float(np.mean([a == b
                           for s8, s32 in zip(toks["int8"], toks["f32"])
                           for a, b in zip(s8, s32)]))
    ratio = (out["int8"]["token_capacity"] / out["f32"]["token_capacity"]
             if out["f32"]["token_capacity"] else 0.0)
    return {"samples_per_sec": out["int8"]["tokens_per_sec"],
            "tokens_per_sec": out["int8"]["tokens_per_sec"],
            "f32_tokens_per_sec": out["f32"]["tokens_per_sec"],
            "kv_budget_bytes": kv_budget,
            "token_capacity_ratio_equal_bytes": round(ratio, 2),
            "greedy_token_agreement": round(agree, 4),
            "block_size": block_size,
            "n_requests": n_requests,
            "f32": out["f32"], "int8": out["int8"]}


def bench_serving_fleet(n_replicas=3, n_requests=48, rate_rps=40.0,
                        ttft_slo_ms=2000.0, block_size=8, seed=17):
    """Fleet chaos drill + affinity win (serving/fleet/, ISSUE 17).

    One open-loop repeated-prefix trace against a 3-replica fleet
    while (a) a replica is KILLED mid-traffic (no drain) and (b) a
    rolling canaried deploy reloads the survivors — the acceptance bar
    is ZERO failed healthy requests and fleet p99 TTFT inside the SLO
    through both events. Then the affinity column: the SAME trace
    routed with prefix affinity vs uniformly at random, scored on the
    replicas' actual prefix-cache hit rate — affinity must beat
    random (it concentrates each shared prefix on its rendezvous home,
    so the cache warms once instead of once per replica)."""
    import threading
    from types import SimpleNamespace

    from deeplearning4j_tpu.serving.fleet import (FleetReplica,
                                                  FleetRouter,
                                                  RollingDeploy)
    from deeplearning4j_tpu.serving.loadgen import FleetLoadGenerator
    from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_paged_spec)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, max_seq_len=64)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    spec = gpt_paged_spec(sd, cfg)     # shared -> one compile set

    def replica(name, warm=False):
        return FleetReplica(name, server=PagedGenerativeServer(
            spec, max_slots=4, block_size=block_size, max_seq_len=64,
            warmup=warm))

    pool = [(np.arange(block_size, dtype=np.int32) * k + k)
            % cfg.vocab_size for k in (1, 3)]

    def loadgen(front_door, gen_seed):
        return FleetLoadGenerator(front_door,
                                  vocab_size=cfg.vocab_size,
                                  seed=gen_seed, prompt_len=(1, 8),
                                  new_tokens=(2, 8), prefix_pool=pool,
                                  prefix_p=0.75)

    # -- the drill: kill + rolling reload under open-loop load ---------
    replicas = [replica(f"r{i}", warm=(i == 0))
                for i in range(n_replicas)]
    router = FleetRouter(replicas, retry_budget=4,
                         poll_interval_s=0.05)
    deploy_report = {}

    def mid_run():
        replicas[-1].kill()            # no drain: the chaos kill
        deploy_report.update(RollingDeploy(
            router, probes=[(np.arange(6, dtype=np.int32), 4, None)],
            drain_timeout_s=60.0).run(canary="r0"))
    chaos = threading.Timer(0.3, mid_run)
    chaos.start()
    res = loadgen(router.generate, seed).run_open(
        n_requests=n_requests, rate_rps=rate_rps)
    chaos.join()
    rec = router.metrics.to_record()
    for r in replicas:
        if r.alive:
            r.stop(drain=True)

    # -- affinity vs random placement, scored on REAL prefix hits ------
    def prefix_hit_rate(route_random):
        reps = [replica(f"h{i}") for i in range(n_replicas)]
        rt = FleetRouter(reps, poll_interval_s=0.05)
        rng = np.random.default_rng(seed + 1)

        def random_door(prompt, max_new_tokens=16, timeout_ms=None):
            rep = reps[int(rng.integers(len(reps)))]
            h = rep.submit(prompt, max_new_tokens=max_new_tokens,
                           timeout_ms=timeout_ms)
            return SimpleNamespace(tokens=h.result(), replica=rep.name,
                                   retries=0, routed="random",
                                   ttft_ms=None, intertoken_ms=[])
        door = random_door if route_random else rt.generate
        r = loadgen(door, seed + 2).run_open(n_requests=32,
                                             rate_rps=rate_rps)
        hits = sum(rep.server.metrics.counters["prefix_hits"]
                   for rep in reps)
        lookups = sum(rep.server.metrics.counters["prefix_lookups"]
                      for rep in reps)
        for rep in reps:
            rep.stop(drain=True)
        return (hits / lookups if lookups else 0.0), r.n_failed
    affinity_rate, aff_failed = prefix_hit_rate(route_random=False)
    random_rate, rnd_failed = prefix_hit_rate(route_random=True)

    ttft_p99 = res.ttft_percentile(99)
    return {"samples_per_sec": round(res.tokens_per_sec, 1),
            "tokens_per_sec": round(res.tokens_per_sec, 1),
            "n_replicas": n_replicas,
            "n_requests": n_requests,
            "rate_rps": rate_rps,
            "n_ok": res.n_ok,
            # the acceptance bar: nothing healthy failed through a
            # kill AND a rolling reload
            "n_failed_through_chaos": res.n_failed + aff_failed
            + rnd_failed,
            "retries_absorbed": res.retries_total,
            "deploy_ok": bool(deploy_report.get("ok")),
            "deploy_rolled": deploy_report.get("rolled"),
            "ttft_p50_ms": round(res.ttft_percentile(50), 3),
            "ttft_p99_ms": round(ttft_p99, 3),
            "ttft_slo_ms": ttft_slo_ms,
            "ttft_p99_within_slo": bool(ttft_p99 <= ttft_slo_ms),
            "affinity_prefix_hit_rate": round(affinity_rate, 4),
            "random_prefix_hit_rate": round(random_rate, 4),
            "affinity_beats_random": bool(affinity_rate > random_rate),
            "replica_deaths_seen":
                rec["counters"]["replica_deaths_seen"],
            "fleet_affinity_hit_rate":
                rec["fleet"]["affinity_hit_rate"]}


def bench_serving_durability(n_requests=24, rate_rps=60.0, block_size=8,
                             kill_after=5, seed=23):
    """Durable generative requests drill (serving/fleet/durable.py,
    ISSUE 19) for BENCH_r14.

    Three legs. (1) Mid-stream kill: a replica is killed after
    ``kill_after`` streamed tokens and the router resumes the request
    on a survivor from the emitted prefix — the bar is tokens_salvaged
    > 0, an exactly-once stream (the streamed sequence IS the final
    result, zero dedup drops), and final output bit-identical to an
    uninterrupted run, greedy AND seeded-sampled. (2) Router
    kill-and-restart: with a write-ahead journal armed and a zero
    retry budget the same kill strands the request; a fresh router
    replays the journal and must finish it bit-identically, exactly
    once. (3) The journal's price: open-loop throughput with the
    fsync'd journal armed vs without."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.faults.chaos import ChaosMonkey
    from deeplearning4j_tpu.serving.fleet import (FleetReplica,
                                                  FleetRouter,
                                                  FleetUnavailableError,
                                                  RequestJournal)
    from deeplearning4j_tpu.serving.loadgen import FleetLoadGenerator
    from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_paged_spec)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, max_seq_len=64)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    spec = gpt_paged_spec(sd, cfg)     # shared -> one compile set

    def replica(name):
        return FleetReplica(name, server=PagedGenerativeServer(
            spec, max_slots=4, block_size=block_size, max_seq_len=64,
            warmup=False))

    prompt = [3, 1, 4, 1, 5]
    n_new = 24

    def baseline(**kw):
        rep = replica("base")
        try:
            return rep.submit(prompt, max_new_tokens=n_new,
                              **kw).result(timeout=120)
        finally:
            rep.stop(drain=False)

    # -- leg 1: kill a replica mid-stream, greedy and sampled ----------
    def kill_drill(**kw):
        reps = [replica(f"r{i}") for i in range(2)]
        router = FleetRouter(reps, retry_budget=3, affinity=False,
                             poll_interval_s=0.0)
        ChaosMonkey(seed=seed).kill_mid_stream(reps[0],
                                               after_tokens=kill_after)
        streamed = []
        try:
            res = router.generate(prompt, max_new_tokens=n_new,
                                  on_token=streamed.append, **kw)
        finally:
            for r in reps:
                if r.alive:
                    r.stop(drain=False)
        return {"tokens": res.tokens, "streamed": streamed,
                "resumes": res.resumes,
                "tokens_salvaged": res.tokens_salvaged,
                "dedup_drops":
                    router.durability.counters["dedup_drops"]}
    greedy = kill_drill()
    sampled_kw = dict(temperature=0.8, top_k=16, seed=seed)
    sampled = kill_drill(**sampled_kw)
    greedy_identical = greedy["tokens"] == baseline()
    sampled_identical = sampled["tokens"] == baseline(**sampled_kw)
    exactly_once = (greedy["streamed"] == greedy["tokens"]
                    and sampled["streamed"] == sampled["tokens"]
                    and greedy["dedup_drops"] == 0
                    and sampled["dedup_drops"] == 0)

    # -- leg 2: kill the only replica, restart the router, replay -----
    jdir = tempfile.mkdtemp(prefix="dl4j_durable_journal_")
    try:
        journal = RequestJournal(jdir, flush_every=2)
        r0 = replica("r0")
        router1 = FleetRouter([r0], retry_budget=0, affinity=False,
                              poll_interval_s=0.0, journal=journal)
        ChaosMonkey(seed=seed).kill_mid_stream(r0,
                                               after_tokens=kill_after)
        try:
            router1.generate(prompt, max_new_tokens=n_new)
            stranded = False
        except FleetUnavailableError:
            stranded = True
        finally:
            if r0.alive:
                r0.stop(drain=False)
        open_entries = journal.incomplete()
        r1 = replica("r1")
        router2 = FleetRouter([r1], affinity=False, poll_interval_s=0.0)
        try:
            recovered = router2.recover(journal)
            second_pass = router2.recover()
        finally:
            r1.stop(drain=False)
        replay_identical = (len(recovered) == 1
                            and next(iter(recovered.values())).tokens
                            == baseline())
        recovery = {
            "stranded_open_entries": len(open_entries),
            "journal_tokens_salvaged":
                router2.durability.counters["tokens_salvaged"],
            "replay_bit_identical": bool(stranded and replay_identical),
            "replay_exactly_once": bool(len(recovered) == 1
                                        and second_pass == {}
                                        and not journal.incomplete())}
        journal.close()

        # -- leg 3: the journal's price under open-loop load -----------
        def throughput(jn):
            reps = [replica(f"t{i}") for i in range(2)]
            rt = FleetRouter(reps, poll_interval_s=0.05, journal=jn)
            res = FleetLoadGenerator(
                rt.generate, vocab_size=cfg.vocab_size, seed=seed,
                prompt_len=(1, 8), new_tokens=(2, 8)).run_open(
                    n_requests=n_requests, rate_rps=rate_rps)
            for r in reps:
                r.stop(drain=True)
            return res
        throughput(None)               # discard: pays the bucket compiles
        bare = throughput(None)
        journal2 = RequestJournal(os.path.join(jdir, "load"))
        journaled = throughput(journal2)
        fsync_p99 = journal2.metrics.to_dict()["journal_fsync_ms"]["p99"]
        journal2.close()
    finally:
        shutil.rmtree(jdir, ignore_errors=True)

    overhead = (bare.tokens_per_sec / journaled.tokens_per_sec
                if journaled.tokens_per_sec else 0.0)
    return {"samples_per_sec": round(journaled.tokens_per_sec, 1),
            "tokens_per_sec": round(journaled.tokens_per_sec, 1),
            "bare_tokens_per_sec": round(bare.tokens_per_sec, 1),
            "journal_overhead_x": round(overhead, 3),
            "journal_fsync_p99_ms": round(fsync_p99, 3),
            "n_failed": bare.n_failed + journaled.n_failed,
            # the acceptance bars
            "tokens_salvaged": greedy["tokens_salvaged"]
            + sampled["tokens_salvaged"],
            "resumes": greedy["resumes"] + sampled["resumes"],
            "exactly_once_stream": bool(exactly_once),
            "greedy_bit_identical": bool(greedy_identical),
            "sampled_bit_identical": bool(sampled_identical),
            **recovery}


def bench_reqtrace_overhead(n_replicas=2, n_requests=32, concurrency=4,
                            repeats=2, block_size=8, seed=29):
    """Cost of the request-tracing + SLO rail (monitor/reqtrace.py,
    ISSUE 20) for BENCH_r15: the fleet loadgen closed loop with span
    tracing + per-request waterfall assembly + SLO tracking ON vs the
    whole rail OFF (tracer disabled, ``slo=False``/``reqtrace=False``
    router). Same best-of-``repeats`` interleaved estimator as
    tracer_overhead; the acceptance bar is ≤3% tokens/sec (the PR-5
    discipline — observability must never become the workload). Also
    records how many traces the run kept and the worst-TTFT waterfall's
    breakdown (where the slowest request's first token went)."""
    from deeplearning4j_tpu.monitor import disable_tracing, enable_tracing
    from deeplearning4j_tpu.serving.fleet import FleetReplica, FleetRouter
    from deeplearning4j_tpu.serving.loadgen import FleetLoadGenerator
    from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
    from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                            gpt_paged_spec)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, intermediate_size=128, max_seq_len=64)
    sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
    spec = gpt_paged_spec(sd, cfg)     # shared -> one compile set

    def run(traced):
        reps = [FleetReplica(f"t{i}", server=PagedGenerativeServer(
            spec, max_slots=4, block_size=block_size, max_seq_len=64,
            warmup=False)) for i in range(n_replicas)]
        if traced:
            enable_tracing(reset=True)
            rt = FleetRouter(reps, poll_interval_s=0.05,
                             trace_sample=1.0)
        else:
            disable_tracing()
            rt = FleetRouter(reps, poll_interval_s=0.05,
                             slo=False, reqtrace=False)
        try:
            res = FleetLoadGenerator(
                rt.generate, vocab_size=cfg.vocab_size, seed=seed,
                prompt_len=(1, 8), new_tokens=(2, 8)).run_closed(
                    n_requests=n_requests, concurrency=concurrency)
        finally:
            disable_tracing()
            for r in reps:
                r.stop(drain=True)
        return res, rt

    run(False)                         # discard: pays the bucket compiles
    best = {False: 0.0, True: 0.0}
    traced_router = None
    traced_res = None
    for _ in range(repeats):
        for flag in (False, True):
            res, rt = run(flag)
            if res.tokens_per_sec > best[flag]:
                best[flag] = res.tokens_per_sec
                if flag:
                    traced_router, traced_res = rt, res
    overhead = (best[False] - best[True]) / best[False] * 100.0 \
        if best[False] else 0.0
    kept = traced_router.reqtrace.summaries() if traced_router else []
    worst = None
    slo_sub = None
    if traced_router is not None and traced_router.slo is not None:
        slo_sub = traced_router.slo.to_dict()
        worst_list = slo_sub.get("worst_traces") or []
        if worst_list:
            worst = worst_list[0]
    return {"samples_per_sec": round(best[True], 1),
            "tokens_per_sec": round(best[True], 1),
            "tokens_per_sec_untraced": round(best[False], 1),
            "reqtrace_overhead_pct": round(overhead, 2),
            "n_requests": n_requests,
            "concurrency": concurrency,
            "sampled_traces_kept": len(kept),
            "worst_ttft_waterfall": worst,
            "slo_ttft_attainment": (slo_sub or {}).get(
                "objectives", {}).get("ttft_ms", {}).get("attainment"),
            "slo_attainment_loadgen_2s": round(
                traced_res.slo_attainment(2000.0), 4)
            if traced_res is not None else None}


def bench_disk_stream(batch=128, fused_steps=8, n=2048, shard_size=512,
                      worker_counts=(1, 2, 4)):
    """Disk-backed streaming training vs the device-cached window bench
    (datapipe/, docs/data_pipeline.md — the ROADMAP item-4 acceptance
    bar: within ~5% of cached). The BASELINE config-2 MLP trains
    through ``StreamingDataPipeline`` — sha256-verified shard reads +
    supervised parallel prefetch feeding the fused-window stager — at
    several prefetch-worker counts (the scaling column), against the
    same model fed from ``DeviceCachedIterator``. One monitored run
    reports the per-flush data-wait fraction (the number that says
    whether the prefetch actually hides the disk)."""
    import shutil
    import tempfile

    from deeplearning4j_tpu.autodiff import ScoreIterationListener
    from deeplearning4j_tpu.datapipe import (StreamingDataPipeline,
                                             write_dataset)
    from deeplearning4j_tpu.monitor import (MonitorListener,
                                            disable_tracing,
                                            enable_tracing)
    from deeplearning4j_tpu.ui.stats import StatsStorage

    cached = bench_samediff_mlp(batch=batch, listener=True,
                                fused_steps=fused_steps)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 784)).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    ds_dir = tempfile.mkdtemp(prefix="dl4j_disk_stream_")
    try:
        write_dataset(os.path.join(ds_dir, "ds"), X, Y,
                      shard_size=shard_size, overwrite=True)
        path = os.path.join(ds_dir, "ds")
        per_workers = {}
        epochs = 6
        for workers in worker_counts:
            sd = _build_mlp_sd(fused_steps=fused_steps)
            listeners = [ScoreIterationListener(print_every=10 ** 9,
                                                print_fn=lambda *a: None)]
            pipe = StreamingDataPipeline(path, batch_size=batch,
                                         shuffle=False,
                                         n_workers=workers)
            sd.fit(pipe, epochs=2, listeners=listeners)   # warmup
            sps = _median_rate(lambda: sd.fit(pipe, epochs=epochs,
                                              listeners=listeners),
                               epochs * n)
            per_workers[str(workers)] = round(sps, 1)
        best_workers, best = max(per_workers.items(),
                                 key=lambda kv: kv[1])
        # one monitored (traced) run at the best worker count for the
        # per-flush data-wait fraction — not part of the timing
        storage = StatsStorage()
        sd = _build_mlp_sd(fused_steps=fused_steps)
        pipe = StreamingDataPipeline(path, batch_size=batch,
                                     shuffle=False,
                                     n_workers=int(best_workers))
        listeners = [ScoreIterationListener(print_every=10 ** 9,
                                            print_fn=lambda *a: None)]
        sd.fit(pipe, epochs=2, listeners=listeners)       # warmup
        enable_tracing(reset=True)
        try:
            sd.fit(pipe, epochs=2,
                   listeners=listeners + [MonitorListener(storage)])
        finally:
            disable_tracing()
        waits = [r["data_wait_frac"] for r in storage.of_type("datapipe")
                 if r.get("data_wait_frac") is not None]
        cached_sps = cached.get("samples_per_sec", 0.0)
        gap = (cached_sps - best) / cached_sps * 100.0 if cached_sps \
            else 0.0
        return {"samples_per_sec": best,
                "samples_per_sec_cached": cached_sps,
                "disk_vs_cached_pct": round(gap, 2),
                "workers_best": int(best_workers),
                "samples_per_sec_by_workers": per_workers,
                "data_wait_frac_per_flush": [round(w, 4)
                                             for w in waits[-12:]],
                "data_wait_frac_mean": round(
                    float(np.mean(waits)), 4) if waits else None,
                "shards": (n + shard_size - 1) // shard_size,
                "shard_size": shard_size, "batch": batch,
                "fused_steps": fused_steps}
    finally:
        shutil.rmtree(ds_dir, ignore_errors=True)


def bench_resnet50(batch=128, steps=32, image=224, mixed_precision=True):
    """BASELINE config 3: zoo ResNet-50 training step, ImageNet shapes,
    bf16 mixed precision (f32 master params) at MXU-saturating batch."""
    from deeplearning4j_tpu.autodiff import MixedPrecision
    from deeplearning4j_tpu.nn import ComputationGraph
    from deeplearning4j_tpu.zoo import ResNet50

    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    rng = np.random.default_rng(0)
    conf = ResNet50(height=image, width=image, channels=3,
                    num_classes=1000).conf()
    if mixed_precision:
        conf.mixed_precision = MixedPrecision()
    net = ComputationGraph(conf).init()
    n = batch * steps
    X = rng.normal(size=(n, 3, image, image)).astype(np.float32)
    Y = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, n)]
    it = DeviceCachedIterator(X, Y, batch_size=batch)
    net.fit(it, epochs=1)                       # warmup/compile
    sps = _median_rate(lambda: net.fit(it, epochs=2), 2 * n)
    # ResNet-50 fwd FLOPs/image: 4.1e9 at 224x224; conv FLOPs scale with
    # spatial area for other image sizes
    fwd_flops = 4.1e9 * (image / 224.0) ** 2
    return {"samples_per_sec": round(sps, 1),
            "step_time_ms": round(1000.0 * batch / sps, 3),
            "mfu_est": round(3 * fwd_flops * sps / V5E_PEAK_FLOPS, 5),
            "batch": batch,
            "precision": "bf16_mixed" if mixed_precision else "f32",
            **_memory_stats()}


def bench_bert_base(batch=16, seq_len=128, steps=16, mixed_precision=True):
    # steps=16 (was 4): with ~40-80 ms steps, 4-step epochs measure the
    # tunnel's dispatch jitter more than the model (observed 199-409
    # samples/sec across runs of the identical binary); 16 steps per
    # epoch amortizes it
    """BASELINE config 4: BERT-base imported from a frozen TF GraphDef,
    fine-tune step (pooled-output classifier, softmax-CE, Adam)."""
    from deeplearning4j_tpu.autodiff import MixedPrecision, TrainingConfig
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.zoo.bert import BERT_BASE, bert_base

    sd = bert_base(BERT_BASE, batch=batch, seq_len=seq_len, num_labels=2)
    sd.training_config = TrainingConfig(
        updater=Adam(2e-5),
        data_set_feature_mapping=["input_ids", "input_mask",
                                  "token_type_ids"],
        data_set_label_mapping=["labels"],
        mixed_precision=MixedPrecision() if mixed_precision else None)
    rng = np.random.default_rng(0)
    n = batch * steps
    ids = rng.integers(0, BERT_BASE.vocab_size, (n, seq_len)).astype(np.int32)
    mask = np.ones((n, seq_len), np.int32)
    tt = np.zeros((n, seq_len), np.int32)
    labels = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    it = DeviceCachedIterator([ids, mask, tt], [labels], batch_size=batch)
    sd.fit(it, epochs=1)                        # warmup/compile
    sps = _median_rate(lambda: sd.fit(it, epochs=2), 2 * n)
    # fwd matmul FLOPs per example: per layer qkv+attn-out (8h^2/token) +
    # ffn (16h^2/token) + attention scores/context (4*s*h/token)
    h, L, s = BERT_BASE.hidden_size, BERT_BASE.num_layers, seq_len
    fwd_flops = L * (24 * s * h * h + 4 * s * s * h)
    return {"samples_per_sec": round(sps, 1),
            "step_time_ms": round(1000.0 * batch / sps, 3),
            "mfu_est": round(3 * fwd_flops * sps / V5E_PEAK_FLOPS, 5),
            "batch": batch, "seq_len": seq_len,
            "precision": "bf16_mixed" if mixed_precision else "f32",
            **_memory_stats()}


def bench_gpt_medium(batch=16, seq_len=512, steps=8, mixed_precision=True,
                     ce_tail_dtype=None):
    """Compute-dense flagship: GPT-medium-class decoder LM (h=1536, 16
    layers, ffn 6144, vocab 32k, ~510M params), seq 512, per-layer remat
    (sd.remat_scope), weight-tied head, sparse CE. This is the config
    where MXU saturation is actually reachable — matmul-dominated,
    bf16, one fused attention op per layer.

    ``ce_tail_dtype="bfloat16"`` (the gpt_medium_bf16_ce config) keeps
    the [B,S,32k] log-softmax tail in bf16 instead of f32 — PROFILE.md
    round 5 named the f32 CE tail the largest remaining delta to
    hand-written JAX; the per-token losses still reduce in f32."""
    from deeplearning4j_tpu.autodiff import MixedPrecision, TrainingConfig
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.zoo.gpt import GPT_MEDIUM, build_gpt

    cfg = GPT_MEDIUM
    sd = build_gpt(cfg, batch=batch, seq_len=seq_len)
    sd.training_config = TrainingConfig(
        updater=Adam(1e-4),
        data_set_feature_mapping=["input_ids"],
        data_set_label_mapping=["targets"],
        mixed_precision=MixedPrecision(softmax_dtype=ce_tail_dtype)
        if mixed_precision else None)
    rng = np.random.default_rng(0)
    n = batch * steps
    ids = rng.integers(0, cfg.vocab_size, (n, seq_len)).astype(np.int32)
    tgt = rng.integers(0, cfg.vocab_size, (n, seq_len)).astype(np.int32)
    it = DeviceCachedIterator([ids], [tgt], batch_size=batch)
    sd.fit(it, epochs=1)                        # warmup/compile
    sps = _median_rate(lambda: sd.fit(it, epochs=2), 2 * n)
    h, L, f, S, V = (cfg.hidden_size, cfg.num_layers, cfg.intermediate_size,
                     seq_len, cfg.vocab_size)
    # fwd matmul FLOPs/token: qkv+proj (8h^2) + mlp (4*h*f) + tied head
    # (2hV); attention scores+context 4*S*h
    fwd_flops = S * (L * (8 * h * h + 4 * h * f + 4 * S * h) + 2 * h * V)
    return {"samples_per_sec": round(sps, 2),
            "step_time_ms": round(1000.0 * batch / sps, 3),
            "tokens_per_sec": round(sps * seq_len, 1),
            "mfu_est": round(3 * fwd_flops * sps / V5E_PEAK_FLOPS, 5),
            "batch": batch, "seq_len": seq_len,
            "precision": "bf16_mixed" if mixed_precision else "f32",
            # the CE-tail knob rides MixedPrecision; without it the tail
            # is plain f32 regardless of what was requested
            "ce_tail_dtype": (ce_tail_dtype or "float32")
            if mixed_precision else "float32",
            **_memory_stats()}


# -- cold start: fresh-process first-compile vs warm-restart ------------
# (compilecache/, docs/cold_start.md — restart-to-first-step is a
# tracked metric alongside throughput from BENCH_r06 on)

def _cold_start_child_main(model: str, cache_dir: str) -> None:
    """One restart probe, run in ITS OWN process (`bench.py
    _cold_start_child <model> <cache_dir>`): wire the persistent cache
    through Environment, build the model, AOT-precompile, fit one short
    epoch. Prints a JSON line of phase timings + compile accounting.
    Run once against an empty cache dir = cold start; again against the
    now-populated dir = warm restart."""
    t0 = time.perf_counter()
    from deeplearning4j_tpu.environment import environment
    env = environment()
    env.set("compilation_cache_dir", cache_dir)
    env.set("compilation_cache_min_entry_size", -1)   # cache everything
    env.set("compilation_cache_min_compile_time", 0.0)
    from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                 install_compile_watcher)
    install_compile_watcher()
    from deeplearning4j_tpu.autodiff import (MixedPrecision,
                                             ScoreIterationListener,
                                             TrainingConfig)
    from deeplearning4j_tpu.dataset import DeviceCachedIterator
    from deeplearning4j_tpu.learning.updaters import Adam
    t_import = time.perf_counter()

    rng = np.random.default_rng(0)
    listeners = []
    if model == "samediff_mlp":
        # the BASELINE config-2 graph (same builder as
        # bench_samediff_mlp) on the production (fused-window +
        # listener) tier: precompile covers K=8 plus the pow2 tails
        sd = _build_mlp_sd(fused_steps=8)
        batch, n = 128, 1024
        X = rng.normal(size=(n, 784)).astype(np.float32)
        Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
        it = DeviceCachedIterator(X, Y, batch_size=batch)
        listeners = [ScoreIterationListener(print_every=10 ** 9,
                                            print_fn=lambda *a: None)]
        precompile = lambda: sd.precompile(batch_size=batch)
    elif model in ("gpt_medium", "gpt_tiny"):
        from deeplearning4j_tpu.zoo.gpt import (GPT_MEDIUM, GPT_TINY,
                                                build_gpt)
        cfg, batch, seq_len = (GPT_MEDIUM, 16, 512) \
            if model == "gpt_medium" else (GPT_TINY, 4, 32)
        sd = build_gpt(cfg, batch=batch, seq_len=seq_len)
        sd.training_config = TrainingConfig(
            updater=Adam(1e-4),
            data_set_feature_mapping=["input_ids"],
            data_set_label_mapping=["targets"],
            mixed_precision=MixedPrecision())
        steps = 2
        n = batch * steps
        ids = rng.integers(0, cfg.vocab_size, (n, seq_len)) \
            .astype(np.int32)
        tgt = rng.integers(0, cfg.vocab_size, (n, seq_len)) \
            .astype(np.int32)
        it = DeviceCachedIterator([ids], [tgt], batch_size=batch)
        # the listener-free device-cached fit takes the scanned tier
        precompile = lambda: sd.precompile(epoch_steps=steps,
                                           tiers=("epoch",))
    else:
        raise SystemExit(f"unknown cold-start model {model!r}")
    t_build = time.perf_counter()
    info = precompile()
    t_pre = time.perf_counter()
    sd.fit(it, epochs=1, listeners=listeners)
    t_fit = time.perf_counter()
    snap = COMPILE_STATS.snapshot()
    print(json.dumps({
        "model": model,
        "import_s": round(t_import - t0, 4),
        "build_s": round(t_build - t_import, 4),
        "precompile_s": round(t_pre - t_build, 4),
        "first_fit_s": round(t_fit - t_pre, 4),
        "restart_to_first_step_s": round(t_fit - t0, 4),
        "backend_compiles": int(snap["backend_compiles"]),
        "cache_hits": int(snap["cache_hits"]),
        "cache_misses": int(snap["cache_misses"]),
        "precompile": info}))


def bench_cold_start(models=None, timeout_s=900):
    """Restart-to-first-step per model, cold (empty persistent cache)
    vs warm (the cache the cold run just populated) — each probe a
    FRESH python process, because in-process jit caches would fake the
    warmth a real restart does not have. The headline
    ``warm_restart_speedup`` is cold/warm restart time; acceptance for
    gpt_medium is ≥5x (the XLA compile dominates its cold start).
    Override models via $DL4J_BENCH_COLD_START_MODELS (comma list)."""
    import shutil
    import subprocess
    import sys
    import tempfile
    if models is None:
        env_models = os.environ.get("DL4J_BENCH_COLD_START_MODELS")
        models = tuple(env_models.split(",")) if env_models \
            else ("samediff_mlp", "gpt_medium")
    here = os.path.abspath(__file__)
    out = {}
    for model in models:
        cache_dir = tempfile.mkdtemp(prefix=f"dl4j_coldstart_{model}_")
        try:
            runs = {}
            for phase in ("cold", "warm"):
                proc = subprocess.run(
                    [sys.executable, here, "_cold_start_child", model,
                     cache_dir],
                    capture_output=True, text=True, timeout=timeout_s,
                    cwd=os.path.dirname(here), env=os.environ.copy())
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"{phase} probe failed: {proc.stderr[-800:]}")
                runs[phase] = json.loads(proc.stdout.strip()
                                         .splitlines()[-1])
            cold_t = runs["cold"]["restart_to_first_step_s"]
            warm_t = runs["warm"]["restart_to_first_step_s"]
            out[model] = {
                "cold": runs["cold"], "warm": runs["warm"],
                "warm_restart_speedup": round(cold_t / warm_t, 2)
                if warm_t else None,
                "warm_cache_hits": runs["warm"]["cache_hits"],
                "warm_miss_compiles": max(
                    0, runs["warm"]["backend_compiles"]
                    - runs["warm"]["cache_hits"])}
        except Exception as e:
            out[model] = {"error": repr(e)}
        finally:
            shutil.rmtree(cache_dir, ignore_errors=True)
    # headline = gpt_medium (the model the >=5x acceptance bar names —
    # its cold start is compile-dominated), else the first model that ran
    headline = None
    for model in ("gpt_medium", *models):
        speedup = out.get(model, {}).get("warm_restart_speedup")
        if speedup is not None:
            headline = speedup
            break
    return {"models": out, "warm_restart_speedup": headline,
            "headline_model": model if headline is not None else None}


def main():
    import sys
    import traceback
    argv = sys.argv[1:]
    if argv and argv[0] == "_cold_start_child":
        _cold_start_child_main(argv[1], argv[2])
        return
    # capture a memory plan for every compiled train program so the
    # per-model hbm/plan trajectory lands in BENCH_r08+ (same lowering,
    # one compile either way — the child cold-start probes stay
    # untouched so their numbers remain comparable across rounds)
    from deeplearning4j_tpu.monitor import memstats
    memstats.enable_plan_capture()
    only = set(argv) or None     # `bench.py cold_start` runs a subset
    configs = {}
    registry = (("lenet_mnist", bench_lenet),
                     ("samediff_mlp", bench_samediff_mlp),
                     # listener-path tiers (fused windows, K=8): the
                     # production configuration BENCH_r05 showed
                     # dispatch-bound — tracked so the listener-path
                     # speedup shows up in BENCH_r*.json going forward
                     ("lenet_listener",
                      lambda: bench_lenet(listener=True, fused_steps=8)),
                     ("samediff_mlp_listener",
                      lambda: bench_samediff_mlp(listener=True,
                                                 fused_steps=8)),
                     # the fault rail's cost stays visible: fused-window
                     # steps/s with divergence sentinels on vs off
                     ("sentinel_overhead", bench_sentinel_overhead),
                     # the tensorstats rail's cost (in-graph per-layer
                     # grad/update/param summaries at default cadence,
                     # ≤3% bar) for BENCH_r07
                     ("tensorstats_overhead", bench_tensorstats_overhead),
                     # the HBM telemetry rail's cost (per-flush memory
                     # records + plan capture + MFU gauge, ≤2% bar) +
                     # the hbm_peak/plan-bytes trajectory for BENCH_r08+
                     ("memory_overhead", bench_memory_overhead),
                     # the static analyzer's warm-path cost (~0: it
                     # runs once per graph version, pre-compile) +
                     # its one-time wall seconds (analyze/)
                     ("analyze_overhead", bench_analyze_overhead),
                     # the observability rail's cost + the step-time
                     # breakdown (where fused listener-path wall time
                     # goes), emitted into BENCH_r*.json going forward
                     ("tracer_overhead", bench_tracer_overhead),
                     # the serving resilience rail's cost (admission +
                     # breaker + supervision on the batched path, ≤3%
                     # bar) for BENCH_r08
                     ("serving_resilience_overhead",
                      bench_serving_resilience_overhead),
                     # continuous-batching generative serving vs the
                     # static wait-for-full-batch baseline on one
                     # seeded mixed-length trace (tokens/sec/chip,
                     # p50/p99 TTFT, inter-token p50, slot occupancy —
                     # serving/generative.py) for BENCH_r10
                     ("generative", bench_generative),
                     # paged KV vs dense at equal HBM: concurrent
                     # capacity ratio (≥4x bar), prefix-hit TTFT vs
                     # decode-step p50, tp=2 greedy bit-identity
                     # (serving/paged/) for BENCH_r11
                     ("serving_paged", bench_serving_paged),
                     # fleet chaos drill: kill a replica + rolling
                     # reload under open-loop load (zero failed healthy
                     # requests, p99 TTFT inside the SLO) and the
                     # affinity-vs-random prefix-hit-rate column
                     # (serving/fleet/) for BENCH_r12
                     ("serving_fleet", bench_serving_fleet),
                     # durable requests: mid-stream-kill salvage +
                     # exactly-once stream + bit-identity (greedy AND
                     # sampled), router kill/restart journal replay,
                     # and the fsync'd journal's throughput price
                     # (serving/fleet/durable.py) for BENCH_r14
                     ("serving_durability", bench_serving_durability),
                     # the request-tracing + SLO rail's cost on the
                     # fleet loadgen closed loop (trace tagging +
                     # waterfall assembly + SLO windows, ≤3% bar) plus
                     # kept-trace count and the worst-TTFT waterfall
                     # (monitor/reqtrace.py) for BENCH_r15
                     ("reqtrace_overhead", bench_reqtrace_overhead),
                     # speculative decoding vs plain decode on the
                     # skewed trace: acceptance-ceiling self-draft,
                     # >= 1.5x tokens/sec bar, temp-0 bit-identity bit
                     # (serving/generative.py draft_spec) for BENCH_r13
                     ("serving_speculative", bench_serving_speculative),
                     # int8 weights + KV: paged-pool token capacity at
                     # equal slab bytes (>= 1.9x bar, ~4x expected) +
                     # f32-vs-int8 throughput and greedy-token
                     # agreement (zoo/gpt.py quantize_*) for BENCH_r13
                     ("serving_quant", bench_serving_quant),
                     # the integrity rail's cost (state fingerprints +
                     # stall-watchdog guards on the fused K=8 listener
                     # path, ≤2% bar) for BENCH_r10
                     ("integrity_overhead", bench_integrity_overhead),
                     # disk-backed streaming vs the cached-window bench
                     # (datapipe/, ~5% bar) + data-wait per flush +
                     # prefetch-worker scaling, for BENCH_r09
                     ("disk_stream", bench_disk_stream),
                     # cold-start: fresh-process first-compile vs
                     # warm-cache restart per model (compilecache/)
                     ("cold_start", bench_cold_start),
                     ("resnet50", bench_resnet50),
                     ("bert_base", bench_bert_base),
                     ("gpt_medium", bench_gpt_medium),
                     # the CE-tail precision lever on the flagship LM
                     # (MixedPrecision.softmax_dtype, PROFILE.md r6)
                     ("gpt_medium_bf16_ce",
                      lambda: bench_gpt_medium(ce_tail_dtype="bfloat16")))
    if only:
        # an unknown name running NOTHING with a success-shaped zero
        # result would let a typo'd CI invocation report 0 forever
        unknown = only - {name for name, _ in registry}
        if unknown:
            raise SystemExit(
                f"unknown bench config(s) {sorted(unknown)}; "
                f"have {sorted(name for name, _ in registry)}")
    for name, fn in registry:
        if only and name not in only:
            continue
        # per-config plan attribution: _memory_stats() reads the ACTIVE
        # plan, which must not be a stale one from the previous config
        memstats.PLANS.reset()
        try:
            configs[name] = fn()
        except Exception:
            traceback.print_exc(file=sys.stderr)
            configs[name] = {"error": "failed"}
    headline = configs.get("resnet50", {})
    if "samples_per_sec" not in headline:     # fall back to whatever ran
        named = [(k, v) for k, v in configs.items()
                 if "samples_per_sec" in v]
        metric, headline = (named[0] if named
                            else ("none", {"samples_per_sec": 0.0}))
    else:
        metric = "resnet50"
    print(json.dumps({
        "metric": f"{metric}_train_throughput",
        "value": headline["samples_per_sec"],
        "unit": "samples/sec/chip",
        "vs_baseline": None,    # reference publishes no numbers
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
