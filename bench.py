"""Benchmark: flagship LeNet-class CNN training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric = steady-state training samples/sec (PerformanceListener definition,
reference optimize/listeners/PerformanceListener.java:46-118) for
MultiLayerNetwork.fit() on MNIST-shaped synthetic data, batch 128 —
BASELINE.md target config 1 (LeNet MNIST fit()). The reference publishes no
numbers (BASELINE.json "published": {}), so vs_baseline is reported as 1.0
(parity placeholder) until a measured reference baseline exists.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    from __graft_entry__ import _flagship

    batch = 128
    steps_per_epoch = 8
    n = batch * steps_per_epoch
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, 1, 28, 28)).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]

    net = _flagship()

    class _It:
        def reset(self): ...
        def __iter__(self):
            for i in range(0, n, batch):
                yield X[i:i + batch], Y[i:i + batch]

    # warmup epoch (compile) then timed epochs
    net.fit(_It(), epochs=1)
    t0 = time.perf_counter()
    timed_epochs = 5
    net.fit(_It(), epochs=timed_epochs)
    dt = time.perf_counter() - t0

    samples_per_sec = timed_epochs * n / dt
    print(json.dumps({
        "metric": "lenet_mnist_train_throughput",
        "value": round(samples_per_sec, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
