"""Paged-KV generative serving: block pool, prefix caching, tensor
parallelism.

What this shows (docs/serving.md "Paged KV & prefix caching"):

1. train a tiny GPT, then serve it through the PAGED memory tier
   (``zoo.gpt.gpt_paged_spec`` + ``PagedGenerativeServer``): K/V live
   in fixed-size token blocks from one preallocated slab, each request
   holds a block table grown at decode-step boundaries — capacity is
   proportional to tokens actually held, not ``max_slots x max_seq``;
2. the HBM sizing math: the same budget a small dense deployment
   preallocates, spent as a block pool (``kv_hbm_bytes=``), and the
   pool accounting in ``memory_report()``;
3. prefix caching: a repeated system prompt prefills only its SUFFIX —
   the shared full blocks are chain-hashed, refcounted and reused, so
   repeat TTFT approaches one decode step;
4. greedy output bit-identical to the unbatched dense reference
   (``greedy_decode``) — paged vs dense is a memory-layout change,
   not a numerics change;
5. tensor-parallel serving (``tp=2`` when 2+ devices are visible):
   params + KV slabs sharded over the model mesh axis, same tokens.
"""
import numpy as np

from deeplearning4j_tpu.autodiff import TrainingConfig
from deeplearning4j_tpu.dataset import DeviceCachedIterator
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.serving.generative import greedy_decode
from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec,
                                        gpt_paged_spec)

VOCAB, SEQ, MSL = 96, 16, 32
cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_seq_len=MSL)

# -- 1. train briefly on random token sequences -------------------------
sd = build_gpt(cfg, batch=4, seq_len=SEQ, seed=0)
sd.training_config = TrainingConfig(
    updater=Adam(1e-3),
    data_set_feature_mapping=["input_ids"],
    data_set_label_mapping=["targets"])
rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
tgt = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
hist = sd.fit(DeviceCachedIterator([ids], [tgt], batch_size=4),
              epochs=2)
print(f"trained 2 epochs; final loss "
      f"{hist.loss_curve.losses[-1]:.4f}")

# -- 2. the paged server: a dense deployment's budget as a block pool ---
dense_spec = gpt_generative_spec(sd, cfg)     # reference + sizing only
paged_spec = gpt_paged_spec(sd, cfg)
dense_bytes = 2 * int(np.prod(dense_spec.kv_shape(4, MSL))) * 4
server = PagedGenerativeServer(paged_spec, max_slots=8, block_size=8,
                               kv_hbm_bytes=dense_bytes,
                               max_seq_len=MSL, warmup=True)
rep = server.memory_report()
print(f"pool: {rep['num_blocks']} blocks x {rep['block_size']} tokens "
      f"({rep['kv_bytes_per_block'] / 1024:.1f} KiB/block) from the "
      f"same {dense_bytes / 1024:.0f} KiB a 4-slot dense slab "
      f"preallocates — serving {server.max_slots} slots")

# -- 3. prefix caching: the repeated system prompt prefills its suffix --
system = (np.arange(9, dtype=np.int32) * 5) % VOCAB   # 1 full block
questions = [rng.integers(0, VOCAB, int(rng.integers(2, 8)))
             .astype(np.int32) for _ in range(4)]
prompts = [np.concatenate([system, q]) for q in questions]
budgets = [6, 9, 4, 8]
handles = [server.submit(p, max_new_tokens=n)
           for p, n in zip(prompts, budgets)]
streamed = [list(h.tokens(timeout=120)) for h in handles]
paged_rec = server.metrics.to_record()["paged"]
print(f"prefix cache: hit rate {paged_rec['prefix_hit_rate']:.0%}, "
      f"{paged_rec['prefix_blocks_hit']} shared blocks reused across "
      f"{len(prompts)} requests with one system prompt")

# -- 4. bit-identical to the unbatched dense reference ------------------
for i, (p, n) in enumerate(zip(prompts, budgets)):
    ref = greedy_decode(dense_spec, p, n, max_seq_len=MSL)
    assert streamed[i] == ref, (i, streamed[i], ref)
print("all paged generations == dense unbatched greedy_decode")
print(server.metrics.stats())
server.shutdown()

# -- 5. tensor parallel: same tokens from a sharded server --------------
import jax

if len(jax.devices()) >= 2:
    tp_server = PagedGenerativeServer(paged_spec, max_slots=4,
                                      block_size=8, max_seq_len=MSL,
                                      tp=2, warmup=False)
    got = [tp_server.submit(p, max_new_tokens=n).result(timeout=120)
           for p, n in zip(prompts, budgets)]
    tp_server.shutdown()
    assert got == streamed
    print(f"tp=2 over {len(jax.devices())} devices: params + KV "
          f"sharded, greedy tokens identical")
else:
    print("single device visible: skipping the tp=2 leg")
