"""Checkpoint + bit-exact resume (docs/checkpointing.md).

Trains an MLP with async atomic checkpoints, "crashes" after a few
epochs, then resumes in a fresh network and shows the resumed run
reproduces the uninterrupted run exactly — params, updater state, and
loss trajectory. Also demonstrates torn-checkpoint recovery: a
checkpoint corrupted mid-write is skipped by restore_latest().
"""
import os
import shutil
import tempfile

import numpy as np

from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                           CheckpointManager)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)

EPOCHS, CRASH_AFTER = 8, 3


def make_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(learning_rate=0.05))
            .list()
            .layer(DenseLayer(n_out=32, activation="tanh", dropout=0.9))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(2))
            .build())
    return MultiLayerNetwork(conf).init()


def make_data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(256, 2)).astype(np.float32)
    Y = np.eye(2, dtype=np.float32)[(X[:, 0] * X[:, 1] > 0).astype(int)]
    return X, Y


def main():
    X, Y = make_data()
    workdir = tempfile.mkdtemp(prefix="ckpt_example_")
    ckpt_dir = os.path.join(workdir, "ckpts")

    # --- reference: uninterrupted run (checkpointing too, so every run
    # takes the same listener-equipped fit path) ----------------------
    net_ref = make_net()
    ref_mgr = CheckpointManager(os.path.join(workdir, "ref_ckpts"))
    ref_losses = list(net_ref.fit(
        X, Y, epochs=EPOCHS, batch_size=32,
        listeners=[CheckpointListener(ref_mgr, every_n_epochs=1)])
        .loss_curve.losses)
    print(f"uninterrupted run: {EPOCHS} epochs, "
          f"final loss {ref_losses[-1]:.6f}")

    # --- run 1: train with async checkpoints, then 'crash' -----------
    mgr = CheckpointManager(ckpt_dir, keep_last_n=3)
    net1 = make_net()
    listener = CheckpointListener(mgr, every_n_epochs=1)
    losses1 = list(net1.fit(X, Y, epochs=CRASH_AFTER, batch_size=32,
                            listeners=[listener]).loss_curve.losses)
    mgr.wait_until_finished()
    print(f"run 1: trained {CRASH_AFTER} epochs, committed steps "
          f"{mgr.all_steps()} ... process dies here")

    # simulate a checkpoint torn by the crash: a half-written .tmp dir
    torn = os.path.join(ckpt_dir, "step_99999999.tmp")
    os.makedirs(torn)
    with open(os.path.join(torn, "arrays.npz"), "wb") as fh:
        fh.write(b"half a checkpoint")

    # --- run 2: fresh process resumes from the latest commit ---------
    mgr2 = CheckpointManager(ckpt_dir, keep_last_n=3)
    net2 = make_net()                      # fresh init, same config/seed
    step, state = mgr2.restore_latest(model=net2)
    print(f"run 2: restored committed step {step} "
          f"(iteration {state.iteration}, epoch {state.epoch}); "
          f"torn dir skipped: {os.path.basename(torn)}")
    losses2 = list(net2.fit(
        X, Y, epochs=EPOCHS - CRASH_AFTER, batch_size=32,
        listeners=[CheckpointListener(mgr2, every_n_epochs=1)])
        .loss_curve.losses)

    # --- bit-exact? --------------------------------------------------
    resumed = losses1 + losses2
    exact = np.array_equal(np.asarray(ref_losses), np.asarray(resumed))
    print(f"loss trajectory identical to uninterrupted run: {exact}")
    p_ref, p_res = net_ref.params(), net2.params()
    same = all(np.array_equal(p_ref[n], p_res[n]) for n in p_ref)
    print(f"final params bit-exact: {same}")
    assert exact and same, "resume was not bit-exact"

    shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
