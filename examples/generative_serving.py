"""Continuous-batching generative serving of a GPT decoder LM.

What this shows (docs/serving.md "Generative serving"):

1. train a tiny GPT with the normal fit rail, then hand the SAME graph
   to the generative serving tier via the decode-mode hook
   (``zoo.gpt.gpt_generative_spec``);
2. AOT warmup: ONE decode program + pow2 prefill buckets compile before
   the first request (0 compiles under traffic — with a persistent
   compilation cache a warm restart serves immediately);
3. mixed-length concurrent requests admitted into KV slots at decode
   step boundaries, tokens STREAMED per request as they resolve;
4. greedy output bit-identical to the unbatched single-request
   reference (`greedy_decode`);
5. the serving metrics: TTFT / inter-token latency lanes, slot
   occupancy, tokens/sec.
"""
import numpy as np

from deeplearning4j_tpu.autodiff import TrainingConfig
from deeplearning4j_tpu.dataset import DeviceCachedIterator
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.serving.generative import (GenerativeServer,
                                                   greedy_decode)
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec)

VOCAB, SEQ = 96, 16
cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_seq_len=32)

# -- 1. train briefly on random token sequences -------------------------
sd = build_gpt(cfg, batch=4, seq_len=SEQ, seed=0)
sd.training_config = TrainingConfig(
    updater=Adam(1e-3),
    data_set_feature_mapping=["input_ids"],
    data_set_label_mapping=["targets"])
rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
tgt = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
hist = sd.fit(DeviceCachedIterator([ids], [tgt], batch_size=4),
              epochs=2)
print(f"trained 2 epochs; final loss "
      f"{hist.loss_curve.losses[-1]:.4f}")

# -- 2. serve it: decode-mode spec + continuous-batching server ---------
spec = gpt_generative_spec(sd, cfg)
server = GenerativeServer(spec, max_slots=4, max_seq_len=32,
                          warmup=True)
print(f"warmup: {server.warmup_report['prefill_buckets']} prefill "
      f"buckets + 1 decode program in "
      f"{server.warmup_report['seconds']:.2f}s")
print(f"KV slabs: {server.memory_report()['kv_slab_bytes'] / 1024:.0f} "
      f"KiB for {server.max_slots} slots x 32 positions")

# -- 3. mixed-length concurrent requests, streamed ----------------------
prompts = [rng.integers(0, VOCAB, int(rng.integers(2, 12)))
           .astype(np.int32) for _ in range(6)]
budgets = [4, 12, 6, 9, 3, 10]
handles = [server.submit(p, max_new_tokens=n)
           for p, n in zip(prompts, budgets)]
streamed = []
for i, h in enumerate(handles):
    toks = list(h.tokens(timeout=120))      # arrives token by token
    streamed.append(toks)
    print(f"request {i}: prompt len {prompts[i].size:2d} -> "
          f"{len(toks):2d} tokens: {toks}")

# -- 4. bit-identical to the unbatched reference ------------------------
for i, (p, n) in enumerate(zip(prompts, budgets)):
    ref = greedy_decode(spec, p, n, max_seq_len=32)
    assert streamed[i] == ref, (i, streamed[i], ref)
print("all 6 continuous-batched generations == unbatched greedy_decode")

# -- 5. the serving metrics ---------------------------------------------
print(server.metrics.stats())
server.shutdown()
