"""Self-healing training: divergence sentinels + rollback-and-retry.

A small MLP trains under injected faults — a flaky loader that raises
mid-epoch and a poisoned (all-NaN) batch that would silently corrupt the
parameters — and finishes with a finite loss anyway:

- the device-side sentinel (TrainingConfig.sentinel) flags the non-finite
  step inside the fused window and names it;
- FaultTolerantFit rolls back to the last committed checkpoint, retries
  under a bounded backoff budget, and completes the run;
- the loader exception is retried one layer down by RetryingIterator
  without costing a rollback.

See docs/fault_tolerance.md.
"""
import tempfile

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.checkpoint import CheckpointManager
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.faults import (ChaosMonkey, FaultTolerantFit,
                                       RetryPolicy)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.ui.stats import StatsStorage


def build_mlp():
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 16))
    w0 = sd.var("w0", value=rng.normal(0, .1, (16, 32)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(32, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (32, 4)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"],
        fused_steps=4)               # the production fused-window tier
    return sd


def main():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(256, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 256)]

    sd = build_mlp()
    chaos = ChaosMonkey(seed=7)
    it = ArrayDataSetIterator(X, Y, batch_size=16)      # 16 steps/epoch
    it = chaos.flaky_iterator(it, fail_at_batch=5)      # loader IOError
    it = chaos.poison_batches(it, at_step=21)           # NaN mid-epoch-1

    with tempfile.TemporaryDirectory() as ckpt_dir:
        storage = StatsStorage()
        manager = CheckpointManager(ckpt_dir, keep_last_n=3)
        ftf = FaultTolerantFit(
            sd, manager,
            policy=RetryPolicy(max_retries=2, backoff_base=0.0,
                               quarantine_corrupt=False),
            checkpoint_every_n_iterations=8,
            stats_storage=storage)
        history = ftf.fit(it, epochs=4)
        manager.close()

        print(f"final loss: {history.final_loss():.4f}")
        print(f"rollbacks: {ftf.rollbacks}, recovery overhead: "
              f"{ftf.recovery_seconds:.3f}s")
        for rec in storage.of_type("faults"):
            detail = {k: v for k, v in rec.items()
                      if k not in ("type", "t") and v is not None}
            print(f"  faults event: {detail}")
        assert np.isfinite(history.final_loss())
        assert ftf.rollbacks >= 1
        print("self-healed: finite loss after injected NaN + loader fault")


if __name__ == "__main__":
    main()
