"""Word2Vec on a toy corpus: train skip-gram embeddings on device, query
nearest words (the deeplearning4j-nlp quickstart)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

from deeplearning4j_tpu.nlp import Word2Vec

CORPUS = [
    "the king rules the kingdom",
    "the queen rules the kingdom",
    "the king is a royal man",
    "the queen is a royal woman",
    "a man walks the dog",
    "a woman walks the dog",
    "the dog chases the cat",
    "the cat sees the dog",
] * 40


def main():
    w2v = (Word2Vec.builder()
           .vector_size(24).window_size(3).min_word_frequency(2)
           .epochs(12).seed(7).build())
    w2v.fit(CORPUS)
    for word in ("king", "dog"):
        print(word, "->", w2v.words_nearest(word, top_n=3))
    print("similarity(king, queen):",
          round(w2v.similarity("king", "queen"), 3))


if __name__ == "__main__":
    main()
