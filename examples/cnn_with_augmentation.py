"""Image ETL -> augmentation pipeline -> CNN training."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import os
import tempfile

import numpy as np

from deeplearning4j_tpu.etl import (FlipImageTransform,
                                    ImageRecordReader,
                                    ImageRecordReaderDataSetIterator,
                                    PipelineImageTransform,
                                    RandomCropTransform)
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (ConvolutionLayer, DenseLayer, InputType,
                                   MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer,
                                   SubsamplingLayer)


def make_dataset(root, n_per_class=16):
    rng = np.random.default_rng(0)
    for lab in ("bright", "dark"):
        os.makedirs(os.path.join(root, lab), exist_ok=True)
        for i in range(n_per_class):
            img = rng.random((40, 40), np.float32)
            img += 0.5 if lab == "bright" else 0.0
            np.save(os.path.join(root, lab, f"{i}.npy"),
                    img.astype(np.float32))


def main():
    root = tempfile.mkdtemp()
    make_dataset(root)
    augment = PipelineImageTransform(
        (FlipImageTransform(None), 1.0),       # random horizontal flip
        RandomCropTransform(32, 32))
    reader = ImageRecordReader(40, 40, channels=1, root=root,
                               transform=augment)
    it = ImageRecordReaderDataSetIterator(reader, batch_size=16)

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(3e-3))
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    convolution_mode="SAME",
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.convolutional(32, 32, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    history = net.fit(it, epochs=8)
    print("losses:", [round(l, 3) for l in history.loss_curve.losses[-3:]])


if __name__ == "__main__":
    main()
