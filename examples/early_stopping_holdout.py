"""Early stopping with a holdout score calculator and best-model restore."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import numpy as np

from deeplearning4j_tpu.autodiff import (DataSetLossCalculator,
                                         EarlyStoppingConfiguration,
                                         EarlyStoppingTrainer,
                                         MaxEpochsTerminationCondition,
                                         ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.dataset import ArrayDataSetIterator
from deeplearning4j_tpu.learning.updaters import Sgd
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)


def main():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    Y = np.eye(2, dtype=np.float32)[y]
    train = ArrayDataSetIterator(X[:384], Y[:384], batch_size=64)
    holdout = ArrayDataSetIterator(X[384:], Y[384:], batch_size=64,
                                   shuffle=False)

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.2))
            .list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=2, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(10))
            .build())
    net = MultiLayerNetwork(conf).init()

    es = (EarlyStoppingConfiguration.builder()
          .epoch_termination_conditions(
              MaxEpochsTerminationCondition(30),
              ScoreImprovementEpochTerminationCondition(4))
          .score_calculator(DataSetLossCalculator(holdout))
          .build())
    result = EarlyStoppingTrainer(es, net, train).fit(max_epochs=30)
    print(result)


if __name__ == "__main__":
    main()
