"""Static analysis before the first compile (analyze/,
docs/static_analysis.md).

Builds a small model, breaks it four different ways, and shows how the
analyzer turns each break into a NAMED diagnostic — the variable, the
op, the producer chain, the fix — instead of an XLA traceback. Then
demonstrates strict mode (fail before any compile), the warm-path cost
(analysis runs once per graph version), and the CLI.
"""
import numpy as np

from deeplearning4j_tpu.analyze import (GraphAnalysisError,
                                        analyze_training)
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.learning.updaters import Adam

rng = np.random.default_rng(0)


def build_mlp(w0_rows=20, fused_steps=1, accum_steps=1,
              feature_mapping=("x",)):
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 20))
    w0 = sd.var("w0", value=rng.normal(0, 0.1, (w0_rows, 16))
                .astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(16, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0), name="h0")
    w1 = sd.var("w1", value=rng.normal(0, 0.1, (16, 4))
                .astype(np.float32))
    logits = h.mmul(w1, name="logits")
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = (
        TrainingConfig.builder().updater(Adam(learning_rate=1e-3))
        .data_set_feature_mapping(*feature_mapping)
        .data_set_label_mapping("labels")
        .fused_steps(fused_steps).accum_steps(accum_steps).build())
    return sd


# -- 1. a healthy model is clean -------------------------------------------
clean = analyze_training(build_mlp(), has_listeners=True)
print(f"clean model: {clean.counts()} in {clean.seconds:.3f}s "
      f"({clean.rules_run} rules)")
assert not clean.errors() and not clean.warnings()

# -- 2. four seeded defects, four named diagnostics ------------------------
print("\n--- shape mismatch (wrong kernel rows) ---")
rep = analyze_training(build_mlp(w0_rows=13))
print(rep.findings[0].render())

print("\n--- config lint: mapping names a ghost placeholder ---")
rep = analyze_training(build_mlp(feature_mapping=("features",)))
print([f.rule_id for f in rep.findings])

print("\n--- cadence: fused_steps not a multiple of accum_steps ---")
rep = analyze_training(build_mlp(fused_steps=6, accum_steps=4))
print([f.rule_id for f in rep.findings])

print("\n--- numerics: an unguarded log ---")
sd = build_mlp()
sd.get_variable("w1")  # keep graph healthy; add a hazardous branch
bad = SameDiff()
p = bad.placeholder("p", shape=(-1, 4))
bad_loss = p.log(name="raw_log").mean(name="loss")
bad.set_loss_variables(["loss"])
rep = analyze_training(bad)
print([f"{f.rule_id}@{f.subject}" for f in rep.findings])

# -- 3. strict mode: fail BEFORE any XLA compile ---------------------------
sd = build_mlp(w0_rows=13)
sd.training_config.analyze = "strict"
X = rng.normal(size=(32, 20)).astype(np.float32)
Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
batches = [(X[i:i + 8], Y[i:i + 8]) for i in range(0, 32, 8)]
try:
    sd.fit(batches, epochs=1)
    raise SystemExit("strict mode should have raised")
except GraphAnalysisError as e:
    print(f"\nstrict fit refused pre-compile: "
          f"{len(e.report.errors())} error(s), first rule "
          f"{e.report.errors()[0].rule_id}")

# -- 4. warm path: analysis runs once per graph version --------------------
sd = build_mlp()
sd.fit(batches, epochs=1)
first = sd.last_analysis
sd.fit(batches, epochs=1)
assert sd.last_analysis is first
print("\nwarm fit reused the cached report "
      f"(one-time cost {first.seconds:.3f}s, ~0 per-fit after)")

# -- 5. the CLI runs the same rules on a saved artifact --------------------
import subprocess
import sys
import tempfile

with tempfile.TemporaryDirectory() as d:
    path = f"{d}/model.zip"
    build_mlp(w0_rows=13).save(path)
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.analyze", path],
        capture_output=True, text=True)
    print(f"\nCLI exit code {proc.returncode} (1 = error findings):")
    print(proc.stdout.splitlines()[0])
print("done.")
