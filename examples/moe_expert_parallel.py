"""Switch-MoE with expert parallelism over a (data, expert) mesh.

Run with a virtual CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/moe_expert_parallel.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import (
    EXPERT_AXIS, expert_parallel_specs, init_moe_params, moe_train_step)


def main():
    n = jax.device_count()
    ep = 2 if n % 2 == 0 else 1
    dp = max(n // ep, 1)
    d, f, e = 16, 64, ep * 2
    rng = np.random.default_rng(0)
    params = init_moe_params(rng, d, f, e)
    x = jnp.asarray(rng.normal(size=(dp * 64, d)), jnp.float32)
    tgt = jnp.tanh(x)

    mesh = Mesh(np.array(jax.devices()[:dp * ep]).reshape(dp, ep),
                ("data", EXPERT_AXIS))
    specs = expert_parallel_specs()
    with mesh:
        p = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
             for k, v in params.items()}
        xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        ts = jax.device_put(tgt, NamedSharding(mesh, P("data", None)))
        step = jax.jit(lambda pp, a, b: moe_train_step(
            pp, a, b, expert_sharded=True))
        for i in range(10):
            p, loss = step(p, xs, ts)
        print(f"mesh data={dp} x expert={ep}, {e} experts, "
              f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
