"""Keras .h5 import -> run -> transfer-learning fine-tune.

Builds a tiny Keras-format h5 with h5py (stand-in for a real exported
model), imports it, and replaces the head for a new task.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import json
import tempfile

import h5py
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.modelimport import (
    import_keras_sequential_model_and_weights)
from deeplearning4j_tpu.nn import (FineTuneConfiguration, OutputLayer,
                                   TransferLearning)


def write_fixture(path):
    rng = np.random.RandomState(0)
    w1 = rng.randn(8, 16).astype(np.float32) * 0.3
    b1 = np.zeros(16, np.float32)
    w2 = rng.randn(16, 4).astype(np.float32) * 0.3
    b2 = np.zeros(4, np.float32)
    cfg = {"class_name": "Sequential", "config": {"name": "m", "layers": [
        {"class_name": "InputLayer",
         "config": {"batch_input_shape": [None, 8], "dtype": "float32",
                    "name": "input"}},
        {"class_name": "Dense",
         "config": {"name": "d1", "units": 16, "activation": "relu",
                    "use_bias": True}},
        {"class_name": "Dense",
         "config": {"name": "d2", "units": 4, "activation": "softmax",
                    "use_bias": True}}]}}
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(cfg)
        mw = f.create_group("model_weights")
        for name, ws in (("d1", (w1, b1)), ("d2", (w2, b2))):
            g = mw.create_group(name)
            names = []
            for suffix, arr in zip(("kernel", "bias"), ws):
                full = f"{name}/{suffix}:0"
                mw.create_dataset(full, data=arr)
                names.append(full.encode())
            g.attrs["weight_names"] = names


def main():
    path = tempfile.mktemp(suffix=".h5")
    write_fixture(path)
    net = import_keras_sequential_model_and_weights(path)
    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    print("imported model output:", net.output(x).to_numpy().shape)

    # freeze the trunk, replace the 4-class head with a 2-class one
    tuned = (TransferLearning.builder(net)
             .fine_tune_configuration(FineTuneConfiguration(
                 updater=Adam(1e-2)))
             .set_feature_extractor(0)          # freeze layer 0
             .remove_output_layer()
             .add_layer(OutputLayer(n_out=2, loss_function="MCXENT"))
             .build())
    y = (x[:, 0] > 0).astype(int)
    hist = tuned.fit(x, np.eye(2, dtype=np.float32)[y], epochs=10,
                     batch_size=4)
    print("fine-tune loss:", round(hist.loss_curve.losses[0], 3), "->",
          round(hist.loss_curve.losses[-1], 3))


if __name__ == "__main__":
    main()
