"""Data x tensor parallel training over a device mesh.

Run with a virtual CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/multi_device_training.py
On real hardware the same code uses the actual chips.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import jax
import numpy as np

from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.parallel import (
    DeviceMesh, ParallelTrainer, data_parallel,
    megatron_data_and_tensor_parallel)


def main():
    n = jax.device_count()
    model = 2 if n % 2 == 0 else 1
    data = max(n // model, 1)
    print(f"{n} devices -> mesh data={data} x model={model}")

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=4, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(16))
            .build())
    net = MultiLayerNetwork(conf).init()

    mesh = DeviceMesh.create(devices=jax.devices()[:data * model],
                             data=data, model=model)
    strategy = (megatron_data_and_tensor_parallel(mesh, net)
                if model > 1 else data_parallel(mesh))
    trainer = ParallelTrainer(net, strategy)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(data * 32, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, len(X))]
    history = trainer.fit([(X, Y)], epochs=5)
    print("losses:", [round(l, 3) for l in history.loss_curve.losses])


if __name__ == "__main__":
    main()
