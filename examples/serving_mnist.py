"""Model serving: train an MNIST MLP, then serve it with
ParallelInference — dynamic batching, shape buckets, backpressure, and
a closed-loop load test with latency percentiles.

The served path is bit-identical to ``net.output()`` while compiling
only O(buckets) XLA programs for arbitrarily mixed request sizes.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import numpy as np

from deeplearning4j_tpu.dataset import load_mnist
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)
from deeplearning4j_tpu.serving import (InferenceMode, LoadGenerator,
                                        ParallelInference)
from deeplearning4j_tpu.ui.stats import StatsStorage


def main():
    X, y = load_mnist(train=True, n_synthetic=2048)
    Y = np.eye(10, dtype=np.float32)[y]
    X = X.reshape(len(X), -1)

    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=10, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(X, Y, epochs=2, batch_size=128)

    # serve it: coalesce concurrent requests into padded bucket batches
    storage = StatsStorage()
    server = ParallelInference(net, mode=InferenceMode.BATCHED, workers=2,
                               max_batch_size=32, max_delay_ms=3.0,
                               max_queue_len=256, stats_storage=storage)

    # the served path matches the direct path bit for bit
    probe = X[:5]
    assert np.array_equal(server.output(probe),
                          net.output(probe).to_numpy())
    print("served output == direct output(): bit-identical")

    # closed-loop load: 4 clients, mixed request sizes 1..8 rows
    def make_request(rng, i):
        rows = int(rng.integers(1, 9))
        idx = rng.integers(0, len(X), size=rows)
        return X[idx]

    result = LoadGenerator(server, make_request, seed=7).run_closed(
        n_requests=200, concurrency=4)
    print(result.stats())

    server.shutdown()               # drains, then publishes metrics
    print(server.metrics.stats())
    rec = storage.of_type("serving")[0]
    print("compiled shapes:", rec["counters"]["compiles"],
          "| padding waste:", rec["batch"]["padding_waste"])


if __name__ == "__main__":
    main()
