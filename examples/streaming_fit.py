"""Fault-tolerant streaming training from checksummed disk shards.

The datapipe/ walkthrough (docs/data_pipeline.md):

1. commit a dataset directory of sha256-manifested shards
   (``write_dataset`` — the checkpoint staged-commit protocol applied
   to training data);
2. stream it through ``StreamingDataPipeline`` (supervised parallel
   prefetch feeding the fused-window stager) into a
   ``FaultTolerantFit`` — while the chaos harness injects a transient
   torn shard, flaky reads, and a prefetch-worker crash mid-fit;
3. checkpoint mid-epoch, then resume in a FRESH model + FRESH pipeline
   by SEEKING (PipelineState rides the checkpoint) and verify the
   resumed trajectory is bit-exact vs the uninterrupted one.
"""
import os
import tempfile

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.checkpoint import (CheckpointListener,
                                           CheckpointManager)
from deeplearning4j_tpu.checkpoint.state import restore_training_state
from deeplearning4j_tpu.datapipe import (StreamingDataPipeline,
                                         verify_dataset, write_dataset)
from deeplearning4j_tpu.faults import ChaosMonkey, FaultTolerantFit, \
    RetryPolicy
from deeplearning4j_tpu.learning.updaters import Adam


def build_model():
    rng = np.random.default_rng(7)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 16))
    w0 = sd.var("w0", value=rng.normal(0, 0.2, (16, 32)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(32, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, 0.2, (32, 4)).astype(np.float32))
    b1 = sd.var("b1", value=np.zeros(4, np.float32))
    logits = h.mmul(w1).add(b1, name="logits")
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = (TrainingConfig.builder()
                          .updater(Adam(learning_rate=5e-3))
                          .data_set_feature_mapping("x")
                          .data_set_label_mapping("labels")
                          .fused_steps(4)      # windowed tier + stager
                          .build())
    sd._seed = 123
    return sd


def main():
    work = tempfile.mkdtemp(prefix="dl4j_streaming_fit_")
    ds_dir = os.path.join(work, "dataset")

    # -- 1. commit a checksummed shard directory ------------------------
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[np.arange(512) % 4]
    manifest = write_dataset(ds_dir, X, Y, shard_size=64)
    print(f"committed {manifest.record_count} records in "
          f"{len(manifest.shards)} sha256-manifested shards")
    assert verify_dataset(ds_dir) == [], "pre-flight verify failed"

    # -- 2. chaos-streamed FaultTolerantFit -----------------------------
    def pipeline():
        return StreamingDataPipeline(ds_dir, batch_size=32, seed=11,
                                     n_workers=2, read_retries=3)

    sd = build_model()
    pipe = pipeline()
    mgr = CheckpointManager(os.path.join(work, "ckpt"),
                            keep_last_n=None, async_write=False)
    ftf = FaultTolerantFit(sd, mgr, checkpoint_every_n_iterations=4,
                           policy=RetryPolicy(backoff_base=0.0))
    chaos = ChaosMonkey(seed=42)
    torn = chaos.torn_shard(ds_dir, shard_index=3,
                            heal_after_failures=2, pipeline=pipe)
    torn.inject()                     # transient bit-rot: heals on retry
    try:
        with chaos.worker_killer(at_batch=5, times=1):
            with chaos.flaky_read(times=2, every=4):
                ftf.fit(pipe, epochs=2)
    finally:
        torn.heal()
    stats = pipe.stats()
    print(f"survived chaos: {stats['read_retries']} read retries, "
          f"{stats['worker_restarts']} worker restart(s), "
          f"{stats['requeues']} requeue(s); "
          f"{stats['records']} records streamed, zero dropped")

    # -- 3. mid-epoch seek-resume, bit-exact ----------------------------
    # uninterrupted reference (same seeds, no chaos)
    sd_ref = build_model()
    sd_ref.fit(pipeline(), epochs=3, listeners=[
        CheckpointListener(os.path.join(work, "ck_ref"),
                           every_n_iterations=10 ** 9)])
    # interrupted run: checkpoint mid-epoch, "crash", resume fresh
    sd_a = build_model()
    mgr_a = CheckpointManager(os.path.join(work, "ck_a"),
                              keep_last_n=None, async_write=False)
    sd_a.fit(pipeline(), epochs=1, listeners=[
        CheckpointListener(mgr_a, every_n_iterations=10)])
    step = mgr_a.latest_step()
    state = mgr_a.restore(step)
    dp_state = state.metadata["datapipe"]
    print(f"restored step {step}: pipeline at pass "
          f"{dp_state['pass_index']}, batch cursor {dp_state['cursor']}")
    sd_b = build_model()
    restore_training_state(sd_b, state)
    pipe_b = pipeline()
    pipe_b.restore_state(dp_state)    # seek — no pass replay
    sd_b.fit(pipe_b, epochs=3)        # finish epoch 0 + epochs 1..2
    resumed = sd_b.trainable_params()
    for name, ref in sd_ref.trainable_params().items():
        np.testing.assert_array_equal(
            np.asarray(ref), np.asarray(resumed[name]), err_msg=name)
    print("mid-epoch seek-resume is BIT-EXACT vs the uninterrupted run")


if __name__ == "__main__":
    main()
