"""Fleet serving: a multi-replica front door that survives a kill and
a rolling reload without failing a single healthy request.

What this shows (docs/serving.md "Fleet"):

1. three paged replicas behind one ``FleetRouter`` — least-loaded
   dispatch among ready replicas, prefix-affinity routing (the prompt's
   first full block is chain-hashed with the SAME function the prefix
   cache keys on, so affinity traffic lands on a warm cache);
2. chaos: one replica is KILLED mid-traffic (no drain — what a
   SIGKILL'd process looks like). The router marks it dead on the
   typed failure and retries onto the survivors: zero failed requests;
3. a rolling canaried deploy over the survivors — drain-before-reload,
   shadow-eval token-match gate, the rest of the fleet serving
   throughout;
4. the fleet record: placement kinds, affinity hit rate, retries,
   deaths, deploys — one ``{"type": "fleet"}`` story.
"""
import threading

import numpy as np

from deeplearning4j_tpu.serving.fleet import (FleetReplica, FleetRouter,
                                              RollingDeploy)
from deeplearning4j_tpu.serving.loadgen import FleetLoadGenerator
from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
from deeplearning4j_tpu.zoo.gpt import GPTConfig, build_gpt, gpt_paged_spec

VOCAB, MSL, BS = 96, 32, 8
cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_seq_len=MSL)

# one spec for every replica: the jitted programs are memoized per
# (spec, geometry), so three replicas share ONE compile set
sd = build_gpt(cfg, batch=2, seq_len=8, seed=0)
spec = gpt_paged_spec(sd, cfg)

# -- 1. three replicas behind one front door ----------------------------
replicas = [FleetReplica(f"r{i}", server=PagedGenerativeServer(
                spec, max_slots=4, block_size=BS, max_seq_len=MSL,
                warmup=(i == 0)))          # warm once, share the cache
            for i in range(3)]
router = FleetRouter(replicas, retry_budget=4, poll_interval_s=0.05)
print(f"fleet up: {len(replicas)} replicas, block_size={router.block_size}")

# -- 2. kill a replica under open-loop repeated-prefix traffic ----------
pool = [np.arange(BS, dtype=np.int32),
        (np.arange(BS, dtype=np.int32) * 3 + 1) % VOCAB]
gen = FleetLoadGenerator(router.generate, vocab_size=VOCAB, seed=0,
                         prompt_len=(1, 6), new_tokens=(2, 6),
                         prefix_pool=pool, prefix_p=0.8)
killer = threading.Timer(0.25, replicas[2].kill)
killer.start()
res = gen.run_open(n_requests=24, rate_rps=60.0)
killer.join()
assert replicas[2].state == "dead"
assert res.n_failed == 0, f"healthy requests failed: {res.n_failed}"
assert res.n_ok == 24
print(f"chaos drill: r2 killed mid-traffic -> {res.n_ok}/24 ok, "
      f"0 failed ({res.retries_total} router retries; readiness "
      f"polling routes around the corpse between scrapes)")
print(f"  per replica: {res.by_replica()}")

# -- 3. rolling canaried reload over the survivors ----------------------
report = RollingDeploy(
    router, probes=[(np.arange(6, dtype=np.int32), 4, None)],
    drain_timeout_s=30.0).run(canary="r0")
assert report["ok"], report
print(f"rolling deploy: canary {report['canary']} gated, "
      f"rolled {report['rolled']} in {report['seconds']:.2f}s "
      f"({report['probes']} shadow-eval probe(s), token-matched)")

# -- 4. post-deploy traffic + the fleet record --------------------------
res2 = FleetLoadGenerator(router.generate, vocab_size=VOCAB, seed=1,
                          prompt_len=(1, 6), new_tokens=(2, 6),
                          prefix_pool=pool,
                          prefix_p=0.8).run_open(n_requests=12,
                                                 rate_rps=60.0)
assert res2.n_failed == 0 and res2.n_ok == 12
rec = router.metrics.to_record()
print(f"post-deploy: {res2.n_ok}/12 ok on the new model")
print(f"fleet record: {rec['fleet']['n_ready']}/"
      f"{rec['fleet']['n_replicas']} ready, affinity hit rate "
      f"{rec['fleet']['affinity_hit_rate']:.0%}, "
      f"{rec['counters']['replica_deaths_seen']} death(s) seen, "
      f"{rec['counters']['deploys']} deploy(s)")
print(res.stats())

for r in replicas:
    if r.alive:
        r.stop(drain=True)
print("fleet drained and stopped: zero failed healthy requests end to end")
