"""Quickstart: an MLP on (synthetic) MNIST through the layer API.

The whole train step (forward + backward + updater) compiles to ONE XLA
computation; with DeviceCachedIterator each EPOCH is one dispatch.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))   # run from a source checkout

import numpy as np

from deeplearning4j_tpu.dataset import DeviceCachedIterator, load_mnist
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.nn import (DenseLayer, InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration, OutputLayer)


def main():
    X, y = load_mnist(train=True, n_synthetic=4096)
    Y = np.eye(10, dtype=np.float32)[y]
    X = X.reshape(len(X), -1)

    conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-3))
            .list()
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(DenseLayer(n_out=128, activation="relu"))
            .layer(OutputLayer(n_out=10, loss_function="MCXENT"))
            .set_input_type(InputType.feed_forward(784))
            .build())
    net = MultiLayerNetwork(conf).init()
    print(net.summary())

    it = DeviceCachedIterator(X, Y, batch_size=128)
    history = net.fit(it, epochs=5)
    print("final loss:", round(history.final_loss(), 4))

    ev = net.evaluate(X[:1024], Y[:1024])
    print(ev.stats())


if __name__ == "__main__":
    main()
