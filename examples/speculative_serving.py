"""Fast decode: speculative decoding + seeded sampling + int8 KV.

What this shows (docs/serving.md "Decode speed"):

1. train a tiny GPT target and a SMALLER draft on the same vocabulary,
   then pair them: ``GenerativeServer(spec, draft_spec=..., speculate_k
   =4)`` — per round the draft proposes K tokens per active slot and
   the target checks the whole window in ONE batched verify dispatch;
2. temp-0 output is bit-identical to the non-speculative server AND to
   unbatched ``greedy_decode`` — the draft only sets the acceptance
   rate (how many tokens land per round), never the tokens;
3. seeded sampling: ``submit(..., temperature=0.9, seed=7)`` draws on
   the host keyed by (seed, absolute token index) — the same request
   replays identically whatever shares the batch;
4. the lint-time companion: ``analyze_speculation_config`` names a
   broken pairing (vocab mismatch = error) before any server exists;
5. int8 KV on the paged tier: the same byte budget holds ~4x the
   token capacity (``kv_dtype`` drives the pool's bytes-per-block).
"""
import dataclasses

import numpy as np

from deeplearning4j_tpu.analyze import analyze_speculation_config
from deeplearning4j_tpu.autodiff import TrainingConfig
from deeplearning4j_tpu.dataset import DeviceCachedIterator
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.serving.generative import (GenerativeServer,
                                                   greedy_decode)
from deeplearning4j_tpu.serving.paged import PagedGenerativeServer
from deeplearning4j_tpu.zoo.gpt import (GPTConfig, build_gpt,
                                        gpt_generative_spec,
                                        gpt_paged_spec)

VOCAB, SEQ = 96, 16
cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_seq_len=32)
draft_cfg = dataclasses.replace(cfg, hidden_size=16, num_layers=1,
                                intermediate_size=32)

# -- 1. train target + draft on the same tokens -------------------------
rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
tgt = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
tc = lambda: TrainingConfig(updater=Adam(1e-3),              # noqa: E731
                            data_set_feature_mapping=["input_ids"],
                            data_set_label_mapping=["targets"])
sd = build_gpt(cfg, batch=4, seq_len=SEQ, seed=0)
sd.training_config = tc()
sd.fit(DeviceCachedIterator([ids], [tgt], batch_size=4), epochs=2)
draft_sd = build_gpt(draft_cfg, batch=4, seq_len=SEQ, seed=1)
draft_sd.training_config = tc()
draft_sd.fit(DeviceCachedIterator([ids], [tgt], batch_size=4), epochs=2)

# -- 2. lint the pairing before building anything -----------------------
spec = gpt_generative_spec(sd, cfg)
draft = gpt_generative_spec(draft_sd, draft_cfg)
report = analyze_speculation_config(spec, draft)
assert not report.findings, report.render()
bad = gpt_generative_spec(
    build_gpt(dataclasses.replace(draft_cfg, vocab_size=48),
              batch=2, seq_len=4, seed=2),
    dataclasses.replace(draft_cfg, vocab_size=48))
bad_report = analyze_speculation_config(spec, bad)
assert bad_report.errors(), "vocab mismatch must be an error finding"
print("lint:", bad_report.errors()[0].render().splitlines()[0])

# -- 3. speculative server: K drafts, ONE verify, same tokens -----------
server = GenerativeServer(spec, max_slots=4, max_seq_len=32,
                          draft_spec=draft, speculate_k=4, warmup=True)
print(f"warmup: {server.metrics.counters['warmup_compiles']} programs "
      f"(speculative={server.warmup_report['speculative']}) in "
      f"{server.warmup_report['seconds']:.2f}s")
prompts = [rng.integers(0, VOCAB, int(rng.integers(2, 12)))
           .astype(np.int32) for _ in range(6)]
budgets = [4, 12, 6, 9, 3, 10]
outs = [server.submit(p, max_new_tokens=n).result(timeout=120)
        for p, n in zip(prompts, budgets)]
for p, n, got in zip(prompts, budgets, outs):
    assert got == greedy_decode(spec, p, n, max_seq_len=32)
rec = server.metrics.to_record()["generative"]
print(f"speculation: {rec['draft_accepted']}/{rec['draft_tokens']} "
      f"draft tokens accepted ({rec['draft_acceptance_rate']:.0%}) "
      f"over {rec['spec_rounds']} rounds — all 6 greedy outputs "
      f"bit-identical to unbatched greedy_decode")
assert server.metrics.counters["compiles"] == 0   # all AOT-warmed

# -- 4. seeded sampling: reproducible whatever shares the batch ---------
a = server.submit(prompts[0], max_new_tokens=8, temperature=0.9,
                  seed=7).result(timeout=120)
b = server.submit(prompts[0], max_new_tokens=8, temperature=0.9,
                  seed=7).result(timeout=120)
c = server.submit(prompts[0], max_new_tokens=8, temperature=0.9,
                  seed=8).result(timeout=120)
assert a == b, "same (prompt, seed, temperature) must replay exactly"
print(f"sampled seed=7 twice: {a} == {b}; seed=8 differs: {c}")
server.shutdown()

# -- 5. int8 KV: ~4x paged token capacity at equal bytes ----------------
budget = 1 << 20
f32_srv = PagedGenerativeServer(gpt_paged_spec(sd, cfg), max_slots=4,
                                max_seq_len=32, block_size=8,
                                kv_hbm_bytes=budget, warmup=False)
q_srv = PagedGenerativeServer(
    gpt_paged_spec(sd, cfg, quantize_weights=True, quantize_kv=True),
    max_slots=4, max_seq_len=32, block_size=8,
    kv_hbm_bytes=budget, warmup=False)
f32_blocks = f32_srv.metrics.to_record()["paged"]["num_blocks"]
q_blocks = q_srv.metrics.to_record()["paged"]["num_blocks"]
got = q_srv.submit(prompts[0], max_new_tokens=8).result(timeout=120)
print(f"int8 KV pool: {q_blocks} blocks vs {f32_blocks} f32 blocks at "
      f"the same {budget >> 10} KiB ({q_blocks / f32_blocks:.1f}x); "
      f"int8 greedy sample: {got}")
assert q_blocks >= 2 * f32_blocks
f32_srv.shutdown()
q_srv.shutdown()
