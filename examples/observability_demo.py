"""Observability: trace spans, step-time attribution, unified metrics.

One fused-window training run with the monitor/ subsystem armed,
producing every observability artifact in one go:

- a Perfetto/chrome://tracing-loadable span trace (``trace.json``) whose
  window spans contain data-wait / dispatch / flush children and the
  stager's H2D lane;
- ``{"type": "steptime"}`` records: per-flush wall-time breakdown —
  WHERE the step time went — with rolling percentiles and an EMA
  straggler watcher;
- a unified MetricsRegistry folding the fit tier's dispatch stats and
  the step-time totals into one namespace (serving counters, checkpoint
  timings and fault events fold in the same way), exported as
  Prometheus text;
- the static HTML report grown a span-timeline swimlane and a stacked
  step-time-breakdown chart;
- ``{"type": "tensorstats"}`` records: per-layer gradient/update/param
  summaries computed INSIDE the compiled step (``TrainingConfig.
  tensorstats``) — the DL4J BaseStatsListener signal, device-side;
- the live telemetry HTTP endpoint (``MonitorListener(serve_port=0)``):
  /metrics, /healthz, /report served from the running process.

See docs/observability.md.
"""
import json
import os
import tempfile
import urllib.request

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.learning.updaters import Adam
from deeplearning4j_tpu.monitor import (MetricsRegistry, MonitorListener,
                                        StragglerWatcher,
                                        TensorStatsConfig, TRACER,
                                        enable_tracing)
from deeplearning4j_tpu.ui import StatsStorage, write_report


def build_mlp():
    rng = np.random.default_rng(0)
    sd = SameDiff()
    x = sd.placeholder("x", shape=(-1, 16))
    w0 = sd.var("w0", value=rng.normal(0, .1, (16, 32)).astype(np.float32))
    b0 = sd.var("b0", value=np.zeros(32, np.float32))
    h = sd.nn.relu(x.mmul(w0).add(b0))
    w1 = sd.var("w1", value=rng.normal(0, .1, (32, 4)).astype(np.float32))
    logits = h.mmul(w1)
    labels = sd.placeholder("labels", shape=(-1, 4))
    sd.loss.softmax_cross_entropy(logits, labels, name="loss")
    sd.set_loss_variables(["loss"])
    sd.training_config = TrainingConfig(
        updater=Adam(1e-2), data_set_feature_mapping=["x"],
        data_set_label_mapping=["labels"],
        fused_steps=8,               # the production fused-window tier
        sentinel=True,               # divergence rail shares the carry
        tensorstats=TensorStatsConfig(every_n=8))  # in-graph layer stats
    return sd


def main():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(512, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 512)]

    out_dir = tempfile.mkdtemp(prefix="observability_")
    enable_tracing(reset=True)

    storage = StatsStorage(os.path.join(out_dir, "stats.jsonl"))
    registry = MetricsRegistry()
    monitor = MonitorListener(storage, registry=registry, frequency=16,
                              straggler=StragglerWatcher(threshold=3.0),
                              serve_port=0)   # live telemetry endpoint

    sd = build_mlp()
    it = ArrayDataSetIterator(X, Y, batch_size=16)   # 32 steps/epoch
    history = sd.fit(it, epochs=3, listeners=[monitor])
    print(f"final loss: {history.final_loss():.4f}")

    # -- where did the time go? ----------------------------------------
    for rec in storage.of_type("steptime"):
        if rec.get("event") == "straggler":
            print(f"  straggler at iter {rec.get('iteration')}: "
                  f"{rec['step_s'] * 1e3:.2f} ms "
                  f"({rec['ratio']:.1f}x the EMA)")
            continue
        print(f"  steptime epoch {rec['epoch']}: {rec['steps']} steps, "
              f"data-wait {rec['data_wait_s'] * 1e3:.1f} ms, "
              f"dispatch {rec['dispatch_s'] * 1e3:.1f} ms, "
              f"flush {rec['flush_s'] * 1e3:.1f} ms "
              f"(step p50 {rec['step_ms_p50']:.2f} ms)")

    # -- per-layer training health, computed on device -----------------
    ts = storage.of_type("tensorstats")
    last = ts[-1]
    print(f"tensorstats: {len(ts)} in-graph samples; at iteration "
          f"{last['iter']}:")
    for layer, ent in sorted(last["layers"].items()):
        print(f"  {layer}: grad L2 {ent['grad_l2']:.4g}, "
              f"update:param {ent['update_ratio']:.3g}, "
              f"nonfinite {ent['grad_nonfinite']}")

    # -- one namespace over every subsystem ----------------------------
    prom = registry.to_prometheus_text()
    print("metrics (prometheus text, excerpt):")
    for line in prom.splitlines():
        if line.startswith("dl4j_fit_") or \
                line.startswith("dl4j_steptime_steps"):
            print(f"  {line}")

    # -- HBM memory telemetry (monitor/memstats.py) ---------------------
    mem = storage.of_type("memory")
    last_mem = mem[-1]
    print(f"memory: {len(mem)} samples at flush boundaries; "
          f"{last_mem['bytes_in_use'] / 2**20:.1f} MiB in use across "
          f"{len(last_mem['devices'])} device(s), tagged transfers "
          f"{ {t: f'{b / 2**20:.1f}MiB' for t, b in last_mem['tracked'].items()} }")
    from deeplearning4j_tpu.monitor import memstats
    for plan in memstats.PLANS.plans():
        print(f"  plan {plan.label}: args "
              f"{(plan.argument_bytes or 0) / 2**20:.2f} MiB, temps "
              f"{(plan.temp_bytes or 0) / 2**20:.2f} MiB, "
              f"{(plan.flops_per_step or 0) / 1e6:.1f} MFLOPs/step")

    # -- the live endpoint: scrape the running process ------------------
    server = monitor.server
    with urllib.request.urlopen(server.url + "/metrics", timeout=10) as r:
        live = r.read().decode()
    layer_series = [l for l in live.splitlines()
                    if l.startswith("dl4j_layer_grad_l2")]
    print(f"live {server.url}/metrics: {len(layer_series)} "
          f"dl4j_layer_grad_l2 series")
    with urllib.request.urlopen(server.url + "/healthz", timeout=10) as r:
        health = json.loads(r.read())
    print(f"live /healthz: fault_state={health['fault_state']}, "
          f"last step age {health['last_step_age_s']}s")
    with urllib.request.urlopen(server.url + "/memory", timeout=10) as r:
        mem_probe = json.loads(r.read())
    print(f"live /memory: {mem_probe['bytes_in_use'] / 2**20:.1f} MiB "
          f"in use, {len(mem_probe['plans'])} program plan(s), active "
          f"program {mem_probe['active_program']}")

    # -- artifacts ------------------------------------------------------
    trace_path = TRACER.write_chrome_trace(
        os.path.join(out_dir, "trace.json"))
    report_path = write_report(storage, os.path.join(out_dir,
                                                     "report.html"),
                               title="observed run")
    storage.close()
    n_spans = len(TRACER.spans())
    print(f"chrome trace: {trace_path} ({n_spans} spans — load it at "
          f"https://ui.perfetto.dev)")
    print(f"report: {report_path} (timeline swimlane + stacked "
          f"step-time breakdown)")

    assert storage.of_type("steptime") and storage.of_type("metrics")
    assert storage.of_type("tensorstats") and layer_series
    assert mem and last_mem["devices"]
    assert mem_probe["plans"], "no program memory plans captured"
    assert health["healthy"] is True
    assert any(s.name == "window" for s in TRACER.spans())
    assert np.isfinite(history.final_loss())
    server.close()
    print("observability demo complete")


if __name__ == "__main__":
    main()
