"""Vocabulary cache (reference: models/word2vec/wordstore/VocabCache +
AbstractCache — word↔index maps, frequencies, min-frequency pruning) and
the negative-sampling unigram table (reference builds the same
count^0.75 table in embeddings/learning/impl/elements/SkipGram.java's
sampling path; here it is a numpy array sampled in batches).
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabCache:
    """Word <-> index with counts. Index 0 is reserved for <unk>."""

    UNK = "<unk>"

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency
        self.word2idx: Dict[str, int] = {self.UNK: 0}
        self.idx2word: List[str] = [self.UNK]
        self.counts: Counter = Counter()

    def fit(self, sequences: Iterable[List[str]]) -> "VocabCache":
        for seq in sequences:
            self.counts.update(seq)
        for w, c in self.counts.most_common():
            if c >= self.min_word_frequency and w not in self.word2idx:
                self.word2idx[w] = len(self.idx2word)
                self.idx2word.append(w)
        return self

    # reference VocabCache method names
    def contains_word(self, word: str) -> bool:
        return word in self.word2idx

    def index_of(self, word: str) -> int:
        return self.word2idx.get(word, 0)

    def word_at_index(self, idx: int) -> str:
        return self.idx2word[idx]

    def word_frequency(self, word: str) -> int:
        return self.counts.get(word, 0)

    def num_words(self) -> int:
        return len(self.idx2word)

    def words(self) -> List[str]:
        return list(self.idx2word[1:])

    def encode(self, tokens: List[str], drop_unk: bool = True) -> np.ndarray:
        ids = [self.word2idx.get(t, 0) for t in tokens]
        if drop_unk:
            ids = [i for i in ids if i != 0]
        return np.asarray(ids, dtype=np.int32)

    def unigram_table(self, power: float = 0.75) -> np.ndarray:
        """Sampling distribution over word indices ∝ count^power
        (word2vec's negative-sampling distribution)."""
        probs = np.zeros(self.num_words(), np.float64)
        for w, i in self.word2idx.items():
            if i != 0:
                probs[i] = float(self.counts[w]) ** power
        s = probs.sum()
        return (probs / s) if s > 0 else probs

    def subsample_keep_probs(self, t: float = 1e-3) -> Optional[np.ndarray]:
        """word2vec frequent-word subsampling keep-probability per index
        (reference sampling config Word2Vec.Builder.sampling)."""
        total = sum(self.counts.values()) or 1
        keep = np.ones(self.num_words(), np.float64)
        for w, i in self.word2idx.items():
            if i == 0:
                continue
            f = self.counts[w] / total
            if f > 0:
                keep[i] = min(1.0, (np.sqrt(f / t) + 1.0) * (t / f))
        return keep
