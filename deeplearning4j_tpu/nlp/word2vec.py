"""Word2Vec / fastText / ParagraphVectors on the shared SequenceVectors
trainer.

Reference parity:
- models/sequencevectors/SequenceVectors.java:1 — the shared trainer all
  embedding models extend (Word2Vec, ParagraphVectors, DeepWalk);
- models/word2vec/Word2Vec.java:1 + embeddings/learning/impl/elements/
  SkipGram.java / CBOW.java — elements learning algorithms;
- models/fasttext/FastText.java:1 — subword n-gram embeddings;
- models/paragraphvectors/ParagraphVectors.java:1 — PV-DBOW;
- models/embeddings/loader/WordVectorSerializer.java:1 — text serde.

TPU-native redesign: the reference trains pair-at-a-time in hand-written
C++ kernels (skipgram.cpp) across Java threads. Here an epoch's
(center, context) pairs are built host-side as flat numpy arrays, and
training runs as ONE jitted batched step — gather → batched dot →
logistic loss → jax.grad → SGD — with donated embedding buffers and a
host-free linear LR decay. Negatives are drawn per-batch from the
unigram^0.75 table. Same math, MXU-shaped execution.
"""
from __future__ import annotations

import io
from typing import Dict, Iterable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor, DefaultTokenizerFactory, TokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import VocabCache


class SequenceVectors:
    """Trains input/output embedding tables over id sequences with
    negative-sampling skipgram or CBOW (SequenceVectors.java:1)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 negative: int = 5, epochs: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 batch_size: int = 2048, seed: int = 0,
                 algorithm: str = "skipgram", sampling: float = 0.0,
                 min_word_frequency: int = 1):
        if algorithm not in ("skipgram", "cbow"):
            raise ValueError(f"unknown elements learning algorithm "
                             f"{algorithm!r} (skipgram|cbow)")
        self.vector_size = vector_size
        self.window_size = window_size
        self.negative = negative
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.batch_size = batch_size
        self.seed = seed
        self.algorithm = algorithm
        self.sampling = sampling
        self.min_word_frequency = min_word_frequency
        self.syn0: Optional[np.ndarray] = None     # input vectors [V,D]
        self.syn1: Optional[np.ndarray] = None     # output vectors [V,D]
        self.loss_history: List[float] = []

    # -- pair generation (host side) -----------------------------------
    def _pairs(self, seqs: List[np.ndarray], rng: np.random.Generator,
               keep: Optional[np.ndarray]):
        centers, contexts = [], []
        for ids in seqs:
            if keep is not None and len(ids):
                ids = ids[rng.random(len(ids)) < keep[ids]]
            n = len(ids)
            for i in range(n):
                # reduced-window sampling, as word2vec does (b ~ U[1,w])
                w = int(rng.integers(1, self.window_size + 1))
                lo, hi = max(0, i - w), min(n, i + w + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(ids[i])
                        contexts.append(ids[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _cbow_batches(self, seqs, rng, keep):
        """[B,2w] padded windows + mask + targets."""
        W = 2 * self.window_size
        wins, masks, tgts = [], [], []
        for ids in seqs:
            if keep is not None and len(ids):
                ids = ids[rng.random(len(ids)) < keep[ids]]
            n = len(ids)
            for i in range(n):
                w = int(rng.integers(1, self.window_size + 1))
                ctx = [ids[j] for j in range(max(0, i - w), min(n, i + w + 1))
                       if j != i]
                if not ctx:
                    continue
                pad = W - len(ctx)
                wins.append(ctx + [0] * pad)
                masks.append([1.0] * len(ctx) + [0.0] * pad)
                tgts.append(ids[i])
        return (np.asarray(wins, np.int32), np.asarray(masks, np.float32),
                np.asarray(tgts, np.int32))

    # -- the jitted step ------------------------------------------------
    def _make_step(self):
        from deeplearning4j_tpu.ops import registry
        loss_op = registry.get_op(
            "skipgram_ns_loss" if self.algorithm == "skipgram"
            else "cbow_ns_loss").fn

        if self.algorithm == "skipgram":
            def loss_fn(tables, centers, contexts, negs, mask):
                return loss_op(tables[0], tables[1], centers, contexts,
                               negs)
        else:
            def loss_fn(tables, wins, tgts, negs, mask):
                return loss_op(tables[0], tables[1], wins, tgts, negs,
                               mask=mask)

        @jax.jit
        def step(tables, acc, a, b, negs, mask, lr):
            # AdaGrad per table: batching replaces the reference's
            # per-pair SGD with few large steps, and a fixed lr there
            # under-trains by ~batch_size; the accumulator restores
            # per-coordinate step sizes invariant to the batching
            loss, grads = jax.value_and_grad(loss_fn)(tables, a, b, negs,
                                                      mask)
            new_acc = tuple(ac + g * g for ac, g in zip(acc, grads))
            new = tuple(t - lr * g / jnp.sqrt(ac + 1e-8)
                        for t, g, ac in zip(tables, grads, new_acc))
            return new, new_acc, loss

        return step

    def fit_sequences(self, seqs: List[np.ndarray], vocab_size: int,
                      unigram: np.ndarray,
                      keep: Optional[np.ndarray] = None) -> None:
        rng = np.random.default_rng(self.seed)
        D, V = self.vector_size, vocab_size
        if self.syn0 is None:
            self.syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
            self.syn1 = np.zeros((V, D), np.float32)
        tables = (jnp.asarray(self.syn0), jnp.asarray(self.syn1))
        acc = tuple(jnp.zeros_like(t) for t in tables)
        step = self._make_step()
        B, K = self.batch_size, self.negative
        lr0, lr_min = self.learning_rate, self.min_learning_rate
        losses = []
        total_batches = None
        done_batches = 0
        for epoch in range(self.epochs):
            if self.algorithm == "skipgram":
                a, b = self._pairs(seqs, rng, keep)
                mask_all = None
            else:
                a, mask_all, b = self._cbow_batches(seqs, rng, keep)
            n = len(b)
            if n == 0:
                continue
            perm = rng.permutation(n)
            a, b = a[perm], b[perm]
            if mask_all is not None:
                mask_all = mask_all[perm]
            n_batches = (n + B - 1) // B
            if total_batches is None:
                total_batches = n_batches * self.epochs
            for bi in range(n_batches):
                sl = slice(bi * B, min(n, (bi + 1) * B))
                ab, bb = a[sl], b[sl]
                nb = len(bb)
                if nb < B:     # pad to the compiled batch shape
                    reps = np.concatenate([np.arange(nb)] * ((B // nb) + 1))
                    idx = reps[:B]
                    ab, bb = ab[idx], bb[idx]
                    mb = mask_all[sl][idx] if mask_all is not None else None
                else:
                    mb = mask_all[sl] if mask_all is not None else None
                negs = rng.choice(len(unigram), size=(B, K),
                                  p=unigram).astype(np.int32)
                frac = done_batches / max(1, total_batches)
                lr = max(lr_min, lr0 * (1.0 - frac))
                tables, acc, loss = step(tables, acc, ab, bb, negs, mb,
                                         np.float32(lr))
                losses.append(loss)
                done_batches += 1
        if losses:
            self.loss_history = [float(x) for x in
                                 np.asarray(jnp.stack(losses))]
        self.syn0 = np.asarray(tables[0])
        self.syn1 = np.asarray(tables[1])


class WordVectors:
    """Lookup API shared by all trained models (reference:
    embeddings/wordvectors/WordVectors.java interface)."""

    _normed: Optional[np.ndarray] = None     # subclasses set their own init

    def __init__(self, vocab: VocabCache, vectors: np.ndarray):
        self.vocab = vocab
        self.vectors = vectors
        self._normed = None

    def has_word(self, word: str) -> bool:
        return self.vocab.contains_word(word)

    def get_word_vector(self, word: str) -> np.ndarray:
        return self.vectors[self.vocab.index_of(word)]

    def _norm(self):
        if self._normed is None or len(self._normed) != len(self.vectors):
            n = np.linalg.norm(self.vectors, axis=1, keepdims=True)
            self._normed = self.vectors / np.maximum(n, 1e-9)
        return self._normed

    def similarity(self, a: str, b: str) -> float:
        n = self._norm()
        return float(n[self.vocab.index_of(a)]
                     @ n[self.vocab.index_of(b)])

    def words_nearest(self, word_or_vec: Union[str, np.ndarray],
                      top_n: int = 10, exclude: Sequence[str] = ()) -> List[str]:
        n = self._norm()
        if isinstance(word_or_vec, str):
            exclude = set(exclude) | {word_or_vec}
            q = n[self.vocab.index_of(word_or_vec)]
        else:
            exclude = set(exclude)
            q = np.asarray(word_or_vec, np.float32)
            q = q / max(np.linalg.norm(q), 1e-9)
        sims = n @ q
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w != VocabCache.UNK and w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out

    def analogy(self, a: str, b: str, c: str, top_n: int = 5) -> List[str]:
        """a : b :: c : ?   (king - man + woman -> queen)."""
        n = self._norm()
        q = (n[self.vocab.index_of(b)] - n[self.vocab.index_of(a)]
             + n[self.vocab.index_of(c)])
        return self.words_nearest(q, top_n, exclude=(a, b, c))


class Word2Vec(WordVectors):
    """reference: models/word2vec/Word2Vec.java:1 (builder names match
    the reference's camelCase builder, snake_cased)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 negative: int = 5, epochs: int = 1,
                 learning_rate: float = 0.025,
                 min_learning_rate: float = 1e-4,
                 min_word_frequency: int = 1, batch_size: int = 2048,
                 seed: int = 0, algorithm: str = "skipgram",
                 sampling: float = 0.0,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.trainer = SequenceVectors(
            vector_size=vector_size, window_size=window_size,
            negative=negative, epochs=epochs, learning_rate=learning_rate,
            min_learning_rate=min_learning_rate, batch_size=batch_size,
            seed=seed, algorithm=algorithm, sampling=sampling,
            min_word_frequency=min_word_frequency)
        self.tokenizer_factory = tokenizer_factory or \
            DefaultTokenizerFactory(CommonPreprocessor())
        self.vocab = VocabCache(min_word_frequency)
        self.vectors = None

    # reference API: builder()
    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self
            return setter

        def build(self) -> "Word2Vec":
            kw = dict(self._kw)
            kw.setdefault("vector_size", kw.pop("layer_size", 100))
            kw.setdefault("epochs", kw.pop("iterations", 1))
            kw.setdefault("negative", kw.pop("negative_sample", 5))
            return Word2Vec(**kw)

    @staticmethod
    def builder() -> "Word2Vec.Builder":
        return Word2Vec.Builder()

    def fit(self, sentences: Iterable[str]) -> "Word2Vec":
        tok = [self.tokenizer_factory.create(s).get_tokens()
               for s in sentences]
        self.vocab.fit(tok)
        seqs = [self.vocab.encode(t) for t in tok]
        keep = (self.vocab.subsample_keep_probs(self.trainer.sampling)
                if self.trainer.sampling > 0 else None)
        self.trainer.fit_sequences(seqs, self.vocab.num_words(),
                                   self.vocab.unigram_table(), keep)
        self.vectors = self.trainer.syn0
        self._normed = None
        return self

    @property
    def loss_history(self):
        return self.trainer.loss_history


class FastText(WordVectors):
    """Subword-augmented skipgram (reference: models/fasttext/
    FastText.java:1): a word's input vector is its word vector plus the
    mean of hashed char-n-gram bucket vectors, so OOV words still get
    vectors at inference."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 negative: int = 5, epochs: int = 1,
                 learning_rate: float = 0.05, min_word_frequency: int = 1,
                 min_n: int = 3, max_n: int = 6, buckets: int = 2 ** 16,
                 batch_size: int = 1024, seed: int = 0):
        self.vector_size = vector_size
        self.window_size = window_size
        self.negative = negative
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.min_n, self.max_n, self.buckets = min_n, max_n, buckets
        self.batch_size = batch_size
        self.seed = seed
        self.vocab = VocabCache(min_word_frequency)
        self.vectors = None
        self.bucket_table: Optional[np.ndarray] = None
        self.syn1 = None
        self._max_ngrams = 24

    def _ngrams(self, word: str) -> List[int]:
        w = f"<{word}>"
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(w) - n + 1):
                # FNV-1a, the hash fastText uses for buckets
                h = 2166136261
                for ch in w[i:i + n].encode("utf-8"):
                    h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
                out.append(h % self.buckets)
        return out[:self._max_ngrams]

    def _word_ngram_matrix(self):
        V = self.vocab.num_words()
        M = self._max_ngrams
        ng = np.zeros((V, M), np.int32)
        mask = np.zeros((V, M), np.float32)
        for w, i in self.vocab.word2idx.items():
            if i == 0:
                continue
            ids = self._ngrams(w)
            ng[i, :len(ids)] = ids
            mask[i, :len(ids)] = 1.0
        return ng, mask

    def compose(self, word: str) -> np.ndarray:
        """Word vector incl. subwords; works for OOV words too."""
        ids = self._ngrams(word)
        sub = (self.bucket_table[ids].mean(axis=0) if ids
               else np.zeros(self.vector_size, np.float32))
        if self.vocab.contains_word(word):
            return self.vectors[self.vocab.index_of(word)] + sub
        return sub

    def get_word_vector(self, word: str) -> np.ndarray:
        return self.compose(word)

    def fit(self, sentences: Iterable[str]) -> "FastText":
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        tok = [fac.create(s).get_tokens() for s in sentences]
        self.vocab.fit(tok)
        seqs = [self.vocab.encode(t) for t in tok]
        V, D = self.vocab.num_words(), self.vector_size
        rng = np.random.default_rng(self.seed)
        syn0 = ((rng.random((V, D)) - 0.5) / D).astype(np.float32)
        buckets = ((rng.random((self.buckets, D)) - 0.5) / D
                   ).astype(np.float32)
        syn1 = np.zeros((V, D), np.float32)
        ngram_ids, ngram_mask = self._word_ngram_matrix()
        unigram = self.vocab.unigram_table()

        def loss_fn(params, centers, contexts, negs):
            s0, bt, s1 = params
            v_w = jnp.take(s0, centers, axis=0)
            c_ng = jnp.take(ngram_ids, centers, axis=0)
            c_mask = jnp.take(ngram_mask, centers, axis=0)
            sub = jnp.einsum("bmd,bm->bd", jnp.take(bt, c_ng, axis=0),
                             c_mask)
            denom = jnp.maximum(jnp.sum(c_mask, -1, keepdims=True), 1.0)
            v_c = v_w + sub / denom
            u_o = jnp.take(s1, contexts, axis=0)
            u_n = jnp.take(s1, negs, axis=0)
            pos = jnp.einsum("bd,bd->b", v_c, u_o)
            neg = jnp.einsum("bd,bkd->bk", v_c, u_n)
            return jnp.mean(-jax.nn.log_sigmoid(pos)
                            - jnp.sum(jax.nn.log_sigmoid(-neg), -1))

        @jax.jit
        def step(params, acc, centers, contexts, negs, lr):
            loss, g = jax.value_and_grad(loss_fn)(params, centers,
                                                  contexts, negs)
            new_acc = tuple(a + gg * gg for a, gg in zip(acc, g))
            new = tuple(p - lr * gg / jnp.sqrt(a + 1e-8)
                        for p, gg, a in zip(params, g, new_acc))
            return new, new_acc, loss

        params = (jnp.asarray(syn0), jnp.asarray(buckets),
                  jnp.asarray(syn1))
        acc = tuple(jnp.zeros_like(p) for p in params)
        sv = SequenceVectors(window_size=self.window_size)
        B, K = self.batch_size, self.negative
        for _ in range(self.epochs):
            a, b = sv._pairs(seqs, rng, None)
            n = len(a)
            if n == 0:
                continue
            perm = rng.permutation(n)
            a, b = a[perm], b[perm]
            for bi in range((n + B - 1) // B):
                sl = slice(bi * B, min(n, (bi + 1) * B))
                ab, bb = a[sl], b[sl]
                if len(ab) < B:
                    idx = np.resize(np.arange(len(ab)), B)
                    ab, bb = ab[idx], bb[idx]
                negs = rng.choice(V, size=(B, K), p=unigram).astype(np.int32)
                params, acc, _ = step(params, acc, ab, bb, negs,
                                      np.float32(self.learning_rate))
        self.vectors = np.asarray(params[0])
        self.bucket_table = np.asarray(params[1])
        self.syn1 = np.asarray(params[2])
        self._normed = None
        return self


class ParagraphVectors(WordVectors):
    """PV-DBOW (reference: models/paragraphvectors/ParagraphVectors.java:1
    with DBOW learning): each document id's vector is trained to predict
    the words in the document — exactly the skipgram objective with the
    doc table as syn0."""

    def __init__(self, vector_size: int = 100, negative: int = 5,
                 epochs: int = 5, learning_rate: float = 0.025,
                 min_word_frequency: int = 1, batch_size: int = 2048,
                 seed: int = 0):
        self.trainer = SequenceVectors(
            vector_size=vector_size, negative=negative, epochs=epochs,
            learning_rate=learning_rate, batch_size=batch_size, seed=seed)
        self.vocab = VocabCache(min_word_frequency)
        self.labels: List[str] = []
        self.doc_vectors: Optional[np.ndarray] = None
        self.vectors = None

    def fit(self, documents: Iterable[str],
            labels: Optional[Sequence[str]] = None) -> "ParagraphVectors":
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        tok = [fac.create(d).get_tokens() for d in documents]
        self.labels = list(labels) if labels is not None else \
            [f"DOC_{i}" for i in range(len(tok))]
        self.vocab.fit(tok)
        seqs = [self.vocab.encode(t) for t in tok]
        n_docs = len(seqs)
        V, D = self.vocab.num_words(), self.trainer.vector_size
        rng = np.random.default_rng(self.trainer.seed)
        # centers = doc ids, contexts = word ids: reuse skipgram op with
        # syn0=[docs] and syn1=[vocab]
        centers = np.concatenate([np.full(len(s), i, np.int32)
                                  for i, s in enumerate(seqs) if len(s)])
        contexts = np.concatenate([s for s in seqs if len(s)])
        from deeplearning4j_tpu.ops import registry
        loss_op = registry.get_op("skipgram_ns_loss").fn

        @jax.jit
        def step(docs, syn1, acc, c, o, negs, lr):
            loss, (gd, g1) = jax.value_and_grad(loss_op, (0, 1))(
                docs, syn1, c, o, negs)
            ad = acc[0] + gd * gd
            a1 = acc[1] + g1 * g1
            return (docs - lr * gd / jnp.sqrt(ad + 1e-8),
                    syn1 - lr * g1 / jnp.sqrt(a1 + 1e-8), (ad, a1), loss)

        docs = ((rng.random((n_docs, D)) - 0.5) / D).astype(np.float32)
        syn1 = np.zeros((V, D), np.float32)
        docs, syn1 = jnp.asarray(docs), jnp.asarray(syn1)
        acc = (jnp.zeros_like(docs), jnp.zeros_like(syn1))
        unigram = self.vocab.unigram_table()
        B, K = self.trainer.batch_size, self.trainer.negative
        n = len(centers)
        for _ in range(self.trainer.epochs):
            perm = rng.permutation(n)
            a, b = centers[perm], contexts[perm]
            for bi in range((n + B - 1) // B):
                sl = slice(bi * B, min(n, (bi + 1) * B))
                ab, bb = a[sl], b[sl]
                if len(ab) < B:
                    idx = np.resize(np.arange(len(ab)), B)
                    ab, bb = ab[idx], bb[idx]
                negs = rng.choice(V, size=(B, K), p=unigram).astype(np.int32)
                docs, syn1, acc, _ = step(
                    docs, syn1, acc, ab, bb, negs,
                    np.float32(self.trainer.learning_rate))
        self.doc_vectors = np.asarray(docs)
        self.syn1 = np.asarray(syn1)
        self.vectors = self.doc_vectors      # WordVectors API over docs
        self._doc_vocab()
        return self

    def _doc_vocab(self):
        # label vocab maps label i -> row i of doc_vectors (no <unk> row)
        vc = VocabCache()
        vc.word2idx = {lb: i for i, lb in enumerate(self.labels)}
        vc.idx2word = list(self.labels)
        vc.counts = type(vc.counts)({lb: 1 for lb in self.labels})
        self._label_vocab = vc
        self._word_vocab = self.vocab
        self.vocab = vc

    def get_doc_vector(self, label: str) -> np.ndarray:
        return self.doc_vectors[self._label_vocab.index_of(label)]

    def infer_vector(self, text: str, steps: int = 50,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Gradient-fit a fresh doc vector against the frozen syn1
        (reference: ParagraphVectors.inferVector)."""
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        ids = self._word_vocab.encode(fac.create(text).get_tokens())
        if len(ids) == 0:
            return np.zeros(self.trainer.vector_size, np.float32)
        rng = np.random.default_rng(0)
        v = jnp.asarray(((rng.random(self.trainer.vector_size) - 0.5)
                         / self.trainer.vector_size).astype(np.float32))
        syn1 = jnp.asarray(self.syn1)
        unigram = self._word_vocab.unigram_table()
        from deeplearning4j_tpu.ops import registry
        loss_op = registry.get_op("skipgram_ns_loss").fn

        @jax.jit
        def step(vec, o, negs, lr):
            def f(vv):
                return loss_op(vv[None, :], syn1,
                               jnp.zeros(len(o), jnp.int32), o, negs)
            loss, g = jax.value_and_grad(f)(vec)
            return vec - lr * g, loss

        K = self.trainer.negative
        for _ in range(steps):
            negs = rng.choice(len(unigram), size=(len(ids), K),
                              p=unigram).astype(np.int32)
            v, _ = step(v, jnp.asarray(ids), negs,
                        np.float32(learning_rate))
        return np.asarray(v)


class WordVectorSerializer:
    """reference: embeddings/loader/WordVectorSerializer.java:1 — the
    text format 'word v1 v2 ...' (one header line 'V D')."""

    @staticmethod
    def write_word_vectors(model: WordVectors, path: str) -> None:
        vocab, vecs = model.vocab, model.vectors
        with open(path, "w", encoding="utf-8") as fh:
            words = [w for w in vocab.idx2word if w != VocabCache.UNK]
            fh.write(f"{len(words)} {vecs.shape[1]}\n")
            for w in words:
                row = " ".join(f"{x:.6f}" for x in vecs[vocab.index_of(w)])
                fh.write(f"{w} {row}\n")

    @staticmethod
    def read_word_vectors(path: str) -> WordVectors:
        with open(path, "r", encoding="utf-8") as fh:
            header = fh.readline().split()
            n, d = int(header[0]), int(header[1])
            vocab = VocabCache()
            rows = [np.zeros(d, np.float32)]       # <unk> row
            for line in fh:
                parts = line.rstrip("\n").split(" ")
                w, vals = parts[0], parts[1:]
                vocab.word2idx[w] = len(vocab.idx2word)
                vocab.idx2word.append(w)
                vocab.counts[w] = 1
                rows.append(np.asarray([float(x) for x in vals],
                                       np.float32))
        assert len(rows) - 1 == n, f"header says {n}, file has {len(rows)-1}"
        return WordVectors(vocab, np.stack(rows))
