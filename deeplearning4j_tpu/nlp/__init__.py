"""NLP subsystem (reference: deeplearning4j-nlp-parent + deeplearning4j-graph).

Word/doc/graph embeddings trained through batched negative-sampling ops
on-device (ops/nlp_ops.py), plus the tokenization and serialization APIs.
"""
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.graph_embeddings import (
    DeepWalk, Graph, Node2Vec, random_walks)
from deeplearning4j_tpu.nlp.tokenization import (
    ENGLISH_STOP_WORDS, BertWordPieceTokenizer,
    BertWordPieceTokenizerFactory, CommonPreprocessor,
    DefaultTokenizerFactory, LineSentenceIterator, LowCasePreProcessor,
    NGramTokenizerFactory, SentenceIterator, Tokenizer, TokenizerFactory,
    TokenPreProcess)
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import (
    FastText, ParagraphVectors, SequenceVectors, Word2Vec, WordVectors,
    WordVectorSerializer)

__all__ = [
    "Word2Vec", "FastText", "ParagraphVectors", "Glove", "SequenceVectors",
    "WordVectors", "WordVectorSerializer", "VocabCache", "DeepWalk",
    "Node2Vec", "Graph", "random_walks", "Tokenizer", "TokenizerFactory",
    "DefaultTokenizerFactory", "NGramTokenizerFactory", "TokenPreProcess",
    "CommonPreprocessor", "LowCasePreProcessor", "SentenceIterator",
    "LineSentenceIterator", "ENGLISH_STOP_WORDS",
    "BertWordPieceTokenizer", "BertWordPieceTokenizerFactory",
]
