"""GloVe (reference: deeplearning4j-nlp models/glove/Glove.java:1 — the
same weighted-least-squares objective over a cooccurrence table,
trained there per-pair with AdaGrad; here the table is built host-side
and batches train through the registry's glove_loss op with jax.grad
and AdaGrad accumulators, one jitted step).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor, DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import WordVectors


class Glove(WordVectors):
    def __init__(self, vector_size: int = 50, window_size: int = 5,
                 epochs: int = 20, learning_rate: float = 0.05,
                 x_max: float = 100.0, alpha: float = 0.75,
                 min_word_frequency: int = 1, batch_size: int = 4096,
                 seed: int = 0):
        self.vector_size = vector_size
        self.window_size = window_size
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max, self.alpha = x_max, alpha
        self.batch_size = batch_size
        self.seed = seed
        self.vocab = VocabCache(min_word_frequency)
        self.vectors = None

    def _cooccurrences(self, seqs):
        """Symmetric 1/d-weighted window counts (GloVe's counting rule;
        the reference accumulates the same in CoOccurrences)."""
        cooc = defaultdict(float)
        for ids in seqs:
            for i, wi in enumerate(ids):
                for j in range(max(0, i - self.window_size), i):
                    cooc[(int(wi), int(ids[j]))] += 1.0 / (i - j)
                    cooc[(int(ids[j]), int(wi))] += 1.0 / (i - j)
        rows = np.array([k[0] for k in cooc], np.int32)
        cols = np.array([k[1] for k in cooc], np.int32)
        counts = np.array(list(cooc.values()), np.float32)
        return rows, cols, counts

    def fit(self, sentences: Iterable[str]) -> "Glove":
        fac = DefaultTokenizerFactory(CommonPreprocessor())
        tok = [fac.create(s).get_tokens() for s in sentences]
        self.vocab.fit(tok)
        seqs = [self.vocab.encode(t) for t in tok]
        rows, cols, counts = self._cooccurrences(seqs)
        V, D = self.vocab.num_words(), self.vector_size
        rng = np.random.default_rng(self.seed)

        from deeplearning4j_tpu.ops import registry
        loss_op = registry.get_op("glove_loss").fn
        x_max, alpha = self.x_max, self.alpha

        def loss_fn(params, r, c, x):
            w, wt, b, bt = params
            return loss_op(w, wt, b, bt, r, c, x, x_max=x_max, alpha=alpha)

        @jax.jit
        def step(params, acc, r, c, x, lr):
            loss, grads = jax.value_and_grad(loss_fn)(params, r, c, x)
            new_acc = tuple(a + g * g for a, g in zip(acc, grads))
            new_params = tuple(
                p - lr * g / jnp.sqrt(a + 1e-8)
                for p, g, a in zip(params, grads, new_acc))
            return new_params, new_acc, loss

        init = lambda shape: ((rng.random(shape) - 0.5) / D).astype(np.float32)
        params = tuple(jnp.asarray(x) for x in
                       (init((V, D)), init((V, D)),
                        np.zeros(V, np.float32), np.zeros(V, np.float32)))
        acc = tuple(jnp.zeros_like(p) for p in params)
        B = self.batch_size
        n = len(rows)
        for _ in range(self.epochs):
            perm = rng.permutation(n)
            r, c, x = rows[perm], cols[perm], counts[perm]
            for bi in range((n + B - 1) // B):
                sl = slice(bi * B, min(n, (bi + 1) * B))
                rb, cb, xb = r[sl], c[sl], x[sl]
                if len(rb) < B:
                    idx = np.resize(np.arange(len(rb)), B)
                    rb, cb, xb = rb[idx], cb[idx], xb[idx]
                params, acc, _ = step(params, acc, rb, cb, xb,
                                      np.float32(self.learning_rate))
        # final vectors = w + w̃ (the GloVe paper's recommendation)
        self.vectors = np.asarray(params[0]) + np.asarray(params[1])
        self._normed = None
        return self
