"""Tokenization API (reference: deeplearning4j-nlp text/tokenization —
TokenizerFactory/Tokenizer/TokenPreProcess interfaces:
tokenization/tokenizerfactory/DefaultTokenizerFactory.java:1,
tokenization/tokenizer/DefaultTokenizer.java:1,
preprocessor/CommonPreprocessor.java:1) and the stopwords list
(text/stopwords/StopWords.java:1).

Same three-interface shape as the reference (factory → tokenizer →
preprocessor), python-idiomatic: tokenizers are iterables of tokens.
"""
from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional

# reference: stopwords file loaded by StopWords.getStopWords()
ENGLISH_STOP_WORDS = frozenset("""a an and are as at be but by for if in into
is it no not of on or such that the their then there these they this to was
will with""".split())


class TokenPreProcess:
    """reference: TokenPreProcess interface — one string in, one out."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference:
    CommonPreprocessor.java:1 — same regex class)."""

    _RE = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._RE.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """reference: Tokenizer interface (hasMoreTokens/nextToken/getTokens)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def get_tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self):
        return iter(self.get_tokens())


class TokenizerFactory:
    """reference: TokenizerFactory interface."""

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference: DefaultTokenizerFactory wraps
    java.util.StringTokenizer — whitespace splitting)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over a base tokenizer (reference:
    NGramTokenizerFactory.java:1)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        self._base = base
        self.min_n, self.max_n = min_n, max_n
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        toks = self._base.create(text).get_tokens()
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(toks) - n + 1):
                out.append(" ".join(toks[i:i + n]))
        return Tokenizer(out, self._pre)


class SentenceIterator:
    """reference: sentenceiterator.SentenceIterator — streams sentences;
    here any iterable of strings qualifies, this class adds reset()."""

    def __init__(self, sentences: Iterable[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        self._sentences = list(sentences)
        self._pre = preprocessor

    def __iter__(self):
        for s in self._sentences:
            yield self._pre(s) if self._pre else s

    def reset(self) -> None:      # list-backed; API parity
        pass


class LineSentenceIterator(SentenceIterator):
    """reference: LineSentenceIterator — one sentence per file line."""

    def __init__(self, path: str, preprocessor=None):
        with open(path, "r", encoding="utf-8") as fh:
            super().__init__([ln.strip() for ln in fh if ln.strip()],
                             preprocessor)
