"""Tokenization API (reference: deeplearning4j-nlp text/tokenization —
TokenizerFactory/Tokenizer/TokenPreProcess interfaces:
tokenization/tokenizerfactory/DefaultTokenizerFactory.java:1,
tokenization/tokenizer/DefaultTokenizer.java:1,
preprocessor/CommonPreprocessor.java:1) and the stopwords list
(text/stopwords/StopWords.java:1).

Same three-interface shape as the reference (factory → tokenizer →
preprocessor), python-idiomatic: tokenizers are iterables of tokens.
"""
from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional

# reference: stopwords file loaded by StopWords.getStopWords()
ENGLISH_STOP_WORDS = frozenset("""a an and are as at be but by for if in into
is it no not of on or such that the their then there these they this to was
will with""".split())


class TokenPreProcess:
    """reference: TokenPreProcess interface — one string in, one out."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (reference:
    CommonPreprocessor.java:1 — same regex class)."""

    _RE = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._RE.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class Tokenizer:
    """reference: Tokenizer interface (hasMoreTokens/nextToken/getTokens)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        self._tokens = tokens
        self._pre = preprocessor

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre

    def get_tokens(self) -> List[str]:
        out = []
        for t in self._tokens:
            if self._pre is not None:
                t = self._pre.pre_process(t)
            if t:
                out.append(t)
        return out

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self):
        return iter(self.get_tokens())


class TokenizerFactory:
    """reference: TokenizerFactory interface."""

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre: TokenPreProcess) -> None:
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenizer (reference: DefaultTokenizerFactory wraps
    java.util.StringTokenizer — whitespace splitting)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self._pre = preprocessor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text.split(), self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    """Word n-grams over a base tokenizer (reference:
    NGramTokenizerFactory.java:1)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        self._base = base
        self.min_n, self.max_n = min_n, max_n
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        toks = self._base.create(text).get_tokens()
        out = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(toks) - n + 1):
                out.append(" ".join(toks[i:i + n]))
        return Tokenizer(out, self._pre)


class SentenceIterator:
    """reference: sentenceiterator.SentenceIterator — streams sentences;
    here any iterable of strings qualifies, this class adds reset()."""

    def __init__(self, sentences: Iterable[str],
                 preprocessor: Optional[Callable[[str], str]] = None):
        self._sentences = list(sentences)
        self._pre = preprocessor

    def __iter__(self):
        for s in self._sentences:
            yield self._pre(s) if self._pre else s

    def reset(self) -> None:      # list-backed; API parity
        pass


class LineSentenceIterator(SentenceIterator):
    """reference: LineSentenceIterator — one sentence per file line."""

    def __init__(self, path: str, preprocessor=None):
        with open(path, "r", encoding="utf-8") as fh:
            super().__init__([ln.strip() for ln in fh if ln.strip()],
                             preprocessor)


# ---------------------------------------------------------------------------
# BERT WordPiece (reference: deeplearning4j-nlp
# tokenization.tokenizer.BertWordPieceTokenizer +
# tokenizerfactory.BertWordPieceTokenizerFactory — greedy longest-match
# wordpiece over a BERT vocab.txt, '##' continuation prefix, [UNK]
# fallback, optional lower-casing basic tokenization first)
# ---------------------------------------------------------------------------

class BertWordPieceTokenizer(Tokenizer):
    """Tokenize one string into wordpieces (reference:
    BertWordPieceTokenizer.java)."""

    def __init__(self, text: str, vocab: dict, lower_case: bool = True,
                 unk_token: str = "[UNK]",
                 max_chars_per_word: int = 100):
        pieces = []
        for word in _basic_tokenize(text, lower_case):
            pieces.extend(_wordpiece(word, vocab, unk_token,
                                     max_chars_per_word))
        super().__init__(pieces)


def _basic_tokenize(text: str, lower_case: bool) -> List[str]:
    """Whitespace + punctuation splitting (reference: the
    BasicTokenizer step inside BertWordPieceTokenizer)."""
    if lower_case:
        text = text.lower()
    out = []
    word = []
    for ch in text:
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif not ch.isalnum():
            # every punctuation char splits and stands alone, matching
            # BERT's BasicTokenizer (contractions become don ' t)
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


def _wordpiece(word: str, vocab: dict, unk: str,
               max_chars: int) -> List[str]:
    """Greedy longest-match-first subword split."""
    if len(word) > max_chars:
        return [unk]
    pieces = []
    start = 0
    while start < len(word):
        end = len(word)
        cur = None
        while start < end:
            sub = word[start:end]
            if start > 0:
                sub = "##" + sub
            if sub in vocab:
                cur = sub
                break
            end -= 1
        if cur is None:
            return [unk]
        pieces.append(cur)
        start = end
    return pieces


class BertWordPieceTokenizerFactory(TokenizerFactory):
    """(reference: BertWordPieceTokenizerFactory.java — built from a
    BERT vocab.txt; exposes the vocab and encodes to ids)."""

    def __init__(self, vocab=None, vocab_path: str = None,
                 lower_case: bool = True, unk_token: str = "[UNK]"):
        if (vocab is None) == (vocab_path is None):
            raise ValueError("pass exactly one of vocab= or vocab_path=")
        if vocab_path is not None:
            with open(vocab_path, encoding="utf-8") as fh:
                tokens = [ln.rstrip("\n") for ln in fh]
            vocab = {t: i for i, t in enumerate(tokens) if t}
        elif not isinstance(vocab, dict):
            vocab = {t: i for i, t in enumerate(vocab)}
        self.vocab = vocab
        self.lower_case = lower_case
        self.unk_token = unk_token
        self._pre = None

    def create(self, text: str) -> BertWordPieceTokenizer:
        t = BertWordPieceTokenizer(text, self.vocab, self.lower_case,
                                   self.unk_token)
        if self._pre is not None:
            t.set_token_pre_processor(self._pre)
        return t

    def encode(self, text: str, add_special_tokens: bool = True,
               max_len: int = None):
        """Token ids, BERT-style: [CLS] ... [SEP] when the specials are
        in the vocab; pads with [PAD] to max_len when given."""
        toks = self.create(text).get_tokens()
        ids = [self.vocab.get(t, self.vocab.get(self.unk_token, 0))
               for t in toks]
        specials = add_special_tokens and "[CLS]" in self.vocab
        if max_len is not None and specials:
            # truncate BEFORE the specials so [SEP] survives over-length
            # inputs (BERT sequence structure must stay intact)
            ids = ids[:max(max_len - 2, 0)]
        if specials:
            ids = [self.vocab["[CLS]"]] + ids + [self.vocab.get("[SEP]",
                                                                0)]
        if max_len is not None:
            pad = self.vocab.get("[PAD]", 0)
            ids = ids[:max_len] + [pad] * max(max_len - len(ids), 0)
        return ids
