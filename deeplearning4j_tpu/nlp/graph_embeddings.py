"""Graph vertex embeddings: DeepWalk / node2vec.

Reference parity: deeplearning4j-graph —
graph/api + graph/graph/Graph.java (adjacency-list graph),
graph/iterator/RandomWalkIterator.java (uniform walks),
graph/models/deepwalk/DeepWalk.java:1 (walks -> skipgram; the reference
trains hierarchical softmax per-pair, here walks feed the SAME batched
negative-sampling SequenceVectors trainer Word2Vec uses — one shared
trainer, as the reference shares SequenceVectors).
node2vec's p/q-biased second-order walks (models/node2vec) are the
``p``/``q`` parameters; p=q=1 reduces to DeepWalk.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import SequenceVectors, WordVectors


class Graph:
    """Undirected adjacency-list graph (reference: graph/graph/Graph.java)."""

    def __init__(self, n_vertices: int,
                 edges: Sequence[Tuple[int, int]] = ()):
        self.n = n_vertices
        self.adj: List[List[int]] = [[] for _ in range(n_vertices)]
        for a, b in edges:
            self.add_edge(a, b)

    def add_edge(self, a: int, b: int) -> None:
        self.adj[a].append(b)
        self.adj[b].append(a)

    def degree(self, v: int) -> int:
        return len(self.adj[v])

    def neighbors(self, v: int) -> List[int]:
        return self.adj[v]


def random_walks(graph: Graph, walk_length: int, walks_per_vertex: int,
                 rng: np.random.Generator, p: float = 1.0,
                 q: float = 1.0) -> List[np.ndarray]:
    """Uniform (p=q=1) or node2vec-biased second-order walks."""
    walks = []
    for _ in range(walks_per_vertex):
        for start in rng.permutation(graph.n):
            if not graph.adj[start]:
                continue
            walk = [int(start)]
            prev = None
            while len(walk) < walk_length:
                cur = walk[-1]
                nbrs = graph.adj[cur]
                if not nbrs:
                    break
                if prev is None or (p == 1.0 and q == 1.0):
                    nxt = nbrs[int(rng.integers(len(nbrs)))]
                else:
                    # node2vec: 1/p back, 1 common, 1/q outward
                    prev_nbrs = set(graph.adj[prev])
                    w = np.array([1.0 / p if nb == prev
                                  else (1.0 if nb in prev_nbrs
                                        else 1.0 / q) for nb in nbrs])
                    w /= w.sum()
                    nxt = nbrs[int(rng.choice(len(nbrs), p=w))]
                prev = cur
                walk.append(int(nxt))
            walks.append(np.asarray(walk, np.int32))
    return walks


class DeepWalk(WordVectors):
    """reference: models/deepwalk/DeepWalk.java:1 (builder:
    windowSize/vectorSize/learningRate; fit(graph, walkLength))."""

    def __init__(self, vector_size: int = 64, window_size: int = 4,
                 walk_length: int = 20, walks_per_vertex: int = 10,
                 negative: int = 5, epochs: int = 3,
                 learning_rate: float = 0.025, seed: int = 0,
                 p: float = 1.0, q: float = 1.0,
                 batch_size: int = 2048):
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.p, self.q = p, q
        self.trainer = SequenceVectors(
            vector_size=vector_size, window_size=window_size,
            negative=negative, epochs=epochs, learning_rate=learning_rate,
            batch_size=batch_size, seed=seed)
        self.vectors = None
        self.vocab: Optional[VocabCache] = None

    def fit(self, graph: Graph) -> "DeepWalk":
        rng = np.random.default_rng(self.trainer.seed)
        # vertex ids shift by 1: VocabCache reserves index 0 for <unk>
        walks = [w + 1 for w in random_walks(
            graph, self.walk_length, self.walks_per_vertex, rng,
            self.p, self.q)]
        vc = VocabCache()
        vc.word2idx = {VocabCache.UNK: 0}
        vc.idx2word = [VocabCache.UNK]
        for v in range(graph.n):
            vc.word2idx[str(v)] = v + 1
            vc.idx2word.append(str(v))
            vc.counts[str(v)] = max(1, graph.degree(v))
        self.vocab = vc
        self.trainer.fit_sequences(walks, graph.n + 1,
                                   vc.unigram_table())
        self.vectors = self.trainer.syn0
        self._normed = None
        return self

    def vertex_vector(self, v: int) -> np.ndarray:
        return self.vectors[v + 1]

    def similarity_vertex(self, a: int, b: int) -> float:
        return self.similarity(str(a), str(b))


class Node2Vec(DeepWalk):
    """p/q-biased DeepWalk (reference: models/node2vec)."""

    def __init__(self, p: float = 1.0, q: float = 0.5, **kw):
        super().__init__(p=p, q=q, **kw)
