"""Pairwise / broadcastable binary ops.

Reference parity: legacy PAIRWISE/BROADCAST families (loops/legacy_ops.h)
and declarable broadcastables (ops/declarable/generic/broadcastable/*.cpp).
Broadcasting is numpy-style (the reference implements the same semantics via
its TAD/broadcast machinery).
"""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_P = "pairwise"


def _reg(name, fn, aliases=()):
    op(name, _P, n_inputs=2, aliases=aliases)(fn)


_reg("add", jnp.add)
_reg("subtract", jnp.subtract, aliases=("sub",))
_reg("multiply", jnp.multiply, aliases=("mul",))
_reg("divide", jnp.divide, aliases=("div",))
_reg("reversesubtract", lambda a, b: b - a, aliases=("rsub",))
_reg("reversedivide", lambda a, b: b / a, aliases=("rdiv",))
_reg("floordiv", jnp.floor_divide)
_reg("floormod", lambda a, b: a - jnp.floor(a / b) * b)
_reg("fmod", jnp.fmod)  # C-style sign semantics, matching NDArray.fmod
_reg("mod", jnp.mod)
_reg("pow_pairwise", jnp.power)
_reg("maximum", jnp.maximum, aliases=("max_pairwise",))
_reg("minimum", jnp.minimum, aliases=("min_pairwise",))
_reg("atan2", jnp.arctan2)
_reg("squaredsubtract", lambda a, b: jnp.square(a - b), aliases=("squareddifference",))
_reg("hypot", jnp.hypot)
_reg("copysign", jnp.copysign)
_reg("truncatediv", lambda a, b: jnp.trunc(a / b))
_reg("divide_no_nan", lambda a, b: jnp.where(b == 0, jnp.zeros_like(a), a / jnp.where(b == 0, 1, b)))

# comparisons → BOOL output (reference: broadcastable/greater.cpp etc.)
_reg("greater", jnp.greater, aliases=("gt",))
_reg("greater_equal", jnp.greater_equal, aliases=("gte",))
_reg("less", jnp.less, aliases=("lt",))
_reg("less_equal", jnp.less_equal, aliases=("lte",))
_reg("equals", jnp.equal, aliases=("eq",))
_reg("not_equals", jnp.not_equal, aliases=("neq",))

# boolean
_reg("boolean_and", jnp.logical_and, aliases=("and",))
_reg("boolean_or", jnp.logical_or, aliases=("or",))
_reg("boolean_xor", jnp.logical_xor, aliases=("xor",))


@op("igamma", _P, n_inputs=2)
def igamma(a, x):
    import jax.scipy.special as sp
    return sp.gammainc(a, x)


@op("igammac", _P, n_inputs=2)
def igammac(a, x):
    import jax.scipy.special as sp
    return sp.gammaincc(a, x)


@op("axpy", _P, n_inputs=2)
def axpy(x, y, alpha: float = 1.0):
    return alpha * x + y
