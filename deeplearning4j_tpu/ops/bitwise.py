"""Bitwise ops.

Reference parity: ops/declarable/generic/bitwise/ (and, or, xor, shifts,
cyclic shifts, bits_hamming_distance) and SDBitwise namespace.
"""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_B = "bitwise"


@op("bitwise_and", _B, n_inputs=2, differentiable=False)
def bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@op("bitwise_or", _B, n_inputs=2, differentiable=False)
def bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@op("bitwise_xor", _B, n_inputs=2, differentiable=False)
def bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@op("bitwise_not", _B, n_inputs=1, differentiable=False)
def bitwise_not(a):
    return jnp.bitwise_not(a)


@op("shift_left", _B, n_inputs=2, differentiable=False, aliases=("shift_bits",))
def shift_left(a, n):
    return jnp.left_shift(a, n)


@op("shift_right", _B, n_inputs=2, differentiable=False, aliases=("rshift_bits",))
def shift_right(a, n):
    return jnp.right_shift(a, n)


@op("cyclic_shift_left", _B, n_inputs=2, differentiable=False, aliases=("cyclic_shift_bits",))
def cyclic_shift_left(a, n):
    bits = a.dtype.itemsize * 8
    return jnp.bitwise_or(jnp.left_shift(a, n), jnp.right_shift(a, bits - n))


@op("cyclic_shift_right", _B, n_inputs=2, differentiable=False, aliases=("cyclic_rshift_bits",))
def cyclic_shift_right(a, n):
    bits = a.dtype.itemsize * 8
    return jnp.bitwise_or(jnp.right_shift(a, n), jnp.left_shift(a, bits - n))


@op("bits_hamming_distance", _B, n_inputs=2, differentiable=False)
def bits_hamming_distance(a, b):
    return _popcount_sum(jnp.bitwise_xor(a, b))


def _popcount_sum(x):
    bits = x.dtype.itemsize * 8
    count = jnp.zeros_like(x)
    for shift in range(bits):
        count = count + jnp.bitwise_and(jnp.right_shift(x, shift), 1)
    return jnp.sum(count.astype(jnp.int32))


@op("toggle_bits", _B, n_inputs=1, differentiable=False)
def toggle_bits(a):
    return jnp.bitwise_not(a)
