"""Op-breadth wave 2: creation/shape tail, scalar comparisons, SRU +
static/dynamic RNN wrappers, pooling/conv tail, loss with_logits
variants, and reference-name aliases.

Reference parity: libnd4j/include/ops/declarable/generic — each section
cites its directory. The reference's *_bp ops are intentionally absent
everywhere in this framework: gradients come from jax.grad of the
forward definitions (SURVEY §3), so a _bp op would be dead code.
Coverage enforced by the ledger gate (tests/test_op_ledger.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import add_alias, op
# the alias block at the bottom points at ops these modules register;
# importing them here keeps direct `import breadth2` working too
from deeplearning4j_tpu.ops import (  # noqa: F401
    elementwise as _elementwise, image as _image, linalg as _linalg,
    nn_ops as _nn_ops, pairwise as _pairwise, shape_ops as _shape_ops)

_S = "shape"
_E = "elementwise"
_P = "pairwise"
_N = "nn"
_L = "loss"
_I = "image"
_LA = "linalg"


# ---------------------------------------------------------------------------
# creation / shape tail (reference: generic/shape, generic/parity_ops)
# ---------------------------------------------------------------------------

@op("ones_as", _S, n_inputs=1, differentiable=False)
def ones_as(x):
    """(reference: shape/ones_as.cpp)"""
    return jnp.ones_like(x)


@op("zeros_as", _S, n_inputs=1, differentiable=False)
def zeros_as(x):
    """(reference: shape/zeros_as.cpp)"""
    return jnp.zeros_like(x)


@op("fill_as", _S, n_inputs=1, differentiable=False)
def fill_as(x, value):
    """(reference: parity_ops/fill_as.cpp)"""
    return jnp.full_like(x, value)


@op("create", _S, n_inputs=0, differentiable=False)
def create(shape, dtype="float32"):
    """(reference: parity_ops/create.cpp — zero-initialized array)"""
    return jnp.zeros(tuple(shape), dtype)


@op("reshapeas", _S, n_inputs=2)
def reshapeas(x, y):
    """(reference: shape/reshape_as.cpp)"""
    return jnp.reshape(x, y.shape)


@op("size_at", _S, n_inputs=1, differentiable=False)
def size_at(x, dim: int):
    """(reference: shape/size_at.cpp)"""
    return jnp.asarray(x.shape[dim], jnp.int64)


@op("shapes_of", _S, differentiable=False)
def shapes_of(*xs):
    """(reference: shape/shapes_of.cpp) — shape vectors of every input."""
    outs = tuple(jnp.asarray(x.shape, jnp.int64) for x in xs)
    return outs if len(outs) > 1 else outs[0]


@op("set_shape", _S, n_inputs=1, differentiable=False)
def set_shape(x, shape):
    """(reference: shape/set_shape.cpp) — reshape with size validation."""
    shape = tuple(int(s) for s in shape)
    if int(np.prod(shape)) != int(np.prod(x.shape)):
        raise ValueError(f"set_shape {shape} incompatible with {x.shape}")
    return jnp.reshape(x, shape)


@op("broadcast_dynamic_shape", _S, n_inputs=2, differentiable=False)
def broadcast_dynamic_shape(s1, s2):
    """(reference: parity_ops/broadcast_dynamic_shape.cpp)"""
    out = np.broadcast_shapes(tuple(int(v) for v in np.asarray(s1)),
                              tuple(int(v) for v in np.asarray(s2)))
    return jnp.asarray(out, jnp.int64)


@op("noop", _S, differentiable=False)
def noop(*xs):
    """(reference: parity_ops/noop.cpp)"""
    return jnp.zeros((), jnp.int32)


@op("expose", _S, n_inputs=1)
def expose(x):
    """(reference: parity_ops/expose.cpp — identity exposure of a var
    into the active scope)"""
    return jnp.asarray(x)


@op("unique_with_counts", _S, n_inputs=1, differentiable=False)
def unique_with_counts(x, size: int = None):
    """(reference: parity_ops/unique.cpp second output set)"""
    vals, idx, counts = jnp.unique(x, return_inverse=True,
                                   return_counts=True, size=size)
    return vals, idx, counts


# ---------------------------------------------------------------------------
# scalar comparisons (reference: generic/boolean/*_scalar.cpp)
# ---------------------------------------------------------------------------

def _scalar_cmp(name, fn):
    @op(name, "elementwise", n_inputs=1, differentiable=False)
    def cmp(x, scalar=0.0, _fn=fn):
        return _fn(x, scalar)
    return cmp


eq_scalar = _scalar_cmp("eq_scalar", lambda x, s: jnp.equal(x, s))
neq_scalar = _scalar_cmp("neq_scalar", lambda x, s: jnp.not_equal(x, s))
gt_scalar = _scalar_cmp("gt_scalar", lambda x, s: jnp.greater(x, s))
gte_scalar = _scalar_cmp("gte_scalar",
                         lambda x, s: jnp.greater_equal(x, s))
lt_scalar = _scalar_cmp("lt_scalar", lambda x, s: jnp.less(x, s))
lte_scalar = _scalar_cmp("lte_scalar", lambda x, s: jnp.less_equal(x, s))


# ---------------------------------------------------------------------------
# math tail
# ---------------------------------------------------------------------------

@op("reversemod", _P, n_inputs=2)
def reversemod(x, y):
    """(reference: broadcastable/reversemod.cpp) — mod with operands
    swapped."""
    return jnp.mod(y, x)


@op("compare_and_bitpack", _E, n_inputs=1, differentiable=False)
def compare_and_bitpack(x, threshold=0.0):
    """(reference: parity_ops/compare_and_bitpack.cpp / TF op): last dim
    must be a multiple of 8; packs (x > threshold) bits MSB-first."""
    bits = (jnp.asarray(x) > threshold).astype(jnp.uint8)
    if bits.shape[-1] % 8:
        raise ValueError(f"last dim {bits.shape[-1]} not a multiple of 8")
    bits = bits.reshape(bits.shape[:-1] + (bits.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return (bits * weights).sum(axis=-1).astype(jnp.uint8)


@op("clipbyavgnorm", _E, n_inputs=1)
def clipbyavgnorm(x, clip_norm: float = 1.0):
    """(reference: transforms/clip.cpp clipbyavgnorm — scale so the
    AVERAGE l2 norm (norm / numElements) is at most clip_norm)."""
    n = x.size
    avg = jnp.sqrt(jnp.sum(x * x)) / n
    scale = jnp.where(avg > clip_norm, clip_norm / avg, 1.0)
    return x * scale


@op("check_numerics", _E, n_inputs=1)
def check_numerics(x, message: str = "check_numerics"):
    """(reference: parity_ops/check_numerics.cpp). Under jit this is the
    identity (XLA cannot raise); executed eagerly (sd.exec_debug's
    op-by-op mode) it raises on NaN/Inf — which is exactly where the
    reference's check runs, in the debugging executioner."""
    if not isinstance(x, jax.core.Tracer):
        if not bool(jnp.isfinite(x).all()):
            raise FloatingPointError(
                f"{message}: tensor contains NaN or Inf")
    return jnp.asarray(x)


@op("is_numeric_tensor", _E, n_inputs=1, differentiable=False)
def is_numeric_tensor(x):
    """(reference: parity_ops/is_numeric_tensor.cpp)"""
    return jnp.asarray(jnp.issubdtype(jnp.asarray(x).dtype, jnp.number))


@op("print_variable", _E, n_inputs=1, differentiable=False)
def print_variable(x, message: str = ""):
    """(reference: util/print_variable.cpp) — debug print that survives
    jit via jax.debug.print; passes the input through."""
    x = jnp.asarray(x)
    jax.debug.print(message + "{x}", x=x)
    return x


# ---------------------------------------------------------------------------
# nn tail (reference: generic/nn/convo, generic/nn/pooling)
# ---------------------------------------------------------------------------

@op("pointwise_conv2d", _N, n_inputs=2)
def pointwise_conv2d(x, w, b=None, data_format: str = "NHWC"):
    """(reference: convo/pointwiseConv2d.cpp) — 1x1 conv. w: (1, 1, Ci,
    Co) or (Ci, Co)."""
    if w.ndim == 2:
        w = w[None, None]
    dn = (data_format, "HWIO", data_format)
    out = lax.conv_general_dilated(x, w, (1, 1), "VALID",
                                   dimension_numbers=dn)
    if b is not None:
        out = out + b
    return out


@op("sep_conv2d", _N, aliases=("sconv2d",))
def sep_conv2d(x, depth_w, point_w=None, b=None, strides=(1, 1),
               padding: str = "SAME", data_format: str = "NHWC"):
    """(reference: convo/sconv2d.cpp) — depthwise then optional
    pointwise. depth_w: (kh, kw, Ci, mult); point_w: (1, 1, Ci*mult, Co)."""
    kh, kw, ci, mult = depth_w.shape
    dn = (data_format, "HWIO", data_format)
    dw = depth_w.reshape(kh, kw, 1, ci * mult)
    out = lax.conv_general_dilated(
        x, dw, tuple(strides), padding, dimension_numbers=dn,
        feature_group_count=ci)
    if point_w is not None:
        out = lax.conv_general_dilated(out, point_w, (1, 1), "VALID",
                                       dimension_numbers=dn)
    if b is not None:
        out = out + b
    return out


@op("deconv3d", _N, n_inputs=2)
def deconv3d(x, w, strides=(1, 1, 1), padding: str = "SAME",
             data_format: str = "NDHWC"):
    """(reference: convo/deconv3d.cpp) — transposed 3D conv. w:
    (kd, kh, kw, Co, Ci) like deconv2d's (kh, kw, Co, Ci) layout."""
    dn = (data_format, "DHWIO", data_format)
    w = jnp.swapaxes(w, -1, -2)          # (kd,kh,kw,Ci,Co) for transpose
    return lax.conv_transpose(x, w, tuple(strides), padding,
                              dimension_numbers=dn)


@op("max_pool_with_argmax", _N, n_inputs=1)
def max_pool_with_argmax(x, pool=(2, 2), strides=None,
                         padding: str = "VALID"):
    """(reference: convo/max_pool_with_argmax.cpp; NHWC). Returns
    (pooled, flat argmax indices into each image's H*W*C) — one
    reduce_window over a (value, index) pair carrier."""
    strides = tuple(strides or pool)
    b, h, w, c = x.shape
    flat_idx = jnp.broadcast_to(
        jnp.arange(h * w * c, dtype=jnp.int32).reshape(1, h, w, c),
        x.shape)
    dims = (1,) + tuple(pool) + (1,)
    strd = (1,) + strides + (1,)

    def reducer(a, bv):
        av, ai = a
        bvv, bi = bv
        take_b = bvv > av
        return (jnp.where(take_b, bvv, av), jnp.where(take_b, bi, ai))

    init = (jnp.asarray(-jnp.inf, x.dtype), jnp.asarray(-1, jnp.int32))
    vals, idxs = lax.reduce_window((x, flat_idx), init, reducer, dims,
                                   strd, padding)
    return vals, idxs


@op("pnormpool2d", _N, n_inputs=1)
def pnormpool2d(x, pool=(2, 2), strides=None, padding: str = "VALID",
                p: float = 2.0):
    """(reference: convo/pnormpool2d.cpp; NHWC) — p-norm pooling."""
    strides = tuple(strides or pool)
    dims = (1,) + tuple(pool) + (1,)
    strd = (1,) + tuple(strides) + (1,)
    s = lax.reduce_window(jnp.abs(x) ** p, jnp.asarray(0.0, x.dtype),
                          lax.add, dims, strd, padding)
    return s ** (1.0 / p)


@op("fused_batch_norm", _N)
def fused_batch_norm(x, scale, offset, mean=None, variance=None,
                     epsilon: float = 1e-3, training: bool = True,
                     data_format: str = "NHWC"):
    """(reference: parity_ops/fused_batch_norm.cpp / TF FusedBatchNorm):
    returns (y, batch_mean, batch_variance)."""
    axes = (0, 1, 2) if data_format == "NHWC" else (0, 2, 3)
    if training or mean is None:
        mean = jnp.mean(x, axis=axes)
        variance = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    c_axis = -1 if data_format == "NHWC" else 1
    shape[c_axis] = x.shape[c_axis]
    mr, vr = mean.reshape(shape), variance.reshape(shape)
    y = (x - mr) * lax.rsqrt(vr + epsilon) * scale.reshape(shape) \
        + offset.reshape(shape)
    return y, mean, variance


# ---------------------------------------------------------------------------
# SRU + static/dynamic RNN wrappers (reference: generic/nn/recurrent)
# ---------------------------------------------------------------------------

@op("sru_cell", _N, aliases=("sruCell",))
def sru_cell(x, c_prev, w, b):
    """One SRU step (reference: recurrent/sruCell.cpp; Lei et al. 2018).
    x: (B, D); w: (D, 3D) packing [x̃ | f | r]; b: (2D,) = [bf | br]."""
    d = x.shape[-1]
    z = jnp.matmul(x, w)
    xt, zf, zr = z[..., :d], z[..., d:2 * d], z[..., 2 * d:]
    f = jax.nn.sigmoid(zf + b[:d])
    r = jax.nn.sigmoid(zr + b[d:])
    c = f * c_prev + (1.0 - f) * xt
    h = r * jnp.tanh(c) + (1.0 - r) * x
    return h, c


@op("sru", _N)
def sru(x, c0, w, b):
    """Full-sequence SRU via one lax.scan (reference: recurrent/sru.cpp).
    x: (B, T, D) → (outputs (B, T, D), final cell (B, D))."""
    xs = jnp.swapaxes(x, 0, 1)

    def step(c, xt):
        h, c2 = sru_cell(xt, c, w, b)
        return c2, h

    cT, hs = lax.scan(step, c0, xs)
    return jnp.swapaxes(hs, 0, 1), cT


@op("sru_bi", _N)
def sru_bi(x, c0_fwd, c0_bwd, w_fwd, b_fwd, w_bwd, b_bwd):
    """Bidirectional SRU (reference: recurrent/sru_bi.cpp) — concat of
    forward and time-reversed backward passes."""
    out_f, cf = sru(x, c0_fwd, w_fwd, b_fwd)
    out_b, cb = sru(jnp.flip(x, axis=1), c0_bwd, w_bwd, b_bwd)
    return jnp.concatenate([out_f, jnp.flip(out_b, axis=1)], axis=-1), \
        cf, cb


def _rnn_scan(x, h0, w, u, b, activation=jnp.tanh):
    xs = jnp.swapaxes(x, 0, 1)

    def step(h, xt):
        h2 = activation(jnp.matmul(xt, w) + jnp.matmul(h, u) + b)
        return h2, h2

    hT, hs = lax.scan(step, h0, xs)
    return jnp.swapaxes(hs, 0, 1), hT


@op("static_rnn", _N)
def static_rnn(x, h0, w, u, b):
    """(reference: recurrent/staticRNN.cpp) — fixed-length simple RNN."""
    return _rnn_scan(x, h0, w, u, b)


@op("dynamic_rnn", _N)
def dynamic_rnn(x, h0, w, u, b, seq_lengths=None):
    """(reference: recurrent/dynamicRNN.cpp) — per-example lengths mask
    the outputs; the final state is the state AT each row's length."""
    outs, _ = _rnn_scan(x, h0, w, u, b)
    if seq_lengths is None:
        return outs, outs[:, -1]
    t = jnp.arange(outs.shape[1])
    mask = (t[None, :] < seq_lengths[:, None]).astype(outs.dtype)
    outs = outs * mask[..., None]
    last = jnp.clip(seq_lengths - 1, 0, outs.shape[1] - 1)
    final = outs[jnp.arange(outs.shape[0]), last]
    return outs, final


@op("static_bidirectional_rnn", _N)
def static_bidirectional_rnn(x, h0_f, h0_b, w_f, u_f, b_f, w_b, u_b, b_b):
    """(reference: recurrent/staticBidirectionalRNN.cpp)"""
    out_f, hf = _rnn_scan(x, h0_f, w_f, u_f, b_f)
    out_b, hb = _rnn_scan(jnp.flip(x, 1), h0_b, w_b, u_b, b_b)
    return jnp.concatenate([out_f, jnp.flip(out_b, 1)], axis=-1), hf, hb


@op("dynamic_bidirectional_rnn", _N)
def dynamic_bidirectional_rnn(x, h0_f, h0_b, w_f, u_f, b_f, w_b, u_b, b_b,
                              seq_lengths=None):
    """(reference: recurrent/dynamicBidirectionalRNN.cpp) — the backward
    pass reverses only each row's valid prefix."""
    out_f, hf = dynamic_rnn(x, h0_f, w_f, u_f, b_f, seq_lengths)
    if seq_lengths is None:
        xr = jnp.flip(x, 1)
    else:
        from deeplearning4j_tpu.ops.shape_ops import reverse_sequence
        xr = reverse_sequence(x, seq_lengths, seq_axis=1, batch_axis=0)
    out_b, hb = dynamic_rnn(xr, h0_b, w_b, u_b, b_b, seq_lengths)
    if seq_lengths is None:
        out_b = jnp.flip(out_b, 1)
    else:
        from deeplearning4j_tpu.ops.shape_ops import reverse_sequence
        out_b = reverse_sequence(out_b, seq_lengths, seq_axis=1,
                                 batch_axis=0)
    return jnp.concatenate([out_f, out_b], axis=-1), hf, hb


# ---------------------------------------------------------------------------
# loss with_logits variants (reference: generic/loss)
# ---------------------------------------------------------------------------

@op("softmax_cross_entropy_loss_with_logits", _L, n_inputs=2)
def softmax_cross_entropy_loss_with_logits(logits, labels, axis: int = -1):
    """(reference: loss/softmaxCrossEntropyWithLogits.cpp) — per-example
    losses, NO reduction (that is the _loss op's job)."""
    logp = jax.nn.log_softmax(logits, axis=axis)
    return -(labels * logp).sum(axis=axis)


@op("sparse_softmax_cross_entropy_loss_with_logits", _L, n_inputs=2)
def sparse_softmax_cross_entropy_loss_with_logits(labels, logits):
    """(reference: loss/sparseSoftmaxCrossEntropyWithLogits.cpp) — int
    class labels; input order matches the reference (labels first)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]


# ---------------------------------------------------------------------------
# image tail (reference: generic/images)
# ---------------------------------------------------------------------------

@op("non_max_suppression_overlaps", _I, differentiable=False)
def non_max_suppression_overlaps(overlaps, scores, max_output_size: int,
                                 overlap_threshold: float = 0.5,
                                 score_threshold: float = -jnp.inf):
    """(reference: images/non_max_suppression_overlaps.cpp) — NMS on a
    precomputed pairwise overlap matrix. Static-size output: (indices
    padded with -1, valid_count)."""
    n = scores.shape[0]
    overlaps = jnp.asarray(overlaps)   # traced indices index this below
    scores = jnp.where(jnp.asarray(scores) >= score_threshold,
                       jnp.asarray(scores), -jnp.inf)

    def body(carry, _):
        sc, chosen = carry
        i = jnp.argmax(sc)
        valid = sc[i] > -jnp.inf
        idx = jnp.where(valid, i, -1)
        suppress = overlaps[i] > overlap_threshold
        sc = jnp.where(valid & suppress, -jnp.inf, sc)
        sc = sc.at[i].set(-jnp.inf)
        return (sc, None), idx

    (final, _), picks = lax.scan(body, (scores, None), None,
                                 length=min(max_output_size, n))
    count = (picks >= 0).sum()
    return picks.astype(jnp.int32), count


# ---------------------------------------------------------------------------
# linalg tail (reference: generic/linalg, generic/blas)
# ---------------------------------------------------------------------------

@op("batched_gemm", _LA)
def batched_gemm(a, b, c=None, alpha: float = 1.0, beta: float = 0.0,
                 transpose_a: bool = False, transpose_b: bool = False):
    """(reference: blas/batched_gemm.cpp) — alpha*op(A)@op(B) + beta*C
    over a leading batch axis; MXU-batched in one einsum."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    out = alpha * jnp.matmul(a, b)
    if c is not None and beta:
        out = out + beta * c
    return out


@op("solve_ls", _LA, n_inputs=2)
def solve_ls(a, b, l2_regularizer: float = 0.0):
    """(reference: linalg/lstsq.cpp solve_ls) — least-squares solve via
    the normal equations with optional ridge term (TPU-friendly:
    Cholesky on A^T A instead of host SVD)."""
    at = jnp.swapaxes(a, -1, -2)
    gram = jnp.matmul(at, a)
    gram = gram + l2_regularizer * jnp.eye(gram.shape[-1], dtype=a.dtype)
    rhs = jnp.matmul(at, b)
    return jnp.linalg.solve(gram, rhs)


# ---------------------------------------------------------------------------
# reference-name aliases for ops that already exist under this
# framework's canonical names (the reference declares these same
# kernels under legacy/new-style names)
# ---------------------------------------------------------------------------
# reference names whose kernels exist under this framework's canonical
# names (creation/selection ops predate this module)
add_alias("eye", "eye_op")
add_alias("range", "range_op")
add_alias("lin_space", "linspace_op")
add_alias("linspace", "linspace_op")
add_alias("assign", "assign_op")
add_alias("where", "where_op")
add_alias("where_np", "where_op")
add_alias("biasadd", "bias_add")
add_alias("conv3dnew", "conv3d")
add_alias("avgpool3dnew", "avg_pool3d")
add_alias("maxpool3dnew", "max_pool3d")
add_alias("tf_atan2", "atan2")
add_alias("scatter_upd", "scatter_update")
add_alias("matrix_diag_part", "diag_part")
add_alias("lrelu", "leaky_relu")
add_alias("non_max_suppression_v3", "non_max_suppression")
