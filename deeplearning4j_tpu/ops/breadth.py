"""Op-breadth wave: list / segment / scatter-nd / image-tail / cast /
math-tail families.

Reference parity: the declarable-op families this module completes are
cited per section (libnd4j/include/ops/declarable/generic/<dir>). Every
op is a pure jax function; coverage enforced by the ledger gate
(tests/test_op_ledger.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import op

# ---------------------------------------------------------------------------
# list ops (reference: generic/list/*.cpp — the NDArrayList/TensorArray
# family). TPU-native representation: a "list" is a stacked array with a
# leading element axis (XLA has no ragged storage; the reference's list
# is likewise a vector of same-shape NDArrays for every op below).
# ---------------------------------------------------------------------------
_L = "list"


@op("create_list", _L, differentiable=False)
def create_list(template, size: int):
    """Empty list of ``size`` elements shaped like ``template``
    (reference: create_list.cpp)."""
    return jnp.zeros((size,) + tuple(template.shape), template.dtype)


@op("write_list", _L, n_inputs=2)
def write_list(lst, value, index: int):
    """(reference: write_list.cpp)"""
    lst = jnp.asarray(lst)
    return lst.at[index].set(value.astype(lst.dtype))


@op("read_list", _L, n_inputs=1)
def read_list(lst, index: int):
    """(reference: read_list.cpp)"""
    return lst[index]


@op("gather_list", _L, n_inputs=2)
def gather_list(lst, indices):
    """(reference: gather_list.cpp)"""
    return jnp.take(lst, indices.astype(jnp.int32), axis=0)


@op("scatter_list", _L, n_inputs=3)
def scatter_list(lst, indices, values):
    """(reference: scatter_list.cpp)"""
    lst = jnp.asarray(lst)
    return lst.at[indices.astype(jnp.int32)].set(values.astype(lst.dtype))


@op("stack_list", _L, n_inputs=1)
def stack_list(lst):
    """List -> single stacked array (reference: stack_list.cpp; the
    representation already is the stack, so this is identity)."""
    return lst


@op("unstack_list", _L, n_inputs=1)
def unstack_list(arr):
    """Array -> list along axis 0 (reference: unstack_list.cpp)."""
    return arr


@op("split_list", _L, n_inputs=1)
def split_list(arr, sizes):
    """(reference: split_list.cpp)"""
    idx, acc = [], 0
    for s in list(sizes)[:-1]:
        acc += int(s)
        idx.append(acc)
    return tuple(jnp.split(arr, idx, axis=0))


@op("size_list", _L, n_inputs=1, differentiable=False)
def size_list(lst):
    """(reference: size_list.cpp)"""
    return jnp.asarray(lst.shape[0], jnp.int32)


@op("pick_list", _L, n_inputs=2)
def pick_list(lst, indices):
    """Gather + concatenate along the element axis (reference:
    pick_list.cpp)."""
    return jnp.concatenate(
        [lst[i] for i in np.asarray(indices).astype(np.int64).tolist()], 0) \
        if np.ndim(indices) else lst[int(indices)]


@op("clone_list", _L, n_inputs=1)
def clone_list(lst):
    """(reference: clone_list.cpp)"""
    return jnp.array(lst, copy=True)


# ---------------------------------------------------------------------------
# unsorted segment ops (reference: generic/parity_ops/unsorted_segment_*)
# ---------------------------------------------------------------------------
_S = "segment"


def _seg(reducer, data, segment_ids, num_segments):
    ids = segment_ids.astype(jnp.int32)
    return reducer(data, ids, num_segments=int(num_segments))


@op("unsorted_segment_sum", _S, n_inputs=2)
def unsorted_segment_sum(data, segment_ids, num_segments: int):
    return _seg(jax.ops.segment_sum, data, segment_ids, num_segments)


@op("unsorted_segment_mean", _S, n_inputs=2)
def unsorted_segment_mean(data, segment_ids, num_segments: int):
    s = _seg(jax.ops.segment_sum, data, segment_ids, num_segments)
    n = _seg(jax.ops.segment_sum, jnp.ones_like(data), segment_ids,
             num_segments)
    return s / jnp.maximum(n, 1)


@op("unsorted_segment_min", _S, n_inputs=2)
def unsorted_segment_min(data, segment_ids, num_segments: int):
    return _seg(jax.ops.segment_min, data, segment_ids, num_segments)


@op("unsorted_segment_max", _S, n_inputs=2)
def unsorted_segment_max(data, segment_ids, num_segments: int):
    return _seg(jax.ops.segment_max, data, segment_ids, num_segments)


@op("unsorted_segment_prod", _S, n_inputs=2)
def unsorted_segment_prod(data, segment_ids, num_segments: int):
    return _seg(jax.ops.segment_prod, data, segment_ids, num_segments)


@op("unsorted_segment_sqrt_n", _S, n_inputs=2)
def unsorted_segment_sqrt_n(data, segment_ids, num_segments: int):
    s = _seg(jax.ops.segment_sum, data, segment_ids, num_segments)
    n = _seg(jax.ops.segment_sum, jnp.ones_like(data), segment_ids,
             num_segments)
    return s / jnp.sqrt(jnp.maximum(n, 1))


# ---------------------------------------------------------------------------
# scatter-nd updates (reference: generic/parity_ops/scatter_nd_*.cpp)
# ---------------------------------------------------------------------------
_SC = "shape"


def _nd_idx(indices):
    ix = indices.astype(jnp.int32)
    return tuple(jnp.moveaxis(ix, -1, 0))


@op("scatter_nd_update", _SC, n_inputs=3, differentiable=False)
def scatter_nd_update(ref, indices, updates):
    return ref.at[_nd_idx(indices)].set(updates.astype(ref.dtype))


@op("scatter_nd_add", _SC, n_inputs=3)
def scatter_nd_add(ref, indices, updates):
    return ref.at[_nd_idx(indices)].add(updates.astype(ref.dtype))


@op("scatter_nd_sub", _SC, n_inputs=3)
def scatter_nd_sub(ref, indices, updates):
    return ref.at[_nd_idx(indices)].add(-updates.astype(ref.dtype))


# ---------------------------------------------------------------------------
# image tail (reference: generic/images/*.cpp, parity_ops resize family)
# ---------------------------------------------------------------------------
_I = "image"


@op("resize_area", _I, n_inputs=1)
def resize_area(images, height: int, width: int):
    """Area (box) resampling (reference: resize_area.cpp)."""
    b, h, w, c = images.shape
    return jax.image.resize(images, (b, height, width, c), method="linear") \
        if (height > h or width > w) else _box_downsample(images, height, width)


def _box_downsample(images, height, width):
    b, h, w, c = images.shape
    if h % height == 0 and w % width == 0:
        fh, fw = h // height, w // width
        x = images.reshape(b, height, fh, width, fw, c)
        return x.mean(axis=(2, 4))
    return jax.image.resize(images, (b, height, width, c), method="linear")


@op("mirror_pad", _I, n_inputs=1, aliases=("mirrorPad",))
def mirror_pad(x, paddings, mode: str = "REFLECT"):
    """(reference: parity_ops/mirrorPad.cpp)"""
    pw = [tuple(int(v) for v in p) for p in np.asarray(paddings)]
    return jnp.pad(x, pw, mode="reflect" if mode.upper() == "REFLECT"
                   else "symmetric")


@op("rgb_to_yiq", _I, n_inputs=1)
def rgb_to_yiq(images):
    """(reference: images/rgbToYiq.cpp — NTSC matrix)"""
    m = jnp.asarray([[0.299, 0.587, 0.114],
                     [0.5959, -0.2746, -0.3213],
                     [0.2115, -0.5227, 0.3112]], images.dtype)
    return jnp.einsum("...c,yc->...y", images, m)


@op("yiq_to_rgb", _I, n_inputs=1)
def yiq_to_rgb(images):
    """(reference: images/yiqToRgb.cpp)"""
    m = jnp.asarray([[0.299, 0.587, 0.114],
                     [0.5959, -0.2746, -0.3213],
                     [0.2115, -0.5227, 0.3112]], jnp.float64)
    inv = jnp.linalg.inv(m).astype(images.dtype)
    return jnp.einsum("...c,yc->...y", images, inv)


@op("random_crop", _I, n_inputs=1)
def random_crop(images, size, key=None, seed: int = 0):
    """(reference: parity_ops/random_crop.cpp)"""
    if key is None:
        key = jax.random.key(seed)
    size = tuple(int(s) for s in size)
    starts = []
    for i, (dim, want) in enumerate(zip(images.shape, size)):
        k = jax.random.fold_in(key, i)
        starts.append(
            jax.random.randint(k, (), 0, dim - want + 1, dtype=jnp.int32)
            if dim > want else jnp.asarray(0, jnp.int32))
    return lax.dynamic_slice(images, tuple(starts), size)


@op("draw_bounding_boxes", _I, n_inputs=2, differentiable=False)
def draw_bounding_boxes(images, boxes, colors=None):
    """(reference: parity_ops/draw_bounding_boxes.cpp) — boxes
    [B, N, 4] normalized (ymin, xmin, ymax, xmax); 1-pixel outlines."""
    b, h, w, c = images.shape
    out = jnp.asarray(images)
    boxes = np.asarray(boxes)
    colors = (np.asarray(colors) if colors is not None
              else np.ones((1, c), np.float32))
    yy = jnp.arange(h)[:, None]
    xx = jnp.arange(w)[None, :]
    for bi in range(boxes.shape[0]):
        for ni in range(boxes.shape[1]):
            ymin, xmin, ymax, xmax = boxes[bi, ni]
            y0, y1 = int(ymin * (h - 1)), int(ymax * (h - 1))
            x0, x1 = int(xmin * (w - 1)), int(xmax * (w - 1))
            col = jnp.asarray(colors[ni % len(colors)], images.dtype)
            on_edge = (((yy == y0) | (yy == y1)) & (xx >= x0) & (xx <= x1)) \
                | (((xx == x0) | (xx == x1)) & (yy >= y0) & (yy <= y1))
            out = out.at[bi].set(
                jnp.where(on_edge[..., None], col, out[bi]))
    return out


@op("dilation2d", _I, n_inputs=2)
def dilation2d(x, filt, strides=(1, 1), rates=(1, 1), padding: str = "SAME"):
    """Grayscale morphological dilation (reference:
    parity_ops/dilation2d.cpp; NHWC, filter [fh, fw, c])."""
    fh, fw, c = filt.shape
    sh, sw = (strides if len(strides) == 2 else strides[1:3])
    rh, rw = (rates if len(rates) == 2 else rates[1:3])
    patches = _patches(x, fh, fw, sh, sw, rh, rw, padding)  # [b,oh,ow,k,c]
    return jnp.max(patches + filt.reshape(fh * fw, c), axis=3)


def _patches(x, fh, fw, sh, sw, rh, rw, padding):
    b, h, w, c = x.shape
    cols = lax.conv_general_dilated_patches(
        x, (fh, fw), (sh, sw), padding, rhs_dilation=(rh, rw),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    oh, ow = cols.shape[1], cols.shape[2]
    return cols.reshape(b, oh, ow, c, fh * fw).transpose(0, 1, 2, 4, 3)


@op("histogram", _I, n_inputs=1, differentiable=False)
def histogram(x, num_bins: int):
    """(reference: parity_ops/histogram.cpp)"""
    lo, hi = jnp.min(x), jnp.max(x)
    edges = jnp.linspace(lo, hi, num_bins + 1)
    idx = jnp.clip(jnp.searchsorted(edges[1:-1], x.reshape(-1),
                                    side="right"), 0, num_bins - 1)
    return jax.ops.segment_sum(jnp.ones_like(idx, jnp.int64), idx,
                               num_segments=num_bins)


@op("histogram_fixed_width", _I, n_inputs=1, differentiable=False)
def histogram_fixed_width(x, value_range, num_bins: int = 100):
    """(reference: parity_ops/histogram_fixed_width.cpp)"""
    lo, hi = float(value_range[0]), float(value_range[1])
    scaled = (x.reshape(-1) - lo) / max(hi - lo, 1e-30) * num_bins
    idx = jnp.clip(scaled.astype(jnp.int32), 0, num_bins - 1)
    return jax.ops.segment_sum(jnp.ones_like(idx, jnp.int64), idx,
                               num_segments=num_bins)


# ---------------------------------------------------------------------------
# dtype casts (reference: generic/datatypes/to_*.cpp, bitcast.cpp)
# ---------------------------------------------------------------------------
_D = "datatypes"

for _name, _dt in (("to_double", jnp.float64), ("to_float32", jnp.float32),
                   ("to_float16", jnp.float16), ("to_int32", jnp.int32),
                   ("to_int64", jnp.int64), ("to_uint32", jnp.uint32),
                   ("to_uint64", jnp.uint64)):
    def _mk(dt):
        def cast(x):
            return x.astype(dt)
        cast.__doc__ = f"(reference: generic/datatypes) cast to {dt}"
        return cast
    op(_name, _D, n_inputs=1, differentiable=False)(_mk(_dt))


@op("bitcast", _D, n_inputs=1, differentiable=False)
def bitcast(x, dtype: str):
    """Reinterpret bytes (reference: datatypes/bitcast.cpp)."""
    return lax.bitcast_convert_type(x, jnp.dtype(dtype))


# ---------------------------------------------------------------------------
# math / transform tail (reference: generic/parity_ops + transforms)
# ---------------------------------------------------------------------------
_M = "elementwise"


@op("betainc", _M, n_inputs=3)
def betainc(a, b, x):
    """(reference: parity_ops/betaInc.cpp)"""
    return jax.scipy.special.betainc(a, b, x)


@op("polygamma", _M, n_inputs=2)
def polygamma(n, x):
    """(reference: parity_ops/polygamma.cpp)"""
    return jax.scipy.special.polygamma(n.astype(jnp.int32), x)


@op("zeta", _M, n_inputs=2)
def zeta(x, q):
    """Hurwitz zeta (reference: parity_ops/zeta.cpp)."""
    return jax.scipy.special.zeta(x, q)


@op("logaddexp", _M, n_inputs=2)
def logaddexp(a, b):
    """(reference: legacy pairwise LogAddExp)"""
    return jnp.logaddexp(a, b)


@op("xlogy", _M, n_inputs=2)
def xlogy(x, y):
    """x*log(y) with 0*log(0)=0 (reference: legacy pairwise)."""
    return jax.scipy.special.xlogy(x, y)


@op("sinc", _M, n_inputs=1)
def sinc(x):
    return jnp.sinc(x)


@op("entr", _M, n_inputs=1)
def entr(x):
    """-x*log(x) elementwise entropy (reference: legacy transforms)."""
    return jax.scipy.special.entr(x)


@op("erfinv", _M, n_inputs=1)
def erfinv(x):
    return jax.scipy.special.erfinv(x)


@op("heaviside", _M, n_inputs=2)
def heaviside(x, h0):
    return jnp.heaviside(x, h0)


@op("nextafter", _M, n_inputs=2, differentiable=False)
def nextafter(a, b):
    return jnp.nextafter(a, b)


@op("ldexp", _M, n_inputs=2)
def ldexp(x, e):
    return jnp.ldexp(x, e.astype(jnp.int32))


@op("crelu", _M, n_inputs=1)
def crelu(x, axis: int = -1):
    """Concatenated ReLU (reference: transforms/crelu.cpp)."""
    return jnp.concatenate([jax.nn.relu(x), jax.nn.relu(-x)], axis=axis)


@op("realdiv", _M, n_inputs=2)
def realdiv(a, b):
    """(reference: broadcastable/realdiv.cpp — always real-valued div)"""
    af = a.astype(jnp.result_type(a.dtype, jnp.float32))
    return af / b.astype(af.dtype)


@op("reduce_dot", _M, n_inputs=2)
def reduce_dot(a, b, axes=None, keep_dims: bool = False):
    """sum(a*b, axes) (reference: reduce/reduce_dot.cpp)."""
    prod = a * b.astype(a.dtype)
    ax = tuple(axes) if axes is not None else None
    return jnp.sum(prod, axis=ax, keepdims=keep_dims)


@op("percentile", _M, n_inputs=1, differentiable=False)
def percentile(x, q: float, axis=None, interpolation: str = "linear"):
    """(reference: parity_ops/percentile.cpp)"""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.percentile(x, q, axis=ax, method=interpolation)


@op("roll", _M, n_inputs=1)
def roll(x, shift, axis=None):
    """(reference: parity_ops/roll.cpp)"""
    sh = tuple(shift) if isinstance(shift, (list, tuple)) else int(shift)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.roll(x, sh, axis=ax)


@op("tri_op", _M, differentiable=False, aliases=("tri",))
def tri_op(n: int, m: int = None, k: int = 0, dtype: str = "float32"):
    """(reference: parity_ops/tri.cpp)"""
    return jnp.tri(n, m, k, dtype=jnp.dtype(dtype))


@op("triu_op", _M, n_inputs=1, aliases=("triu",))
def triu_op(x, k: int = 0):
    """(reference: parity_ops/triu.cpp)"""
    return jnp.triu(x, k)


@op("tril_op", _M, n_inputs=1, aliases=("tril",))
def tril_op(x, k: int = 0):
    return jnp.tril(x, k)


@op("sqrtm", _M, n_inputs=1, differentiable=False)
def sqrtm(x):
    """Matrix square root (reference: parity_ops/sqrtm.cpp)."""
    return jax.scipy.linalg.sqrtm(x).real.astype(x.dtype)


@op("nth_element", _M, n_inputs=1, differentiable=False)
def nth_element(x, n: int, reverse: bool = False):
    """(reference: parity_ops/nth_element.cpp) — n-th order statistic
    along the last axis."""
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


@op("sequence_mask", _M, n_inputs=1, differentiable=False)
def sequence_mask(lengths, maxlen: int = None, dtype: str = "bool"):
    """(reference: parity_ops/sequence_mask.cpp)"""
    ml = int(maxlen) if maxlen is not None else int(jnp.max(lengths))
    rng = jnp.arange(ml)
    return (rng[None, :] < lengths.astype(jnp.int32)[..., None]) \
        .astype(jnp.dtype(dtype))


@op("invert_permutation", _M, n_inputs=1, differentiable=False)
def invert_permutation(p):
    """(reference: parity_ops/invertPermutation.cpp)"""
    p = p.astype(jnp.int32)
    return jnp.zeros_like(p).at[p].set(jnp.arange(p.shape[0], dtype=jnp.int32))


@op("is_non_decreasing", _M, n_inputs=1, differentiable=False)
def is_non_decreasing(x):
    f = x.reshape(-1)
    return jnp.all(f[1:] >= f[:-1]) if f.shape[0] > 1 else jnp.asarray(True)


@op("is_strictly_increasing", _M, n_inputs=1, differentiable=False)
def is_strictly_increasing(x):
    f = x.reshape(-1)
    return jnp.all(f[1:] > f[:-1]) if f.shape[0] > 1 else jnp.asarray(True)


@op("ismax", _M, n_inputs=1, differentiable=False)
def ismax(x, axis=None):
    """1 where the (axis-wise) max sits (reference: legacy IsMax)."""
    if axis is None:
        return (x == jnp.max(x)).astype(x.dtype)
    return (x == jnp.max(x, axis=axis, keepdims=True)).astype(x.dtype)


@op("listdiff", _M, n_inputs=2, differentiable=False)
def listdiff(x, y):
    """Values (and their indices) of x not present in y (reference:
    parity_ops/listdiff.cpp)."""
    keep = ~jnp.isin(x, y)
    idx = jnp.where(keep)[0]
    return x[idx], idx.astype(jnp.int32)


@op("merge_add", _M, aliases=("mergeadd", "accumulate_n"))
def merge_add(*xs):
    """(reference: transforms/merge_add.cpp)"""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@op("merge_avg", _M, aliases=("mergeavg",))
def merge_avg(*xs):
    return merge_add(*xs) / len(xs)


@op("merge_max", _M, aliases=("mergemax",))
def merge_max(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = jnp.maximum(out, x)
    return out


@op("merge_max_idx", _M, differentiable=False, aliases=("mergemaxindex",))
def merge_max_idx(*xs):
    """Index of the input holding the elementwise max (reference:
    transforms/merge_max_idx.cpp)."""
    return jnp.argmax(jnp.stack(xs, axis=0), axis=0).astype(jnp.int32)


@op("col2im", _M, n_inputs=1)
def col2im(cols, height: int, width: int, kernel=(2, 2), stride=(1, 1),
           padding=(0, 0), dilation=(1, 1)):
    """Inverse of im2col: scatter-add patches back (reference:
    transforms/col2im.cpp). cols: [b, c, kh, kw, oh, ow]."""
    b, c, kh, kw, oh, ow = cols.shape
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    img = jnp.zeros((b, c, height + 2 * ph, width + 2 * pw), cols.dtype)
    for i in range(kh):
        for j in range(kw):
            y, x = i * dh, j * dw
            patch = cols[:, :, i, j]
            up = jnp.zeros((b, c, (oh - 1) * sh + 1, (ow - 1) * sw + 1),
                           cols.dtype)
            up = up.at[:, :, ::sh, ::sw].set(patch)
            pad_cfg = [(0, 0), (0, 0),
                       (y, img.shape[2] - y - up.shape[2]),
                       (x, img.shape[3] - x - up.shape[3])]
            img = img + jnp.pad(up, pad_cfg)
    return img[:, :, ph:ph + height, pw:pw + width]


@op("maxpool_with_argmax", _M, n_inputs=1)
def maxpool_with_argmax(x, kernel=(2, 2), stride=None, padding: str = "VALID"):
    """(reference: nn/pooling/maxpool_with_argmax.cpp; NHWC) — returns
    (pooled, flat argmax indices per window)."""
    kh, kw = kernel
    sh, sw = stride or kernel
    b, h, w, c = x.shape
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    oh, ow = patches.shape[1], patches.shape[2]
    # patches channels ordered [c, kh*kw]
    p = patches.reshape(b, oh, ow, c, kh * kw)
    pooled = jnp.max(p, axis=-1)
    arg_in_window = jnp.argmax(p, axis=-1)
    # flat NHWC index of the argmax element
    wy = arg_in_window // kw
    wx = arg_in_window % kw
    oy = jnp.arange(oh)[None, :, None, None]
    ox = jnp.arange(ow)[None, None, :, None]
    iy = oy * sh + wy
    ix = ox * sw + wx
    cc = jnp.arange(c)[None, None, None, :]
    flat = (iy * w + ix) * c + cc
    return pooled, flat.astype(jnp.int64)


@op("batch_to_space_nd", _M, n_inputs=1)
def batch_to_space_nd(x, block_shape, crops):
    """(reference: parity_ops/batch_to_space_nd.cpp)"""
    block = [int(v) for v in np.asarray(block_shape).reshape(-1)]
    crops = np.asarray(crops).reshape(-1, 2)
    b = x.shape[0]
    prod = int(np.prod(block))
    spatial = x.shape[1:1 + len(block)]
    rest = x.shape[1 + len(block):]
    y = x.reshape(tuple(block) + (b // prod,) + spatial + rest)
    perm = [len(block)]
    for i in range(len(block)):
        perm += [len(block) + 1 + i, i]
    perm += list(range(2 * len(block) + 1, y.ndim))
    y = y.transpose(perm)
    new_spatial = tuple(s * bl for s, bl in zip(spatial, block))
    y = y.reshape((b // prod,) + new_spatial + rest)
    slices = [slice(None)]
    for i, (c0, c1) in enumerate(crops):
        slices.append(slice(int(c0), new_spatial[i] - int(c1)))
    return y[tuple(slices)]


@op("space_to_batch_nd", _M, n_inputs=1)
def space_to_batch_nd(x, block_shape, paddings):
    """(reference: parity_ops/space_to_batch_nd.cpp)"""
    block = [int(v) for v in np.asarray(block_shape).reshape(-1)]
    pads = np.asarray(paddings).reshape(-1, 2)
    nb = len(block)
    pad_cfg = [(0, 0)] + [tuple(int(v) for v in p) for p in pads] \
        + [(0, 0)] * (x.ndim - 1 - nb)
    x = jnp.pad(x, pad_cfg)
    b = x.shape[0]
    spatial = x.shape[1:1 + nb]
    rest = x.shape[1 + nb:]
    shape = (b,)
    for s, bl in zip(spatial, block):
        shape += (s // bl, bl)
    shape += rest
    y = x.reshape(shape)
    perm = []
    for i in range(nb):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in range(nb):
        perm.append(1 + 2 * i)
    perm += list(range(1 + 2 * nb, y.ndim))
    y = y.transpose(perm)
    return y.reshape((b * int(np.prod(block)),)
                     + tuple(s // bl for s, bl in zip(spatial, block))
                     + rest)


@op("fake_quant_with_min_max_vars", _M, n_inputs=1)
def fake_quant_with_min_max_vars(x, min_val: float = -6.0,
                                 max_val: float = 6.0, num_bits: int = 8,
                                 narrow_range: bool = False):
    """(reference: parity_ops/fake_quant_with_min_max_vars.cpp)"""
    qmin = 1 if narrow_range else 0
    qmax = 2 ** num_bits - 1
    scale = (max_val - min_val) / (qmax - qmin)
    zp = qmin - min_val / scale
    q = jnp.round(jnp.clip(x / scale + zp, qmin, qmax))
    return (q - zp) * scale


@op("fake_quant_with_min_max_vars_per_channel", _M, n_inputs=3)
def fake_quant_per_channel(x, min_val, max_val, num_bits: int = 8,
                           narrow_range: bool = False):
    qmin = 1 if narrow_range else 0
    qmax = 2 ** num_bits - 1
    scale = (max_val - min_val) / (qmax - qmin)
    zp = qmin - min_val / scale
    q = jnp.round(jnp.clip(x / scale + zp, qmin, qmax))
    return (q - zp) * scale


@op("clip_by_averaged_norm", _M, n_inputs=1)
def clip_by_averaged_norm(x, clip_norm: float):
    """(reference: parity_ops/clip_by_averaged_norm.cpp)"""
    avg_norm = jnp.sqrt(jnp.mean(x * x))
    factor = jnp.minimum(1.0, clip_norm / jnp.maximum(avg_norm, 1e-30))
    return x * factor


@op("identity_n", _M, differentiable=True)
def identity_n(*xs):
    """(reference: parity_ops/identity_n.cpp)"""
    return tuple(xs) if len(xs) > 1 else xs[0]


@op("reshape_as", _M, n_inputs=2)
def reshape_as(x, template):
    """(reference: shape/reshape_as.cpp)"""
    return x.reshape(template.shape)


@op("tile_to_shape", _M, n_inputs=1)
def tile_to_shape(x, shape):
    """Tile up to ``shape`` — repeats = target/input per dim (reference:
    shape/tile_to_shape.cpp; broadcast-compatible dims repeat too)."""
    shape = tuple(int(s) for s in shape)
    if len(shape) != x.ndim:
        x = x.reshape((1,) * (len(shape) - x.ndim) + x.shape)
    reps = []
    for want, have in zip(shape, x.shape):
        if want % have:
            raise ValueError(
                f"tile_to_shape: target {shape} not a multiple of input "
                f"{x.shape}")
        reps.append(want // have)
    return jnp.tile(x, reps)


@op("relu_layer", _M, n_inputs=2)
def relu_layer(x, w, b=None):
    """relu(x@w+b) (reference: nn/relu_layer.cpp)."""
    y = x @ w
    if b is not None:
        y = y + b
    return jax.nn.relu(y)


@op("upsampling3d", _M, n_inputs=1)
def upsampling3d(x, factor=(2, 2, 2), data_format: str = "NDHWC"):
    """(reference: nn/convo/upsampling3d.cpp)"""
    fd, fh, fw = factor
    if data_format == "NCDHW":
        return jnp.repeat(jnp.repeat(jnp.repeat(x, fd, 2), fh, 3), fw, 4)
    return jnp.repeat(jnp.repeat(jnp.repeat(x, fd, 1), fh, 2), fw, 3)


@op("cyclic_shift", "bitwise", n_inputs=2, differentiable=False,
    aliases=("rotl",))
def cyclic_shift(x, shift):
    """Rotate bits left (reference: bitwise/cyclic_shift.cpp)."""
    bits = x.dtype.itemsize * 8
    s = shift.astype(x.dtype) % bits
    ux = x.astype(jnp.uint32 if bits == 32 else jnp.uint64) \
        if not jnp.issubdtype(x.dtype, jnp.unsignedinteger) else x
    inv = ((bits - s) % bits).astype(ux.dtype)   # s==0: shift by width is UB
    out = (ux << s.astype(ux.dtype)) | jnp.where(s == 0, 0, ux >> inv)
    return out.astype(x.dtype)


@op("cyclic_rshift", "bitwise", n_inputs=2, differentiable=False,
    aliases=("rotr",))
def cyclic_rshift(x, shift):
    """Rotate bits right (reference: bitwise/cyclic_rshift.cpp)."""
    bits = x.dtype.itemsize * 8
    s = shift.astype(x.dtype) % bits
    ux = x.astype(jnp.uint32 if bits == 32 else jnp.uint64) \
        if not jnp.issubdtype(x.dtype, jnp.unsignedinteger) else x
    inv = ((bits - s) % bits).astype(ux.dtype)   # s==0: shift by width is UB
    out = (ux >> s.astype(ux.dtype)) | jnp.where(s == 0, 0, ux << inv)
    return out.astype(x.dtype)


@op("multinomial", "random", differentiable=False)
def multinomial(logits, num_samples: int, key=None, seed: int = 0):
    """(reference: random/multinomial.cpp)"""
    if key is None:
        key = jax.random.key(seed)
    s = jax.random.categorical(key, logits, axis=-1,
                               shape=(num_samples,) + logits.shape[:-1])
    return jnp.moveaxis(s, 0, -1).astype(jnp.int64)


@op("log_poisson_loss", "loss", n_inputs=2)
def log_poisson_loss(log_input, targets, full: bool = False,
                     reduction: str = "mean"):
    """(reference: loss/log_poisson_loss.cpp)"""
    loss = jnp.exp(log_input) - targets * log_input
    if full:
        stirling = (targets * jnp.log(jnp.maximum(targets, 1e-30))
                    - targets + 0.5 * jnp.log(2 * jnp.pi
                                              * jnp.maximum(targets, 1.0)))
        loss = loss + jnp.where(targets > 1, stirling, 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@op("weighted_cross_entropy_with_logits", "loss", n_inputs=3)
def weighted_cross_entropy_with_logits(targets, logits, weights):
    """(reference: loss/weighted_cross_entropy_with_logits.cpp)"""
    log_weight = 1 + (weights - 1) * targets
    return jnp.mean(
        (1 - targets) * logits
        + log_weight * (jnp.log1p(jnp.exp(-jnp.abs(logits)))
                        + jax.nn.relu(-logits)))
