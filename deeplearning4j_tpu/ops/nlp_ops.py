"""NLP embedding-training ops: negative-sampling skipgram / CBOW.

Reference parity: libnd4j/include/ops/declarable/generic/nlp/skipgram.cpp
and cbow.cpp — the reference's hot loops are hand-written C++ kernels
doing per-pair dot products + SGD updates with hierarchical-softmax
and/or negative sampling, dispatched row-by-row.

TPU-native redesign: one BATCH of (center, context, negatives) pairs is a
single fused gather → batched-dot → logistic-loss program. The MXU sees
[batch, dim] × [batch, K+1, dim] contractions instead of scalar loops;
gradients come from jax.grad of the loss (no hand-written update rule),
so the same op powers Word2Vec, fastText (subword-summed centers),
ParagraphVectors and DeepWalk.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_N = "nlp"


def _ns_logits(center_vec, ctx_vec, neg_vec):
    """center [B,D]; ctx [B,D]; neg [B,K,D] -> pos [B], neg [B,K]."""
    pos = jnp.einsum("bd,bd->b", center_vec, ctx_vec)
    neg = jnp.einsum("bd,bkd->bk", center_vec, neg_vec)
    return pos, neg


@op("skipgram_ns_loss", _N)
def skipgram_ns_loss(syn0, syn1, centers, contexts, negatives):
    """Mean negative-sampling skipgram loss over a pair batch.

    syn0 [V,D] input vectors (the embeddings kept after training),
    syn1 [V,D] output vectors; centers/contexts [B] int ids;
    negatives [B,K] int ids drawn from the unigram^0.75 table.
    loss = -log σ(u_ctx·v_c) - Σ_k log σ(-u_negk·v_c)
    (skipgram.cpp computes the same objective pair-at-a-time).
    """
    v_c = jnp.take(syn0, centers, axis=0)          # [B,D]
    u_o = jnp.take(syn1, contexts, axis=0)         # [B,D]
    u_n = jnp.take(syn1, negatives, axis=0)        # [B,K,D]
    pos, neg = _ns_logits(v_c, u_o, u_n)
    loss = -jax.nn.log_sigmoid(pos) - jnp.sum(jax.nn.log_sigmoid(-neg), -1)
    return jnp.mean(loss)


@op("cbow_ns_loss", _N)
def cbow_ns_loss(syn0, syn1, context_windows, targets, negatives,
                 mask=None):
    """Mean negative-sampling CBOW loss: mean-of-window inputs predict
    the target word (cbow.cpp). context_windows [B,W] int ids (pad with
    any id + mask=0), targets [B], negatives [B,K], mask [B,W]."""
    ctx = jnp.take(syn0, context_windows, axis=0)  # [B,W,D]
    if mask is not None:
        m = mask.astype(ctx.dtype)[..., None]
        ctx = ctx * m
        denom = jnp.maximum(jnp.sum(m, axis=1), 1.0)
        h = jnp.sum(ctx, axis=1) / denom
    else:
        h = jnp.mean(ctx, axis=1)                  # [B,D]
    u_t = jnp.take(syn1, targets, axis=0)
    u_n = jnp.take(syn1, negatives, axis=0)
    pos, neg = _ns_logits(h, u_t, u_n)
    loss = -jax.nn.log_sigmoid(pos) - jnp.sum(jax.nn.log_sigmoid(-neg), -1)
    return jnp.mean(loss)


@op("glove_loss", _N)
def glove_loss(w, w_tilde, b, b_tilde, rows, cols, counts,
               x_max: float = 100.0, alpha: float = 0.75):
    """GloVe weighted least squares on a cooccurrence batch
    (reference: glove/Glove.java trains the same objective per-pair):
    f(X_ij) (w_i·w̃_j + b_i + b̃_j - log X_ij)^2."""
    wi = jnp.take(w, rows, axis=0)
    wj = jnp.take(w_tilde, cols, axis=0)
    bi = jnp.take(b, rows, axis=0)
    bj = jnp.take(b_tilde, cols, axis=0)
    pred = jnp.einsum("bd,bd->b", wi, wj) + bi + bj
    fx = jnp.minimum((counts / x_max) ** alpha, 1.0)
    return jnp.mean(fx * (pred - jnp.log(counts)) ** 2)
