"""Graph control flow: While / Cond / Scan as structural ops.

Reference parity: the reference executes TF-v1 control-flow frames
(Enter/Exit/Switch/Merge/NextIteration) with an interpreter loop that
re-enqueues frame iterations (AbstractSession.java:46-101) and re-designed
them around invokable subgraphs in its own ADR ("New Control flow":
ADRs/0020). The TPU-native answer skips frames entirely: a loop/branch is
ONE graph node whose attrs embed the cond/body/branch subgraphs
(define-then-run, like TF2 functional StatelessWhile/If), and at trace
time the subgraphs compile into `lax.while_loop` / `lax.cond` /
`lax.scan` — XLA-native control flow with static shapes, no interpreter.

Subgraph wire format (the attr value — pure JSON-able dict, so OpNode
serde handles it untouched):
    {"params":   [name, ...],          # formal inputs, positional
     "outputs":  [var name, ...],      # returned values
     "variables":[{name, dtype}, ...], # placeholder decls (params)
     "constants":{name: {"__ndarray__": ..., "dtype": ...}},
     "ops":      [{name, op, inputs, outputs, attrs, random}, ...]}

Differentiability (documented, matching what JAX provides):
- `cond`: reverse-mode differentiable (both branches traced).
- `scan_loop` (static trip count): fully reverse-mode differentiable —
  use it for trainable recurrence (TBPTT-style).
- `while_loop` (data-dependent trip count): NOT reverse-mode
  differentiable (XLA cannot run a dynamic loop backwards without
  storing an unbounded tape); use scan_loop when gradients are needed.
"""
from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.registry import op

_F = "flow"


def _const_to_json(arr: np.ndarray) -> Dict:
    """base64 raw bytes, not tolist(): imported function bodies can carry
    weight-sized consts — nested Python floats would cost tens of MB."""
    import base64
    return {"__ndarray_b64__": base64.b64encode(arr.tobytes()).decode(),
            "dtype": str(arr.dtype), "shape": list(arr.shape)}


def _const_from_json(c: Dict) -> np.ndarray:
    import base64
    if "__ndarray_b64__" in c:
        return np.frombuffer(
            base64.b64decode(c["__ndarray_b64__"]),
            dtype=np.dtype(c["dtype"])).reshape(c["shape"]).copy()
    return np.asarray(c["__ndarray__"], dtype=c["dtype"])   # legacy form


def subgraph_to_json(sub_sd, params: List[str], outputs: List[str]) -> Dict:
    """Encode a recorded sub-SameDiff as the attr dict."""
    from deeplearning4j_tpu.autodiff.variable import VariableType
    consts = {}
    for n, v in sub_sd._vars.items():
        if v.var_type == VariableType.CONSTANT:
            consts[n] = _const_to_json(np.asarray(sub_sd._arrays[n]))
        elif v.var_type == VariableType.VARIABLE:
            raise ValueError(
                f"subgraph may not own trainable variables ({n!r}); pass "
                f"outer variables through `captures=` instead")
    return {
        "params": list(params),
        "outputs": list(outputs),
        "variables": [{"name": n, "dtype": v.dtype}
                      for n, v in sub_sd._vars.items()
                      if v.var_type == VariableType.PLACEHOLDER],
        "constants": consts,
        "ops": [{"name": nd.name, "op": nd.op, "inputs": list(nd.inputs),
                 "outputs": list(nd.outputs), "attrs": dict(nd.attrs),
                 "random": nd.random,
                 **({"group": nd.group} if nd.group else {})}
                for nd in sub_sd.ops()],
    }


def subgraph_from_json(g: Dict):
    """Rebuild a SameDiff from the attr dict."""
    from deeplearning4j_tpu.autodiff.samediff import OpNode, SameDiff
    from deeplearning4j_tpu.autodiff.variable import SDVariable, VariableType
    sub = SameDiff()
    for vd in g["variables"]:
        v = SDVariable(sub, vd["name"], VariableType.PLACEHOLDER, None,
                       vd["dtype"])
        sub._vars[v.name] = v
    for n, c in g["constants"].items():
        arr = _const_from_json(c)
        v = SDVariable(sub, n, VariableType.CONSTANT, arr.shape,
                       str(arr.dtype))
        sub._vars[n] = v
        sub._arrays[n] = jnp.asarray(arr)
    for od in g["ops"]:
        for on in od["outputs"]:
            if on not in sub._vars:
                sub._vars[on] = SDVariable(sub, on, VariableType.ARRAY,
                                           None, "float32")
        node = OpNode(name=od["name"], op=od["op"],
                      inputs=list(od["inputs"]), outputs=list(od["outputs"]),
                      attrs=dict(od["attrs"]),
                      random=od.get("random", False),
                      group=od.get("group"))
        sub._ops[node.name] = node
        sub._op_order.append(node.name)
        for on in node.outputs:
            sub._producer[on] = node.name
    sub._mutated()
    return sub


def compile_subgraph(g: Dict):
    """attr dict -> callable(key, *arrays) -> list of output arrays.
    The PRNG key seeds any random ops in the body (each trace folds it
    per-node, so distinct keys give distinct masks)."""
    sub = subgraph_from_json(g)
    fn = sub._trace_fn(tuple(g["outputs"]))
    params = list(g["params"])
    consts = sub.constants_map()

    def call(key, *arrays):
        res = fn({}, consts, dict(zip(params, arrays)), key)
        return [res[o] for o in g["outputs"]]

    return call


@op("while_loop", _F, differentiable=False, needs_key=True)
def while_loop(*args, cond_graph: Dict, body_graph: Dict, n_loop: int,
               key=None):
    """Run `body` while `cond` holds. args = loop_vars + captures;
    captures feed both subgraphs after the loop vars and pass through
    unchanged. Returns the final loop vars. The key is split every
    iteration so random ops in the body draw fresh masks per step.

    Lowered to `lax.while_loop`: compiled once, executed on-device with
    a data-dependent trip count (reference runs this with host-side
    frame re-enqueueing, AbstractSession.java:46)."""
    loop_vars, captures = args[:n_loop], args[n_loop:]
    cond_fn = compile_subgraph(cond_graph)
    body_fn = compile_subgraph(body_graph)
    if key is None:
        key = jax.random.key(0)

    def c(carry):
        k, lv = carry[0], carry[1:]
        out = cond_fn(k, *lv, *captures)[0]
        return out.reshape(()).astype(bool)

    def b(carry):
        k, lv = carry[0], carry[1:]
        k_step, k_next = jax.random.split(k)
        return (k_next, *body_fn(k_step, *lv, *captures))

    res = jax.lax.while_loop(c, b, (key, *loop_vars))[1:]
    return res if n_loop > 1 else res[0]


@op("cond_branch", _F, needs_key=True)
def cond_branch(pred, *args, true_graph: Dict, false_graph: Dict,
                key=None):
    """`lax.cond` over two subgraphs sharing the operand list.
    Reverse-mode differentiable; both branches must return the same
    shapes/dtypes (XLA requirement)."""
    tf_ = compile_subgraph(true_graph)
    ff_ = compile_subgraph(false_graph)
    if key is None:
        key = jax.random.key(0)
    res = jax.lax.cond(pred.reshape(()).astype(bool),
                       lambda ops: tuple(tf_(key, *ops)),
                       lambda ops: tuple(ff_(key, *ops)),
                       tuple(args))
    return res if len(res) > 1 else res[0]


@op("scan_loop", _F, needs_key=True)
def scan_loop(*args, body_graph: Dict, n_carry: int, n_scan: int,
              length: int = None, reverse: bool = False, key=None):
    """Static-trip-count loop with per-step inputs and stacked per-step
    outputs — the differentiable recurrence primitive (lowered to
    `lax.scan`; reverse-mode AD supported, so RNNs/TBPTT train through
    it). args = carries + scanned (leading axis = time) + captures.
    body returns new carries + per-step outputs (stacked on return).
    The key is split per step (fresh dropout masks along time)."""
    carries = args[:n_carry]
    xs = args[n_carry:n_carry + n_scan]
    captures = args[n_carry + n_scan:]
    body_fn = compile_subgraph(body_graph)
    if key is None:
        key = jax.random.key(0)

    def b(carry, x):
        k, cs = carry[0], carry[1:]
        k_step, k_next = jax.random.split(k)
        res = body_fn(k_step, *cs, *x, *captures)
        return (k_next, *res[:n_carry]), tuple(res[n_carry:])

    (_, *final), stacked = jax.lax.scan(b, (key, *carries), tuple(xs),
                                        length=length, reverse=reverse)
    outs = list(final) + list(stacked)
    return outs if len(outs) > 1 else outs[0]
