"""Image ops.

Reference parity: ops/declarable/generic/images/ (resize family via
helpers/image_resize.h, adjust_contrast/hue/saturation, rgb<->hsv/yuv,
crop_and_resize, extract_image_patches, non_max_suppression).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_I = "image"


@op("resize_bilinear", _I, n_inputs=1)
def resize_bilinear(images, height: int, width: int, align_corners: bool = False,
                    half_pixel_centers: bool = True, data_format: str = "NHWC"):
    if data_format == "NCHW":
        images = jnp.transpose(images, (0, 2, 3, 1))
    out = jax.image.resize(images, (images.shape[0], height, width, images.shape[3]),
                           method="bilinear")
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


@op("resize_nearest_neighbor", _I, n_inputs=1, aliases=("resize_nearest",))
def resize_nearest_neighbor(images, height: int, width: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        images = jnp.transpose(images, (0, 2, 3, 1))
    out = jax.image.resize(images, (images.shape[0], height, width, images.shape[3]),
                           method="nearest")
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


@op("resize_bicubic", _I, n_inputs=1)
def resize_bicubic(images, height: int, width: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        images = jnp.transpose(images, (0, 2, 3, 1))
    out = jax.image.resize(images, (images.shape[0], height, width, images.shape[3]),
                           method="cubic")
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


@op("adjust_contrast", _I, n_inputs=1)
def adjust_contrast(images, factor: float):
    mean = jnp.mean(images, axis=(-3, -2), keepdims=True)
    return (images - mean) * factor + mean


@op("adjust_saturation", _I, n_inputs=1)
def adjust_saturation(images, factor: float):
    hsv = rgb_to_hsv(images)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@op("adjust_hue", _I, n_inputs=1)
def adjust_hue(images, delta: float):
    hsv = rgb_to_hsv(images)
    h = jnp.mod(hsv[..., 0] + delta, 1.0)
    return hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


@op("rgb_to_hsv", _I, n_inputs=1)
def rgb_to_hsv(images):
    r, g, b = images[..., 0], images[..., 1], images[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    diff = mx - mn
    safe = jnp.where(diff == 0, 1.0, diff)
    h = jnp.where(mx == r, jnp.mod((g - b) / safe, 6.0),
                  jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0)) / 6.0
    h = jnp.where(diff == 0, 0.0, h)
    s = jnp.where(mx == 0, 0.0, diff / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@op("hsv_to_rgb", _I, n_inputs=1)
def hsv_to_rgb(images):
    h, s, v = images[..., 0] * 6.0, images[..., 1], images[..., 2]
    i = jnp.floor(h)
    f = h - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


@op("rgb_to_yuv", _I, n_inputs=1)
def rgb_to_yuv(images):
    m = jnp.asarray([[0.299, -0.14714119, 0.61497538],
                     [0.587, -0.28886916, -0.51496512],
                     [0.114, 0.43601035, -0.10001026]], dtype=images.dtype)
    return jnp.matmul(images, m)


@op("yuv_to_rgb", _I, n_inputs=1)
def yuv_to_rgb(images):
    m = jnp.asarray([[1.0, 1.0, 1.0],
                     [0.0, -0.394642334, 2.03206185],
                     [1.13988303, -0.58062185, 0.0]], dtype=images.dtype)
    return jnp.matmul(images, m)


@op("rgb_to_grs", _I, n_inputs=1, aliases=("rgb_to_grayscale",))
def rgb_to_grs(images):
    w = jnp.asarray([0.2989, 0.5870, 0.1140], dtype=images.dtype)
    return jnp.sum(images * w, axis=-1, keepdims=True)


@op("image_flip_lr", _I, n_inputs=1)
def image_flip_lr(images):
    return jnp.flip(images, axis=-2)


@op("image_flip_ud", _I, n_inputs=1)
def image_flip_ud(images):
    return jnp.flip(images, axis=-3)


@op("crop_and_resize", _I)
def crop_and_resize(images, boxes, box_indices, crop_height: int, crop_width: int,
                    method: str = "bilinear"):
    """(reference: generic/images/crop_and_resize.cpp) boxes: (n,4) [y1,x1,y2,x2]
    normalized."""
    images = jnp.asarray(images)   # numpy images + traced idx would fail

    def crop_one(box, idx):
        img = images[idx]
        h, w = images.shape[1], images.shape[2]
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        ys = y1 * (h - 1) + jnp.linspace(0.0, 1.0, crop_height) * (y2 - y1) * (h - 1)
        xs = x1 * (w - 1) + jnp.linspace(0.0, 1.0, crop_width) * (x2 - x1) * (w - 1)
        if method == "nearest":
            yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, h - 1)
            xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, w - 1)
            return img[yi][:, xi]
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        a = img[y0][:, x0]
        b = img[y0][:, x1i]
        c = img[y1i][:, x0]
        d = img[y1i][:, x1i]
        return (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx +
                c * wy * (1 - wx) + d * wy * wx)

    return jax.vmap(crop_one)(boxes, box_indices)


@op("extract_image_patches", _I, n_inputs=1)
def extract_image_patches(images, ksizes, strides, rates, padding: str = "VALID"):
    """(reference: generic/images/extract_image_patches.cpp) NHWC in/out."""
    kh, kw = ksizes
    sh, sw = strides
    rh, rw = rates
    from deeplearning4j_tpu.ops.nn_ops import _conv_padding
    pads = _conv_padding(padding, [images.shape[1], images.shape[2]], (sh, sw),
                         [(kh - 1) * rh + 1, (kw - 1) * rw + 1])
    x = jnp.pad(images, [(0, 0), pads[0], pads[1], (0, 0)])
    oh = (x.shape[1] - (kh - 1) * rh - 1) // sh + 1
    ow = (x.shape[2] - (kw - 1) * rw - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(x[:, i * rh:i * rh + oh * sh:sh, j * rw:j * rw + ow * sw:sw, :])
    return jnp.concatenate(patches, axis=-1)


@op("non_max_suppression", _I, differentiable=False)
def non_max_suppression(boxes, scores, max_output_size: int,
                        iou_threshold: float = 0.5, score_threshold: float = -jnp.inf):
    """(reference: generic/images/nonMaxSuppression.cpp) static-size output:
    returns (indices, valid_count); indices padded with -1."""
    n = boxes.shape[0]
    boxes = jnp.asarray(boxes)     # traced indices index these below
    scores = jnp.asarray(scores)
    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.abs(y2 - y1) * jnp.abs(x2 - x1)

    def iou(i, j):
        yy1 = jnp.maximum(y1[i], y1[j])
        xx1 = jnp.maximum(x1[i], x1[j])
        yy2 = jnp.minimum(y2[i], y2[j])
        xx2 = jnp.minimum(x2[i], x2[j])
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area[j] - inter, 1e-12)

    order = jnp.argsort(-scores)

    def body(state, k):
        selected, count, suppressed = state
        idx = order[k]
        ok = jnp.logical_and(
            jnp.logical_and(~suppressed[idx], scores[idx] >= score_threshold),
            count < max_output_size)

        def select():
            s2 = selected.at[count].set(idx)
            all_idx = jnp.arange(n)
            over = iou(idx, all_idx) > iou_threshold
            return s2, count + 1, jnp.logical_or(suppressed, over)

        def skip():
            return selected, count, suppressed

        state2 = jax.lax.cond(ok, select, skip)
        return state2, None

    init = (jnp.full((max_output_size,), -1, dtype=jnp.int32),
            jnp.asarray(0, dtype=jnp.int32),
            jnp.zeros((n,), dtype=bool))
    (selected, count, _), _ = jax.lax.scan(body, init, jnp.arange(n))
    return selected, count
