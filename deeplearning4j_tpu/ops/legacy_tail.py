"""Legacy opNum tail: the remaining reference legacy-op families.

Reference parity: libnd4j/include/loops/legacy_ops.h — the enumerated
elementwise/reduce/index-reduce/boolean families the earlier waves left
out: absolute-value reductions (AMax/AMin/AMean/ASum), entropy reduces
(Entropy/LogEntropy/ShannonEntropy), index reduces (FirstIndex/
LastIndex/IndexAbsoluteMax/Min), logical ops, conditional set/replace
(CompareAndSet/CompareAndReplace/MatchCondition), and the elementwise
tail (Affine, SetRange, ScaledTanh, TimesOneMinus, SafeDivide,
RelativeError family, Stabilize, LstmClip, SquaredNorm/NormP).
Derivative entries (…Derivative) are n/a by design — jax.grad owns
gradients.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.registry import add_alias, op
# legacy logical negation is the registered 'not' kernel under another
# name; elementwise.py loads before this module in _ensure_loaded
from deeplearning4j_tpu.ops import elementwise as _elementwise  # noqa: F401

_E = "elementwise"
_P = "pairwise"
_R = "reduce"


def _axes(dims, ndim):
    if dims is None or dims == ():
        return None
    return tuple(d % ndim for d in (dims if isinstance(dims, (tuple, list))
                                    else (dims,)))


# -- absolute-value reductions (legacy AMax/AMin/AMean/ASum) ---------------

@op("amax", _R, n_inputs=1)
def amax(x, dims=None, keep_dims: bool = False):
    return jnp.max(jnp.abs(x), axis=_axes(dims, x.ndim),
                   keepdims=keep_dims)


@op("amin", _R, n_inputs=1)
def amin(x, dims=None, keep_dims: bool = False):
    return jnp.min(jnp.abs(x), axis=_axes(dims, x.ndim),
                   keepdims=keep_dims)


@op("amean", _R, n_inputs=1)
def amean(x, dims=None, keep_dims: bool = False):
    return jnp.mean(jnp.abs(x), axis=_axes(dims, x.ndim),
                    keepdims=keep_dims)


@op("asum", _R, n_inputs=1)
def asum(x, dims=None, keep_dims: bool = False):
    return jnp.sum(jnp.abs(x), axis=_axes(dims, x.ndim),
                   keepdims=keep_dims)


@op("squared_norm", _R, n_inputs=1)
def squared_norm(x, dims=None, keep_dims: bool = False):
    """(legacy SquaredNorm; reduce_sqnorm is the axis=/keepdims= form
    already in ops/reduce.py)"""
    return jnp.sum(x * x, axis=_axes(dims, x.ndim), keepdims=keep_dims)


@op("norm_p", _R, n_inputs=1)
def norm_p(x, p: float = 2.0, dims=None, keep_dims: bool = False):
    return jnp.sum(jnp.abs(x) ** p, axis=_axes(dims, x.ndim),
                   keepdims=keep_dims) ** (1.0 / p)


# -- entropy reduces (legacy Entropy/LogEntropy/ShannonEntropy) ------------

@op("entropy", _R, n_inputs=1)
def entropy(x, dims=None, keep_dims: bool = False):
    """-sum(p * log(p)); zero-probability entries contribute 0."""
    p = jnp.asarray(x)
    t = p * jnp.log(jnp.maximum(p, 1e-30))
    return -jnp.sum(t, axis=_axes(dims, p.ndim), keepdims=keep_dims)


@op("shannon_entropy", _R, n_inputs=1)
def shannon_entropy(x, dims=None, keep_dims: bool = False):
    p = jnp.asarray(x)
    t = p * jnp.log2(jnp.maximum(p, 1e-30))
    return -jnp.sum(t, axis=_axes(dims, p.ndim), keepdims=keep_dims)


@op("log_entropy", _R, n_inputs=1)
def log_entropy(x, dims=None, keep_dims: bool = False):
    return jnp.log(entropy(x, dims, keep_dims))


# -- index reduces (legacy FirstIndex/LastIndex/IndexAbsoluteMax/Min) ------

_CONDS = {
    "gt": lambda x, v: x > v, "lt": lambda x, v: x < v,
    "gte": lambda x, v: x >= v, "lte": lambda x, v: x <= v,
    "eq": lambda x, v: x == v, "neq": lambda x, v: x != v,
    "abs_gt": lambda x, v: jnp.abs(x) > v,
    "abs_lt": lambda x, v: jnp.abs(x) < v,
}


@op("first_index", _R, n_inputs=1, differentiable=False)
def first_index(x, condition: str = "gt", value: float = 0.0,
                dims=None):
    """Index of the first element matching the condition (-1 when none
    matches). No dims = scalar index into the flattened array, matching
    the sibling index-reduces (iamax/match_condition) and the
    reference's BooleanIndexing.firstIndex scalar form; dims = per-slice
    indices along that axis."""
    mask = _CONDS[condition](jnp.asarray(x), value)
    if dims is None:
        mask = mask.reshape(-1)
        axis = 0
    else:
        axis = dims[0] if isinstance(dims, (tuple, list)) else dims
    idx = jnp.argmax(mask, axis=axis)
    any_ = jnp.any(mask, axis=axis)
    return jnp.where(any_, idx, -1)


@op("last_index", _R, n_inputs=1, differentiable=False)
def last_index(x, condition: str = "gt", value: float = 0.0, dims=None):
    """Global scalar with no dims (see first_index); per-slice with."""
    mask = _CONDS[condition](jnp.asarray(x), value)
    if dims is None:
        mask = mask.reshape(-1)
        axis = 0
    else:
        axis = dims[0] if isinstance(dims, (tuple, list)) else dims
    n = mask.shape[axis]
    rev = jnp.flip(mask, axis=axis)
    idx = n - 1 - jnp.argmax(rev, axis=axis)
    any_ = jnp.any(mask, axis=axis)
    return jnp.where(any_, idx, -1)


@op("iamax", _R, n_inputs=1, differentiable=False)
def iamax(x, dims=None):
    """argmax(|x|) (legacy IndexAbsoluteMax / BLAS iamax)."""
    axis = None if dims is None else (dims[0] if isinstance(
        dims, (tuple, list)) else dims)
    return jnp.argmax(jnp.abs(x), axis=axis)


@op("iamin", _R, n_inputs=1, differentiable=False)
def iamin(x, dims=None):
    axis = None if dims is None else (dims[0] if isinstance(
        dims, (tuple, list)) else dims)
    return jnp.argmin(jnp.abs(x), axis=axis)


@op("match_condition", _R, n_inputs=1, differentiable=False)
def match_condition(x, condition: str = "gt", value: float = 0.0,
                    dims=None):
    """Count of elements matching the condition (reference:
    MatchCondition reduce; INDArray.matchCondition pairs with the
    boolean form)."""
    mask = _CONDS[condition](jnp.asarray(x), value)
    return jnp.sum(mask, axis=_axes(dims, mask.ndim)).astype(jnp.int64)


# -- logical ops (legacy LogicalAnd/Or/Not/Xor — boolean semantics,
#    distinct from the bitwise int family) --------------------------------

@op("logical_and", _P, n_inputs=2, differentiable=False)
def logical_and(x, y):
    return jnp.logical_and(jnp.asarray(x) != 0, jnp.asarray(y) != 0)


@op("logical_or", _P, n_inputs=2, differentiable=False)
def logical_or(x, y):
    return jnp.logical_or(jnp.asarray(x) != 0, jnp.asarray(y) != 0)


@op("logical_xor", _P, n_inputs=2, differentiable=False)
def logical_xor(x, y):
    return jnp.logical_xor(jnp.asarray(x) != 0, jnp.asarray(y) != 0)


add_alias("logical_not", "not")


# -- conditional set/replace (legacy CompareAndSet/CompareAndReplace) ------

@op("compare_and_set", _E, n_inputs=1)
def compare_and_set(x, compare: float = 0.0, set_value: float = 0.0,
                    condition: str = "eq", eps: float = 1e-7):
    """x[i] = set_value where cond(x[i], compare) (reference:
    CompareAndSet; eq uses epsilon equality like the reference)."""
    x = jnp.asarray(x)
    if condition == "eq":
        mask = jnp.abs(x - compare) < eps
    else:
        mask = _CONDS[condition](x, compare)
    return jnp.where(mask, jnp.asarray(set_value, x.dtype), x)


@op("compare_and_replace", _P, n_inputs=2)
def compare_and_replace(x, y, compare: float = 0.0,
                        condition: str = "lt"):
    """x[i] = y[i] where cond(x[i], compare) (reference:
    CompareAndReplace — replacement values come from the second
    tensor)."""
    x = jnp.asarray(x)
    mask = _CONDS[condition](x, compare)
    return jnp.where(mask, jnp.asarray(y, x.dtype), x)


# -- elementwise tail ------------------------------------------------------

@op("affine", _E, n_inputs=1)
def affine(x, a: float = 1.0, b: float = 0.0):
    """a*x + b (legacy Affine)."""
    return a * jnp.asarray(x) + b


@op("set_range", _E, n_inputs=1)
def set_range(x, min: float = 0.0, max: float = 1.0):
    """Clip into [min, max] (legacy SetRange)."""
    return jnp.clip(jnp.asarray(x), min, max)


@op("scaled_tanh", _E, n_inputs=1)
def scaled_tanh(x, a: float = 1.7159, b: float = 2.0 / 3.0):
    """a * tanh(b * x) (legacy ScaledTanh; LeCun's constants)."""
    return a * jnp.tanh(b * jnp.asarray(x))


@op("times_one_minus", _E, n_inputs=1)
def times_one_minus(x):
    """x * (1 - x) — the sigmoid-derivative form (legacy TimesOneMinus)."""
    x = jnp.asarray(x)
    return x * (1.0 - x)


@op("safe_divide", _P, n_inputs=2)
def safe_divide(x, y):
    """x / y with 0 where y == 0 (legacy SafeDivide)."""
    y = jnp.asarray(y)
    return jnp.where(y == 0, jnp.zeros_like(jnp.asarray(x) * y),
                     jnp.asarray(x) / jnp.where(y == 0, 1, y))


@op("relative_error", _P, n_inputs=2)
def relative_error(x, y):
    """|x - y| / max(|x|, |y|), 0 where both are 0 (legacy
    RelativeError / BinaryRelativeError)."""
    x, y = jnp.asarray(x), jnp.asarray(y)
    den = jnp.maximum(jnp.abs(x), jnp.abs(y))
    return jnp.where(den == 0, 0.0, jnp.abs(x - y)
                     / jnp.where(den == 0, 1, den))


@op("stabilize", _E, n_inputs=1)
def stabilize(x, k: float = 1.0, cutoff: float = -100.0):
    """Clamp k*x away from exp-underflow range (legacy Stabilize —
    the reference uses it to keep logits in a numerically safe band)."""
    x = jnp.asarray(x) * k
    return jnp.clip(x, cutoff, -cutoff)


@op("lstm_clip", _E, n_inputs=1)
def lstm_clip(x, clip: float = 1.0):
    """Cell-state clipping (legacy LstmClip)."""
    return jnp.clip(jnp.asarray(x), -clip, clip)


@op("is_negative", _E, n_inputs=1, differentiable=False)
def is_negative(x):
    return jnp.asarray(x) < 0


@op("is_positive", _E, n_inputs=1, differentiable=False)
def is_positive(x):
    return jnp.asarray(x) > 0


@op("is_inf_or_nan", _E, n_inputs=1, differentiable=False)
def is_inf_or_nan(x):
    x = jnp.asarray(x)
    return jnp.logical_or(jnp.isinf(x), jnp.isnan(x))
