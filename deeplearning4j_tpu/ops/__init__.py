from deeplearning4j_tpu.ops.registry import (
    Op, OpTraceEntry, exec_op, get_op, has_op, list_op_traces, op, op_names,
    ops_by_category, print_op_trace, purge_op_trace,
    replay_op_trace_as_graph, toggle_op_trace,
)

__all__ = ["Op", "OpTraceEntry", "exec_op", "get_op", "has_op", "op",
           "op_names", "ops_by_category", "toggle_op_trace",
           "list_op_traces", "purge_op_trace", "print_op_trace",
           "replay_op_trace_as_graph"]
