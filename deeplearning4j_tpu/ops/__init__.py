from deeplearning4j_tpu.ops.registry import (
    Op, exec_op, get_op, has_op, op, op_names, ops_by_category,
)

__all__ = ["Op", "exec_op", "get_op", "has_op", "op", "op_names", "ops_by_category"]
