"""Random distribution ops.

Reference parity: legacy RANDOM_OPS (loops/legacy_ops.h:105-111 — uniform,
gaussian, bernoulli, binomial, exponential, truncated/log normal, dropout,
alpha-dropout) and declarable generic/random/. The reference RNG is
counter-based (graph/RandomGenerator.h); the TPU-native equivalent is jax's
threefry with explicit keys. Every op takes ``key`` (a jax PRNG key) or
``seed`` (int attr) — in graphs the key is threaded as a real input so the
whole step stays reproducible and jit-stable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_R = "random"


def _key(key=None, seed=None):
    if key is not None:
        return key
    if seed is not None:
        return jax.random.key(seed)
    from deeplearning4j_tpu.ndarray import factory
    return factory.get_random().next_key()


@op("random_uniform", _R, differentiable=False, aliases=("randomuniform",))
def random_uniform(shape, minval: float = 0.0, maxval: float = 1.0,
                   dtype: str = "float32", key=None, seed=None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return jax.random.uniform(_key(key, seed), tuple(shape),
                              dtype=DataType.from_any(dtype).jnp,
                              minval=minval, maxval=maxval)


@op("random_normal", _R, differentiable=False, aliases=("randomnormal", "random_gaussian"))
def random_normal(shape, mean: float = 0.0, stddev: float = 1.0,
                  dtype: str = "float32", key=None, seed=None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return mean + stddev * jax.random.normal(
        _key(key, seed), tuple(shape), dtype=DataType.from_any(dtype).jnp)


@op("random_truncated_normal", _R, differentiable=False, aliases=("truncated_normal",))
def random_truncated_normal(shape, mean: float = 0.0, stddev: float = 1.0,
                            dtype: str = "float32", key=None, seed=None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return mean + stddev * jax.random.truncated_normal(
        _key(key, seed), -2.0, 2.0, tuple(shape), dtype=DataType.from_any(dtype).jnp)


@op("random_lognormal", _R, differentiable=False)
def random_lognormal(shape, mean: float = 0.0, stddev: float = 1.0, key=None, seed=None):
    return jnp.exp(mean + stddev * jax.random.normal(_key(key, seed), tuple(shape)))


@op("random_bernoulli", _R, differentiable=False, aliases=("bernoulli_dist",))
def random_bernoulli(shape, prob: float = 0.5, dtype: str = "float32", key=None, seed=None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return jax.random.bernoulli(_key(key, seed), prob, tuple(shape)).astype(
        DataType.from_any(dtype).jnp)


@op("random_binomial", _R, differentiable=False)
def random_binomial(shape, trials: int = 1, prob: float = 0.5, key=None, seed=None):
    draws = jax.random.bernoulli(_key(key, seed), prob, (trials,) + tuple(shape))
    return jnp.sum(draws, axis=0).astype(jnp.float32)


@op("random_exponential", _R, differentiable=False)
def random_exponential(shape, lam: float = 1.0, key=None, seed=None):
    return jax.random.exponential(_key(key, seed), tuple(shape)) / lam


@op("random_gamma", _R, differentiable=False)
def random_gamma(shape, alpha: float = 1.0, beta: float = 1.0, key=None, seed=None):
    return jax.random.gamma(_key(key, seed), alpha, tuple(shape)) / beta


@op("random_poisson", _R, differentiable=False)
def random_poisson(shape, lam: float = 1.0, key=None, seed=None):
    return jax.random.poisson(_key(key, seed), lam, tuple(shape)).astype(jnp.float32)


@op("random_multinomial", _R, n_inputs=1, differentiable=False)
def random_multinomial(logits, num_samples: int, key=None, seed=None):
    # batched logits: insert a broadcast dim so the requested shape's
    # sample axis is compatible with the logits batch dims
    logits = jnp.asarray(logits)
    return jax.random.categorical(_key(key, seed), logits[..., None, :],
                                  axis=-1,
                                  shape=logits.shape[:-1] + (num_samples,))


@op("random_shuffle", _R, n_inputs=1, differentiable=False)
def random_shuffle(x, key=None, seed=None):
    return jax.random.permutation(_key(key, seed), x, axis=0)


@op("dropout", _R, n_inputs=1)
def dropout(x, p: float, key=None, seed=None, training: bool = True):
    """Inverted dropout (reference: legacy DropOutInverted / generic dropout).

    ``p`` is the RETAIN probability, matching the reference's convention
    (deeplearning4j nn/conf/dropout/Dropout.java: p = probability to keep).
    """
    if not training or p >= 1.0:
        return x
    mask = jax.random.bernoulli(_key(key, seed), p, x.shape)
    return jnp.where(mask, x / p, 0.0).astype(x.dtype)


@op("alpha_dropout", _R, n_inputs=1)
def alpha_dropout(x, p: float, key=None, seed=None, training: bool = True):
    """SELU-compatible dropout (reference: legacy AlphaDropOut)."""
    if not training or p >= 1.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    mask = jax.random.bernoulli(_key(key, seed), p, x.shape)
    a = (p + alpha_p ** 2 * p * (1 - p)) ** -0.5
    b = -a * alpha_p * (1 - p)
    return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


@op("gaussian_dropout", _R, n_inputs=1)
def gaussian_dropout(x, rate: float, key=None, seed=None, training: bool = True):
    if not training or rate <= 0.0:
        return x
    stddev = (rate / (1.0 - rate)) ** 0.5
    return x * (1.0 + stddev * jax.random.normal(_key(key, seed), x.shape, dtype=x.dtype))


@op("gaussian_noise", _R, n_inputs=1)
def gaussian_noise(x, stddev: float, key=None, seed=None, training: bool = True):
    if not training:
        return x
    return x + stddev * jax.random.normal(_key(key, seed), x.shape, dtype=x.dtype)


@op("spatial_dropout", _R, n_inputs=1)
def spatial_dropout(x, p: float, key=None, seed=None, training: bool = True,
                    channel_axis: int = -1):
    """Channel-wise dropout: one Bernoulli per (batch, channel), the
    whole feature map drops together (reference:
    nn/conf/dropout/SpatialDropout.java; p = retain probability)."""
    if not training or p >= 1.0:
        return x
    axis = channel_axis % x.ndim
    mask_shape = tuple(x.shape[d] if d in (0, axis) else 1
                       for d in range(x.ndim))
    mask = jax.random.bernoulli(_key(key, seed), p, mask_shape)
    return jnp.where(mask, x / p, 0.0).astype(x.dtype)
