"""Reduction ops.

Reference parity: legacy REDUCE_FLOAT/REDUCE_SAME/REDUCE_BOOL/REDUCE_LONG,
INDEX_REDUCE, REDUCE3 and SUMMARY_STATS families (loops/legacy_ops.h) plus
declarable reduce ops (ops/declarable/generic/reduce/). Axis handling follows
the reference: ``axis=None`` reduces all dims; keep_dims mirrors the
reference's boolean attr.
"""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_R = "reduce"


def _norm_axis(axis):
    if axis is None or isinstance(axis, int):
        return axis
    t = tuple(int(a) for a in axis)
    return t if t else None


def _reg(name, fn, aliases=()):
    @op(name, _R, n_inputs=1, aliases=aliases)
    def _f(x, axis=None, keep_dims: bool = False, _fn=fn):
        return _fn(x, axis=_norm_axis(axis), keepdims=keep_dims)
    _f.__name__ = name
    return _f


_reg("reduce_sum", jnp.sum, aliases=("sum",))
_reg("reduce_mean", jnp.mean, aliases=("mean",))
_reg("reduce_prod", jnp.prod, aliases=("prod",))
_reg("reduce_max", jnp.max, aliases=("amax_reduce",))
_reg("reduce_min", jnp.min, aliases=("amin_reduce",))
_reg("reduce_logsumexp", lambda x, axis=None, keepdims=False: (
    __import__("jax").scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)),
    aliases=("logsumexp",))
_reg("reduce_norm1", lambda x, axis=None, keepdims=False: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims),
     aliases=("norm1",))
_reg("reduce_norm2", lambda x, axis=None, keepdims=False: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)),
     aliases=("norm2",))
_reg("reduce_norm_max", lambda x, axis=None, keepdims=False: jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims),
     aliases=("normmax",))
_reg("reduce_sqnorm", lambda x, axis=None, keepdims=False: jnp.sum(x * x, axis=axis, keepdims=keepdims),
     aliases=("sqnorm",))
_reg("reduce_any", lambda x, axis=None, keepdims=False: jnp.any(x, axis=axis, keepdims=keepdims),
     aliases=("any",))
_reg("reduce_all", lambda x, axis=None, keepdims=False: jnp.all(x, axis=axis, keepdims=keepdims),
     aliases=("all",))


@op("reduce_variance", _R, n_inputs=1, aliases=("variance",))
def reduce_variance(x, axis=None, keep_dims: bool = False, bias_corrected: bool = True):
    return jnp.var(x, axis=_norm_axis(axis), keepdims=keep_dims,
                   ddof=1 if bias_corrected else 0)


@op("reduce_stdev", _R, n_inputs=1, aliases=("standarddeviation", "std"))
def reduce_stdev(x, axis=None, keep_dims: bool = False, bias_corrected: bool = True):
    return jnp.std(x, axis=_norm_axis(axis), keepdims=keep_dims,
                   ddof=1 if bias_corrected else 0)


@op("count_nonzero", _R, n_inputs=1, differentiable=False)
def count_nonzero(x, axis=None, keep_dims: bool = False):
    return jnp.count_nonzero(x, axis=_norm_axis(axis), keepdims=keep_dims)


@op("count_zero", _R, n_inputs=1, differentiable=False)
def count_zero(x, axis=None, keep_dims: bool = False):
    return jnp.sum((x == 0), axis=_norm_axis(axis), keepdims=keep_dims)


# -- index reductions (legacy INDEX_REDUCE) ------------------------------
@op("argmax", _R, n_inputs=1, differentiable=False, aliases=("imax",))
def argmax(x, axis=None, keep_dims: bool = False):
    r = jnp.argmax(x, axis=axis if isinstance(axis, int) else None)
    if keep_dims and isinstance(axis, int):
        r = jnp.expand_dims(r, axis)
    return r


@op("argmin", _R, n_inputs=1, differentiable=False, aliases=("imin",))
def argmin(x, axis=None, keep_dims: bool = False):
    r = jnp.argmin(x, axis=axis if isinstance(axis, int) else None)
    if keep_dims and isinstance(axis, int):
        r = jnp.expand_dims(r, axis)
    return r


@op("argamax", _R, n_inputs=1, differentiable=False)
def argamax(x, axis=None):
    return jnp.argmax(jnp.abs(x), axis=axis if isinstance(axis, int) else None)


@op("argamin", _R, n_inputs=1, differentiable=False)
def argamin(x, axis=None):
    return jnp.argmin(jnp.abs(x), axis=axis if isinstance(axis, int) else None)


# -- reduce3 (pairwise distance reductions, legacy REDUCE_3) -------------
@op("cosine_similarity", _R, n_inputs=2, aliases=("cosinesimilarity",))
def cosine_similarity(a, b, axis=None, keep_dims: bool = False):
    ax = _norm_axis(axis)
    num = jnp.sum(a * b, axis=ax, keepdims=keep_dims)
    na = jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keep_dims))
    nb = jnp.sqrt(jnp.sum(b * b, axis=ax, keepdims=keep_dims))
    return num / (na * nb)


@op("cosine_distance", _R, n_inputs=2, aliases=("cosinedistance",))
def cosine_distance(a, b, axis=None, keep_dims: bool = False):
    return 1.0 - cosine_similarity(a, b, axis=axis, keep_dims=keep_dims)


@op("euclidean_distance", _R, n_inputs=2, aliases=("euclidean",))
def euclidean_distance(a, b, axis=None, keep_dims: bool = False):
    d = a - b
    return jnp.sqrt(jnp.sum(d * d, axis=_norm_axis(axis), keepdims=keep_dims))


@op("manhattan_distance", _R, n_inputs=2, aliases=("manhattan",))
def manhattan_distance(a, b, axis=None, keep_dims: bool = False):
    return jnp.sum(jnp.abs(a - b), axis=_norm_axis(axis), keepdims=keep_dims)


@op("hamming_distance", _R, n_inputs=2, differentiable=False)
def hamming_distance(a, b, axis=None, keep_dims: bool = False):
    return jnp.sum((a != b), axis=_norm_axis(axis), keepdims=keep_dims)


@op("jaccard_distance", _R, n_inputs=2)
def jaccard_distance(a, b, axis=None, keep_dims: bool = False):
    ax = _norm_axis(axis)
    num = jnp.sum(jnp.minimum(a, b), axis=ax, keepdims=keep_dims)
    den = jnp.sum(jnp.maximum(a, b), axis=ax, keepdims=keep_dims)
    return 1.0 - num / den


@op("dot", _R, n_inputs=2)
def dot(a, b, axis=None, keep_dims: bool = False):
    return jnp.sum(a * b, axis=_norm_axis(axis), keepdims=keep_dims)


# -- summary stats (legacy SUMMARY_STATS) --------------------------------
@op("moments", _R, n_inputs=1)
def moments(x, axis=None, keep_dims: bool = False):
    ax = _norm_axis(axis)
    mean = jnp.mean(x, axis=ax, keepdims=keep_dims)
    var = jnp.var(x, axis=ax, keepdims=keep_dims)
    return mean, var


@op("normalize_moments", _R, n_inputs=3)
def normalize_moments(counts, means_ss, variances_ss, shift: float = 0.0):
    div = jnp.maximum(counts, 1.0)
    mean = means_ss / div + shift
    var = variances_ss / div - jnp.square(means_ss / div)
    return mean, var


# -- segment / unsorted-segment reductions (generic/parity_ops/segment_*) -
@op("segment_sum", _R, n_inputs=2)
def segment_sum(data, segment_ids, num_segments: int):
    import jax.ops
    import jax
    return jax.ops.segment_sum(data, segment_ids, num_segments)


@op("segment_max", _R, n_inputs=2)
def segment_max(data, segment_ids, num_segments: int):
    import jax
    return jax.ops.segment_max(data, segment_ids, num_segments)


@op("segment_min", _R, n_inputs=2)
def segment_min(data, segment_ids, num_segments: int):
    import jax
    return jax.ops.segment_min(data, segment_ids, num_segments)


@op("segment_mean", _R, n_inputs=2)
def segment_mean(data, segment_ids, num_segments: int):
    import jax
    s = jax.ops.segment_sum(data, segment_ids, num_segments)
    n = jax.ops.segment_sum(jnp.ones_like(data[..., :1] if data.ndim > 1 else data),
                            segment_ids, num_segments)
    return s / jnp.maximum(n, 1)


@op("segment_prod", _R, n_inputs=2)
def segment_prod(data, segment_ids, num_segments: int):
    import jax
    return jax.ops.segment_prod(data, segment_ids, num_segments)


@op("zero_fraction", _R, n_inputs=1)
def zero_fraction(x):
    return jnp.mean((x == 0).astype(jnp.float32))
