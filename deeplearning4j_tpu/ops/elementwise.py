"""Elementwise transform ops.

Reference parity: libnd4j transform op families
(loops/legacy_ops.h TRANSFORM_STRICT/TRANSFORM_FLOAT/TRANSFORM_SAME/
TRANSFORM_BOOL lists) plus declarable activations
(ops/declarable/generic/transforms/ and .../nn/activations/). Each is one HLO
elementwise op; XLA fuses chains of these into the surrounding matmul/conv.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

_E = "elementwise"


def _reg(name, fn, aliases=()):
    op(name, _E, n_inputs=1, aliases=aliases)(fn)


# -- strict float transforms (legacy TRANSFORM_STRICT) -------------------
_reg("abs", jnp.abs)
_reg("exp", jnp.exp)
_reg("log", jnp.log)
_reg("log2", jnp.log2)
_reg("log10", jnp.log10)
_reg("log1p", jnp.log1p)
_reg("expm1", jnp.expm1)
_reg("sqrt", jnp.sqrt)
_reg("rsqrt", lax.rsqrt)
_reg("square", jnp.square)
_reg("cube", lambda x: x * x * x)
_reg("reciprocal", jnp.reciprocal)
_reg("neg", jnp.negative, aliases=("negative",))
_reg("sign", jnp.sign)
_reg("floor", jnp.floor)
_reg("ceil", jnp.ceil)
_reg("round", jnp.round)
_reg("rint", jnp.rint)
_reg("trunc", jnp.trunc)

_reg("sin", jnp.sin)
_reg("cos", jnp.cos)
_reg("tan", jnp.tan)
_reg("asin", jnp.arcsin)
_reg("acos", jnp.arccos)
_reg("atan", jnp.arctan)
_reg("sinh", jnp.sinh)
_reg("cosh", jnp.cosh)
_reg("tanh", jnp.tanh)
_reg("asinh", jnp.arcsinh)
_reg("acosh", jnp.arccosh)
_reg("atanh", jnp.arctanh)

_reg("erf", jax.scipy.special.erf)
_reg("erfc", jax.scipy.special.erfc)
_reg("lgamma", jax.scipy.special.gammaln)
_reg("digamma", jax.scipy.special.digamma)

_reg("isnan", jnp.isnan)
_reg("isinf", jnp.isinf)
_reg("isfinite", jnp.isfinite)
_reg("not", jnp.logical_not, aliases=("boolean_not",))

_reg("oneminus", lambda x: 1.0 - x, aliases=("one_minus",))
_reg("onesas", jnp.ones_like)
_reg("zerosas", jnp.zeros_like)
_reg("identity", lambda x: x, aliases=("linear",))


# -- activations (reference: generic/nn/activations/*.cpp) ---------------
@op("sigmoid", _E, n_inputs=1)
def sigmoid(x):
    return jax.nn.sigmoid(x)


@op("log_sigmoid", _E, n_inputs=1)
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@op("hard_sigmoid", _E, n_inputs=1, aliases=("hardsigmoid",))
def hard_sigmoid(x):
    # reference: hard_sigmoid = clamp(0.2*x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@op("hard_tanh", _E, n_inputs=1, aliases=("hardtanh",))
def hard_tanh(x):
    return jnp.clip(x, -1.0, 1.0)


@op("relu", _E, n_inputs=1)
def relu(x, cutoff: float = 0.0):
    return jnp.where(x > cutoff, x, 0.0).astype(x.dtype)


@op("relu6", _E, n_inputs=1)
def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


@op("leaky_relu", _E, n_inputs=1, aliases=("leakyrelu",))
def leaky_relu(x, alpha: float = 0.01):
    return jnp.where(x >= 0, x, alpha * x).astype(x.dtype)


@op("elu", _E, n_inputs=1)
def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


@op("selu", _E, n_inputs=1)
def selu(x):
    return jax.nn.selu(x)


@op("celu", _E, n_inputs=1)
def celu(x, alpha: float = 1.0):
    return jax.nn.celu(x, alpha)


@op("gelu", _E, n_inputs=1)
def gelu(x, precise: bool = False):
    # reference gelu (generic/nn/activations/gelu.cpp) uses the tanh approx
    return jax.nn.gelu(x, approximate=not precise)


@op("softplus", _E, n_inputs=1)
def softplus(x):
    return jax.nn.softplus(x)


@op("softsign", _E, n_inputs=1)
def softsign(x):
    return jax.nn.soft_sign(x)


@op("swish", _E, n_inputs=1, aliases=("silu",))
def swish(x):
    return jax.nn.silu(x)


@op("mish", _E, n_inputs=1)
def mish(x):
    return jax.nn.mish(x)


@op("rationaltanh", _E, n_inputs=1)
def rationaltanh(x):
    # reference: transform same family — 1.7159 * tanh(2x/3) rational approx
    return 1.7159 * jnp.tanh(2.0 * x / 3.0)


@op("rectifiedtanh", _E, n_inputs=1)
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x)).astype(x.dtype)


@op("thresholdedrelu", _E, n_inputs=1)
def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0).astype(x.dtype)


@op("prelu", _E, n_inputs=2)
def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x).astype(x.dtype)


@op("step", _E, n_inputs=1)
def step(x, cutoff: float = 0.0):
    return (x > cutoff).astype(x.dtype)


@op("clip_by_value", _E, n_inputs=1, aliases=("clipbyvalue", "clip"))
def clip_by_value(x, clip_min: float, clip_max: float):
    return jnp.clip(x, clip_min, clip_max)


@op("clip_by_norm", _E, n_inputs=1, aliases=("clipbynorm",))
def clip_by_norm(x, clip_norm: float, axis=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=axis is not None))
    scale = jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)
    return x * scale


@op("clip_by_global_norm", _E, differentiable=True)
def clip_by_global_norm(*arrays, clip_norm: float):
    gn = jnp.sqrt(sum(jnp.sum(a * a) for a in arrays))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    return tuple(a * scale for a in arrays)


@op("scalar_add", _E, n_inputs=1)
def scalar_add(x, scalar: float):
    return x + scalar


@op("scalar_mul", _E, n_inputs=1)
def scalar_mul(x, scalar: float):
    return x * scalar


@op("scalar_max", _E, n_inputs=1)
def scalar_max(x, scalar: float):
    return jnp.maximum(x, scalar)


@op("scalar_min", _E, n_inputs=1)
def scalar_min(x, scalar: float):
    return jnp.minimum(x, scalar)


@op("pow", _E, n_inputs=1, aliases=("pow_scalar",))
def pow_(x, exponent: float = 2.0):
    return jnp.power(x, exponent)


@op("cast", _E, n_inputs=1, differentiable=False)
def cast(x, dtype: str):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return x.astype(DataType.from_any(dtype).jnp)


@op("nan_to_num", _E, n_inputs=1, aliases=("replace_nans",))
def nan_to_num(x, value: float = 0.0):
    return jnp.nan_to_num(x, nan=value)


@op("softmax", _E, n_inputs=1)
def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax", _E, n_inputs=1)
def log_softmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


@op("cumsum", _E, n_inputs=1)
def cumsum(x, axis: int = 0, exclusive: bool = False, reverse: bool = False):
    if reverse:
        x = jnp.flip(x, axis)
    r = jnp.cumsum(x, axis=axis)
    if exclusive:
        r = r - x
    if reverse:
        r = jnp.flip(r, axis)
    return r


@op("cumprod", _E, n_inputs=1)
def cumprod(x, axis: int = 0, exclusive: bool = False, reverse: bool = False):
    if reverse:
        x = jnp.flip(x, axis)
    if exclusive:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        shifted = jnp.pad(x, pad, constant_values=1)
        shifted = lax.slice_in_dim(shifted, 0, x.shape[axis], axis=axis)
        r = jnp.cumprod(shifted, axis=axis)
    else:
        r = jnp.cumprod(x, axis=axis)
    if reverse:
        r = jnp.flip(r, axis)
    return r
