"""Shape / gather-scatter / restructuring ops.

Reference parity: ops/declarable/generic/shape/ (reshape, permute, squeeze,
expand_dims, ...), generic/transforms/ (concat, stack, unstack, split, tile,
reverse, pad, gather, scatter_*), generic/parity_ops/. All shapes are static
(XLA requirement); dynamic-shape reference ops (e.g. boolean mask with
data-dependent output size) surface size-bounded variants.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

_S = "shape"


@op("reshape", _S, n_inputs=1)
def reshape(x, shape):
    return jnp.reshape(x, tuple(shape))


@op("permute", _S, n_inputs=1, aliases=("transpose_nd",))
def permute(x, axes=None):
    return jnp.transpose(x, tuple(axes) if axes is not None else None)


@op("transpose", _S, n_inputs=1)
def transpose(x):
    return jnp.transpose(x)


@op("squeeze", _S, n_inputs=1)
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


@op("expand_dims", _S, n_inputs=1)
def expand_dims(x, axis: int = 0):
    return jnp.expand_dims(x, axis)


@op("flatten_2d", _S, n_inputs=1)
def flatten_2d(x, axis: int = 1):
    lead = 1
    for d in x.shape[:axis]:
        lead *= d
    return jnp.reshape(x, (lead, -1))


@op("concat", _S)
def concat(*xs, axis: int = 0):
    return jnp.concatenate(xs, axis=axis)


@op("stack", _S, aliases=("parallel_stack",))
def stack(*xs, axis: int = 0):
    return jnp.stack(xs, axis=axis)


@op("unstack", _S, n_inputs=1)
def unstack(x, axis: int = 0):
    return tuple(jnp.moveaxis(x, axis, 0))


@op("split", _S, n_inputs=1)
def split(x, num_split: int, axis: int = 0):
    return tuple(jnp.split(x, num_split, axis=axis))


@op("split_v", _S, n_inputs=1)
def split_v(x, sizes, axis: int = 0):
    idx = []
    acc = 0
    for s in sizes[:-1]:
        acc += s
        idx.append(acc)
    return tuple(jnp.split(x, idx, axis=axis))


@op("tile", _S, n_inputs=1)
def tile(x, reps):
    return jnp.tile(x, tuple(reps))


@op("repeat", _S, n_inputs=1)
def repeat(x, repeats, axis: int = 0):
    return jnp.repeat(x, repeats, axis=axis)


@op("reverse", _S, n_inputs=1, aliases=("flip",))
def reverse(x, axis):
    return jnp.flip(x, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis)


@op("reverse_sequence", _S, n_inputs=2)
def reverse_sequence(x, seq_lengths, seq_axis: int = 1, batch_axis: int = 0):
    def rev_one(row, n):
        idx = jnp.arange(row.shape[seq_axis - 1 if seq_axis > batch_axis else seq_axis])
        src = jnp.where(idx < n, n - 1 - idx, idx)
        return jnp.take(row, src, axis=seq_axis - 1 if seq_axis > batch_axis else seq_axis)
    xm = jnp.moveaxis(x, batch_axis, 0)
    out = jax.vmap(rev_one)(xm, seq_lengths)
    return jnp.moveaxis(out, 0, batch_axis)


@op("pad", _S, n_inputs=1)
def pad(x, paddings, mode: str = "constant", constant: float = 0.0):
    mode = mode.lower()
    pw = tuple(tuple(p) for p in paddings)
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant)
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    if mode == "symmetric":
        return jnp.pad(x, pw, mode="symmetric")
    raise ValueError(f"unknown pad mode {mode}")


@op("slice", _S, n_inputs=1)
def slice_(x, begin, size):
    size = [x.shape[i] - b if s == -1 else s for i, (b, s) in enumerate(zip(begin, size))]
    return lax.dynamic_slice(x, tuple(begin), tuple(size)) if any(
        not isinstance(b, int) for b in begin) else lax.slice(
        x, tuple(begin), tuple(b + s for b, s in zip(begin, size)))


@op("strided_slice", _S, n_inputs=1)
def strided_slice(x, begin, end, strides=None):
    idx = tuple(slice(b, e, s) for b, e, s in zip(
        begin, end, strides or [1] * len(begin)))
    return x[idx]


@op("gather", _S, n_inputs=2)
def gather(x, indices, axis: int = 0):
    return jnp.take(x, indices, axis=axis)


@op("gather_nd", _S, n_inputs=2)
def gather_nd(x, indices):
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return x[idx]


@op("scatter_update", _S, n_inputs=3, differentiable=False)
def scatter_update(ref, indices, updates):
    return ref.at[indices].set(updates)


@op("scatter_add", _S, n_inputs=3)
def scatter_add(ref, indices, updates):
    return ref.at[indices].add(updates)


@op("scatter_sub", _S, n_inputs=3)
def scatter_sub(ref, indices, updates):
    return ref.at[indices].add(-updates)


@op("scatter_mul", _S, n_inputs=3)
def scatter_mul(ref, indices, updates):
    return ref.at[indices].multiply(updates)


@op("scatter_div", _S, n_inputs=3)
def scatter_div(ref, indices, updates):
    return ref.at[indices].divide(updates)


@op("scatter_max", _S, n_inputs=3)
def scatter_max(ref, indices, updates):
    return ref.at[indices].max(updates)


@op("scatter_min", _S, n_inputs=3)
def scatter_min(ref, indices, updates):
    return ref.at[indices].min(updates)


@op("scatter_nd", _S, n_inputs=2)
def scatter_nd(indices, updates, shape):
    out = jnp.zeros(tuple(shape), dtype=updates.dtype)
    idx = tuple(jnp.moveaxis(indices, -1, 0))
    return out.at[idx].add(updates)


@op("size", _S, n_inputs=1, differentiable=False)
def size(x):
    return jnp.asarray(x.size, dtype=jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)


@op("shape_of", _S, n_inputs=1, differentiable=False, aliases=("shape",))
def shape_of(x):
    return jnp.asarray(x.shape, dtype=jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)


@op("rank", _S, n_inputs=1, differentiable=False)
def rank(x):
    return jnp.asarray(x.ndim, dtype=jnp.int32)


@op("fill", _S, differentiable=False)
def fill(shape, value: float, dtype: str = "float32"):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return jnp.full(tuple(shape), value, dtype=DataType.from_any(dtype).jnp)


@op("zeros_like", _S, n_inputs=1)
def zeros_like(x):
    return jnp.zeros_like(x)


@op("ones_like", _S, n_inputs=1)
def ones_like(x):
    return jnp.ones_like(x)


@op("eye_op", _S, differentiable=False)
def eye_op(rows: int, cols: int = None, dtype: str = "float32",
           batch_shape=()):
    """(reference: parity_ops/eye.cpp — optional leading batch dims)"""
    from deeplearning4j_tpu.ndarray.dtype import DataType
    m = jnp.eye(rows, cols, dtype=DataType.from_any(dtype).jnp)
    if batch_shape:
        m = jnp.broadcast_to(m, tuple(batch_shape) + m.shape)
    return m


@op("range_op", _S, differentiable=False, aliases=("arange",))
def range_op(start, limit=None, delta=1, dtype: str = None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    dt = DataType.from_any(dtype).jnp if dtype else None
    if limit is None:
        return jnp.arange(start, dtype=dt)
    return jnp.arange(start, limit, delta, dtype=dt)


@op("linspace_op", _S, differentiable=False)
def linspace_op(start, stop, num: int, dtype: str = None):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    dt = DataType.from_any(dtype).jnp if dtype else None
    return jnp.linspace(start, stop, int(num), dtype=dt)


@op("meshgrid", _S)
def meshgrid(*xs, indexing: str = "xy"):
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


@op("broadcast_to", _S, n_inputs=1)
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, tuple(shape))


@op("where_op", _S, aliases=("select",))
def where_op(cond, x=None, y=None):
    """3-input form = select; 1-input form returns coordinates of true
    elements (reference: parity_ops/where.cpp / where_np.cpp) — a
    data-dependent shape, so that form executes eagerly like `unique`."""
    if x is not None:
        return jnp.where(cond, x, y)
    if isinstance(cond, jax.core.Tracer):
        raise ValueError(
            "where(condition) has a data-dependent output shape and "
            "cannot run under jit; use where(cond, x, y) or run eagerly")
    import numpy as _np
    return jnp.asarray(_np.argwhere(_np.asarray(cond)))


@op("one_hot", _S, n_inputs=1, differentiable=False, aliases=("onehot",))
def one_hot(indices, depth: int, on_value: float = 1.0, off_value: float = 0.0,
            axis: int = -1, dtype: str = "float32"):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    oh = jax.nn.one_hot(indices, depth, axis=axis,
                        dtype=DataType.from_any(dtype).jnp)
    return oh * (on_value - off_value) + off_value


@op("diag", _S, n_inputs=1)
def diag(x):
    return jnp.diagflat(x) if x.ndim == 1 else jnp.diagonal(x)


@op("diag_part", _S, n_inputs=1)
def diag_part(x):
    return jnp.diagonal(x, axis1=-2, axis2=-1)


@op("matrix_diag", _S, n_inputs=1)
def matrix_diag(x):
    return x[..., None] * jnp.eye(x.shape[-1], dtype=x.dtype)


@op("matrix_set_diag", _S, n_inputs=2)
def matrix_set_diag(x, diagonal):
    n = min(x.shape[-2], x.shape[-1])
    r = jnp.arange(n)
    return x.at[..., r, r].set(diagonal)


@op("space_to_depth", _S, n_inputs=1)
def space_to_depth(x, block_size: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    b, h, w, c = x.shape
    bs = block_size
    x = x.reshape(b, h // bs, bs, w // bs, bs, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, h // bs, w // bs, bs * bs * c)
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("depth_to_space", _S, n_inputs=1)
def depth_to_space(x, block_size: int, data_format: str = "NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    b, h, w, c = x.shape
    bs = block_size
    x = x.reshape(b, h, w, bs, bs, c // (bs * bs))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, h * bs, w * bs, c // (bs * bs))
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@op("batch_to_space", _S, n_inputs=1)
def batch_to_space(x, block_shape, crops):
    import numpy as np
    bs = list(block_shape)
    b = x.shape[0]
    prod = int(np.prod(bs))
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    x = x.reshape(bs + [b // prod] + list(spatial) + list(rest))
    perm = [len(bs)]
    for i in range(len(bs)):
        perm += [len(bs) + 1 + i, i]
    perm += list(range(2 * len(bs) + 1, x.ndim))
    x = jnp.transpose(x, perm)
    new_spatial = [spatial[i] * bs[i] for i in range(len(bs))]
    x = x.reshape([b // prod] + new_spatial + list(rest))
    idx = [slice(None)]
    for i, (c0, c1) in enumerate(crops):
        idx.append(slice(c0, new_spatial[i] - c1))
    return x[tuple(idx)]


@op("space_to_batch", _S, n_inputs=1)
def space_to_batch(x, block_shape, paddings):
    import numpy as np
    bs = list(block_shape)
    pw = [(0, 0)] + [tuple(p) for p in paddings] + [(0, 0)] * (x.ndim - 1 - len(bs))
    x = jnp.pad(x, pw)
    b = x.shape[0]
    spatial = x.shape[1:1 + len(bs)]
    rest = x.shape[1 + len(bs):]
    shape = [b]
    for i in range(len(bs)):
        shape += [spatial[i] // bs[i], bs[i]]
    shape += list(rest)
    x = x.reshape(shape)
    perm = []
    for i in range(len(bs)):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in range(len(bs)):
        perm.append(1 + 2 * i)
    perm += list(range(1 + 2 * len(bs), x.ndim))
    x = jnp.transpose(x, perm)
    prod = int(np.prod(bs))
    return x.reshape([b * prod] + [spatial[i] // bs[i] for i in range(len(bs))] + list(rest))


@op("top_k", _S, n_inputs=1, differentiable=False)
def top_k(x, k: int, sorted: bool = True):
    values, indices = lax.top_k(x, k)
    return values, indices


@op("in_top_k", _S, n_inputs=2, differentiable=False)
def in_top_k(predictions, targets, k: int):
    _, idx = lax.top_k(predictions, k)
    return jnp.any(idx == targets[:, None], axis=-1)


@op("unique", _S, n_inputs=1, differentiable=False)
def unique(x, size: int = None):
    # XLA needs static sizes; `size` bounds the output (pads with first value)
    vals, idx = jnp.unique(x, return_inverse=True, size=size)
    return vals, idx


@op("dynamic_partition", _S, n_inputs=2, differentiable=False)
def dynamic_partition(x, partitions, num_partitions: int):
    # static-size variant: returns mask-selected, zero-padded partitions;
    # partitions indexes the leading dim(s), broadcast over the rest
    mask_shape = partitions.shape + (1,) * (x.ndim - partitions.ndim)
    p = partitions.reshape(mask_shape)
    return tuple(jnp.where(p == i, x, jnp.zeros_like(x))
                 for i in range(num_partitions))


@op("dynamic_stitch", _S, differentiable=False)
def dynamic_stitch(indices_list_then_data_list, *rest):
    args = (indices_list_then_data_list,) + rest
    n = len(args) // 2
    idxs, datas = args[:n], args[n:]
    total = sum(int(i.size) for i in idxs)
    elem_shape = datas[0].shape[idxs[0].ndim:]
    out = jnp.zeros((total,) + elem_shape, dtype=datas[0].dtype)
    for i, d in zip(idxs, datas):
        out = out.at[i.reshape(-1)].set(d.reshape((-1,) + d.shape[i.ndim:]))
    return out


@op("confusion_matrix", _S, n_inputs=2, differentiable=False)
def confusion_matrix(labels, predictions, num_classes: int, weights=None):
    cm = jnp.zeros((num_classes, num_classes), dtype=jnp.float32 if weights is not None else jnp.int32)
    w = weights if weights is not None else jnp.ones_like(labels, dtype=cm.dtype)
    return cm.at[labels, predictions].add(w)


@op("assign_op", _S, n_inputs=2, aliases=("copy",))
def assign_op(x, y):
    return jnp.broadcast_to(y.astype(x.dtype), x.shape)


@op("stop_gradient", _S, n_inputs=1)
def stop_gradient(x):
    return lax.stop_gradient(x)


@op("checknumerics", _S, n_inputs=1, differentiable=False)
def checknumerics(x, message: str = "CheckNumerics failed"):
    # reference: parity_ops/check_numerics.cpp — NaN/Inf panic (SURVEY §5)
    from jax.experimental import checkify  # noqa: F401
    return jax.lax.cond(
        jnp.all(jnp.isfinite(x)), lambda: x,
        lambda: x * jnp.nan)  # propagates NaN; host-side checks live in executioner


@op("bincount", _S, n_inputs=1, differentiable=False)
def bincount(x, weights=None, minlength: int = 0, maxlength: int = None, length: int = None):
    n = length if length is not None else maxlength
    if n is None and minlength > 0:
        n = minlength
    return jnp.bincount(x.reshape(-1), weights=None if weights is None else weights.reshape(-1),
                        length=n)
