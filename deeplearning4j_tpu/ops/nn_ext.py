"""Extended NN ops: peephole LSTM, capsule routing, YOLOv2 loss.

Reference parity:
- graves_lstm_layer: layers/recurrent GravesLSTM (peephole connections,
  Graves 2013) — deeplearning4j-nn nn/conf/layers/GravesLSTM.java + the
  native lstmLayer peephole mode (libnd4j helpers/lstmLayer.h).
- capsule ops: nn/conf/layers/{CapsuleLayer, PrimaryCapsules,
  CapsuleStrengthLayer}.java (Sabour et al. dynamic routing).
- yolo2_loss: nn/layers/objdetect/Yolo2OutputLayer.java loss — label
  format [minibatch, 4+C, H, W] (grid-unit corner bbox + class one-hot),
  sigmoid xy, anchor-scaled exp wh, squared-error objectness weighted by
  IoU, lambda coord/noobj weighting per the YOLOv2 paper.

All TPU-native: scans compile to one XLA While loop; routing iterations
are a static python loop (fixed trip count -> fully unrolled/fused).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

_N = "nn"


# ---------------------------------------------------------------------------
@op("graves_lstm_cell", _N)
def graves_lstm_cell(x, h_prev, c_prev, w_ih, w_hh, w_peep, b):
    """Peephole LSTM cell. Gate order [i, f, g, o] like lstm_cell;
    w_peep: (3, units) peephole weights for i (c_prev), f (c_prev),
    o (c_new)."""
    u = h_prev.shape[-1]
    z = jnp.matmul(x, w_ih) + jnp.matmul(h_prev, w_hh) + b
    zi, zf, zg, zo = (z[..., :u], z[..., u:2 * u], z[..., 2 * u:3 * u],
                      z[..., 3 * u:])
    i = jax.nn.sigmoid(zi + w_peep[0] * c_prev)
    f = jax.nn.sigmoid(zf + w_peep[1] * c_prev)
    g = jnp.tanh(zg)
    c = f * c_prev + i * g
    o = jax.nn.sigmoid(zo + w_peep[2] * c)
    h = o * jnp.tanh(c)
    return h, c


@op("graves_lstm_layer", _N)
def graves_lstm_layer(x, h0, c0, w_ih, w_hh, w_peep, b,
                      time_major: bool = False,
                      return_sequences: bool = True):
    """Full-sequence peephole LSTM via one lax.scan (reference:
    GravesLSTM layer forward, layers/recurrent/LSTMHelpers.java)."""
    xs = x if time_major else jnp.swapaxes(x, 0, 1)

    def step(carry, xt):
        h, c = carry
        h2, c2 = graves_lstm_cell(xt, h, c, w_ih, w_hh, w_peep, b)
        return (h2, c2), h2

    (hT, cT), hs = lax.scan(step, (h0, c0), xs)
    if return_sequences:
        out = hs if time_major else jnp.swapaxes(hs, 0, 1)
        return out, hT, cT
    return hT, hT, cT


# ---------------------------------------------------------------------------
@op("capsule_squash", _N, n_inputs=1)
def capsule_squash(x, axis: int = -1, epsilon: float = 1e-8):
    """squash(s) = |s|^2/(1+|s|^2) * s/|s| (Sabour et al. eq. 1)."""
    sq = jnp.sum(jnp.square(x), axis=axis, keepdims=True)
    norm = jnp.sqrt(sq + epsilon)
    return (sq / (1.0 + sq)) * x / norm


@op("capsule_routing", _N, n_inputs=2)
def capsule_routing(x, w, n_capsules: int = 0, capsule_dim: int = 0,
                    routings: int = 3):
    """Dynamic routing-by-agreement (reference: CapsuleLayer.java).

    x: (B, n_in, d_in) input capsules; w: (n_in, n_caps, d_in, d_out)
    transform. Returns (B, n_caps, d_out).
    """
    # prediction vectors u_hat: (B, n_in, n_caps, d_out)
    u_hat = jnp.einsum("bid,icdo->bico", x, w)
    B, n_in, n_caps, _ = u_hat.shape
    logits = jnp.zeros((B, n_in, n_caps), u_hat.dtype)
    # gradients flow through the full routing (matching the reference's
    # SameDiff-autodiffed CapsuleLayer); the loop is static so XLA unrolls
    # and fuses the iterations
    v = None
    for r in range(routings):
        c = jax.nn.softmax(logits, axis=2)                  # over out caps
        s = jnp.einsum("bic,bico->bco", c, u_hat)
        v = capsule_squash(s, axis=-1)
        if r < routings - 1:
            logits = logits + jnp.einsum("bico,bco->bic", u_hat, v)
    return v


# ---------------------------------------------------------------------------
@op("yolo2_loss", _N, n_inputs=2)
def yolo2_loss(pred, labels, anchors=(), lambda_coord: float = 5.0,
               lambda_noobj: float = 0.5):
    """YOLOv2 training loss (reference: objdetect/Yolo2OutputLayer loss).

    pred:   (B, H, W, A*(5+C)) raw network output (channels-last runtime)
    labels: (B, H, W, 4+C) — bbox corners (x1,y1,x2,y2) in GRID units +
            class one-hot; a cell with all-zero class vector has no object
            (reference label format [mb, 4+C, H, W], transposed).
    anchors: flat (A*2) anchor (w, h) pairs in grid units.
    """
    anchors = jnp.asarray(anchors, pred.dtype).reshape(-1, 2)
    A = anchors.shape[0]
    B, H, W, _ = pred.shape
    C = labels.shape[-1] - 4
    p = pred.reshape(B, H, W, A, 5 + C)
    txy, twh, tconf = p[..., 0:2], p[..., 2:4], p[..., 4]
    tcls = p[..., 5:]

    # decode predictions (paper eqns): center in cell via sigmoid,
    # size = anchor * exp(t)
    pxy = jax.nn.sigmoid(txy)
    pwh = anchors * jnp.exp(jnp.clip(twh, -8.0, 8.0))
    pconf = jax.nn.sigmoid(tconf)

    # label decode
    cls = labels[..., 4:]
    obj_mask = (jnp.sum(cls, axis=-1) > 0).astype(pred.dtype)   # (B,H,W)
    x1, y1, x2, y2 = (labels[..., 0], labels[..., 1], labels[..., 2],
                      labels[..., 3])
    gwh = jnp.stack([x2 - x1, y2 - y1], -1)                      # grid units
    cx = jnp.arange(W, dtype=pred.dtype)[None, None, :]
    cy = jnp.arange(H, dtype=pred.dtype)[None, :, None]
    gxy = jnp.stack([(x1 + x2) / 2 - cx, (y1 + y2) / 2 - cy], -1)

    # responsible anchor = best IoU with the cell's box (by shape)
    inter = jnp.minimum(gwh[..., None, 0], anchors[:, 0]) * \
        jnp.minimum(gwh[..., None, 1], anchors[:, 1])
    union = gwh[..., 0:1] * gwh[..., 1:2] + anchors[:, 0] * anchors[:, 1] \
        - inter
    iou_a = inter / jnp.maximum(union, 1e-8)                     # (B,H,W,A)
    resp = jax.nn.one_hot(jnp.argmax(iou_a, -1), A, dtype=pred.dtype)
    resp = resp * obj_mask[..., None]                            # (B,H,W,A)

    # coordinate loss on the responsible anchor
    exy = jnp.sum(jnp.square(pxy - gxy[..., None, :]), -1)
    ewh = jnp.sum(jnp.square(jnp.sqrt(jnp.maximum(pwh, 1e-8))
                             - jnp.sqrt(jnp.maximum(gwh[..., None, :], 1e-8))), -1)
    loss_coord = jnp.sum(resp * (exy + ewh))

    # objectness: responsible -> IoU target; others -> 0
    conf_target = resp * iou_a
    loss_obj = jnp.sum(resp * jnp.square(pconf - conf_target))
    loss_noobj = jnp.sum((1.0 - resp) * jnp.square(pconf))

    # classification on responsible anchors
    pc = jax.nn.softmax(tcls, axis=-1)
    loss_cls = jnp.sum(resp[..., None] * jnp.square(pc - cls[..., None, :]))

    n = jnp.maximum(jnp.sum(obj_mask), 1.0)
    return (lambda_coord * loss_coord + loss_obj
            + lambda_noobj * loss_noobj + loss_cls) / n


# ---------------------------------------------------------------------------
@op("conv_lstm2d", _N)
def conv_lstm2d(x, h0, c0, w_ih, w_hh, b, strides=(1, 1),
                padding: str = "SAME", return_sequences: bool = True):
    """Convolutional LSTM over an image sequence (reference: the Keras
    ConvLSTM2D layer the modelimport module maps —
    keras/layers/convolutional/KerasConvLSTM2D.java; recurrence per
    Shi et al. 2015). One lax.scan over time; each step computes all four
    gates with two convolutions (input + recurrent), so the whole layer
    compiles to a single fused XLA While loop.

    x: (B, T, H, W, Cin) channels-last; h0/c0: (B, H', W', F);
    w_ih: (kh, kw, Cin, 4F); w_hh: (kh, kw, F, 4F); b: (4F,).
    Gate order [i, f, g, o] (Keras's i, f, c, o).
    """
    xs = jnp.swapaxes(x, 0, 1)                     # (T, B, H, W, C)
    dn = ("NHWC", "HWIO", "NHWC")

    def conv(inp, w, stride, pad):
        return lax.conv_general_dilated(
            inp, w, window_strides=tuple(stride), padding=pad,
            dimension_numbers=dn)

    def step(carry, xt):
        h, c = carry
        # the recurrent conv is ALWAYS stride-1 SAME (Keras semantics):
        # h must keep the spatial shape the input conv produced, under
        # any input padding/stride
        z = (conv(xt, w_ih, strides, padding)
             + conv(h, w_hh, (1, 1), "SAME") + b)
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    (hT, cT), hs = lax.scan(step, (h0, c0), xs)
    if return_sequences:
        return jnp.swapaxes(hs, 0, 1), hT, cT      # (B, T, H', W', F)
    return hT, hT, cT


@op("conv_lstm2d_init_state", _N, n_inputs=1, differentiable=False)
def conv_lstm2d_init_state(x, units: int, height: int, width: int):
    """Zero initial state (B, H', W', F) from the (B, T, H, W, C) input."""
    return jnp.zeros((x.shape[0], height, width, units), x.dtype)
