"""Neural-network ops: conv, pooling, normalization, attention, recurrent.

Reference parity: ops/declarable/generic/nn/ (conv2d.cpp:39, conv2d_bp,
pooling2d, batchnorm.cpp, dot_product_attention.cpp:34,
multi_head_dot_product_attention.cpp:34, lstmLayer via helpers/lstmLayer.h,
...). The reference implements these as im2col+GEMM or cuDNN calls; here they
lower to lax.conv_general_dilated / lax.reduce_window / dot_general which XLA
maps straight onto the MXU — backward passes come from jax AD instead of the
reference's hand-written *_bp ops.

Data formats: DL4J convs default to NCHW with NHWC configurable
(nn/conf/CNN2DFormat.java); both are supported via the data_format attr.
Weight layout convention here is HWIO for 2d convs (TPU/XLA-preferred).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.ops.registry import op

_N = "nn"


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _same_pad(in_size: int, stride: int, k_eff: int) -> Tuple[int, int]:
    out = -(-in_size // stride)
    total = max(0, (out - 1) * stride + k_eff - in_size)
    return total // 2, total - total // 2


def _conv_padding(pad, in_sizes, strides, k_effs):
    if isinstance(pad, str):
        p = pad.upper()
        if p == "SAME":
            return [_same_pad(i, s, k) for i, s, k in zip(in_sizes, strides, k_effs)]
        if p == "VALID":
            return [(0, 0)] * len(in_sizes)
        raise ValueError(f"unknown padding {pad}")
    pads = [_pair(p) for p in pad] if isinstance(pad, (list, tuple)) else [_pair(pad)] * len(in_sizes)
    return pads


# ----------------------------------------------------------------------
# convolutions
# ----------------------------------------------------------------------
@op("conv2d", _N, n_inputs=2)
def conv2d(x, w, bias=None, strides=(1, 1), padding="SAME", dilation=(1, 1),
           data_format: str = "NCHW"):
    """2D convolution (reference: generic/nn/convo/conv2d.cpp:39).

    ``w`` layout: HWIO (kH, kW, inC, outC) — the reference's [kH,kW,iC,oC]
    default weights format matches.
    """
    strides = _pair(strides)
    dilation = _pair(dilation)
    dn = ("NCHW", "HWIO", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")
    spatial = (2, 3) if data_format == "NCHW" else (1, 2)
    k_effs = [(w.shape[i] - 1) * dilation[i] + 1 for i in range(2)]
    pad = _conv_padding(padding, [x.shape[s] for s in spatial], strides, k_effs)
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW"
                     else bias.reshape(1, 1, 1, -1))
    return out


@op("conv1d", _N, n_inputs=2)
def conv1d(x, w, bias=None, stride=1, padding="SAME", dilation=1,
           data_format: str = "NCW"):
    """1D convolution (reference: generic/nn/convo/conv1d.cpp). w: (k, inC, outC)."""
    dn = ("NCH", "HIO", "NCH") if data_format in ("NCW", "NCH") else ("NHC", "HIO", "NHC")
    spatial = 2 if data_format in ("NCW", "NCH") else 1
    k_eff = (w.shape[0] - 1) * dilation + 1
    pad = _conv_padding(padding, [x.shape[spatial]], [stride], [k_eff])
    out = lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=pad, rhs_dilation=(dilation,),
        dimension_numbers=dn)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1) if spatial == 2 else bias.reshape(1, 1, -1))
    return out


@op("conv3d", _N, n_inputs=2)
def conv3d(x, w, bias=None, strides=(1, 1, 1), padding="SAME",
           dilation=(1, 1, 1), data_format: str = "NCDHW"):
    """3D convolution (reference: generic/nn/convo/conv3d.cpp). w: (kD,kH,kW,inC,outC)."""
    strides = tuple(strides) if not isinstance(strides, int) else (strides,) * 3
    dilation = tuple(dilation) if not isinstance(dilation, int) else (dilation,) * 3
    dn = (("NCDHW", "DHWIO", "NCDHW") if data_format == "NCDHW"
          else ("NDHWC", "DHWIO", "NDHWC"))
    spatial = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    k_effs = [(w.shape[i] - 1) * dilation[i] + 1 for i in range(3)]
    pad = _conv_padding(padding, [x.shape[s] for s in spatial], strides, k_effs)
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn)
    if bias is not None:
        shape = [1] * 5
        shape[1 if data_format == "NCDHW" else 4] = -1
        out = out + bias.reshape(shape)
    return out


@op("depthwise_conv2d", _N, n_inputs=2)
def depthwise_conv2d(x, w, bias=None, strides=(1, 1), padding="SAME",
                     dilation=(1, 1), data_format: str = "NCHW"):
    """Depthwise conv (reference: generic/nn/convo/depthwiseConv2d.cpp).

    w: (kH, kW, inC, multiplier) — reference layout.
    """
    strides = _pair(strides)
    dilation = _pair(dilation)
    c_in = x.shape[1] if data_format == "NCHW" else x.shape[3]
    mult = w.shape[3]
    # XLA depthwise = grouped conv with feature_group_count = C
    w_r = w.reshape(w.shape[0], w.shape[1], 1, c_in * mult)
    dn = ("NCHW", "HWIO", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")
    spatial = (2, 3) if data_format == "NCHW" else (1, 2)
    k_effs = [(w.shape[i] - 1) * dilation[i] + 1 for i in range(2)]
    pad = _conv_padding(padding, [x.shape[s] for s in spatial], strides, k_effs)
    out = lax.conv_general_dilated(
        x, w_r, window_strides=strides, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=c_in)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW"
                     else bias.reshape(1, 1, 1, -1))
    return out


@op("separable_conv2d", _N, n_inputs=3)
def separable_conv2d(x, depth_w, point_w, bias=None, strides=(1, 1),
                     padding="SAME", dilation=(1, 1), data_format: str = "NCHW"):
    """Separable conv (reference: generic/nn/convo/sconv2d.cpp)."""
    y = depthwise_conv2d(x, depth_w, None, strides, padding, dilation, data_format)
    return conv2d(y, point_w, bias, (1, 1), "VALID", (1, 1), data_format)


@op("deconv2d", _N, n_inputs=2, aliases=("conv2d_transpose",))
def deconv2d(x, w, bias=None, strides=(1, 1), padding="SAME",
             dilation=(1, 1), data_format: str = "NCHW"):
    """Transposed conv (reference: generic/nn/convo/deconv2d.cpp). w: HWIO
    with I = output channels of the deconv (weights stored like the fwd conv
    they transpose: (kH, kW, oC, iC))."""
    strides = _pair(strides)
    dn = ("NCHW", "HWIO", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")
    out = lax.conv_transpose(
        x, w, strides=strides,
        padding=padding if isinstance(padding, str) else [_pair(p) for p in padding],
        rhs_dilation=_pair(dilation), dimension_numbers=dn,
        transpose_kernel=True)
    if bias is not None:
        out = out + (bias.reshape(1, -1, 1, 1) if data_format == "NCHW"
                     else bias.reshape(1, 1, 1, -1))
    return out


@op("im2col", _N, n_inputs=1)
def im2col(x, kernel=(1, 1), strides=(1, 1), padding=(0, 0), dilation=(1, 1)):
    """Patch extraction (reference: helpers/im2col.h). x: NCHW →
    (N, C, kH, kW, outH, outW). Exists for parity/debug; convs do NOT go
    through im2col here — XLA lowers conv directly."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    n, c, h, w_ = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - (kh - 1) * dh - 1) // sh + 1
    ow = (w_ + 2 * pw - (kw - 1) * dw - 1) // sw + 1
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(x[:, :, i * dh:i * dh + oh * sh:sh, j * dw:j * dw + ow * sw:sw])
    out = jnp.stack(patches, axis=2)  # N, C, kh*kw, oh, ow
    return out.reshape(n, c, kh, kw, oh, ow)


@op("upsampling2d", _N, n_inputs=1)
def upsampling2d(x, factor=(2, 2), data_format: str = "NCHW"):
    """Nearest-neighbour upsampling (reference: generic/nn/convo/upsampling2d.cpp)."""
    fh, fw = _pair(factor)
    if data_format == "NCHW":
        return jnp.repeat(jnp.repeat(x, fh, axis=2), fw, axis=3)
    return jnp.repeat(jnp.repeat(x, fh, axis=1), fw, axis=2)


# ----------------------------------------------------------------------
# pooling
# ----------------------------------------------------------------------
def _pool2d(x, kernel, strides, padding, data_format, init, reduce_fn, post=None):
    kh, kw = _pair(kernel)
    sh, sw = _pair(strides)
    if data_format == "NCHW":
        dims, strd = (1, 1, kh, kw), (1, 1, sh, sw)
        spatial = (2, 3)
    else:
        dims, strd = (1, kh, kw, 1), (1, sh, sw, 1)
        spatial = (1, 2)
    pads = _conv_padding(padding, [x.shape[s] for s in spatial], (sh, sw), (kh, kw))
    full_pad = [(0, 0), (0, 0), pads[0], pads[1]] if data_format == "NCHW" else \
               [(0, 0), pads[0], pads[1], (0, 0)]
    out = lax.reduce_window(x, init, reduce_fn, dims, strd, full_pad)
    if post is not None:
        out = post(out, x, dims, strd, full_pad)
    return out


@op("max_pool2d", _N, n_inputs=1, aliases=("maxpool2d",))
def max_pool2d(x, kernel=(2, 2), strides=None, padding="VALID",
               data_format: str = "NCHW"):
    strides = strides if strides is not None else kernel
    return _pool2d(x, kernel, strides, padding, data_format,
                   -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                   lax.max)


@op("avg_pool2d", _N, n_inputs=1, aliases=("avgpool2d",))
def avg_pool2d(x, kernel=(2, 2), strides=None, padding="VALID",
               data_format: str = "NCHW", count_include_pad: bool = True):
    strides = strides if strides is not None else kernel
    def post(out, xin, dims, strd, full_pad):
        if count_include_pad:
            k = 1
            for d in dims:
                k *= d
            return out / k
        ones = jnp.ones_like(xin)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strd, full_pad)
        return out / counts
    return _pool2d(x, kernel, strides, padding, data_format, 0.0, lax.add, post)


@op("pnorm_pool2d", _N, n_inputs=1)
def pnorm_pool2d(x, kernel=(2, 2), strides=None, padding="VALID", pnorm: int = 2,
                 data_format: str = "NCHW"):
    """P-norm pooling (reference: pooling2d PNORM mode, SubsamplingLayer)."""
    strides = strides if strides is not None else kernel
    powed = jnp.power(jnp.abs(x), pnorm)
    s = _pool2d(powed, kernel, strides, padding, data_format, 0.0, lax.add)
    return jnp.power(s, 1.0 / pnorm)


@op("max_pool3d", _N, n_inputs=1)
def max_pool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID",
               data_format: str = "NCDHW"):
    strides = strides if strides is not None else kernel
    k = tuple(kernel) if not isinstance(kernel, int) else (kernel,) * 3
    s = tuple(strides) if not isinstance(strides, int) else (strides,) * 3
    if data_format == "NCDHW":
        dims, strd, spatial = (1, 1) + k, (1, 1) + s, (2, 3, 4)
    else:
        dims, strd, spatial = (1,) + k + (1,), (1,) + s + (1,), (1, 2, 3)
    pads = _conv_padding(padding, [x.shape[a] for a in spatial], s, k)
    fp = ([(0, 0), (0, 0)] + pads) if data_format == "NCDHW" else ([(0, 0)] + pads + [(0, 0)])
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strd, fp)


@op("avg_pool3d", _N, n_inputs=1)
def avg_pool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID",
               data_format: str = "NCDHW"):
    strides = strides if strides is not None else kernel
    k = tuple(kernel) if not isinstance(kernel, int) else (kernel,) * 3
    s = tuple(strides) if not isinstance(strides, int) else (strides,) * 3
    if data_format == "NCDHW":
        dims, strd, spatial = (1, 1) + k, (1, 1) + s, (2, 3, 4)
    else:
        dims, strd, spatial = (1,) + k + (1,), (1,) + s + (1,), (1, 2, 3)
    pads = _conv_padding(padding, [x.shape[a] for a in spatial], s, k)
    fp = ([(0, 0), (0, 0)] + pads) if data_format == "NCDHW" else ([(0, 0)] + pads + [(0, 0)])
    kn = 1
    for d in k:
        kn *= d
    return lax.reduce_window(x, 0.0, lax.add, dims, strd, fp) / kn


@op("global_avg_pool", _N, n_inputs=1)
def global_avg_pool(x, data_format: str = "NCHW", keep_dims: bool = False):
    ax = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.mean(x, axis=ax, keepdims=keep_dims)


@op("global_max_pool", _N, n_inputs=1)
def global_max_pool(x, data_format: str = "NCHW", keep_dims: bool = False):
    ax = (2, 3) if data_format == "NCHW" else (1, 2)
    return jnp.max(x, axis=ax, keepdims=keep_dims)


# ----------------------------------------------------------------------
# normalization
# ----------------------------------------------------------------------
@op("batchnorm", _N, aliases=("batch_norm",))
def batchnorm(x, mean, variance, gamma=None, beta=None, epsilon: float = 1e-5,
              axis: int = 1):
    """Inference-form batch norm (reference: generic/nn/batchnorm.cpp —
    applyScale/applyOffset flags map to gamma/beta being present).

    Output is always x's dtype: under the mixed-precision policy the
    running stats stay float32 masters while activations are bf16 —
    without the final cast, jax type promotion would silently upcast the
    whole downstream graph to f32."""
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    inv = lax.rsqrt(variance.astype(jnp.float32) + epsilon)
    a = inv if gamma is None else gamma.astype(jnp.float32) * inv
    b = -mean.astype(jnp.float32) * a
    if beta is not None:
        b = b + beta.astype(jnp.float32)
    # one elementwise pass in x's dtype (per-channel a,b precomputed)
    return x * a.astype(x.dtype).reshape(shape) + b.astype(x.dtype).reshape(shape)


@op("batchnorm_train", _N)
def batchnorm_train(x, gamma, beta, running_mean, running_var,
                    momentum: float = 0.9, epsilon: float = 1e-5, axis: int = 1):
    """Training-form batch norm: batch stats + updated running stats.

    Returns (out, new_running_mean, new_running_var). Reference decay
    semantics (BatchNormalization.java 'decay'): new = decay*old + (1-decay)*batch.

    Batch statistics are computed in float32 regardless of x's dtype —
    bf16 mean/variance reductions over large batches lose the low bits
    that the running-stat EMA depends on. The big-tensor math stays in
    x's dtype: the reductions accumulate in f32 (XLA fuses the convert
    into the reduce, reading bf16 from HBM once), and the normalization
    is a per-channel scale+shift a*x+b with a/b derived from the f32
    stats — so no f32 copy of the activation is ever materialized
    (HBM bandwidth is the TPU bottleneck, not FLOPs).
    """
    red = tuple(i for i in range(x.ndim) if i != axis)
    lowp = x.dtype in (jnp.bfloat16, jnp.float16)
    xf = x.astype(jnp.float32) if lowp else x
    # ONE-PASS moments: jnp.var is two-pass (read x for the mean, re-read
    # for (x-mean)^2) — profiled at ~30% of the ResNet-50 step as
    # subtract_subtract/convert_reduce fusions. Sibling mean reductions
    # fuse into a single multi-output fusion that reads x from HBM once;
    # E[x^2]-E[x]^2 in f32 is plenty for normalization statistics.
    mean = jnp.mean(xf, axis=red)                 # convert fused into reduce
    m2 = jnp.mean(xf * xf, axis=red)
    var = jnp.maximum(m2 - mean * mean, 0.0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    inv = lax.rsqrt(var + epsilon)
    # per-channel (tiny) f32 math, then one bf16 elementwise pass
    a = (gamma.astype(jnp.float32) * inv).astype(x.dtype)
    b = (beta.astype(jnp.float32)
         - gamma.astype(jnp.float32) * inv * mean).astype(x.dtype)
    out = x * a.reshape(shape) + b.reshape(shape)
    n = x.size // x.shape[axis]
    unbiased = var * n / max(n - 1, 1)
    new_mean = momentum * running_mean + (1 - momentum) * mean.astype(running_mean.dtype)
    new_var = momentum * running_var + (1 - momentum) * unbiased.astype(running_var.dtype)
    return out, new_mean, new_var


@op("layer_norm", _N, aliases=("layernorm",))
def layer_norm(x, gamma, beta=None, axis=-1, epsilon: float = 1e-5):
    """Layer norm (reference: generic/nn/layer_norm.cpp — standardize +
    scale + optional shift)."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    # one-pass moments (see batchnorm_train): sibling means fuse into one
    # read of x; avoids jnp.var's second full pass. Statistics in f32 —
    # E[x^2]-E[x]^2 cancels catastrophically in bf16 when |mean| >> std;
    # XLA fuses the convert into the reduces so x is still read once in
    # its own dtype and no f32 copy is materialized.
    lowp = x.dtype in (jnp.bfloat16, jnp.float16)
    xf = x.astype(jnp.float32) if lowp else x
    mean = jnp.mean(xf, axis=ax, keepdims=True)
    m2 = jnp.mean(xf * xf, axis=ax, keepdims=True)
    var = jnp.maximum(m2 - mean * mean, 0.0)
    inv = lax.rsqrt(var + epsilon)
    out = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) * gamma
    if beta is not None:
        out = out + beta
    return out


@op("standardize", _N, n_inputs=1)
def standardize(x, axis=-1, epsilon: float = 0.0):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    mean = jnp.mean(x, axis=ax, keepdims=True)
    std = jnp.std(x, axis=ax, keepdims=True)
    return (x - mean) / jnp.maximum(std, 1e-12 if epsilon == 0.0 else epsilon)


@op("lrn", _N, n_inputs=1)
def lrn(x, depth: int = 5, bias: float = 1.0, alpha: float = 1.0,
        beta: float = 0.5, data_format: str = "NCHW"):
    """Local response normalization (reference: generic/nn/lrn.cpp).

    depth = half-window (n/2), matching the reference's LRN config k/n/alpha/beta.
    """
    caxis = 1 if data_format == "NCHW" else x.ndim - 1
    sq = jnp.square(x)
    win = 2 * depth + 1
    mv = jnp.moveaxis(sq, caxis, -1)
    padded = jnp.pad(mv, [(0, 0)] * (x.ndim - 1) + [(depth, depth)])
    acc = jnp.zeros_like(mv)
    for i in range(win):
        acc = acc + lax.dynamic_slice_in_dim(padded, i, mv.shape[-1], axis=x.ndim - 1)
    acc = jnp.moveaxis(acc, -1, caxis)
    return x / jnp.power(bias + alpha * acc, beta)


# ----------------------------------------------------------------------
# embeddings & misc
# ----------------------------------------------------------------------
@op("embedding_lookup", _N, n_inputs=2)
def embedding_lookup(table, ids):
    """(reference: generic/parity_ops/embedding_lookup.cpp) — gather rows;
    one-hot-matmul is used automatically by XLA where it wins on TPU."""
    return jnp.take(table, ids, axis=0)


@op("bias_add", _N, n_inputs=2)
def bias_add(x, bias, data_format: str = "NHWC"):
    if data_format == "NCHW" and x.ndim > 2:
        shape = [1] * x.ndim
        shape[1] = -1
        return x + bias.reshape(shape)
    return x + bias


@op("linear_layer", _N, aliases=("xw_plus_b",))
def linear_layer(x, w, b=None):
    out = jnp.matmul(x, w)
    return out + b if b is not None else out


# ----------------------------------------------------------------------
# attention (reference: generic/nn/dot_product_attention.cpp:34 and
# multi_head_dot_product_attention.cpp:34)
# ----------------------------------------------------------------------
@op("dot_product_attention", _N)
def dot_product_attention(queries, keys, values, mask=None, scaled: bool = True,
                          with_weights: bool = False):
    """Single-head scaled dot-product attention.

    Shapes follow jax convention (..., seq, depth); the nn layer adapters
    handle the reference's [batch, depth, seq] layout.
    """
    d = queries.shape[-1]
    scores = jnp.matmul(queries, jnp.swapaxes(keys, -1, -2))
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.finfo(scores.dtype).min)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.matmul(weights, values)
    return (out, weights) if with_weights else out


@op("scaled_dot_product_attention", _N, aliases=("sdpa",))
def scaled_dot_product_attention(q, k, v, mask=None, causal: bool = False,
                                 scale: float = None):
    """Fused multi-head attention core, TPU-shaped: q/k/v are
    (batch, heads, seq, head_dim); score accumulation and softmax run in
    f32 regardless of input dtype (bf16-safe — the MXU accumulates f32
    natively so the upcast is free), probabilities are cast back to the
    value dtype for the PV matmul.

    ``causal=True`` applies the autoregressive mask; ``mask`` (broadcast
    to [batch, heads, sq, sk], nonzero = attend) composes with it.
    Reference: multi_head_dot_product_attention.cpp:34 computes the same
    math head-by-head via mmul/softmax graph ops; here it is one op so
    XLA sees the whole pattern and its backward as a unit.
    """
    d = q.shape[-1]
    s = (1.0 / np.sqrt(d)) if scale is None else scale
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(cm, scores, jnp.float32(-1e30))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.float32(-1e30))
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@op("multi_head_dot_product_attention", _N)
def multi_head_dot_product_attention(queries, keys, values, wq, wk, wv, wo,
                                     nheads: int, mask=None, scaled: bool = True):
    """Multi-head attention with projection weights (reference:
    multi_head_dot_product_attention.cpp:34 — projects with Wq/Wk/Wv, applies
    scaled dot-product per head, recombines with Wo).

    queries/keys/values: (batch, seq, dmodel); wq/wk/wv: (dmodel, nheads*dk);
    wo: (nheads*dv, dmodel); ``nheads`` is explicit (the reference derives it
    from rank-3 per-head weight tensors, which 2-D projections can't encode).
    """
    b, sq, _ = queries.shape
    sk = keys.shape[1]
    q = jnp.matmul(queries, wq)
    k = jnp.matmul(keys, wk)
    v = jnp.matmul(values, wv)

    def split_heads(t, seq):
        return jnp.transpose(t.reshape(b, seq, nheads, -1), (0, 2, 1, 3))

    att = dot_product_attention(split_heads(q, sq), split_heads(k, sk),
                                split_heads(v, sk), mask=mask, scaled=scaled)
    merged = jnp.transpose(att, (0, 2, 1, 3)).reshape(b, sq, -1)
    return jnp.matmul(merged, wo)


# ----------------------------------------------------------------------
# recurrent cells (reference: helpers/lstmLayer.h, generic/recurrent/)
# ----------------------------------------------------------------------
@op("lstm_cell", _N)
def lstm_cell(x, h_prev, c_prev, w_ih, w_hh, b):
    """One LSTM step. Gate order [i, f, g, o] (reference lstmLayer gate order
    with forget-gate semantics; cIFOG handled at the layer adapter).

    x: (batch, in), h/c: (batch, units), w_ih: (in, 4*units),
    w_hh: (units, 4*units), b: (4*units,).
    """
    z = jnp.matmul(x, w_ih) + jnp.matmul(h_prev, w_hh) + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("lstm_layer", _N, aliases=("lstmLayer",))
def lstm_layer(x, h0, c0, w_ih, w_hh, b, time_major: bool = False,
               return_sequences: bool = True):
    """Full-sequence LSTM via lax.scan — ONE compiled loop, not per-step
    dispatch (reference: generic/recurrent/lstmLayer.cpp executes the same
    recurrence as a C++ loop over time steps).
    """
    xs = x if time_major else jnp.swapaxes(x, 0, 1)  # (T, B, in)

    def step(carry, xt):
        h, c = carry
        h2, c2 = lstm_cell(xt, h, c, w_ih, w_hh, b)
        return (h2, c2), h2

    (hT, cT), hs = lax.scan(step, (h0, c0), xs)
    if return_sequences:
        out = hs if time_major else jnp.swapaxes(hs, 0, 1)
        return out, hT, cT
    return hT, hT, cT


@op("rnn_init_state", _N, n_inputs=1, differentiable=False)
def rnn_init_state(x, units: int, time_major: bool = False):
    """Zero initial hidden state (batch, units) derived from the sequence
    input inside the graph — keeps batch size dynamic (no host-side shape
    dependency; reference layers allocate h0/c0 eagerly per minibatch)."""
    batch = x.shape[0] if not time_major else x.shape[1]
    return jnp.zeros((batch, units), x.dtype)


@op("gru_cell", _N)
def gru_cell(x, h_prev, w_ih, w_hh, b_ih, b_hh):
    """One GRU step (reference: generic/recurrent/gruCell.cpp gate order r,u,c)."""
    gi = jnp.matmul(x, w_ih) + b_ih
    gh = jnp.matmul(h_prev, w_hh) + b_hh
    i_r, i_u, i_c = jnp.split(gi, 3, axis=-1)
    h_r, h_u, h_c = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    u = jax.nn.sigmoid(i_u + h_u)
    c = jnp.tanh(i_c + r * h_c)
    return u * h_prev + (1 - u) * c


@op("gru_layer", _N, aliases=("gru",))
def gru_layer(x, h0, w_ih, w_hh, b_ih, b_hh, time_major: bool = False):
    xs = x if time_major else jnp.swapaxes(x, 0, 1)

    def step(h, xt):
        h2 = gru_cell(xt, h, w_ih, w_hh, b_ih, b_hh)
        return h2, h2

    hT, hs = lax.scan(step, h0, xs)
    out = hs if time_major else jnp.swapaxes(hs, 0, 1)
    return out, hT


def _rnn_activation(name: str):
    """Resolve an activation registry-op name to its jnp-level fn (any
    elementwise activation op works; layers pre-resolve DL4J aliases)."""
    from deeplearning4j_tpu.ops import registry
    key = name.lower()
    if key in ("identity", "linear"):
        return lambda z: z
    if registry.has_op(key):
        return registry.get_op(key).fn
    raise ValueError(f"unknown rnn activation {name!r}")


@op("simple_rnn_cell", _N, aliases=("sru_cell_simple",))
def simple_rnn_cell(x, h_prev, w_ih, w_hh, b, activation: str = "tanh"):
    act = _rnn_activation(activation)
    return act(jnp.matmul(x, w_ih) + jnp.matmul(h_prev, w_hh) + b)


@op("simple_rnn_layer", _N)
def simple_rnn_layer(x, h0, w_ih, w_hh, b, time_major: bool = False,
                     activation: str = "tanh"):
    xs = x if time_major else jnp.swapaxes(x, 0, 1)

    def step(h, xt):
        h2 = simple_rnn_cell(xt, h, w_ih, w_hh, b, activation)
        return h2, h2

    hT, hs = lax.scan(step, h0, xs)
    out = hs if time_major else jnp.swapaxes(hs, 0, 1)
    return out, hT
