"""TF-compat ops: structural args arrive as *tensors*, TF-style.

Reference parity: libnd4j ships a `compat` op category for framework-import
semantics (ops/declarable/generic/compat/) and the TF importer maps nodes
whose shape/axis arguments are tensors (Reshape's shape input, Mean's axes
input, StridedSlice's begin/end/strides) onto ops that accept them as
inputs (ImportGraph.kt:218 mapping rules).

TPU-native twist: under jit every array shape is static, so a `Shape` op
returns a *concrete* (non-tracer) array at trace time and any arithmetic on
it stays concrete. These compat ops convert their structural-arg inputs
with np.asarray at trace time — which succeeds exactly when the value is
trace-time-concrete (i.e. derived from shapes and constants, not from
placeholder *data*). Genuinely data-dependent shapes raise jax's
TracerArrayConversionError with a clear chain back to the offending op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.registry import op

_C = "compat"


def _ints(v):
    """Structural tensor -> tuple of python ints (trace-time concrete)."""
    a = np.asarray(v)
    return tuple(int(x) for x in a.reshape(-1))


def _int1(v):
    a = np.asarray(v)
    return int(a.reshape(()))


@op("tf_reshape", _C, n_inputs=2)
def tf_reshape(x, shape):
    """Reshape with the target shape as a tensor input (TF Reshape)."""
    return jnp.reshape(x, _ints(shape))


@op("tf_fill", _C, n_inputs=2, differentiable=False)
def tf_fill(dims, value):
    return jnp.full(_ints(dims), value)


@op("tf_range", _C, n_inputs=3, differentiable=False)
def tf_range(start, limit, delta):
    return jnp.arange(_int1(start), _int1(limit), _int1(delta),
                      dtype=jnp.asarray(start).dtype)


@op("tf_broadcast_to", _C, n_inputs=2)
def tf_broadcast_to(x, shape):
    return jnp.broadcast_to(x, _ints(shape))


@op("tf_tile", _C, n_inputs=2)
def tf_tile(x, multiples):
    return jnp.tile(x, _ints(multiples))


@op("tf_expand_dims", _C, n_inputs=2)
def tf_expand_dims(x, axis):
    return jnp.expand_dims(x, _int1(axis))


@op("tf_squeeze", _C, n_inputs=1)
def tf_squeeze(x, axis=None):
    if axis:
        axis = tuple(a % max(x.ndim, 1) for a in axis)
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis) if axis else x
    return jnp.squeeze(x)


@op("tf_reduce", _C, n_inputs=2)
def tf_reduce(x, axes, reduction: str = "mean", keepdims: bool = False):
    ax = _ints(axes)
    # TF semantics: an explicitly EMPTY reduction_indices tensor reduces over
    # no axes (identity), while a scalar/None means reduce over all axes.
    if np.asarray(axes).ndim > 0 and len(ax) == 0:
        return x
    fn = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max, "min": jnp.min,
          "prod": jnp.prod, "any": jnp.any, "all": jnp.all}[reduction]
    return fn(x, axis=ax or None, keepdims=keepdims)


@op("tf_transpose", _C, n_inputs=2)
def tf_transpose(x, perm):
    return jnp.transpose(x, _ints(perm))


@op("tf_concat", _C)
def tf_concat(*args):
    """ConcatV2: last input is the axis tensor."""
    *xs, axis = args
    return jnp.concatenate(xs, axis=_int1(axis))


@op("tf_slice", _C, n_inputs=3)
def tf_slice(x, begin, size):
    begin = _ints(begin)
    size = [x.shape[i] - b if s == -1 else s
            for i, (b, s) in enumerate(zip(begin, _ints(size)))]
    return jax.lax.slice(x, begin, tuple(b + s for b, s in zip(begin, size)))


def _strided_slice_index(begin, end, strides, begin_mask, end_mask,
                         ellipsis_mask, new_axis_mask, shrink_axis_mask):
    idx = []
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            idx.append(Ellipsis)
        elif new_axis_mask & (1 << i):
            idx.append(jnp.newaxis)
        elif shrink_axis_mask & (1 << i):
            idx.append(begin[i])
        else:
            b = None if (begin_mask & (1 << i)) else begin[i]
            e = None if (end_mask & (1 << i)) else end[i]
            idx.append(slice(b, e, strides[i]))
    return tuple(idx)


@op("tf_strided_slice", _C, n_inputs=4)
def tf_strided_slice(x, begin, end, strides, begin_mask: int = 0,
                     end_mask: int = 0, ellipsis_mask: int = 0,
                     new_axis_mask: int = 0, shrink_axis_mask: int = 0):
    """Full TF StridedSlice semantics with static begin/end/strides."""
    idx = _strided_slice_index(_ints(begin), _ints(end), _ints(strides),
                               begin_mask, end_mask, ellipsis_mask,
                               new_axis_mask, shrink_axis_mask)
    return x[idx]


@op("strided_slice_masked", _C, n_inputs=1)
def strided_slice_masked(x, begin=(), end=(), strides=(), begin_mask: int = 0,
                         end_mask: int = 0, ellipsis_mask: int = 0,
                         new_axis_mask: int = 0, shrink_axis_mask: int = 0):
    """tf_strided_slice with begin/end/strides as STATIC attrs — the TF
    importer folds the structural inputs at import time and emits this,
    keeping the traced graph free of trace-time np.asarray conversions."""
    idx = _strided_slice_index(tuple(begin), tuple(end),
                               tuple(strides) or (1,) * len(tuple(begin)),
                               begin_mask, end_mask, ellipsis_mask,
                               new_axis_mask, shrink_axis_mask)
    return x[idx]


@op("tf_gather", _C, n_inputs=3)
def tf_gather(params, indices, axis, batch_dims: int = 0):
    return _gather_impl(params, indices, _int1(axis), batch_dims)


@op("gather_batch_dims", _C, n_inputs=2)
def gather_batch_dims(params, indices, axis: int = 0, batch_dims: int = 0):
    """GatherV2 with static axis/batch_dims attrs (importer-emitted)."""
    return _gather_impl(params, indices, axis, batch_dims)


def _gather_impl(params, indices, axis, batch_dims):
    axis = axis % params.ndim
    if batch_dims == 0:
        return jnp.take(params, indices, axis=axis)
    # batched gather: vmap take over leading batch dims
    fn = lambda p, i: jnp.take(p, i, axis=axis - batch_dims)
    for _ in range(batch_dims):
        fn = jax.vmap(fn)
    return fn(params, indices)


@op("tf_one_hot", _C, n_inputs=4)
def tf_one_hot(indices, depth, on_value, off_value, axis: int = -1):
    d = _int1(depth)
    oh = jax.nn.one_hot(indices, d, axis=axis)
    on = jnp.asarray(on_value)
    off = jnp.asarray(off_value)
    return (oh * (on - off) + off).astype(on.dtype)


@op("tf_split", _C, n_inputs=2)
def tf_split(axis, value, num_split: int = 1):
    """TF Split: (axis, value) input order."""
    return tuple(jnp.split(value, num_split, axis=_int1(axis)))


@op("tf_split_v", _C, n_inputs=3)
def tf_split_v(value, size_splits, axis):
    sizes = _ints(size_splits)
    bounds = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(value, bounds, axis=_int1(axis)))


@op("tf_pad", _C, n_inputs=2)
def tf_pad(x, paddings, mode: str = "CONSTANT", constant: float = 0.0):
    pads = np.asarray(paddings).reshape(-1, 2).tolist()
    mode = {"CONSTANT": "constant", "REFLECT": "reflect",
            "SYMMETRIC": "symmetric"}[mode.upper()]
    if mode == "constant":
        return jnp.pad(x, pads, mode=mode, constant_values=constant)
    return jnp.pad(x, pads, mode=mode)


@op("tf_cumsum", _C, n_inputs=2)
def tf_cumsum(x, axis, exclusive: bool = False, reverse: bool = False):
    ax = _int1(axis)
    if reverse:
        x = jnp.flip(x, ax)
    out = jnp.cumsum(x, axis=ax)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, ax)
    return out


@op("tf_argmax", _C, n_inputs=2, differentiable=False)
def tf_argmax(x, axis, output_dtype: str = "int64"):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return jnp.argmax(x, axis=_int1(axis)).astype(
        DataType.from_any(output_dtype).jnp)


@op("tf_argmin", _C, n_inputs=2, differentiable=False)
def tf_argmin(x, axis, output_dtype: str = "int64"):
    from deeplearning4j_tpu.ndarray.dtype import DataType
    return jnp.argmin(x, axis=_int1(axis)).astype(
        DataType.from_any(output_dtype).jnp)


@op("tf_addn", _C)
def tf_addn(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@op("tf_fused_batch_norm", _C, n_inputs=5)
def tf_fused_batch_norm(x, scale, offset, mean, variance,
                        epsilon: float = 1e-3, data_format: str = "NHWC",
                        is_training: bool = False):
    """FusedBatchNormV3 (inference or batch-stats training forward)."""
    caxis = 3 if data_format == "NHWC" else 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    if is_training:
        m = jnp.mean(x, axes, dtype=jnp.float32)
        v = jnp.var(x.astype(jnp.float32), axes)
    else:
        m, v = mean, variance
    sh = [1] * x.ndim
    sh[caxis] = -1
    scale_ = (scale * jax.lax.rsqrt(v + epsilon)).reshape(sh).astype(x.dtype)
    shift_ = (offset - scale * m * jax.lax.rsqrt(v + epsilon)).reshape(sh).astype(x.dtype)
    return x * scale_ + shift_, m, v
