"""Loss ops.

Reference parity: ops/declarable/generic/loss/ (softmax_cross_entropy,
sigm_cross_entropy, hinge, huber, log_loss, mean_pairwssqerr, mean_sqerr,
absolute_difference, cosine_distance, ctc) and the DL4J ILossFunction set
(nd4j-api .../lossfunctions/impl/). ``reduction`` follows the reference modes:
"none" | "sum" | "mean_by_weight" | "mean_by_nonzero_weight" (the reference's
NONE/SUM/MEAN_BY_WEIGHT/MEAN_BY_NONZERO_WEIGHT_COUNT).
"""
from __future__ import annotations

import contextlib
import contextvars

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op

_L = "loss"

#: Active softmax/CE tail dtype policy (None = upcast to f32, the safe
#: default). Set via :func:`softmax_dtype_scope`; consulted at TRACE
#: time, so the scope must wrap the jitted function's execution — the
#: train step builder (SameDiff._build_step_parts) does this when
#: ``MixedPrecision.softmax_dtype`` is set.
_SOFTMAX_DTYPE: contextvars.ContextVar = contextvars.ContextVar(
    "dl4j_softmax_dtype", default=None)


@contextlib.contextmanager
def softmax_dtype_scope(dtype):
    """While active, the softmax-CE losses keep their log-softmax tail
    in ``dtype`` instead of upcasting to float32. The per-example
    losses are STILL reduced to the scalar loss in f32 (the accumulation
    is where bf16 actually loses training signal); what changes is the
    [batch..., vocab]-shaped exp/log/normalize tail — on a 32k vocab
    that tail is the single largest f32 tensor in a bf16 LM step
    (PROFILE.md round 5) and the MXU/VPU runs it at twice the rate in
    bf16. Routed from ``MixedPrecision.softmax_dtype``
    (docs/training_performance.md)."""
    token = _SOFTMAX_DTYPE.set(None if dtype is None else jnp.dtype(dtype))
    try:
        yield
    finally:
        _SOFTMAX_DTYPE.reset(token)


def _f32(x):
    """Loss math runs internally in float32: under bf16 compute the
    log-softmax/log reductions would otherwise lose the precision the
    training signal lives in. XLA fuses the cast into the producer."""
    return x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) \
        else x


def _tail(x):
    """Softmax-CE tail dtype: the policy dtype when a
    :func:`softmax_dtype_scope` is active, else the f32 upcast."""
    dt = _SOFTMAX_DTYPE.get()
    if dt is None:
        return _f32(x)
    return x.astype(dt)


def _reduce_loss(per_ex, weights, reduction: str):
    if weights is None:
        weights = jnp.ones_like(per_ex)
    w = jnp.broadcast_to(weights, per_ex.shape)
    weighted = per_ex * w
    r = reduction.lower()
    if r == "none":
        return weighted
    # the reduction to the scalar loss is where bf16 actually loses the
    # training signal (an 8-bit mantissa stops accumulating once the
    # running sum is ~256x a term): force an f32 accumulator whenever
    # the per-example losses arrive in a low-precision dtype — the same
    # contract the dense softmax-CE vocab sum already keeps
    acc = jnp.float32 if weighted.dtype in (jnp.bfloat16, jnp.float16) \
        else None
    if r == "sum":
        return jnp.sum(weighted, dtype=acc)
    if r in ("mean_by_weight", "weighted_mean"):
        return jnp.sum(weighted, dtype=acc) / \
            jnp.maximum(jnp.sum(w, dtype=acc), 1e-12)
    if r in ("mean_by_nonzero_weight", "mean"):
        # the nonzero COUNT accumulates f32 regardless: counting in
        # bf16 saturates at 256 examples
        nz = jnp.sum(w != 0, dtype=jnp.float32)
        return jnp.sum(weighted, dtype=acc) / \
            jnp.maximum(nz, 1.0).astype(weighted.dtype if acc is None
                                        else acc)
    raise ValueError(f"unknown reduction {reduction}")


@op("mean_sqerr_loss", _L, aliases=("mse_loss", "l2_loss_full"))
def mean_sqerr_loss(predictions, labels, weights=None, reduction: str = "mean"):
    predictions, labels = _f32(predictions), _f32(labels)
    per = jnp.mean(jnp.square(predictions - labels), axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("absolute_difference_loss", _L, aliases=("mae_loss", "l1_loss"))
def absolute_difference_loss(predictions, labels, weights=None, reduction: str = "mean"):
    per = jnp.mean(jnp.abs(predictions - labels), axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("softmax_cross_entropy", _L, aliases=("softmax_cross_entropy_loss",))
def softmax_cross_entropy(logits, labels, weights=None, reduction: str = "mean",
                          label_smoothing: float = 0.0):
    """(reference: generic/loss/softmaxCrossEntropy.cpp) labels are
    one-hot/probability distributions. The log-softmax tail honors
    :func:`softmax_dtype_scope`; the per-example reduction to the
    scalar loss is always f32."""
    logits, labels = _tail(logits), _tail(labels)
    if label_smoothing > 0.0:
        n = labels.shape[-1]
        labels = labels * (1.0 - label_smoothing) + label_smoothing / n
    logp = jax.nn.log_softmax(logits, axis=-1)
    # the vocab-axis accumulation is where bf16 actually loses signal:
    # force an f32 accumulator even when the tail runs in bf16
    per = -jnp.sum(labels * logp, axis=-1, dtype=jnp.float32)
    return _reduce_loss(per, weights, reduction)


@op("sparse_softmax_cross_entropy", _L)
def sparse_softmax_cross_entropy(logits, labels, weights=None, reduction: str = "mean"):
    """labels are integer class ids (reference:
    sparseSoftmaxCrossEntropyWithLogits.cpp). The log-softmax tail over
    the vocab axis honors :func:`softmax_dtype_scope` — the lever that
    shrinks the [B, S, 32k] f32 tail of a bf16 LM step; the gathered
    per-token losses are reduced in f32 regardless."""
    logits = _tail(logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    per = _f32(-jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0])
    return _reduce_loss(per, weights, reduction)


@op("sigm_cross_entropy", _L, aliases=("sigmoid_cross_entropy",))
def sigm_cross_entropy(logits, labels, weights=None, reduction: str = "mean",
                       label_smoothing: float = 0.0):
    logits, labels = _f32(logits), _f32(labels)
    if label_smoothing > 0.0:
        labels = labels * (1.0 - label_smoothing) + 0.5 * label_smoothing
    # numerically stable: max(x,0) - x*z + log(1+exp(-|x|))
    per_el = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    per = jnp.mean(per_el, axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("hinge_loss", _L)
def hinge_loss(predictions, labels, weights=None, reduction: str = "mean"):
    """labels in {0,1} mapped to {-1,1} (reference: hingeLoss.cpp)."""
    all_ones = jnp.ones_like(labels)
    lab = 2.0 * labels - all_ones
    per = jnp.mean(jnp.maximum(0.0, all_ones - lab * predictions), axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("squared_hinge_loss", _L)
def squared_hinge_loss(predictions, labels, weights=None, reduction: str = "mean"):
    lab = 2.0 * labels - 1.0
    per = jnp.mean(jnp.square(jnp.maximum(0.0, 1.0 - lab * predictions)), axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("huber_loss", _L)
def huber_loss(predictions, labels, weights=None, delta: float = 1.0,
               reduction: str = "mean"):
    err = jnp.abs(predictions - labels)
    quad = jnp.minimum(err, delta)
    per_el = 0.5 * quad * quad + delta * (err - quad)
    per = jnp.mean(per_el, axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("log_loss", _L)
def log_loss(predictions, labels, weights=None, epsilon: float = 1e-7,
             reduction: str = "mean"):
    predictions, labels = _f32(predictions), _f32(labels)
    p = jnp.clip(predictions, epsilon, 1.0 - epsilon)
    per_el = -labels * jnp.log(p) - (1.0 - labels) * jnp.log(1.0 - p)
    per = jnp.mean(per_el, axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("poisson_loss", _L)
def poisson_loss(predictions, labels, weights=None, reduction: str = "mean",
                 log_input: bool = False):
    predictions, labels = _f32(predictions), _f32(labels)
    if log_input:
        per_el = jnp.exp(predictions) - labels * predictions
    else:
        per_el = predictions - labels * jnp.log(jnp.maximum(predictions, 1e-12))
    per = jnp.mean(per_el, axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("kl_divergence_loss", _L, aliases=("kld_loss",))
def kl_divergence_loss(predictions, labels, weights=None, reduction: str = "mean"):
    predictions, labels = _f32(predictions), _f32(labels)
    per = jnp.sum(labels * (jnp.log(jnp.maximum(labels, 1e-12)) -
                            jnp.log(jnp.maximum(predictions, 1e-12))), axis=-1)
    return _reduce_loss(per, weights, reduction)


@op("cosine_distance_loss", _L)
def cosine_distance_loss(predictions, labels, weights=None, axis: int = -1,
                         reduction: str = "mean"):
    per = 1.0 - jnp.sum(predictions * labels, axis=axis)
    return _reduce_loss(per, weights, reduction)


@op("mean_pairwssqerr_loss", _L)
def mean_pairwssqerr_loss(predictions, labels, weights=None, reduction: str = "mean"):
    """Mean pairwise squared error (reference: meanPairWsSqErr.cpp)."""
    d = predictions - labels
    n = d.shape[-1]
    sum_d = jnp.sum(d, axis=-1, keepdims=True)
    sum_d2 = jnp.sum(d * d, axis=-1, keepdims=True)
    # sum over pairs (i<j) of (d_i - d_j)^2 = n*sum(d^2) - (sum d)^2
    pair = (n * sum_d2 - sum_d * sum_d)[..., 0]
    denom = max(n * (n - 1) // 2, 1)
    per = pair / (2.0 * denom)
    return _reduce_loss(per, weights, reduction)


@op("l2_loss", _L, n_inputs=1)
def l2_loss(x):
    return 0.5 * jnp.sum(x * x)


@op("ctc_loss", _L)
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank: int = 0):
    """CTC loss via dynamic-programming scan (reference: generic/nn/ctcLoss.cpp,
    helpers/ctcLoss). log_probs: (B, T, C) log-softmaxed; labels: (B, S) int.

    Implemented as a lax.scan over time with a (B, 2S+1) alpha lattice —
    XLA-friendly: no data-dependent shapes.
    """
    b, t_max, _ = log_probs.shape
    s_max = labels.shape[1]
    # extended label sequence with blanks: length 2S+1
    ext = jnp.full((b, 2 * s_max + 1), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_len = 2 * label_lengths + 1

    neg_inf = jnp.asarray(-1e30, dtype=log_probs.dtype)
    alpha0 = jnp.full((b, 2 * s_max + 1), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(log_probs[:, 0, :], ext[:, 1:2], axis=1)[:, 0])

    same_as_two_back = jnp.concatenate(
        [jnp.ones((b, 2), dtype=bool),
         ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, logp_t):
        # logp_t: (B, C)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (B, 2S+1)
        shift1 = jnp.concatenate([jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate([jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(same_as_two_back, neg_inf, shift2)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        return merged + emit, None

    def scan_step(carry, inp):
        alpha, t = carry
        logp_t = inp
        new_alpha, _ = step(alpha, logp_t)
        # freeze past input_length
        active = (t < input_lengths)[:, None]
        alpha = jnp.where(active, new_alpha, alpha)
        return (alpha, t + 1), None

    (alpha_T, _), _ = jax.lax.scan(scan_step, (alpha0, jnp.asarray(1)),
                                   jnp.swapaxes(log_probs, 0, 1)[1:])
    idx_last = jnp.clip(ext_len - 1, 0, 2 * s_max)
    idx_prev = jnp.clip(ext_len - 2, 0, 2 * s_max)
    p_last = jnp.take_along_axis(alpha_T, idx_last[:, None], axis=1)[:, 0]
    p_prev = jnp.take_along_axis(alpha_T, idx_prev[:, None], axis=1)[:, 0]
    return -jnp.logaddexp(p_last, p_prev)
