"""Named-op registry.

Reference parity: libnd4j's OpRegistrator + DeclarableOp table
(libnd4j/include/ops/declarable/OpRegistrator.h:67, DeclarableOp.h:67) and the
legacy opNum families (libnd4j/include/loops/legacy_ops.h). The reference
dispatches ops by name/hash into hand-written kernels; here every op is a pure
function over jax arrays that emits HLO — XLA fuses and schedules, so there is
no per-op kernel to write and the registry's job is discovery, namespacing and
introspection:

- the eager layer calls ops directly (``nd.exec_op("exp", x)``),
- the autodiff graph records op *names* and re-emits them at trace time,
- autodiff comes from jax's AD instead of per-op ``doDiff`` methods.

Ops take positional jax arrays plus keyword attrs (the reference's
iArgs/tArgs/bArgs) and return one jax array or a tuple of them.
"""
from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import NDArray, _as_jax


@dataclasses.dataclass(frozen=True)
class Op:
    name: str
    fn: Callable
    category: str
    n_inputs: Optional[int]  # None = variadic
    differentiable: bool = True
    aliases: Tuple[str, ...] = ()
    # takes a `key=` attr at trace time (all "random" ops do; structural
    # ops like while/cond/scan also do, to seed random ops in their
    # subgraph bodies)
    needs_key: bool = False

    def __call__(self, *args, **attrs):
        return self.fn(*args, **attrs)


_REGISTRY: Dict[str, Op] = {}


def op(name: str, category: str, n_inputs: Optional[int] = None,
       differentiable: bool = True, aliases: Sequence[str] = (),
       needs_key: bool = False):
    """Decorator: register a pure jax function as a named op."""
    def deco(fn: Callable) -> Callable:
        o = Op(name=name, fn=fn, category=category, n_inputs=n_inputs,
               differentiable=differentiable, aliases=tuple(aliases),
               needs_key=needs_key or category == "random")
        if name in _REGISTRY:
            raise ValueError(f"duplicate op registration: {name}")
        _REGISTRY[name] = o
        for a in aliases:
            if a in _REGISTRY:
                raise ValueError(f"duplicate op alias: {a}")
            _REGISTRY[a] = o
        return fn
    return deco


def get_op(name: str) -> Op:
    _ensure_loaded()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown op: {name!r}; {len(op_names())} ops registered") from None


def has_op(name: str) -> bool:
    _ensure_loaded()
    return name in _REGISTRY


def add_alias(alias: str, target: str) -> None:
    """Register an additional reference/TF name for an existing op
    (the reference declares several ops under legacy + new names)."""
    if alias in _REGISTRY:
        raise ValueError(f"duplicate op alias: {alias}")
    _REGISTRY[alias] = _REGISTRY[target]


def op_names() -> List[str]:
    _ensure_loaded()
    return sorted({o.name for o in _REGISTRY.values()})


def ops_by_category() -> Dict[str, List[str]]:
    _ensure_loaded()
    out: Dict[str, List[str]] = {}
    for o in set(_REGISTRY.values()):
        out.setdefault(o.category, []).append(o.name)
    return {k: sorted(v) for k, v in sorted(out.items())}


def exec_op(name: str, *args, **attrs):
    """Execute by name on NDArray/array inputs, wrap results as NDArray.

    Reference: Nd4j.exec(DynamicCustomOp) →
    NativeOpExecutioner.execCustomOp2 (SURVEY.md §3.5) — here "dispatch" is
    just calling the jax function; XLA compiles/caches per shape signature.
    """
    import numpy as _np
    o = get_op(name)
    jargs = [_as_jax(a) if isinstance(a, (NDArray, jax.Array, _np.ndarray)) else a
             for a in args]
    if _trace_enabled:
        # every positional arg is recorded: arrays by signature, scalar
        # literals by value — a trace missing literals could not replay
        inputs = tuple(
            ("array", tuple(a.shape), str(a.dtype))
            if hasattr(a, "shape") and hasattr(a, "dtype")
            else ("literal", a) for a in jargs)
        _op_trace.append(OpTraceEntry(
            op=o.name,
            input_shapes=tuple(i[1] for i in inputs if i[0] == "array"),
            input_dtypes=tuple(i[2] for i in inputs if i[0] == "array"),
            attrs={k: v for k, v in attrs.items()},
            inputs=inputs))
    result = o.fn(*jargs, **attrs)
    if isinstance(result, (tuple, list)):
        return [NDArray(r) for r in result]
    return NDArray(result)


# ---------------------------------------------------------------------------
# Op tracing (reference: the C ABI's toggleOpTrace/listOpTraces/
# printOpTrace, NativeOps.h:56-121 + ADR "0024 - Execution Tracing":
# record each dispatched op's shapes/args, replayable as a graph).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OpTraceEntry:
    op: str
    input_shapes: tuple     # array inputs only (summary view)
    input_dtypes: tuple
    attrs: dict
    # full positional record: ("array", shape, dtype) | ("literal", value)
    inputs: tuple = ()


_trace_enabled = False
_op_trace: List["OpTraceEntry"] = []


def toggle_op_trace(enabled: bool) -> None:
    """(reference: NativeOps.toggleOpTrace)"""
    global _trace_enabled
    _trace_enabled = bool(enabled)


def list_op_traces() -> List["OpTraceEntry"]:
    """(reference: NativeOps.listOpTraces)"""
    return list(_op_trace)


def purge_op_trace() -> None:
    """(reference: NativeOps.purgeOpTrace)"""
    _op_trace.clear()


def print_op_trace(print_fn=print) -> None:
    """(reference: NativeOps.printOpTrace)"""
    for i, e in enumerate(_op_trace):
        print_fn(f"[{i}] {e.op} shapes={list(e.input_shapes)} "
                 f"dtypes={list(e.input_dtypes)} attrs={e.attrs}")


def replay_op_trace_as_graph(trace=None):
    """Rebuild the traced dispatch sequence as a SameDiff graph with
    placeholders for each op's array inputs (ADR 0024's 'replayable as a
    SameDiff graph'). Linear traces only: each entry's arrays become
    fresh placeholders (the eager path does not record producer/consumer
    identity)."""
    from deeplearning4j_tpu.autodiff import SameDiff
    sd = SameDiff()
    outs = []
    for i, e in enumerate(trace if trace is not None else _op_trace):
        ins = []
        spec = e.inputs or tuple(("array", s, dt) for s, dt in
                                 zip(e.input_shapes, e.input_dtypes))
        j = 0
        for entry in spec:
            if entry[0] == "array":
                ins.append(sd.placeholder(f"t{i}_in{j}", shape=entry[1],
                                          dtype=entry[2]))
                j += 1
            else:
                ins.append(sd.constant(entry[1], f"t{i}_lit{len(ins)}"))
        outs.append(sd.invoke(e.op, ins, dict(e.attrs),
                              name=f"t{i}_{e.op}"))
    return sd, outs


_LOADED = False


def _ensure_loaded() -> None:
    """Import all op modules (registration side effects)."""
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from deeplearning4j_tpu.ops import (  # noqa: F401
        breadth, control_flow, elementwise, legacy_tail, pairwise,
        reduce as _reduce, shape_ops, random as _random, linalg, nlp_ops,
        nn_ops, nn_ext, loss, bitwise, image, tf_compat,
    )
    # breadth2 last: its reference-name aliases point at ops the modules
    # above register
    from deeplearning4j_tpu.ops import breadth2  # noqa: F401
