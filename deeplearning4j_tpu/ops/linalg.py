"""Linear algebra ops.

Reference parity: MmulHelper/BlasHelper (libnd4j/include/helpers/MmulHelper.h)
and declarable generic/linalg/ (svd, lup, cholesky, triangular_solve, matrix
inverse, ...). GEMM maps to lax.dot_general (MXU); decompositions use XLA's
linalg lowerings. ``bf16_matmul`` flags the TPU-native mixed-precision path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

_L = "linalg"


@op("matmul", _L, n_inputs=2, aliases=("mmul",))
def matmul(a, b, transpose_a: bool = False, transpose_b: bool = False,
           transpose_result: bool = False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    r = jnp.matmul(a, b)
    return jnp.swapaxes(r, -1, -2) if transpose_result else r


@op("gemm", _L, n_inputs=2)
def gemm(a, b, alpha: float = 1.0, beta: float = 0.0, c=None,
         transpose_a: bool = False, transpose_b: bool = False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    r = alpha * jnp.matmul(a, b)
    if c is not None and beta != 0.0:
        r = r + beta * c
    return r


@op("bf16_matmul", _L, n_inputs=2)
def bf16_matmul(a, b):
    """Cast operands to bfloat16 for the MXU, accumulate in float32."""
    return jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)


@op("tensordot", _L, n_inputs=2, aliases=("tensormmul",))
def tensordot(a, b, axes_a, axes_b):
    return jnp.tensordot(a, b, axes=(tuple(axes_a), tuple(axes_b)))


@op("einsum", _L)
def einsum(*operands, equation: str):
    return jnp.einsum(equation, *operands)


@op("batched_matmul", _L, n_inputs=2, aliases=("batch_mmul",))
def batched_matmul(a, b, transpose_a: bool = False, transpose_b: bool = False):
    return matmul(a, b, transpose_a, transpose_b)


@op("svd", _L, n_inputs=1, differentiable=False)
def svd(x, full_matrices: bool = False, compute_uv: bool = True):
    if compute_uv:
        u, s, vt = jnp.linalg.svd(x, full_matrices=full_matrices)
        return s, u, jnp.swapaxes(vt, -1, -2)  # reference returns s, u, v
    return jnp.linalg.svd(x, compute_uv=False)


@op("qr", _L, n_inputs=1, differentiable=False)
def qr(x, full_matrices: bool = False):
    return jnp.linalg.qr(x, mode="complete" if full_matrices else "reduced")


@op("cholesky", _L, n_inputs=1)
def cholesky(x):
    return jnp.linalg.cholesky(x)


@op("lu", _L, n_inputs=1, differentiable=False)
def lu(x):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv


@op("solve", _L, n_inputs=2, aliases=("linear_solve",))
def solve(a, b, adjoint: bool = False):
    if adjoint:
        a = jnp.swapaxes(a, -1, -2)
    return jnp.linalg.solve(a, b)


@op("triangular_solve", _L, n_inputs=2)
def triangular_solve(a, b, lower: bool = True, adjoint: bool = False):
    return jax.scipy.linalg.solve_triangular(a, b, lower=lower, trans=1 if adjoint else 0)


@op("lstsq", _L, n_inputs=2, differentiable=False)
def lstsq(a, b, fast: bool = True):
    return jnp.linalg.lstsq(a, b)[0]


@op("matrix_inverse", _L, n_inputs=1)
def matrix_inverse(x):
    return jnp.linalg.inv(x)


@op("matrix_determinant", _L, n_inputs=1, aliases=("det",))
def matrix_determinant(x):
    return jnp.linalg.det(x)


@op("log_matrix_determinant", _L, n_inputs=1, aliases=("logdet",))
def log_matrix_determinant(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return logabs


@op("trace", _L, n_inputs=1)
def trace(x):
    return jnp.trace(x, axis1=-2, axis2=-1)


@op("matrix_band_part", _L, n_inputs=1)
def matrix_band_part(x, num_lower: int, num_upper: int):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    in_band = jnp.logical_and(
        (i - j) <= (num_lower if num_lower >= 0 else m),
        (j - i) <= (num_upper if num_upper >= 0 else n))
    return jnp.where(in_band, x, jnp.zeros_like(x))


@op("cross", _L, n_inputs=2)
def cross(a, b, axis: int = -1):
    return jnp.cross(a, b, axis=axis)


@op("outer", _L, n_inputs=2)
def outer(a, b):
    return jnp.outer(a, b)


@op("norm", _L, n_inputs=1)
def norm(x, ord=None, axis=None, keep_dims: bool = False):
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keep_dims)


@op("l2_normalize", _L, n_inputs=1)
def l2_normalize(x, axis: int = -1, epsilon: float = 1e-12):
    return x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=axis, keepdims=True), epsilon))


@op("eig", _L, n_inputs=1, differentiable=False)
def eig(x):
    # XLA supports symmetric eigendecomposition natively on TPU
    return jnp.linalg.eigh(x)


@op("sufficient_statistics", _L, n_inputs=1)
def sufficient_statistics(x, axis, shift: float = None):
    ax = tuple(axis)
    count = jnp.asarray(1.0)
    for a in ax:
        count = count * x.shape[a]
    s = x - shift if shift is not None else x
    mean_ss = jnp.sum(s, axis=ax)
    var_ss = jnp.sum(s * s, axis=ax)
    return count, mean_ss, var_ss
