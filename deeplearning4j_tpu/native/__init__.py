"""Native runtime components (C++ + ctypes).

Reference parity: the reference keeps IO/parse hot loops native (datavec
readers over JVM IO, libnd4j for everything numeric). Here the numeric
compute path is XLA; the native pieces are the host-side runtime — this
package builds small C++ kernels with the system toolchain on first use
and binds them with ctypes (no pybind11 in the environment). Every
native path has a pure-Python fallback, so the framework works without
a compiler.
"""
from deeplearning4j_tpu.native.build import native_available
from deeplearning4j_tpu.native.fastcsv import read_csv_f32

__all__ = ["native_available", "read_csv_f32"]
