// Native numeric-CSV loader for the ETL subsystem.
//
// Reference parity: datavec's record-reading hot loop is native
// (datavec-api CSVRecordReader backed by JVM IO; the wider reference
// stack keeps IO/parse off the interpreted path). This is the
// TPU-framework equivalent: a single-pass C++ parser that turns an
// all-numeric CSV straight into a float32 matrix, bound to Python via
// ctypes (no pybind11 in this environment). The Python CSVRecordReader
// remains the general path (quoting, strings, categoricals); this
// kernel accelerates the schema-all-numeric case that feeds training.
//
// Exported C ABI:
//   csv_probe(path, delim, skip, *rows, *cols) -> 0 ok / CSV_EIO on
//     unreadable file / -2 on ragged input
//   csv_parse_f32(path, delim, skip, out, rows, cols) -> 0 ok /
//     CSV_EIO on unreadable or truncated file / -(row+2) for the first
//     malformed cell (so a bad cell at data row 0 returns -2, never
//     colliding with an I/O code)
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cstdint>
#include <climits>
#include <vector>

// I/O failure sentinel, far outside the -(row+2) bad-cell range.
#define CSV_EIO INT_MIN

namespace {

// Read the whole file into a buffer (CSV inputs are host-side and far
// smaller than HBM tensors; one read beats line-buffered stdio).
char* read_all(const char* path, size_t* len) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return nullptr;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    if (n < 0) { std::fclose(f); return nullptr; }
    std::fseek(f, 0, SEEK_SET);
    char* buf = static_cast<char*>(std::malloc(static_cast<size_t>(n) + 1));
    if (!buf) { std::fclose(f); return nullptr; }
    size_t got = std::fread(buf, 1, static_cast<size_t>(n), f);
    std::fclose(f);
    buf[got] = '\0';
    *len = got;
    return buf;
}

inline const char* skip_lines(const char* p, const char* end, int skip) {
    while (skip > 0 && p < end) {
        const char* nl = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        if (!nl) return end;
        p = nl + 1;
        --skip;
    }
    return p;
}

}  // namespace

extern "C" {

int csv_probe(const char* path, char delim, int skip,
              int64_t* rows, int64_t* cols) {
    size_t len = 0;
    char* buf = read_all(path, &len);
    if (!buf) return CSV_EIO;
    const char* p = buf;
    const char* end = buf + len;
    p = skip_lines(p, end, skip);
    int64_t r = 0, c = -1;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* line_end = nl ? nl : end;
        if (line_end > p) {              // non-empty line
            int64_t n = 1;
            for (const char* q = p; q < line_end; ++q)
                if (*q == delim) ++n;
            if (c < 0) c = n;
            else if (n != c) { std::free(buf); return -2; }  // ragged
            ++r;
        }
        p = nl ? nl + 1 : end;
    }
    std::free(buf);
    *rows = r;
    *cols = (c < 0 ? 0 : c);
    return 0;
}

int csv_parse_f32(const char* path, char delim, int skip,
                  float* out, int64_t rows, int64_t cols) {
    size_t len = 0;
    char* buf = read_all(path, &len);
    if (!buf) return CSV_EIO;
    char* p = buf;
    char* end = buf + len;
    p = const_cast<char*>(skip_lines(p, end, skip));
    int64_t r = 0;
    while (p < end && r < rows) {
        char* nl = static_cast<char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        char* line_end = nl ? nl : end;
        if (line_end > p) {
            // bound strtof to THIS line: otherwise its leading-whitespace
            // skip walks across '\n' and silently pulls values from the
            // next record on an empty trailing cell
            char saved = *line_end;       // '\n' or the final '\0'
            *line_end = '\0';
            char* q = p;
            for (int64_t c = 0; c < cols; ++c) {
                char* after = nullptr;
                float v = std::strtof(q, &after);
                if (after == q) {            // empty or non-numeric cell
                    *line_end = saved;
                    std::free(buf);
                    return static_cast<int>(-(r + 2));
                }
                out[r * cols + c] = v;
                q = after;
                // skip padding, but never the delimiter itself (tabs are
                // a legal delimiter)
                while (q < line_end && (*q == ' ' || *q == '\t')
                       && *q != delim)
                    ++q;
                if (c + 1 < cols) {
                    if (q >= line_end || *q != delim) {
                        *line_end = saved;
                        std::free(buf);
                        return static_cast<int>(-(r + 2));
                    }
                    ++q;
                }
            }
            *line_end = saved;
            ++r;
        }
        p = nl ? nl + 1 : end;
    }
    std::free(buf);
    // fewer rows than probed = file changed between probe and parse
    return (r == rows) ? 0 : CSV_EIO;
}

}  // extern "C"
