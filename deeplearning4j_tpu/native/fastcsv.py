"""ctypes binding for the native numeric-CSV loader (fastcsv.cpp),
with a numpy fallback when no toolchain is present."""
from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native.build import load

# I/O-failure sentinel shared with fastcsv.cpp (CSV_EIO = INT_MIN); bad
# cells come back as -(row+2), so the two ranges can never collide.
CSV_EIO = -(2 ** 31)


def _bind(lib: ctypes.CDLL) -> None:
    lib.csv_probe.argtypes = [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                              ctypes.POINTER(ctypes.c_int64),
                              ctypes.POINTER(ctypes.c_int64)]
    lib.csv_probe.restype = ctypes.c_int
    lib.csv_parse_f32.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                  ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_int64, ctypes.c_int64]
    lib.csv_parse_f32.restype = ctypes.c_int
    lib._bound = True


def read_csv_f32(path: str, delimiter: str = ",",
                 skip_num_lines: int = 0) -> np.ndarray:
    """All-numeric CSV file -> float32 (rows, cols) matrix.

    Native single-pass parse when the C++ kernel is available; numpy
    text loading otherwise. Raises ValueError on ragged or non-numeric
    input in both paths.
    """
    lib = load("fastcsv")
    if lib is not None:
        if not getattr(lib, "_bound", False):
            _bind(lib)
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.csv_probe(path.encode(), delimiter.encode(),
                           skip_num_lines, ctypes.byref(rows),
                           ctypes.byref(cols))
        if rc == -2:
            raise ValueError(f"{path}: ragged CSV (unequal column counts)")
        if rc != 0:
            raise ValueError(f"{path}: cannot read")
        out = np.empty((rows.value, cols.value), np.float32)
        rc = lib.csv_parse_f32(
            path.encode(), delimiter.encode(), skip_num_lines,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.value, cols.value)
        if rc == CSV_EIO:
            raise ValueError(f"{path}: cannot read")
        if rc != 0:
            raise ValueError(f"{path}: non-numeric cell at data row "
                             f"{-rc - 2}")
        return out
    # fallback: pure numpy
    try:
        arr = np.loadtxt(path, delimiter=delimiter, dtype=np.float32,
                         skiprows=skip_num_lines, ndmin=2)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None
    return arr
