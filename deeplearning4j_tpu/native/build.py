"""Compile-on-first-use for the native kernels.

Builds each .cpp in this directory into a shared library under
``_build/`` next to the sources (inside the repo; nothing is written
elsewhere). Build happens at most once per source change (mtime check);
failures are cached for the process so a missing compiler costs one
attempt, then every caller takes the Python fallback.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_failed: Dict[str, str] = {}
_loaded: Dict[str, ctypes.CDLL] = {}


def _compiler() -> Optional[str]:
    for cc in ("g++", "c++", "clang++"):
        try:
            subprocess.run([cc, "--version"], capture_output=True,
                           check=True)
            return cc
        except Exception:
            continue
    return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """Load (building if needed) lib<name>.so from <name>.cpp; None if
    the toolchain is unavailable or the build failed."""
    if name in _loaded:
        return _loaded[name]
    if name in _failed:
        return None
    src = os.path.join(_DIR, f"{name}.cpp")
    so = os.path.join(_BUILD, f"lib{name}.so")
    try:
        if not os.path.exists(so) or \
                os.path.getmtime(so) < os.path.getmtime(src):
            cc = _compiler()
            if cc is None:
                _failed[name] = "no C++ compiler on PATH"
                return None
            os.makedirs(_BUILD, exist_ok=True)
            cmd = [cc, "-O3", "-shared", "-fPIC", "-std=c++17",
                   src, "-o", so]
            res = subprocess.run(cmd, capture_output=True, text=True)
            if res.returncode != 0:
                _failed[name] = res.stderr[-2000:]
                return None
        lib = ctypes.CDLL(so)
        _loaded[name] = lib
        return lib
    except Exception as e:            # pragma: no cover - env specific
        _failed[name] = str(e)
        return None


def native_available(name: str = "fastcsv") -> bool:
    return load(name) is not None


def build_error(name: str) -> Optional[str]:
    return _failed.get(name)
