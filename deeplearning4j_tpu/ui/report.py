"""Static HTML training report from a StatsStorage.

Reference parity: the deeplearning4j-vertx dashboard's Overview and
Model tabs (VertxUIServer.java:78; TrainModule's score chart, update:
parameter ratio chart, histograms, system tab) rendered as ONE
self-contained HTML file: inline SVG, zero external assets, no server.
"""
from __future__ import annotations

import html as _html
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.ui.stats import StatsStorage


def _svg_line(points: Sequence[Tuple[float, float]], w=640, h=180,
              color="#1f77b4", label="", ylog=False) -> str:
    if not points:
        return f"<p>(no data for {_html.escape(label)})</p>"
    import math
    xs = [p[0] for p in points]
    ys = [(math.log10(max(p[1], 1e-12)) if ylog else p[1]) for p in points]
    x0, x1 = min(xs), max(xs) or 1
    y0, y1 = min(ys), max(ys)
    if y1 - y0 < 1e-12:
        y0, y1 = y0 - 1, y1 + 1
    px = lambda x: 45 + (x - x0) / max(x1 - x0, 1e-12) * (w - 55)
    py = lambda y: (h - 25) - (y - y0) / (y1 - y0) * (h - 35)
    path = " ".join(f"{'M' if i == 0 else 'L'}{px(x):.1f},{py(y):.1f}"
                    for i, (x, y) in enumerate(zip(xs, ys)))
    fmt = (lambda v: f"1e{v:.1f}") if ylog else (lambda v: f"{v:.4g}")
    return f"""<svg width="{w}" height="{h}" style="background:#fafafa">
<text x="5" y="14" font-size="12" fill="#444">{_html.escape(label)}</text>
<text x="5" y="{h-28}" font-size="10" fill="#888">{fmt(y0)}</text>
<text x="5" y="26" font-size="10" fill="#888">{fmt(y1)}</text>
<path d="{path}" stroke="{color}" fill="none" stroke-width="1.5"/>
</svg>"""


def _svg_hist(hist: List[int], edges: List[float], w=220, h=90,
              label="") -> str:
    if not hist or max(hist) == 0:
        return ""
    n = len(hist)
    bw = (w - 10) / n
    mx = max(hist)
    bars = "".join(
        f'<rect x="{5+i*bw:.1f}" y="{(h-18)*(1-v/mx)+4:.1f}" '
        f'width="{bw-1:.1f}" height="{(h-18)*v/mx:.1f}" fill="#2ca02c"/>'
        for i, v in enumerate(hist))
    return f"""<svg width="{w}" height="{h}" style="background:#fafafa">
{bars}
<text x="5" y="{h-4}" font-size="9" fill="#666">{_html.escape(label)}
 [{edges[0]:.3g}, {edges[1]:.3g}]</text></svg>"""


_STAGE_COLORS = (("data_wait_s", "#1f77b4", "data wait"),
                 ("dispatch_s", "#ff7f0e", "dispatch"),
                 ("flush_s", "#2ca02c", "flush"),
                 ("other_s", "#9467bd", "other"))


def _svg_stack(rows: List[dict], w=640, h=200, label="") -> str:
    """Stacked per-flush bars of the step-time breakdown (one bar per
    {"type": "steptime"} record, stages stacked bottom-up)."""
    rows = [r for r in rows if r.get("steps")]
    if not rows:
        return f"<p>(no data for {_html.escape(label)})</p>"
    totals = [sum(r.get(k, 0.0) for k, _, _ in _STAGE_COLORS)
              for r in rows]
    mx = max(totals) or 1.0
    n = len(rows)
    bw = (w - 60) / n
    parts = [f'<svg width="{w}" height="{h}" style="background:#fafafa">',
             f'<text x="5" y="14" font-size="12" fill="#444">'
             f'{_html.escape(label)}</text>']
    for i, r in enumerate(rows):
        y = h - 22
        for key, color, _ in _STAGE_COLORS:
            v = r.get(key, 0.0)
            bh = (h - 45) * v / mx
            y -= bh
            parts.append(
                f'<rect x="{50 + i * bw:.1f}" y="{y:.1f}" '
                f'width="{max(bw - 1, 1):.1f}" height="{bh:.1f}" '
                f'fill="{color}"><title>{key[:-2]}: {v:.4f}s</title>'
                f'</rect>')
    parts.append(f'<text x="5" y="{h - 26}" font-size="10" fill="#888">'
                 f'0</text>')
    parts.append(f'<text x="5" y="30" font-size="10" fill="#888">'
                 f'{mx:.3g}s</text>')
    lx = 50
    for key, color, name in _STAGE_COLORS:
        parts.append(f'<rect x="{lx}" y="{h - 14}" width="10" height="10" '
                     f'fill="{color}"/>')
        parts.append(f'<text x="{lx + 13}" y="{h - 5}" font-size="10" '
                     f'fill="#444">{name}</text>')
        lx += 13 + 8 * len(name) + 14
    parts.append("</svg>")
    return "\n".join(parts)


def _svg_heatmap(matrix: List[List[float]], row_labels: List[str],
                 w=640, cell_h=18, label="", log10: bool = True) -> str:
    """Rows × columns heatmap (layers × samples), light→dark by value
    (log10 by default — grad norms span decades). NaN/zero cells render
    grey."""
    import math
    rows = [r for r in matrix if r]
    if not rows or not row_labels:
        return f"<p>(no data for {_html.escape(label)})</p>"
    vals = []
    for r in rows:
        for v in r:
            if v and v > 0 and math.isfinite(v):
                vals.append(math.log10(v) if log10 else v)
    if not vals:
        return f"<p>(no finite data for {_html.escape(label)})</p>"
    lo, hi = min(vals), max(vals)
    if hi - lo < 1e-12:
        lo, hi = lo - 1, hi + 1
    ncols = max(len(r) for r in rows)
    x0 = 130
    cw = (w - x0 - 10) / ncols
    h = 24 + cell_h * len(rows) + 18

    def color(v):
        if not v or v <= 0 or not math.isfinite(v):
            return "#ddd"
        t = ((math.log10(v) if log10 else v) - lo) / (hi - lo)
        # light blue -> dark navy ramp
        r0, g0, b0 = 0xdb, 0xe9, 0xf6
        r1, g1, b1 = 0x08, 0x30, 0x6b
        return "#%02x%02x%02x" % (round(r0 + t * (r1 - r0)),
                                  round(g0 + t * (g1 - g0)),
                                  round(b0 + t * (b1 - b0)))

    parts = [f'<svg width="{w}" height="{h}" style="background:#fafafa">',
             f'<text x="5" y="14" font-size="12" fill="#444">'
             f'{_html.escape(label)}</text>']
    for ri, (name, row) in enumerate(zip(row_labels, rows)):
        y = 22 + ri * cell_h
        parts.append(f'<text x="5" y="{y + cell_h - 5}" font-size="10" '
                     f'fill="#666">{_html.escape(str(name)[:18])}</text>')
        for ci, v in enumerate(row):
            parts.append(
                f'<rect x="{x0 + ci * cw:.1f}" y="{y}" '
                f'width="{max(cw - 1, 1):.1f}" height="{cell_h - 2}" '
                f'fill="{color(v)}"><title>{_html.escape(str(name))}'
                f'[{ci}]: {v:.4g}</title></rect>')
    lo10 = f"1e{lo:.1f}" if log10 else f"{lo:.3g}"
    hi10 = f"1e{hi:.1f}" if log10 else f"{hi:.3g}"
    parts.append(f'<text x="{x0}" y="{h - 4}" font-size="10" fill="#888">'
                 f'{lo10} (light) → {hi10} (dark)</text>')
    parts.append("</svg>")
    return "\n".join(parts)


def _span_color(name: str) -> str:
    # crc32, NOT builtin hash(): the name→color mapping must be stable
    # across processes (hash() is salted per run; reports rendered from
    # the same storage twice would recolor every lane)
    import zlib
    palette = ("#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
               "#8c564b", "#e377c2", "#17becf", "#bcbd22", "#7f7f7f")
    return palette[zlib.crc32(name.encode("utf-8")) % len(palette)]


def _svg_swimlane(spans: List[dict], w=940, h_lane=26, label="",
                  max_spans=2000) -> str:
    """Span-timeline swimlane: one lane per thread, one rect per span
    (nesting shown by depth shading), hover for name/duration."""
    spans = [s for s in spans if s.get("dur", 0) > 0][:max_spans]
    if not spans:
        return f"<p>(no data for {_html.escape(label)})</p>"
    t0 = min(s["ts"] for s in spans)
    t1 = max(s["ts"] + s["dur"] for s in spans)
    total = max(t1 - t0, 1e-9)
    lanes: List[int] = []
    lane_names = {}
    for s in spans:
        if s["tid"] not in lanes:
            lanes.append(s["tid"])
            lane_names[s["tid"]] = s.get("thread") or str(s["tid"])
    # nesting depth per span (parent chain within the dump)
    by_sid = {s.get("sid"): s for s in spans if s.get("sid")}
    def depth(s):
        d, p = 0, s.get("parent")
        while p and p in by_sid and d < 8:
            d += 1
            p = by_sid[p].get("parent")
        return d
    h = 20 + h_lane * len(lanes) + 16
    px = lambda t: 120 + (t - t0) / total * (w - 130)
    parts = [f'<svg width="{w}" height="{h}" style="background:#fafafa">',
             f'<text x="5" y="14" font-size="12" fill="#444">'
             f'{_html.escape(label)} ({total:.3f}s)</text>']
    for li, tid in enumerate(lanes):
        y = 20 + li * h_lane
        nm = lane_names[tid][:16]
        parts.append(f'<text x="5" y="{y + 16}" font-size="10" '
                     f'fill="#666">{_html.escape(nm)}</text>')
        parts.append(f'<line x1="120" y1="{y + h_lane - 2}" x2="{w - 10}" '
                     f'y2="{y + h_lane - 2}" stroke="#eee"/>')
    for s in spans:
        li = lanes.index(s["tid"])
        d = depth(s)
        y = 20 + li * h_lane + 2 + d * 4
        x, bw = px(s["ts"]), max(0.6, s["dur"] / total * (w - 130))
        bh = max(3, h_lane - 8 - d * 4)
        tip = (f'{s["name"]} {1e3 * s["dur"]:.3f}ms'
               + (f' {s["args"]}' if s.get("args") else ""))
        parts.append(
            f'<rect x="{x:.1f}" y="{y}" width="{bw:.1f}" height="{bh}" '
            f'fill="{_span_color(s["name"])}" fill-opacity="0.8">'
            f'<title>{_html.escape(tip)}</title></rect>')
    parts.append("</svg>")
    return "\n".join(parts)


#: record types render_report knows how to draw; everything else lands
#: in the forward-compatibility footer instead of being dropped
_KNOWN_TYPES = frozenset({
    "meta", "score", "perf", "params", "memory", "end", "serving",
    "checkpoint", "dispatch", "faults", "metrics", "steptime", "trace",
    "compile", "reshard", "tensorstats", "memory_plan", "analysis",
    "datapipe", "integrity", "fleet"})


#: memory-plan byte components for the stacked budget chart, mirroring
#: monitor/memstats.PLAN_BYTE_FIELDS (colors match the steptime stack)
_PLAN_COLORS = (("argument_bytes", "#1f77b4", "arguments"),
                ("temp_bytes", "#ff7f0e", "temps"),
                ("output_bytes", "#2ca02c", "outputs"),
                ("generated_code_bytes", "#9467bd", "code"))


def _svg_budget(plans: List[dict], w=640, h=220, label="") -> str:
    """Stacked per-program memory-budget bars (one bar per captured
    plan: argument/temp/output/generated-code bytes stacked) — the
    chart version of PROFILE.md's hand-computed HBM breakdown."""
    plans = [p for p in plans
             if any(p.get(k) for k, _, _ in _PLAN_COLORS)]
    if not plans:
        return f"<p>(no data for {_html.escape(label)})</p>"

    def _component(p, key):
        v = p.get(key, 0) or 0
        if key == "argument_bytes":
            # donated/aliased bytes reuse argument space — subtract
            # them here so the bar height equals the plan's
            # total_bytes and the chart agrees with the table's
            # "total MiB" column
            v = max(0, v - (p.get("alias_bytes", 0) or 0))
        return v

    totals = [sum(_component(p, k) for k, _, _ in _PLAN_COLORS)
              for p in plans]
    mx = max(totals) or 1
    n = len(plans)
    bw = min(90, (w - 70) / n)
    parts = [f'<svg width="{w}" height="{h}" style="background:#fafafa">',
             f'<text x="5" y="14" font-size="12" fill="#444">'
             f'{_html.escape(label)}</text>']
    for i, p in enumerate(plans):
        y = h - 36
        x = 60 + i * bw
        for key, color, name in _PLAN_COLORS:
            v = _component(p, key)
            bh = (h - 60) * v / mx
            y -= bh
            parts.append(
                f'<rect x="{x:.1f}" y="{y:.1f}" '
                f'width="{max(bw - 3, 1):.1f}" height="{bh:.1f}" '
                f'fill="{color}"><title>{name}: {v / 2**20:.2f} MiB'
                f'</title></rect>')
        prog = str(p.get("program", "?"))[:12]
        parts.append(f'<text x="{x:.1f}" y="{h - 22}" font-size="9" '
                     f'fill="#666">{_html.escape(prog)}</text>')
    parts.append(f'<text x="5" y="30" font-size="10" fill="#888">'
                 f'{mx / 2**20:.1f} MiB</text>')
    lx = 60
    for _, color, name in _PLAN_COLORS:
        parts.append(f'<rect x="{lx}" y="{h - 14}" width="10" '
                     f'height="10" fill="{color}"/>')
        parts.append(f'<text x="{lx + 13}" y="{h - 5}" font-size="10" '
                     f'fill="#444">{name}</text>')
        lx += 13 + 7 * len(name) + 14
    parts.append("</svg>")
    return "\n".join(parts)


def render_report(storage: StatsStorage, title: str = "Training report"
                  ) -> str:
    scores = storage.of_type("score")
    perf = storage.of_type("perf")
    params = storage.of_type("params")
    memory = storage.of_type("memory")
    memory_plans = storage.of_type("memory_plan")
    oom_events = [r for r in storage.of_type("faults")
                  if r.get("event") == "oom"]
    end = storage.of_type("end")
    tensorstats = storage.of_type("tensorstats")
    steptime = [r for r in storage.of_type("steptime")
                if r.get("event") != "straggler"]
    stragglers = [r for r in storage.of_type("steptime")
                  if r.get("event") == "straggler"]
    traces = storage.of_type("trace")
    metrics = storage.of_type("metrics")
    compiles = storage.of_type("compile")
    analyses = storage.of_type("analysis")
    reshards = storage.of_type("reshard")
    datapipe = storage.of_type("datapipe")
    serving = storage.of_type("serving")
    fleet = storage.of_type("fleet")
    serving_faults = [r for r in storage.of_type("faults")
                      if r.get("origin") == "serving"]
    integrity = storage.of_type("integrity")
    stall_events = [r for r in storage.of_type("faults")
                    if r.get("event") == "stall"]

    parts = [f"""<!doctype html><html><head><meta charset="utf-8">
<title>{_html.escape(title)}</title>
<style>body{{font-family:sans-serif;margin:24px;color:#222}}
h2{{border-bottom:1px solid #ddd;padding-bottom:4px}}
.row{{display:flex;flex-wrap:wrap;gap:12px}}
table{{border-collapse:collapse;font-size:13px}}
td,th{{border:1px solid #ccc;padding:3px 8px}}</style></head><body>
<h1>{_html.escape(title)}</h1>"""]

    # -- overview: score + throughput ------------------------------------
    parts.append("<h2>Overview</h2><div class='row'>")
    parts.append(_svg_line([(r["iter"], r["loss"]) for r in scores],
                           label="score vs iteration", ylog=True))
    parts.append(_svg_line(
        [(r["iter"], r.get("samples_per_sec", r["batches_per_sec"]))
         for r in perf],
        label="throughput (samples/sec)" if any(
            "samples_per_sec" in r for r in perf)
        else "throughput (batches/sec)", color="#ff7f0e"))
    parts.append("</div>")
    if end and end[-1].get("wall_seconds") is not None:
        parts.append(f"<p>wall time: {end[-1]['wall_seconds']:.2f}s, "
                     f"{len(scores)} scored iterations</p>")

    # -- model: update:param ratios + histograms -------------------------
    if params:
        parts.append("<h2>Update : parameter ratios (log10)</h2>"
                     "<div class='row'>")
        names = sorted(params[-1]["params"])
        for name in names:
            pts = [(r["epoch"], r["params"][name]["update_ratio"])
                   for r in params if name in r["params"]
                   and "update_ratio" in r["params"][name]]
            if pts:
                parts.append(_svg_line(pts, w=320, h=120, color="#d62728",
                                       label=name, ylog=True))
        parts.append("</div><h2>Parameter histograms (last epoch)</h2>"
                     "<div class='row'>")
        last = params[-1]["params"]
        for name in names:
            ent = last[name]
            parts.append(_svg_hist(ent["hist"], ent["edges"], label=name))
        parts.append("</div><h2>Parameter stats (last epoch)</h2><table>"
                     "<tr><th>param</th><th>mean</th><th>std</th>"
                     "<th>norm</th><th>update norm</th></tr>")
        for name in names:
            ent = last[name]
            parts.append(
                f"<tr><td>{_html.escape(name)}</td>"
                f"<td>{ent['mean']:.4g}</td><td>{ent['std']:.4g}</td>"
                f"<td>{ent['norm']:.4g}</td>"
                f"<td>{ent.get('update_norm', float('nan')):.4g}</td></tr>")
        parts.append("</table>")

    # -- system: memory (monitor/memstats.py — docs/observability.md) ----
    if memory or memory_plans or oom_events:
        parts.append("<h2>Memory</h2>")
    if memory:
        # x = sample index, NOT iteration/epoch: records from different
        # producers (listener flushes carry iterations, StatsListener
        # epochs, serving samples neither) share one storage, and a
        # mixed axis would make the polyline double back on itself —
        # append order is time order, so the index is always monotonic
        parts.append("<div class='row'>")
        parts.append(_svg_line(
            [(i, r["bytes_in_use"] / 2**20)
             for i, r in enumerate(memory)],
            label="HBM in use (MiB) over samples", color="#9467bd"))
        parts.append(_svg_line(
            [(i, r["peak_bytes"] / 2**20)
             for i, r in enumerate(memory)],
            label="HBM peak (MiB) over samples", color="#8c564b"))
        if any(r.get("headroom") is not None for r in memory):
            parts.append(_svg_line(
                [(i, r["headroom"] / 2**20)
                 for i, r in enumerate(memory)
                 if r.get("headroom") is not None],
                label="HBM headroom (MiB) over samples", color="#2ca02c"))
        parts.append("</div>")
        # per-device watermark curves: a lopsided mesh shows one device
        # pinned at its limit while the fleet total looks healthy
        dev_names = sorted({d.get("device", "?") for r in memory
                            for d in r.get("devices", ())})
        if len(dev_names) > 1:
            parts.append("<div class='row'>")
            for name in dev_names[:16]:
                pts = []
                for i, r in enumerate(memory):
                    for d in r.get("devices", ()):
                        if d.get("device") == name:
                            pts.append((i,
                                        d.get("bytes_in_use", 0) / 2**20))
                if pts:
                    parts.append(_svg_line(
                        pts, w=320, h=120, color="#9467bd",
                        label=f"{name} in use (MiB)"))
            parts.append("</div>")
        last = memory[-1]
        tracked = last.get("tracked") or {}
        bits = [f"{len(memory)} samples"]
        if last.get("bytes_limit"):
            bits.append(f"limit {last['bytes_limit'] / 2**20:.0f} MiB")
        for tag, nb in sorted(tracked.items()):
            bits.append(f"{tag} {nb / 2**20:.1f} MiB "
                        f"({(last.get('tracked_counts') or {}).get(tag, 0)}"
                        f" transfers)")
        if last.get("live_skipped"):
            bits.append(f"{last['live_skipped']} live arrays unsized")
        parts.append("<p>" + ", ".join(bits) + "</p>")
    if memory_plans:
        # newest plan per program label (re-captures refresh)
        by_prog: dict = {}
        for r in memory_plans:
            by_prog[r.get("program", "?")] = r
        plans = [by_prog[k] for k in sorted(by_prog)]
        parts.append(_svg_budget(
            plans, label="compiled-program memory plans "
                         "(memory_analysis)"))
        parts.append(
            "<table><tr><th>program</th><th>steps</th><th>args MiB</th>"
            "<th>temps MiB</th><th>out MiB</th><th>total MiB</th>"
            "<th>GFLOPs/step</th></tr>")
        for p in plans:
            fps = p.get("flops_per_step")
            parts.append(
                f"<tr><td>{_html.escape(str(p.get('program', '?')))}</td>"
                f"<td>{p.get('steps', 1)}</td>"
                f"<td>{(p.get('argument_bytes', 0) or 0) / 2**20:.2f}</td>"
                f"<td>{(p.get('temp_bytes', 0) or 0) / 2**20:.2f}</td>"
                f"<td>{(p.get('output_bytes', 0) or 0) / 2**20:.2f}</td>"
                f"<td>{(p.get('total_bytes', 0) or 0) / 2**20:.2f}</td>"
                f"<td>{'—' if fps is None else format(fps / 1e9, '.3f')}"
                f"</td></tr>")
        parts.append("</table>")
    if oom_events:
        parts.append(
            f"<h3>OOM events ({len(oom_events)})</h3><table>"
            f"<tr><th>program</th><th>step</th><th>epoch</th>"
            f"<th>live arrays</th><th>live MiB</th><th>devices</th>"
            f"</tr>")
        for r in oom_events[-10:]:
            devs = "; ".join(
                f"{d.get('device', '?')}: "
                f"{(d.get('bytes_in_use', 0) or 0) / 2**20:.1f} MiB"
                + (f"/{d.get('bytes_limit', 0) / 2**20:.0f}"
                   if d.get("bytes_limit") else "")
                for d in (r.get("devices") or [])[:4]) or "—"
            lb = r.get("live_bytes")
            parts.append(
                f"<tr><td>{_html.escape(str(r.get('program', '?')))}</td>"
                f"<td>{r.get('step', '—')}</td>"
                f"<td>{r.get('epoch', '—')}</td>"
                f"<td>{r.get('live_arrays', '—')}</td>"
                f"<td>{'—' if lb is None else format(lb / 2**20, '.1f')}"
                f"</td><td>{_html.escape(devs)}</td></tr>")
        parts.append("</table><p>device memory exhausted — forensics "
                     "in the faults records (docs/observability.md "
                     "\"OOM forensics\")</p>")

    # -- layer health: in-graph tensorstats (monitor/tensorstats.py) -----
    if tensorstats:
        # bounded like the trace dump: a long monitored run holds tens
        # of thousands of samples, and /report renders this LIVE per
        # request — stride-downsample to a readable column budget
        # (always keeping the newest record, which feeds the table)
        ts_total = len(tensorstats)
        max_cols = 160
        if ts_total > max_cols:
            stride = -(-ts_total // max_cols)
            tensorstats = tensorstats[::-stride][::-1]
        layer_names = sorted({n for r in tensorstats
                              for n in r.get("layers", {})})
        parts.append("<h2>Layer health (device-side tensorstats)</h2>"
                     "<div class='row'>")
        # update:param ratio over time, one chart per layer (the
        # dead↔exploding spectrum LayerHealthWatcher polices)
        for name in layer_names:
            pts = [(r["iter"], r["layers"][name]["update_ratio"])
                   for r in tensorstats if name in r.get("layers", {})
                   and r["layers"][name].get("update_ratio") is not None]
            if pts:
                parts.append(_svg_line(
                    pts, w=320, h=120, color="#d62728",
                    label=f"{name} update:param (in-graph)", ylog=True))
        parts.append("</div>")
        # grad-norm heatmap: layers x sampled steps, log color scale
        # (None = poisoned/absent stats -> NaN -> grey cell)
        def _fnum(v):
            return float("nan") if v is None else float(v)

        matrix = [[_fnum(r["layers"].get(name, {}).get("grad_l2"))
                   for r in tensorstats] for name in layer_names]
        if any("grad_l2" in r["layers"].get(n, {}) for r in tensorstats
               for n in layer_names):
            parts.append(_svg_heatmap(
                matrix, layer_names,
                label="gradient L2 norm per layer over sampled steps"))
        last = tensorstats[-1]["layers"]
        parts.append(
            "<table><tr><th>layer</th><th>grad L2</th>"
            "<th>update:param</th><th>nonfinite</th><th>zeros</th>"
            "<th>|x| range (log2)</th></tr>")
        for name in layer_names:
            ent = last.get(name, {})
            nonf = sum(ent.get(f"{p}_nonfinite", 0)
                       for p in ("grad", "update", "param"))
            rng = "—"
            if ent.get("grad_hist"):
                lo = tensorstats[-1].get("hist_min_exp", 0)
                nz = [i for i, c in enumerate(ent["grad_hist"]) if c]
                if nz:
                    rng = f"[{lo + nz[0]}, {lo + nz[-1]}]"
            ur = ent.get("update_ratio")
            parts.append(
                f"<tr><td>{_html.escape(name)}</td>"
                f"<td>{_fnum(ent.get('grad_l2')):.4g}</td>"
                f"<td>{'—' if ur is None else format(ur, '.4g')}</td>"
                f"<td>{nonf}</td>"
                f"<td>{ent.get('grad_zeros', 0)}</td>"
                f"<td>{rng}</td></tr>")
        shown = "" if ts_total == len(tensorstats) \
            else f" ({len(tensorstats)} shown)"
        parts.append(
            f"</table><p>{ts_total} in-graph samples{shown} (every "
            f"{tensorstats[-1].get('every_n', '?')} steps) — gradients/"
            f"updates summarized inside the compiled step, fetched at "
            f"flush boundaries (docs/observability.md)</p>")

    # -- observability: step-time breakdown + span timeline --------------
    if steptime:
        parts.append("<h2>Step-time breakdown</h2>")
        parts.append(_svg_stack(
            steptime, label="wall time per flush (stacked by stage)"))
        tot = {k: sum(r.get(k, 0.0) for r in steptime)
               for k, _, _ in _STAGE_COLORS}
        wall = sum(tot.values()) or 1.0
        last = steptime[-1]
        parts.append(
            "<p>" + ", ".join(
                f"{k[:-2].replace('_', ' ')} {100 * v / wall:.1f}%"
                for k, v in tot.items())
            + f" — step ms p50 {last.get('step_ms_p50', 0):.3f} / "
              f"p95 {last.get('step_ms_p95', 0):.3f} over "
              f"{sum(r.get('steps', 0) for r in steptime)} steps</p>")
    if stragglers:
        parts.append(f"<h2>Stragglers ({len(stragglers)})</h2><table>"
                     "<tr><th>iteration</th><th>step (s)</th>"
                     "<th>EMA (s)</th><th>ratio</th></tr>")
        for r in stragglers[-20:]:
            parts.append(
                f"<tr><td>{r.get('iteration', '?')}</td>"
                f"<td>{r.get('step_s', 0):.4f}</td>"
                f"<td>{r.get('ema_s', 0):.4f}</td>"
                f"<td>{r.get('ratio', 0):.2f}x</td></tr>")
        parts.append("</table>")
    if traces:
        parts.append("<h2>Span timeline</h2>")
        parts.append(_svg_swimlane(traces[-1].get("spans", []),
                                   label="trace spans (tail)"))

    # -- compile latency: persistent-cache hit/miss accounting -----------
    if compiles:
        c = compiles[-1]
        misses = c.get("miss_compiles",
                       max(0, c.get("backend_compiles", 0)
                           - c.get("cache_hits", 0)))
        parts.append(
            f"<h2>Compilation</h2><p>{c.get('backend_compiles', 0)} XLA "
            f"compiles — {c.get('cache_hits', 0)} persistent-cache hits, "
            f"{misses} real (miss) compiles; "
            f"{c.get('backend_compile_seconds', 0.0):.2f}s in the "
            f"backend, {c.get('trace_seconds', 0.0):.2f}s tracing, "
            f"{c.get('saved_seconds', 0.0):.2f}s saved by the cache "
            f"(compilecache/, docs/cold_start.md)</p>")

    # -- static analysis: pre-compile graph/config findings (analyze/) ---
    if analyses:
        a = analyses[-1]
        counts = a.get("counts") or {}
        g = a.get("graph") or {}
        sev_color = {"error": "#d62728", "warn": "#ff7f0e",
                     "info": "#888"}
        parts.append(
            f"<h2>Static analysis</h2><p>{a.get('context', '?')} "
            f"context — {g.get('ops', '?')} ops / "
            f"{g.get('vars', '?')} vars, {a.get('rules_run', '?')} "
            f"rules in {a.get('seconds', 0.0):.3f}s: "
            + ", ".join(f"{counts.get(s, 0)} {s}"
                        for s in ("error", "warn", "info"))
            + " (analyze/, docs/static_analysis.md)</p>")
        findings = a.get("findings") or []
        if findings:
            order = {"error": 0, "warn": 1, "info": 2}
            findings = sorted(findings,
                              key=lambda f: order.get(
                                  f.get("severity"), 3))
            parts.append("<table><tr><th>severity</th><th>rule</th>"
                         "<th>subject</th><th>finding</th></tr>")
            for f in findings[:50]:
                sev = str(f.get("severity", "?"))
                tip = " | ".join(
                    list(f.get("provenance") or [])
                    + ([f"fix: {f['fix_hint']}"]
                       if f.get("fix_hint") else []))
                parts.append(
                    f"<tr><td style='color:"
                    f"{sev_color.get(sev, '#222')}'>"
                    f"{_html.escape(sev)}</td>"
                    f"<td>{_html.escape(str(f.get('rule_id', '?')))}"
                    f"</td>"
                    f"<td>{_html.escape(str(f.get('subject', '?')))}"
                    f"</td>"
                    f"<td title='{_html.escape(tip)}'>"
                    f"{_html.escape(str(f.get('message', '')))}"
                    f"</td></tr>")
            parts.append("</table>")
            extra = a.get("truncated", 0) + max(0, len(findings) - 50)
            if extra:
                parts.append(f"<p>({extra} further findings elided)</p>")
        else:
            parts.append("<p>clean — no findings.</p>")

    # -- elasticity: resharded restores across topology changes ----------
    if reshards:
        parts.append(
            f"<h2>Elastic reshards ({len(reshards)})</h2><table>"
            f"<tr><th>step</th><th>shards</th><th>mesh</th>"
            f"<th>arrays</th><th>MiB gathered</th><th>seconds</th></tr>")
        for r in reshards[-20:]:
            fm = r.get("from_mesh")
            tm = r.get("to_mesh")
            mesh = (f"{fm} → {tm}" if fm or tm else "—")
            if r.get("from_shards") is not None or \
                    r.get("to_processes") is not None:
                shards = (f"{r.get('from_shards', '?')} → "
                          f"{r.get('to_processes', '?')}")
            else:
                # trainer-origin records (in-process mesh change, no
                # shard-count crossing) carry device counts instead
                shards = (f"{r.get('from_devices', '?')} → "
                          f"{r.get('to_devices', '?')} dev")
            parts.append(
                f"<tr><td>{r.get('step', '?')}</td>"
                f"<td>{_html.escape(shards)}</td>"
                f"<td>{_html.escape(str(mesh))}</td>"
                f"<td>{r.get('arrays', 0)}</td>"
                f"<td>{r.get('bytes', 0) / 2**20:.2f}</td>"
                f"<td>{r.get('seconds', 0.0):.4f}</td></tr>")
        parts.append("</table><p>save-on-N / restore-on-M elastic "
                     "restores (checkpoint/reshard.py, "
                     "docs/elastic_training.md)</p>")

    # -- data plane: streaming-pipeline telemetry (datapipe/) ------------
    if datapipe:
        parts.append("<h2>Data pipeline</h2><div class='row'>")
        pts = [(i, r["records_per_sec"]) for i, r in enumerate(datapipe)
               if r.get("records_per_sec") is not None]
        if pts:
            parts.append(_svg_line(
                pts, label="records/sec over flushes", color="#17becf"))
        wait_pts = [(i, 100.0 * r["data_wait_frac"])
                    for i, r in enumerate(datapipe)
                    if r.get("data_wait_frac") is not None]
        if wait_pts:
            parts.append(_svg_line(
                wait_pts, label="data-wait % of wall per flush",
                color="#d62728"))
        parts.append("</div>")
        tot = {k: sum(r.get(k, 0) for r in datapipe)
               for k in ("records", "batches", "read_retries",
                         "rows_quarantined", "records_withheld",
                         "worker_restarts", "requeues", "slow_reads")}
        last = datapipe[-1]
        bits = [f"{tot['records']} records / {tot['batches']} batches "
                f"delivered",
                f"{last.get('passes_started', '?')} passes"]
        for key, label in (("read_retries", "read retries"),
                           ("rows_quarantined", "rows quarantined"),
                           ("records_withheld", "records withheld"),
                           ("worker_restarts", "worker restarts"),
                           ("requeues", "requeues"),
                           ("slow_reads", "slow reads")):
            if tot[key]:
                bits.append(f"{tot[key]} {label}")
        if last.get("quarantined_shards"):
            bits.append(f"{last['quarantined_shards']} shards "
                        f"quarantined")
        parts.append("<p>" + ", ".join(bits) +
                     " (datapipe/, docs/data_pipeline.md)</p>")
        util = last.get("worker_utilization") or {}
        if util:
            parts.append("<table><tr><th>prefetch worker</th>"
                         "<th>utilization (last flush)</th></tr>")
            for w in sorted(util):
                parts.append(f"<tr><td>{_html.escape(str(w))}</td>"
                             f"<td>{100.0 * util[w]:.1f}%</td></tr>")
            parts.append("</table>")

    # -- integrity: stalls, scrub cycles, quarantined rot ----------------
    if integrity or stall_events:
        parts.append("<h2>Integrity</h2>")
    if stall_events:
        parts.append(
            f"<h3>Stalls ({len(stall_events)})</h3><table>"
            f"<tr><th>boundary</th><th>blocked (s)</th>"
            f"<th>deadline (s)</th><th>threads dumped</th></tr>")
        for r in stall_events[-20:]:
            parts.append(
                f"<tr><td>{_html.escape(str(r.get('boundary', '?')))}"
                f"</td><td>{r.get('waited_s', 0.0):.3f}</td>"
                f"<td>{r.get('deadline_s', 0.0):.3f}</td>"
                f"<td>{r.get('threads', '—')}</td></tr>")
        parts.append("</table><p>adaptive-deadline expiries "
                     "(integrity/watchdog.py — forensics in the "
                     "integrity records / GET /stacks)</p>")
    if integrity:
        scrubs = [r for r in integrity if r.get("event") == "scrub"]
        rot = [r for r in integrity
               if r.get("event") in ("checkpoint_quarantined",
                                     "checkpoint_rotten")]
        if scrubs:
            tot_dirs = sum(r.get("scanned", 0) for r in scrubs)
            tot_bytes = sum(r.get("bytes", 0) for r in scrubs)
            tot_rot = sum(r.get("rotten", 0) for r in scrubs)
            parts.append(
                f"<p>checkpoint scrubber: {len(scrubs)} cycle(s), "
                f"{tot_dirs} step dir(s) re-hashed "
                f"({tot_bytes / 2**20:.1f} MiB), {tot_rot} rotten "
                f"(checkpoint/scrub.py)</p>")
        if rot:
            parts.append(
                "<table><tr><th>rotten step</th><th>problems</th>"
                "<th>quarantined to</th></tr>")
            for r in rot[-20:]:
                probs = "; ".join(str(p) for p in
                                  (r.get("problems") or [])[:3])
                dest = str(r.get("quarantined_to") or "—")
                parts.append(
                    f"<tr><td>{r.get('step', '?')}</td>"
                    f"<td>{_html.escape(probs)}</td>"
                    f"<td>{_html.escape(dest)}</td></tr>")
            parts.append("</table>")
        probes = [r for r in integrity
                  if r.get("event") == "stall_forensics"]
        if probes:
            parts.append(f"<p>{len(probes)} stall forensics record(s) "
                         f"captured (all-thread stacks + HBM snapshot "
                         f"+ active plan)</p>")

    # -- serving: traffic + the resilience rail --------------------------
    if serving:
        s = serving[-1]
        c = s.get("counters", {})
        parts.append(
            f"<h2>Serving</h2><p>{c.get('requests_served', 0)} served / "
            f"{c.get('requests_submitted', 0)} submitted — "
            f"{c.get('requests_rejected', 0)} rejected (queue full), "
            f"{c.get('requests_shed', 0)} shed (SLO admission/breaker), "
            f"{c.get('requests_timed_out', 0)} timed out, "
            f"{c.get('requests_failed', 0)} failed; "
            f"{c.get('batches_dispatched', 0)} batches, "
            f"{c.get('compiles', 0)} compiled shapes "
            f"({c.get('warmup_compiles', 0)} prewarmed)</p>")
        gen = s.get("generative") or {}
        if gen:
            parts.append(
                f"<p>generative: {gen.get('tokens_generated', 0)} tokens "
                f"({gen.get('tokens_per_sec', 0.0)} tok/s lifetime), "
                f"{gen.get('prefills', 0)} prefills, "
                f"{gen.get('decode_steps', 0)} decode steps, slot "
                f"occupancy {gen.get('slot_occupancy', 0.0):.1%} of "
                f"{gen.get('max_slots', 0)} slots "
                f"(docs/serving.md \"Generative serving\")</p>")
        if gen.get("spec_rounds"):
            parts.append(
                f"<p>speculative: {gen.get('draft_accepted', 0)}/"
                f"{gen.get('draft_tokens', 0)} draft tokens accepted "
                f"({gen.get('draft_acceptance_rate', 0.0):.1%}) over "
                f"{gen.get('spec_rounds', 0)} rounds, "
                f"{gen.get('draft_rejected', 0)} rejected "
                f"(docs/serving.md \"Decode speed\")</p>")
        paged = s.get("paged") or {}
        if paged:
            parts.append(
                f"<p>paged KV: {paged.get('num_blocks', 0)} blocks x "
                f"{paged.get('block_size', 0)} tokens, pool occupancy "
                f"{paged.get('pool_occupancy', 0.0):.1%}, prefix hit "
                f"rate {paged.get('prefix_hit_rate', 0.0):.1%} "
                f"({paged.get('prefix_blocks_hit', 0)} blocks reused), "
                f"{paged.get('blocks_per_request', 0.0)} blocks/request, "
                f"{paged.get('evictions', 0)} cache evictions "
                f"(docs/serving.md \"Paged KV &amp; prefix caching\")</p>")
        lat = s.get("latency_ms", {})
        if lat:
            parts.append("<table><tr><th>lane</th><th>count</th>"
                         "<th>mean</th><th>p50</th><th>p95</th>"
                         "<th>p99</th><th>max (ms)</th></tr>")
            for lane in ("queue_wait", "e2e", "exec", "ttft",
                         "intertoken", "prefill"):
                v = lat.get(lane)
                if v is None:
                    continue
                low = " ⚠" if v.get("low_sample") and \
                    v.get("count") else ""
                parts.append(
                    f"<tr><td>{lane}</td><td>{v.get('count', 0)}{low}"
                    f"</td>"
                    + "".join(f"<td>{v.get(k, 0.0):.3f}</td>"
                              for k in ("mean", "p50", "p95", "p99",
                                        "max"))
                    + "</tr>")
            parts.append("</table>")
        res = s.get("resilience") or {}
        resil_bits = [f"{k.replace('_', ' ')} {c[k]}" for k in
                      ("requests_shed", "breaker_opens", "worker_restarts",
                       "requests_requeued", "poisoned_quarantined",
                       "bisect_splits", "exec_faults", "reloads",
                       "reload_rollbacks") if c.get(k)]
        if res or resil_bits:
            lead = (f"breaker <b>{res.get('breaker_state', '?')}</b>"
                    if res.get("breaker_state") else "")
            reload_note = ""
            if res.get("last_reload_step") is not None:
                reload_note = (
                    f"; last hot reload: step {res['last_reload_step']}"
                    + (" (rolled back)" if res.get("last_reload_failed")
                       else ""))
            parts.append(
                "<p>resilience: " + "; ".join(
                    b for b in ([lead] if lead else []) + resil_bits)
                + reload_note + " (docs/serving.md \"Resilience\")</p>")
    if serving_faults:
        parts.append(
            f"<h3>Serving fault-rail events ({len(serving_faults)})"
            f"</h3><table><tr><th>event</th><th>cause</th>"
            f"<th>detail</th></tr>")
        for r in serving_faults[-20:]:
            detail = {k: v for k, v in r.items()
                      if k not in ("type", "event", "cause", "t",
                                   "origin") and v is not None}
            parts.append(
                f"<tr><td>{_html.escape(str(r.get('event', '?')))}</td>"
                f"<td>{_html.escape(str(r.get('cause', '—')))}</td>"
                f"<td>{_html.escape(str(detail) if detail else '—')}"
                f"</td></tr>")
        parts.append("</table>")

    # -- serving fleet: routing / retries / deploys / autoscale ----------
    if fleet:
        rec = fleet[-1]
        c = rec.get("counters", {})
        agg = rec.get("fleet", {})
        parts.append(f"<h2>Fleet ({agg.get('n_ready', 0)}/"
                     f"{agg.get('n_replicas', 0)} replicas ready)</h2>")
        routing_bits = [
            f"routed {c.get('requests_routed', 0)}",
            f"affinity {c.get('routed_affinity', 0)}",
            f"spill {c.get('routed_spill', 0)}",
            f"least-loaded {c.get('routed_least_loaded', 0)}",
            f"affinity hit rate "
            f"<b>{agg.get('affinity_hit_rate', 0.0):.1%}</b>"]
        parts.append("<p>routing: " + "; ".join(routing_bits) + "</p>")
        retry_bits = [f"{k.replace('_', ' ')} {c[k]}" for k in
                      ("retries", "sheds_seen", "replica_deaths_seen",
                       "retry_giveups", "requests_failed",
                       "requests_timed_out") if c.get(k)]
        if retry_bits:
            parts.append("<p>resilience: " + "; ".join(retry_bits)
                         + f" ({c.get('requests_ok', 0)} ok)</p>")
        ops_bits = [f"{k.replace('_', ' ')} {c[k]}" for k in
                    ("deploys", "deploy_rollbacks", "scale_up_events",
                     "scale_down_events") if c.get(k)]
        if ops_bits:
            parts.append("<p>operations: " + "; ".join(ops_bits)
                         + "</p>")
        dur = rec.get("durability")
        if dur:
            fs = dur.get("journal_fsync_ms") or {}
            parts.append(
                f"<p>durability: <b>{dur.get('resumes', 0)}</b> resumes"
                f" salvaging <b>{dur.get('tokens_salvaged', 0)}</b> "
                f"tokens; {dur.get('dedup_drops', 0)} duplicate "
                f"deliveries absorbed; "
                f"{dur.get('recovered_requests', 0)} journal replays; "
                f"{dur.get('journal_records', 0)} journal records "
                f"(fsync p99 {fs.get('p99', 0.0):.2f} ms)</p>")
        slo = rec.get("slo")
        if slo:
            parts.append("<h3>SLO</h3>")
            objectives = slo.get("objectives") or {}
            head = []
            for field in sorted(objectives):
                o = objectives[field]
                head.append(
                    f"{_html.escape(field)} ≤ {o.get('target_ms', 0):g} "
                    f"ms: attainment <b>{o.get('attainment', 1.0):.2%}"
                    f"</b>, burn rate <b>{o.get('burn_rate', 0.0):.2f}×"
                    f"</b>, p50 {o.get('p50_ms', 0.0):.1f} / p99 "
                    f"{o.get('p99_ms', 0.0):.1f} ms")
            outcomes = slo.get("outcomes") or {}
            oc = "; ".join(f"{k} {v}" for k, v in sorted(outcomes.items())
                           if v)
            parts.append(
                "<p>" + "; ".join(head)
                + f" (window {slo.get('window', 0)} of "
                f"{slo.get('total', 0)} total"
                + (f"; outcomes: {oc}" if oc else "") + ")</p>")
            # attainment over time: one point per published fleet record
            for field in sorted(objectives):
                pts = []
                for i, frec in enumerate(fleet):
                    o = ((frec.get("slo") or {}).get("objectives")
                         or {}).get(field)
                    if o is not None:
                        pts.append((float(i), float(
                            o.get("attainment", 1.0))))
                if len(pts) > 1:
                    parts.append(_svg_line(
                        pts, color="#2ca02c",
                        label=f"SLO attainment ({field})"))
            worst = slo.get("worst_traces") or []
            if worst:
                parts.append(
                    "<p>worst sampled traces (TTFT breakdown — "
                    "where the time went):</p>"
                    "<table><tr><th>trace</th><th>ttft ms</th>"
                    "<th>queue wait</th><th>prefill</th>"
                    "<th>first decode</th><th>e2e ms</th>"
                    "<th>replica</th><th>retries</th><th>kept</th>"
                    "</tr>")
                for e in worst:
                    bd = e.get("breakdown") or {}
                    ttft = e.get("ttft_ms")
                    e2e = e.get("e2e_ms")
                    parts.append(
                        f"<tr><td>{_html.escape(str(e.get('trace_id')))}"
                        f"</td>"
                        f"<td>{0.0 if ttft is None else ttft:.1f}</td>"
                        f"<td>{bd.get('queue_wait_ms', 0.0):.1f}</td>"
                        f"<td>{bd.get('prefill_ms', 0.0):.1f}</td>"
                        f"<td>{bd.get('first_decode_ms', 0.0):.1f}</td>"
                        f"<td>{0.0 if e2e is None else e2e:.1f}</td>"
                        f"<td>{_html.escape(str(e.get('replica') or '—'))}"
                        f"</td><td>{e.get('retries', 0)}</td>"
                        f"<td>{_html.escape(str(e.get('kept') or '—'))}"
                        f"</td></tr>")
                parts.append("</table>")
            parts.append("<p>(docs/observability.md \"Request tracing "
                         "&amp; SLOs\")</p>")
        replicas = rec.get("replicas", {})
        if replicas:
            parts.append(
                "<table><tr><th>replica</th><th>ready</th>"
                "<th>queue</th><th>occupancy</th>"
                "<th>p99 step ms</th><th>routed</th></tr>")
            for name in sorted(replicas):
                rep = replicas[name]
                parts.append(
                    f"<tr><td>{_html.escape(str(name))}</td>"
                    f"<td>{'yes' if rep.get('ready') else 'NO'}</td>"
                    f"<td>{rep.get('queue_depth', 0)}</td>"
                    f"<td>{rep.get('occupancy', 0.0):.0%}</td>"
                    f"<td>{rep.get('p99_decode_step_ms', 0.0):.2f}</td>"
                    f"<td>{rep.get('routed', 0)}</td></tr>")
            parts.append("</table>")
        parts.append("<p>(docs/serving.md \"Fleet\")</p>")

    # -- observability: unified metrics snapshot -------------------------
    if metrics:
        flat = metrics[-1].get("metrics", {})
        parts.append(f"<h2>Metrics (last snapshot, {len(flat)} series)"
                     f"</h2><table><tr><th>metric</th><th>value</th>"
                     f"</tr>")
        for name in sorted(flat):
            v = flat[name]
            vs = f"{v:.6g}" if isinstance(v, float) else str(v)
            parts.append(f"<tr><td>{_html.escape(str(name))}</td>"
                         f"<td>{_html.escape(vs)}</td></tr>")
        parts.append("</table>")

    # -- forward compatibility: record types this renderer predates ------
    unknown: dict = {}
    for r in storage.records:
        t = r.get("type")
        if t not in _KNOWN_TYPES:
            key = str(t)
            unknown[key] = unknown.get(key, 0) + 1
    if unknown:
        listing = ", ".join(f"{_html.escape(k)} ({n})"
                            for k, n in sorted(unknown.items()))
        parts.append(
            f"<p style='color:#888;border-top:1px solid #ddd;"
            f"padding-top:6px'>unrendered record types: {listing} — "
            f"this report predates them; the records are intact in the "
            f"storage</p>")

    parts.append("</body></html>")
    return "\n".join(parts)


def write_report(storage: StatsStorage, path: str,
                 title: str = "Training report") -> str:
    html = render_report(storage, title)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(html)
    return path
