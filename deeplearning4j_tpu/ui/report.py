"""Static HTML training report from a StatsStorage.

Reference parity: the deeplearning4j-vertx dashboard's Overview and
Model tabs (VertxUIServer.java:78; TrainModule's score chart, update:
parameter ratio chart, histograms, system tab) rendered as ONE
self-contained HTML file: inline SVG, zero external assets, no server.
"""
from __future__ import annotations

import html as _html
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.ui.stats import StatsStorage


def _svg_line(points: Sequence[Tuple[float, float]], w=640, h=180,
              color="#1f77b4", label="", ylog=False) -> str:
    if not points:
        return f"<p>(no data for {_html.escape(label)})</p>"
    import math
    xs = [p[0] for p in points]
    ys = [(math.log10(max(p[1], 1e-12)) if ylog else p[1]) for p in points]
    x0, x1 = min(xs), max(xs) or 1
    y0, y1 = min(ys), max(ys)
    if y1 - y0 < 1e-12:
        y0, y1 = y0 - 1, y1 + 1
    px = lambda x: 45 + (x - x0) / max(x1 - x0, 1e-12) * (w - 55)
    py = lambda y: (h - 25) - (y - y0) / (y1 - y0) * (h - 35)
    path = " ".join(f"{'M' if i == 0 else 'L'}{px(x):.1f},{py(y):.1f}"
                    for i, (x, y) in enumerate(zip(xs, ys)))
    fmt = (lambda v: f"1e{v:.1f}") if ylog else (lambda v: f"{v:.4g}")
    return f"""<svg width="{w}" height="{h}" style="background:#fafafa">
<text x="5" y="14" font-size="12" fill="#444">{_html.escape(label)}</text>
<text x="5" y="{h-28}" font-size="10" fill="#888">{fmt(y0)}</text>
<text x="5" y="26" font-size="10" fill="#888">{fmt(y1)}</text>
<path d="{path}" stroke="{color}" fill="none" stroke-width="1.5"/>
</svg>"""


def _svg_hist(hist: List[int], edges: List[float], w=220, h=90,
              label="") -> str:
    if not hist or max(hist) == 0:
        return ""
    n = len(hist)
    bw = (w - 10) / n
    mx = max(hist)
    bars = "".join(
        f'<rect x="{5+i*bw:.1f}" y="{(h-18)*(1-v/mx)+4:.1f}" '
        f'width="{bw-1:.1f}" height="{(h-18)*v/mx:.1f}" fill="#2ca02c"/>'
        for i, v in enumerate(hist))
    return f"""<svg width="{w}" height="{h}" style="background:#fafafa">
{bars}
<text x="5" y="{h-4}" font-size="9" fill="#666">{_html.escape(label)}
 [{edges[0]:.3g}, {edges[1]:.3g}]</text></svg>"""


def render_report(storage: StatsStorage, title: str = "Training report"
                  ) -> str:
    scores = storage.of_type("score")
    perf = storage.of_type("perf")
    params = storage.of_type("params")
    memory = storage.of_type("memory")
    end = storage.of_type("end")

    parts = [f"""<!doctype html><html><head><meta charset="utf-8">
<title>{_html.escape(title)}</title>
<style>body{{font-family:sans-serif;margin:24px;color:#222}}
h2{{border-bottom:1px solid #ddd;padding-bottom:4px}}
.row{{display:flex;flex-wrap:wrap;gap:12px}}
table{{border-collapse:collapse;font-size:13px}}
td,th{{border:1px solid #ccc;padding:3px 8px}}</style></head><body>
<h1>{_html.escape(title)}</h1>"""]

    # -- overview: score + throughput ------------------------------------
    parts.append("<h2>Overview</h2><div class='row'>")
    parts.append(_svg_line([(r["iter"], r["loss"]) for r in scores],
                           label="score vs iteration", ylog=True))
    parts.append(_svg_line(
        [(r["iter"], r.get("samples_per_sec", r["batches_per_sec"]))
         for r in perf],
        label="throughput (samples/sec)" if any(
            "samples_per_sec" in r for r in perf)
        else "throughput (batches/sec)", color="#ff7f0e"))
    parts.append("</div>")
    if end and end[-1].get("wall_seconds") is not None:
        parts.append(f"<p>wall time: {end[-1]['wall_seconds']:.2f}s, "
                     f"{len(scores)} scored iterations</p>")

    # -- model: update:param ratios + histograms -------------------------
    if params:
        parts.append("<h2>Update : parameter ratios (log10)</h2>"
                     "<div class='row'>")
        names = sorted(params[-1]["params"])
        for name in names:
            pts = [(r["epoch"], r["params"][name]["update_ratio"])
                   for r in params if name in r["params"]
                   and "update_ratio" in r["params"][name]]
            if pts:
                parts.append(_svg_line(pts, w=320, h=120, color="#d62728",
                                       label=name, ylog=True))
        parts.append("</div><h2>Parameter histograms (last epoch)</h2>"
                     "<div class='row'>")
        last = params[-1]["params"]
        for name in names:
            ent = last[name]
            parts.append(_svg_hist(ent["hist"], ent["edges"], label=name))
        parts.append("</div><h2>Parameter stats (last epoch)</h2><table>"
                     "<tr><th>param</th><th>mean</th><th>std</th>"
                     "<th>norm</th><th>update norm</th></tr>")
        for name in names:
            ent = last[name]
            parts.append(
                f"<tr><td>{_html.escape(name)}</td>"
                f"<td>{ent['mean']:.4g}</td><td>{ent['std']:.4g}</td>"
                f"<td>{ent['norm']:.4g}</td>"
                f"<td>{ent.get('update_norm', float('nan')):.4g}</td></tr>")
        parts.append("</table>")

    # -- system: memory --------------------------------------------------
    if memory:
        parts.append("<h2>Device memory</h2><div class='row'>")
        parts.append(_svg_line(
            [(r["epoch"], r["bytes_in_use"] / 2**20) for r in memory],
            label="HBM in use (MiB)", color="#9467bd"))
        parts.append(_svg_line(
            [(r["epoch"], r["peak_bytes"] / 2**20) for r in memory],
            label="HBM peak (MiB)", color="#8c564b"))
        parts.append("</div>")

    parts.append("</body></html>")
    return "\n".join(parts)


def write_report(storage: StatsStorage, path: str,
                 title: str = "Training report") -> str:
    html = render_report(storage, title)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(html)
    return path
