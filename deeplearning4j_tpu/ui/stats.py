"""Training stats pipeline: StatsListener -> StatsStorage -> report.

Reference parity: deeplearning4j-ui's stats pipeline —
ui-model/.../stats/BaseStatsListener.java:58 (collects score, timing,
memory, param/update histograms per iteration into a StatsStorage) and
the storage API (api/storage/StatsStorage.java; InMemoryStatsStorage /
FileStatsStorage). The reference serves these to a Vertx web dashboard
(VertxUIServer.java:78); here the dashboard is a STATIC self-contained
HTML artifact (ui/report.py) — no web server, TPU-pod friendly (write
the file, open it anywhere), same charts: score vs iteration,
throughput, update:param ratios, parameter histograms, memory.

Storage format: JSON-lines, one record per event
    {"type": "score",  "iter": i, "epoch": e, "loss": x, "t": wall}
    {"type": "perf",   "iter": i, "batches_per_sec": x, ...}
    {"type": "params", "epoch": e, "params": {name: {mean, std, norm,
        hist, edges, update_norm, update_ratio}}}
    {"type": "memory", "t": wall, "epoch": e, "iteration": i,
        "source": "flush"|"serving"|"probe"|"epoch",
        "bytes_in_use": n, "peak_bytes": n, "bytes_limit": n,
        "headroom": n, "devices": [{device, bytes_in_use, peak_bytes,
        bytes_limit, source, skipped_arrays}], "tracked": {tag: bytes},
        "tracked_counts": {tag: n}, "live_skipped": n}
        (HBM telemetry sampled at listener flush / serving batch
        boundaries — monitor/memstats.memory_record; the short form
        without devices/tracked comes from StatsListener's per-epoch
        sample. Rendered as the report's Memory panel,
        docs/observability.md)
    {"type": "memory_plan", "t": wall, "program": "window_k8",
        "sig": s, "steps": k, "argument_bytes": n, "temp_bytes": n,
        "output_bytes": n, "generated_code_bytes": n, "alias_bytes": n,
        "total_bytes": n, "flops": f, "flops_per_step": f,
        "bytes_accessed": f}
        (one compiled executable's static memory & compute plan —
        compiled.memory_analysis()/cost_analysis() captured at AOT
        precompile / serving warmup / monitored lazy compiles,
        monitor/memstats.py)
    {"type": "serving", "t": wall, "counters": {...},
        "failure_causes": {cause: n}, "timeout_causes": {cause: n},
        "last_error": {kind, cause, error, t} | null, "latency_ms":
        {"queue_wait"|"e2e"|"exec": {count, mean, p50, p95, p99, max}},
        "batch": {mean_size, padding_waste, size_hist}}
        (written by serving/metrics.ServingMetrics.publish)
    {"type": "checkpoint", "step": n, "epoch": e, "iteration": i,
        "bytes": n, "serialize_seconds": s, "commit_seconds": s,
        "queue_seconds": s, "async": bool, "t": wall}
        (written by checkpoint/manager.CheckpointManager on each commit
        when constructed with stats_storage=)
    {"type": "dispatch", "epoch": e, "tier": "per_step"|"windowed"|
        "scanned_epoch", "fused_steps": k, "accum_steps": a,
        "steps_per_epoch": n, "dispatches_per_epoch": n,
        "window_compiles": n, "window_sizes": {length: count}}
        (the fit tier's dispatch/compile accounting, read from
        SameDiff.last_fit_stats at each epoch end — the observable for
        the fused-window executor, docs/training_performance.md)
    {"type": "faults", "event": "fault"|"rollback"|"retry"|"recovered"|
        "retry_exhausted"|"loader_retry"|"loader_failed"|"quarantine"|
        "quarantine_skip", "t": wall, ...event-specific fields: cause,
        step, epoch, batch_index, restored_step, attempt, backoff_s,
        overhead_s, rollbacks}
        (written by faults/recovery.FaultTolerantFit and
        faults/iterators.RetryingIterator when given a stats storage —
        the recovery rail's observable, docs/fault_tolerance.md)
    {"type": "metrics", "t": wall, "namespace": "dl4j",
        "metrics": {"<ns>_<name>{label=\"v\"}": value, ...}}
        (a monitor/registry.MetricsRegistry snapshot — the unified
        counters/gauges/histograms namespace, docs/observability.md)
    {"type": "steptime", "epoch": e, "iteration": i, "windows": n,
        "steps": n, "wall_s": s, "data_wait_s": s, "dispatch_s": s,
        "flush_s": s, "other_s": s, "step_ms_p50"/"p95"/"max": ms}
        and straggler flags {"type": "steptime", "event": "straggler",
        "step_s": s, "ema_s": s, "ratio": r}
        (monitor/steptime.MonitorListener's per-flush wall-time
        attribution — rendered as the report's stacked breakdown)
    {"type": "trace", "t": wall, "spans_total": n, "spans": [{name,
        cat, ts, dur, tid, thread, sid, parent, args}]}
        (a bounded monitor/trace span dump at training end — rendered
        as the report's swimlane timeline)
    {"type": "tensorstats", "iter": i, "epoch": e, "t": wall,
        "every_n": n, "hist_min_exp": m, "layers": {name:
        {"grad_l2"|"grad_mean_abs"|"grad_min"|"grad_max":,
         "grad_nonfinite"|"grad_zeros": n, "grad_hist": [counts],
         ...same families with "update_"/"param_" prefixes...,
         "update_ratio": r}}}
        (in-graph per-layer gradient/update/param summaries sampled
        inside the compiled step — monitor/tensorstats.py, delivered
        through the Listener.tensorstats_done rail and rendered as the
        report's layer-health panel, docs/observability.md)
    {"type": "analysis", "t": wall, "context": "fit"|"precompile"|
        "serving"|"cli", "graph": {"vars": n, "ops": n},
        "rules_run": n, "seconds": s,
        "counts": {"error": n, "warn": n, "info": n},
        "findings": [{rule_id, severity, subject, message, fix_hint,
        provenance: [..]}], "truncated": n}
        (pre-compile static-analysis findings — analyze/
        AnalysisReport.to_record, published by MonitorListener at
        training start and by ParallelInference at construction;
        rendered as the report's "Static analysis" panel, folded to
        dl4j_analysis_* gauges — docs/static_analysis.md)

Unknown record types must DEGRADE GRACEFULLY in consumers: ui/report
renders the sections it knows and lists unrecognized types in a footer
(forward compatibility — an old report reading a new storage must not
silently drop data).
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.autodiff.training import Listener


class StatsStorage:
    """In-memory + optional JSONL-file event store (reference:
    api/storage/StatsStorage.java; FileStatsStorage).

    ``put`` is thread-safe: the async checkpoint writer, serving worker
    threads and the window stager all publish concurrently into one
    storage, so the record append and the JSONL line write happen under
    a lock (otherwise lines can interleave mid-record and the in-memory
    list can drop appends on list reallocation)."""

    def __init__(self, path: Optional[str] = None):
        import threading
        self.path = str(path) if path is not None else None
        self.records: List[dict] = []
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", encoding="utf-8") if self.path \
            else None

    def put(self, record: dict) -> None:
        with self._lock:
            self.records.append(record)
            if self._fh is not None:
                self._fh.write(json.dumps(record) + "\n")
                self._fh.flush()

    def of_type(self, rtype: str) -> List[dict]:
        with self._lock:
            return [r for r in self.records if r.get("type") == rtype]

    def tail(self, n: int = 200, rtype: Optional[str] = None) -> List[dict]:
        """The most recent ``n`` records (optionally one type only) —
        the /stats endpoint's read path (monitor/server.py). ``n <= 0``
        returns nothing (``recs[-0:]`` would silently mean ALL —
        exactly the unbounded dump a tail API exists to prevent)."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            recs = self.records if rtype is None else \
                [r for r in self.records if r.get("type") == rtype]
            return list(recs[-n:])

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    @staticmethod
    def load(path: str, persist: bool = True) -> "StatsStorage":
        """Load a JSONL storage from disk. By default the loaded
        storage KEEPS ``path`` (open in append mode), so subsequent
        ``put``s continue persisting to the same file — a loaded
        storage must not silently become memory-only (round-trip
        tested). Pass ``persist=False`` for a read-only snapshot."""
        records = []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
        st = StatsStorage(path if persist else None)
        st.records.extend(records)
        return st


def _histogram(arr: np.ndarray, bins: int = 16):
    hist, edges = np.histogram(arr, bins=bins)
    return hist.tolist(), [float(edges[0]), float(edges[-1])]


class StatsListener(Listener):
    """Collects per-iteration score/throughput and per-epoch parameter
    statistics into a StatsStorage (reference:
    BaseStatsListener.java:58 — same stat families; histograms and
    update:param ratios are computed per EPOCH here because a jitted
    whole-step design exposes parameters at epoch boundaries, not
    per-op like the reference's interpreter).
    """

    def __init__(self, storage: Optional[StatsStorage] = None,
                 frequency: int = 10, histogram_bins: int = 16):
        self.storage = storage if storage is not None else StatsStorage()
        self.frequency = frequency
        self.histogram_bins = histogram_bins
        self.batch_size = None          # filled by fit()
        self._last_t = None
        self._last_iter = None
        self._prev_params: Dict[str, np.ndarray] = {}
        self._t0 = None

    # -- iteration-level -------------------------------------------------
    def iterations_done(self, sd, epoch: int, iterations: Sequence[int],
                        losses: Sequence[float]):
        now = time.perf_counter()
        for it, lo in zip(iterations, losses):
            self.storage.put({"type": "score", "iter": int(it),
                              "epoch": int(epoch), "loss": float(lo),
                              "t": now})
        it = iterations[-1]
        if self._last_t is not None and it > self._last_iter:
            dt = now - self._last_t
            bps = (it - self._last_iter) / dt if dt > 0 else float("nan")
            rec = {"type": "perf", "iter": int(it),
                   "batches_per_sec": bps}
            if self.batch_size:
                rec["samples_per_sec"] = bps * self.batch_size
            self.storage.put(rec)
        self._last_t, self._last_iter = now, it

    # -- epoch-level -----------------------------------------------------
    def on_training_start(self, sd):
        self._t0 = time.perf_counter()
        self.storage.put({"type": "meta",
                          "params": {n: list(np.shape(a)) for n, a in
                                     sd.trainable_params().items()},
                          "start_t": self._t0})

    def on_epoch_end(self, sd, epoch: int, mean_loss: float):
        stats = {}
        for name, arr in sd.trainable_params().items():
            # ONE device→host transfer per param, computed in float32:
            # the old float64 upcast doubled peak host memory and the
            # epoch-boundary stall for zero statistical benefit (the
            # params are float32 on device; the record schema's Python
            # floats are unchanged)
            a = np.asarray(arr)
            if a.dtype not in (np.float32, np.float64):
                a = a.astype(np.float32)    # bf16/f16/int -> numpy-native
            hist, edges = _histogram(a, self.histogram_bins)
            ent = {"mean": float(a.mean()), "std": float(a.std()),
                   "norm": float(np.linalg.norm(a)),
                   "hist": hist, "edges": edges}
            prev = self._prev_params.get(name)
            if prev is not None and prev.shape == a.shape:
                upd = a - prev
                un = float(np.linalg.norm(upd))
                ent["update_norm"] = un
                ent["update_ratio"] = un / (ent["norm"] + 1e-12)
            self._prev_params[name] = a
            stats[name] = ent
        self.storage.put({"type": "params", "epoch": int(epoch),
                          "mean_loss": (float(mean_loss)
                                        if mean_loss is not None else None),
                          "params": stats})
        mem = self._memory_stats()
        if mem:
            self.storage.put({"type": "memory", "epoch": int(epoch), **mem})
        disp = getattr(sd, "last_fit_stats", None)
        if disp:
            self.storage.put({"type": "dispatch", "epoch": int(epoch),
                              **disp})

    def on_training_end(self, sd):
        self.storage.put({"type": "end",
                          "wall_seconds": time.perf_counter() - self._t0
                          if self._t0 else None})

    @staticmethod
    def _memory_stats() -> Optional[dict]:
        """Device HBM stats where the backend exposes them (TPU does;
        CPU returns None) — the AllocationsTracker analogue mapped onto
        the runtime's own accounting (round-4 Missing #9)."""
        import jax
        try:
            ms = jax.local_devices()[0].memory_stats()
        except Exception:
            return None
        if not ms:
            return None
        return {"bytes_in_use": int(ms.get("bytes_in_use", 0)),
                "peak_bytes": int(ms.get("peak_bytes_in_use", 0)),
                "bytes_limit": int(ms.get("bytes_limit", 0))}
