"""Training UI subsystem (reference: deeplearning4j-ui-parent).

StatsListener collects score/throughput/param-stats/memory into a
StatsStorage (JSONL); ui.report renders the storage as ONE static,
self-contained HTML dashboard — the Vertx web server replaced by an
artifact you can open anywhere (TPU pods rarely allow inbound ports).
"""
from deeplearning4j_tpu.ui.report import render_report, write_report
from deeplearning4j_tpu.ui.stats import StatsListener, StatsStorage

__all__ = ["StatsListener", "StatsStorage", "render_report",
           "write_report"]
