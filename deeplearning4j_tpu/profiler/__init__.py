"""Profiler subsystem (reference: nd4j linalg/profiler — OpProfiler.java:41,
UnifiedProfiler.java:40, EventLogger.java:74).

TPU-native redesign: per-op timing comes from the XLA/TPU runtime trace
(jax.profiler XSpace), not from dispatch hooks — under whole-graph jit
there is no per-op dispatch to hook. ``ProfilerSession`` wraps trace
capture; ``xplane`` decodes the artifact; ``OpProfile`` reports per-op /
per-category device time.
"""
from deeplearning4j_tpu.profiler.session import OpProfile, ProfilerSession
from deeplearning4j_tpu.profiler.xplane import (
    OpTime, category_times, decode_xspace, device_op_times, load_xspace,
    step_times_ms)

__all__ = ["ProfilerSession", "OpProfile", "OpTime", "decode_xspace",
           "load_xspace", "device_op_times", "category_times",
           "step_times_ms"]
