"""Profiler session: capture + aggregate device op times.

Reference parity: ProfilerConfig/OpProfiler enable-collect-report cycle
(OpProfiler.java:41 printOutDashboard). Usage:

    with ProfilerSession() as prof:
        step(...)                 # any device work
    profile = prof.profile()
    print(profile.report(top=10))
"""
from __future__ import annotations

import glob
import os
import tempfile
from typing import Dict, List, Optional

from deeplearning4j_tpu.profiler.xplane import (
    OpTime, category_times, device_op_times, load_xspace)


class OpProfile:
    """Aggregated per-op device times for one capture."""

    def __init__(self, op_times: List[OpTime]):
        self.op_times = op_times

    def top(self, n: int = 10) -> List[OpTime]:
        return self.op_times[:n]

    def by_category(self) -> Dict[str, float]:
        return category_times(self.op_times)

    def total_ms(self) -> float:
        return sum(o.total_ms for o in self.op_times)

    def report(self, top: int = 15) -> str:
        lines = [f"device op time: {self.total_ms():.2f} ms total",
                 f"{'op':<60} {'count':>6} {'ms':>9} {'%':>6}  category"]
        tot = self.total_ms() or 1.0
        for o in self.top(top):
            nm = o.name if len(o.name) <= 60 else o.name[:57] + "..."
            lines.append(f"{nm:<60} {o.count:>6} {o.total_ms:>9.2f} "
                         f"{100*o.total_ms/tot:>5.1f}%  {o.category}")
        lines.append("-- by category --")
        for cat, ms in self.by_category().items():
            lines.append(f"  {cat:<30} {ms:>9.2f} ms {100*ms/tot:>5.1f}%")
        return "\n".join(lines)


class ProfilerSession:
    """Context manager around jax.profiler.start_trace/stop_trace that
    decodes the resulting xplane artifact."""

    def __init__(self, log_dir: Optional[str] = None):
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dl4j_tpu_prof_")
        self._profile: Optional[OpProfile] = None

    def __enter__(self):
        import jax
        jax.profiler.start_trace(self.log_dir)
        return self

    def __exit__(self, exc_type, exc, tb):
        import jax
        jax.profiler.stop_trace()
        return False

    def xplane_paths(self) -> List[str]:
        return sorted(glob.glob(
            os.path.join(self.log_dir, "**", "*.xplane.pb"), recursive=True))

    def profile(self) -> OpProfile:
        if self._profile is None:
            ops: List[OpTime] = []
            for p in self.xplane_paths():
                ops.extend(device_op_times(load_xspace(p)))
            self._profile = OpProfile(sorted(ops, key=lambda o: -o.total_ps))
        return self._profile
