"""Profiler session: capture + aggregate device op times.

Reference parity: ProfilerConfig/OpProfiler enable-collect-report cycle
(OpProfiler.java:41 printOutDashboard). Usage:

    with ProfilerSession() as prof:
        step(...)                 # any device work
    profile = prof.profile()
    print(profile.report(top=10))
"""
from __future__ import annotations

import glob
import os
import tempfile
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.profiler.xplane import (
    OpTime, category_times, device_op_times, load_xspace)


class OpProfile:
    """Aggregated per-op device times for one capture."""

    def __init__(self, op_times: List[OpTime]):
        self.op_times = op_times

    def top(self, n: int = 10) -> List[OpTime]:
        return self.op_times[:n]

    def by_category(self) -> Dict[str, float]:
        return category_times(self.op_times)

    def total_ms(self) -> float:
        return sum(o.total_ms for o in self.op_times)

    def report(self, top: int = 15) -> str:
        lines = [f"device op time: {self.total_ms():.2f} ms total",
                 f"{'op':<60} {'count':>6} {'ms':>9} {'%':>6}  category"]
        tot = self.total_ms() or 1.0
        for o in self.top(top):
            nm = o.name if len(o.name) <= 60 else o.name[:57] + "..."
            lines.append(f"{nm:<60} {o.count:>6} {o.total_ms:>9.2f} "
                         f"{100*o.total_ms/tot:>5.1f}%  {o.category}")
        lines.append("-- by category --")
        for cat, ms in self.by_category().items():
            lines.append(f"  {cat:<30} {ms:>9.2f} ms {100*ms/tot:>5.1f}%")
        return "\n".join(lines)


class ProfilerSession:
    """Context manager around jax.profiler.start_trace/stop_trace that
    decodes the resulting xplane artifact."""

    def __init__(self, log_dir: Optional[str] = None):
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dl4j_tpu_prof_")
        self._profile: Optional[OpProfile] = None
        # capture window in time.perf_counter terms — the clock
        # monitor/trace spans use, so correlate_spans can select the
        # spans that overlap this capture
        self.t_start: Optional[float] = None
        self.t_stop: Optional[float] = None

    def __enter__(self):
        import jax
        jax.profiler.start_trace(self.log_dir)
        self.t_start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        import jax
        self.t_stop = time.perf_counter()
        jax.profiler.stop_trace()
        return False

    def xplane_paths(self) -> List[str]:
        return sorted(glob.glob(
            os.path.join(self.log_dir, "**", "*.xplane.pb"), recursive=True))

    def profile(self) -> OpProfile:
        if self._profile is None:
            ops: List[OpTime] = []
            for p in self.xplane_paths():
                ops.extend(device_op_times(load_xspace(p)))
            self._profile = OpProfile(sorted(ops, key=lambda o: -o.total_ps))
        return self._profile

    def correlate_spans(self, tracer=None, spans=None) -> dict:
        """Correlate this capture's DEVICE op time with the monitor
        tracer's host-side ``window``/``step`` spans.

        The xplane capture knows what the device did but not which fit
        window asked for it; the tracer knows the windows but times only
        the host. This joins them at the capture boundary: window spans
        overlapping [t_start, t_stop] share the capture's total device
        op time proportionally to their wall duration (an ESTIMATE — the
        two clocks are not event-correlated; with equal-length windows,
        which fused training produces by construction, the proportional
        split is exact up to scheduling jitter). Each correlated span
        gains a ``device_ms_est`` arg (visible in the chrome trace) and
        the summary reports device utilization over the window wall time
        — the MFU-shaped number BENCH_r05 had to derive by hand.
        """
        if spans is None:
            if tracer is None:
                from deeplearning4j_tpu.monitor.trace import TRACER as tracer
            spans = [
                s for s in tracer.spans()
                if s.name in ("window", "step")
                and (self.t_start is None or s.t0 + s.dur >= self.t_start)
                and (self.t_stop is None or s.t0 <= self.t_stop)]
        device_ms = self.profile().total_ms()
        wall_s = sum(s.dur for s in spans)
        windows = []
        for s in spans:
            est = device_ms * (s.dur / wall_s) if wall_s > 0 else 0.0
            s.set(device_ms_est=round(est, 4))
            windows.append({
                "name": s.name, "ts": s.t0, "dur_s": round(s.dur, 9),
                "k": int(s.args.get("k", 1)),
                "iteration": s.args.get("iteration"),
                "device_ms_est": round(est, 4)})
        return {"device_total_ms": round(device_ms, 4),
                "window_wall_s": round(wall_s, 6),
                "device_utilization": round(
                    device_ms / (wall_s * 1e3), 6) if wall_s > 0 else 0.0,
                "windows": windows}
