"""XPlane (jax.profiler / XLA trace) decoder.

Reference parity: OpProfiler (nd4j-api/.../linalg/profiler/OpProfiler.java:41)
aggregates per-op invocation counts/timings via executioner hooks;
UnifiedProfiler (UnifiedProfiler.java:40) logs op events for offline
analysis by contrib/unified-profiler-analyzer. On TPU the runtime already
emits the authoritative trace — XLA's XSpace protobuf written by
``jax.profiler.start_trace`` — so the profiler's job is decoding and
aggregating it, not hooking dispatch.

Schema constants are the frozen public fields of
tensorflow/tsl/profiler/protobuf/xplane.proto:
  XSpace:  planes=1
  XPlane:  id=1 name=2 lines=3 event_metadata=4(map: key=1,value=2)
           stat_metadata=5
  XLine:   id=1 name=2 timestamp_ns=3 events=4
  XEvent:  metadata_id=1 offset_ps=2 duration_ps=3 stats=4
  XEventMetadata: id=1 name=2 metadata=3 display_name=4
  XStat:   metadata_id=1 double=2 uint64=3 int64=4 str=5 bytes=6 ref=7
  XStatMetadata:  id=1 name=2
Decoded with the same wire-format decoder the TF model importer uses
(modelimport/protowire.py).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

from deeplearning4j_tpu.modelimport.protowire import Fields


@dataclasses.dataclass
class XEvent:
    name: str
    offset_ps: int
    duration_ps: int
    stats: Dict[str, object]


@dataclasses.dataclass
class XLine:
    name: str
    events: List[XEvent]


@dataclasses.dataclass
class XPlane:
    name: str
    lines: List[XLine]


def _decode_stat(stat: Fields, stat_meta: Dict[int, str]) -> Tuple[str, object]:
    name = stat_meta.get(stat.varint(1), str(stat.varint(1)))
    if stat.has(2):
        return name, stat.f64(2)
    if stat.has(3):
        return name, stat.varint(3)
    if stat.has(4):
        return name, stat.svarint(4)
    if stat.has(5):
        return name, stat.string(5)
    if stat.has(6):
        return name, stat.bytes_(6)
    if stat.has(7):
        return name, stat.varint(7)
    return name, None


def decode_xspace(data: bytes) -> List[XPlane]:
    space = Fields(data)
    planes = []
    for pf in space.repeated_message(1):
        ev_meta: Dict[int, Fields] = {}
        for entry in pf.repeated_message(4):
            val = entry.message(2)
            if val is not None:
                ev_meta[entry.varint(1)] = val
        stat_meta: Dict[int, str] = {}
        for entry in pf.repeated_message(5):
            val = entry.message(2)
            if val is not None:
                stat_meta[entry.varint(1)] = val.string(2)
        ev_names = {mid: m.string(2) for mid, m in ev_meta.items()}
        lines = []
        for lf in pf.repeated_message(3):
            events = []
            for ef in lf.repeated_message(4):
                stats = dict(_decode_stat(s, stat_meta)
                             for s in ef.repeated_message(4))
                events.append(XEvent(
                    name=ev_names.get(ef.varint(1), ""),
                    offset_ps=ef.varint(2),
                    duration_ps=ef.varint(3),
                    stats=stats))
            lines.append(XLine(name=lf.string(2), events=events))
        planes.append(XPlane(name=pf.string(2), lines=lines))
    return planes


def load_xspace(path: str) -> List[XPlane]:
    with open(path, "rb") as fh:
        return decode_xspace(fh.read())


@dataclasses.dataclass
class OpTime:
    """Aggregated device time for one op (XLA fusion/instruction)."""
    name: str
    count: int = 0
    total_ps: int = 0
    category: str = ""

    @property
    def total_ms(self) -> float:
        return self.total_ps / 1e9


def _op_category(ev: XEvent) -> str:
    cat = ev.stats.get("hlo_category")
    if cat:
        return str(cat)
    # optimized-HLO instruction names follow '%<opcode>.<n> = ...'
    nm = ev.name
    if nm.startswith("%"):
        head = nm[1:].split(" ", 1)[0]
        return head.rsplit(".", 1)[0]
    return ""


def device_op_times(planes: List[XPlane],
                    include_async: bool = False) -> List[OpTime]:
    """Per-op device time from the synchronous 'XLA Ops' trace line of each
    device plane ('/device:TPU:N'). The 'Async XLA Ops' line records
    copy-start/done pairs whose durations OVERLAP compute — excluded by
    default (they would double-count the timeline); pass include_async=True
    to see them (labelled 'async:').
    """
    agg: Dict[str, OpTime] = {}

    def _add(ev: XEvent, prefix=""):
        key = prefix + ev.name
        o = agg.setdefault(key, OpTime(name=key))
        o.count += 1
        o.total_ps += ev.duration_ps
        if not o.category:
            o.category = prefix + _op_category(ev)

    for plane in planes:
        if "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "XLA Ops":
                for ev in line.events:
                    _add(ev)
            elif include_async and line.name == "Async XLA Ops":
                for ev in line.events:
                    _add(ev, prefix="async:")
    return sorted(agg.values(), key=lambda o: -o.total_ps)


def step_times_ms(planes: List[XPlane]) -> List[float]:
    """Device step durations from the 'Steps' line (one entry per traced
    step)."""
    out = []
    for plane in planes:
        if "/device:" not in plane.name:
            continue
        for line in plane.lines:
            if line.name == "Steps":
                out.extend(e.duration_ps / 1e9 for e in line.events)
    return out


def category_times(op_times: List[OpTime]) -> Dict[str, float]:
    """Total ms per hlo_category (convolution / fusion / copy / ...)."""
    out: Dict[str, float] = {}
    for o in op_times:
        cat = o.category or "(uncategorized)"
        out[cat] = out.get(cat, 0.0) + o.total_ms
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))
