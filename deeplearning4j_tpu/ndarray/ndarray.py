"""Eager NDArray — the INDArray equivalent.

Reference parity: org.nd4j.linalg.api.ndarray.INDArray (interface,
nd4j-api .../api/ndarray/INDArray.java) and BaseNDArray.java. The reference
implements views as (offset, stride) aliases over a shared DataBuffer and
mutates in place; XLA has value semantics, so this class maps the same user
API onto functional updates:

- A *view* stores its parent plus a (gather, scatter) lens pair. Reads walk
  up to the owning array's current buffer; in-place writes scatter back
  through the chain (``x[1:3].addi(1)`` updates ``x``, like the reference).
- In-place ops on an owner simply rebind the underlying ``jax.Array``.
  Live views see the update because reads are routed through the owner.

This gives reference-compatible aliasing behaviour while every actual
computation stays a pure XLA op (fusable, donation-friendly). Hot paths
(training loops) do not use this class at all — they run through the graph
layer (autodiff/) which compiles whole steps; NDArray is the imperative
convenience layer, like INDArray was for nd4j users.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtype import DataType, default_float

Number = Union[int, float, bool]


def _as_jax(values, dtype=None):
    if isinstance(values, NDArray):
        arr = values.data
        return arr.astype(dtype) if dtype is not None and arr.dtype != dtype else arr
    if isinstance(values, (jnp.ndarray, jax.Array)):
        return values if dtype is None else values.astype(dtype)
    return jnp.asarray(values, dtype=dtype)


class NDArray:
    """Dense n-dimensional tensor handle over a ``jax.Array``."""

    __slots__ = ("_data", "_base", "_gather", "_scatter")

    def __init__(self, data, dtype=None, _base: Optional["NDArray"] = None,
                 _gather: Optional[Callable] = None,
                 _scatter: Optional[Callable] = None):
        if _base is not None:
            self._data = None
            self._base = _base
            self._gather = _gather
            self._scatter = _scatter
        else:
            if dtype is not None:
                dtype = DataType.from_any(dtype).jnp
            self._data = _as_jax(data, dtype)
            self._base = None
            self._gather = None
            self._scatter = None

    # ------------------------------------------------------------------
    # buffer plumbing
    # ------------------------------------------------------------------
    @property
    def data(self) -> jax.Array:
        """Current value as a jax.Array (pure; views re-gather from owner)."""
        if self._base is None:
            return self._data
        return self._gather(self._base.data)

    def _set_data(self, new: jax.Array) -> None:
        """Functional write-through: scatter into the owning buffer."""
        if self._base is None:
            self._data = new
        else:
            self._base._set_data(self._scatter(self._base.data, new))

    def is_view(self) -> bool:
        return self._base is not None

    def _view(self, gather: Callable, scatter: Callable) -> "NDArray":
        return NDArray(None, _base=self, _gather=gather, _scatter=scatter)

    # ------------------------------------------------------------------
    # basic properties  (reference: INDArray.shape()/rank()/length()/...)
    # ------------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def rank(self) -> int:
        return self.data.ndim

    @property
    def ndim(self) -> int:
        return self.data.ndim

    @property
    def length(self) -> int:
        return int(self.data.size)

    @property
    def size_total(self) -> int:
        return int(self.data.size)

    def size(self, dim: int) -> int:
        return self.shape[dim]

    @property
    def dtype(self) -> DataType:
        return DataType.from_any(self.data.dtype.name)

    @property
    def rows(self) -> int:
        return self.shape[0]

    @property
    def columns(self) -> int:
        return self.shape[1]

    def is_scalar(self) -> bool:
        return self.rank == 0 or self.length == 1

    def is_vector(self) -> bool:
        return self.rank == 1 or (self.rank == 2 and 1 in self.shape)

    def is_matrix(self) -> bool:
        return self.rank == 2

    def is_empty(self) -> bool:
        return self.length == 0

    # ------------------------------------------------------------------
    # conversion
    # ------------------------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def item(self) -> Number:
        return self.data.reshape(()).item() if self.length == 1 else self._scalar_err()

    def _scalar_err(self):
        raise ValueError(f"Array with shape {self.shape} is not a scalar")

    def get_double(self, *indices) -> float:
        return float(self.data[tuple(indices)]) if indices else float(self.item())

    def get_int(self, *indices) -> int:
        return int(self.data[tuple(indices)]) if indices else int(self.item())

    def cast_to(self, dtype) -> "NDArray":
        return NDArray(self.data.astype(DataType.from_any(dtype).jnp))

    astype = cast_to

    def dup(self) -> "NDArray":
        """Detached copy (reference: INDArray.dup())."""
        return NDArray(jnp.asarray(self.data))

    # ------------------------------------------------------------------
    # indexing: basic indexing returns a write-through view
    # ------------------------------------------------------------------
    def __getitem__(self, idx) -> "NDArray":
        if not isinstance(idx, tuple):
            idx = (idx,)
        basic = all(isinstance(i, (int, slice, type(Ellipsis), type(None))) for i in idx)
        if basic:
            gather = lambda d: d[idx]
            scatter = lambda d, v: d.at[idx].set(v)
            return self._view(gather, scatter)
        # advanced indexing → copy (matches numpy; reference get(INDArrayIndex...)
        # with NDArrayIndex.indices also copies)
        jidx = tuple(_as_jax(i) if isinstance(i, (list, np.ndarray, NDArray)) else i
                     for i in idx)
        return NDArray(self.data[jidx])

    def __setitem__(self, idx, value) -> None:
        v = _as_jax(value)
        if not isinstance(idx, tuple):
            idx = (idx,)
        jidx = tuple(_as_jax(i) if isinstance(i, (list, np.ndarray, NDArray)) else i
                     for i in idx)
        self._set_data(self.data.at[jidx].set(v.astype(self.data.dtype)))

    def get_row(self, i: int) -> "NDArray":
        return self[i]

    def get_column(self, i: int) -> "NDArray":
        return self[:, i]

    def get_rows(self, rows: Sequence[int]) -> "NDArray":
        return NDArray(self.data[jnp.asarray(list(rows))])

    def get_columns(self, cols: Sequence[int]) -> "NDArray":
        return NDArray(self.data[:, jnp.asarray(list(cols))])

    def put_row(self, i: int, row) -> "NDArray":
        self[i] = _as_jax(row)
        return self

    def put_column(self, i: int, col) -> "NDArray":
        self[:, i] = _as_jax(col)
        return self

    def put_scalar(self, indices, value) -> "NDArray":
        if isinstance(indices, int):
            indices = (indices,)
        self[tuple(indices)] = value
        return self

    def assign(self, other) -> "NDArray":
        """In-place overwrite, broadcasting (reference: INDArray.assign)."""
        v = _as_jax(other)
        self._set_data(jnp.broadcast_to(v.astype(self.data.dtype), self.shape))
        return self

    # ------------------------------------------------------------------
    # shape manipulation — views with write-through where the reference
    # returns views (reshape/transpose/permute), copies elsewhere
    # ------------------------------------------------------------------
    def reshape(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        old_shape = self.shape
        gather = lambda d: d.reshape(shape)
        scatter = lambda d, v: v.reshape(old_shape)
        return self._view(gather, scatter)

    def transpose(self) -> "NDArray":
        axes = tuple(reversed(range(self.rank)))
        return self.permute(*axes)

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    def permute(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        inv = tuple(np.argsort(axes))
        gather = lambda d: jnp.transpose(d, axes)
        scatter = lambda d, v: jnp.transpose(v, inv)
        return self._view(gather, scatter)

    def swap_axes(self, a: int, b: int) -> "NDArray":
        axes = list(range(self.rank))
        axes[a], axes[b] = axes[b], axes[a]
        return self.permute(*axes)

    def ravel(self) -> "NDArray":
        return self.reshape(-1)

    def flatten(self) -> "NDArray":
        return NDArray(self.data.reshape(-1))

    def expand_dims(self, axis: int) -> "NDArray":
        return NDArray(jnp.expand_dims(self.data, axis))

    def squeeze(self, axis=None) -> "NDArray":
        return NDArray(jnp.squeeze(self.data, axis))

    def broadcast_to(self, shape) -> "NDArray":
        return NDArray(jnp.broadcast_to(self.data, tuple(shape)))

    def repeat(self, repeats, axis=None) -> "NDArray":
        return NDArray(jnp.repeat(self.data, repeats, axis))

    def tile(self, reps) -> "NDArray":
        return NDArray(jnp.tile(self.data, reps))

    # ------------------------------------------------------------------
    # arithmetic — out-of-place + "i"-suffixed in-place (reference naming)
    # ------------------------------------------------------------------
    def _binary(self, other, fn) -> "NDArray":
        return NDArray(fn(self.data, _as_jax(other)))

    def _binary_i(self, other, fn) -> "NDArray":
        self._set_data(fn(self.data, _as_jax(other)).astype(self.data.dtype))
        return self

    def add(self, o): return self._binary(o, jnp.add)
    def sub(self, o): return self._binary(o, jnp.subtract)
    def mul(self, o): return self._binary(o, jnp.multiply)
    def div(self, o): return self._binary(o, jnp.divide)
    def rsub(self, o): return self._binary(o, lambda a, b: b - a)
    def rdiv(self, o): return self._binary(o, lambda a, b: b / a)
    def pow(self, o): return self._binary(o, jnp.power)
    def fmod(self, o): return self._binary(o, jnp.fmod)

    def addi(self, o): return self._binary_i(o, jnp.add)
    def subi(self, o): return self._binary_i(o, jnp.subtract)
    def muli(self, o): return self._binary_i(o, jnp.multiply)
    def divi(self, o): return self._binary_i(o, jnp.divide)
    def rsubi(self, o): return self._binary_i(o, lambda a, b: b - a)
    def rdivi(self, o): return self._binary_i(o, lambda a, b: b / a)
    def powi(self, o): return self._binary_i(o, jnp.power)

    def neg(self): return NDArray(-self.data)
    def negi(self): self._set_data(-self.data); return self

    __add__ = add
    __radd__ = add
    __sub__ = sub
    __rsub__ = rsub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rtruediv__ = rdiv
    __pow__ = pow
    __neg__ = neg
    __mod__ = fmod

    def __iadd__(self, o): return self.addi(o)
    def __isub__(self, o): return self.subi(o)
    def __imul__(self, o): return self.muli(o)
    def __itruediv__(self, o): return self.divi(o)

    # comparisons (reference: gt/lt/gte/lte/eq/neq return BOOL arrays)
    def gt(self, o): return self._binary(o, jnp.greater)
    def lt(self, o): return self._binary(o, jnp.less)
    def gte(self, o): return self._binary(o, jnp.greater_equal)
    def lte(self, o): return self._binary(o, jnp.less_equal)
    def eq(self, o): return self._binary(o, jnp.equal)
    def neq(self, o): return self._binary(o, jnp.not_equal)

    __gt__ = gt
    __lt__ = lt
    __ge__ = gte
    __le__ = lte
    __eq__ = eq
    __ne__ = neq
    # elementwise __eq__ makes NDArray unhashable, same as numpy arrays
    __hash__ = None

    def equals(self, other, eps: float = 1e-5) -> bool:
        """Value equality with epsilon (reference: BaseNDArray.equals)."""
        if not isinstance(other, NDArray):
            try:
                other = NDArray(_as_jax(other))
            except (TypeError, ValueError):
                return False
        if self.shape != other.shape:
            return False
        a, b = self.data, other.data
        if self.dtype.is_fp() or other.dtype.is_fp():
            return bool(jnp.all(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)) < eps))
        return bool(jnp.all(a == b))

    # ------------------------------------------------------------------
    # matmul — rides the MXU
    # ------------------------------------------------------------------
    def mmul(self, other) -> "NDArray":
        return NDArray(jnp.matmul(self.data, _as_jax(other)))

    def mmuli(self, other, out: Optional["NDArray"] = None) -> "NDArray":
        r = jnp.matmul(self.data, _as_jax(other))
        if out is not None:
            out._set_data(r.astype(out.data.dtype))
            return out
        self._set_data(r.astype(self.data.dtype))
        return self

    __matmul__ = mmul

    def dot(self, other) -> "NDArray":
        return NDArray(jnp.dot(self.data, _as_jax(other)))

    def tensor_mmul(self, other, axes) -> "NDArray":
        return NDArray(jnp.tensordot(self.data, _as_jax(other), axes=axes))

    # ------------------------------------------------------------------
    # reductions (reference: INDArray.sum/mean/... with dimension varargs)
    # ------------------------------------------------------------------
    def _reduce(self, fn, dims, keep_dims=False) -> "NDArray":
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        return NDArray(fn(self.data, axis=axis, keepdims=keep_dims))

    def sum(self, *dims, keep_dims=False): return self._reduce(jnp.sum, dims, keep_dims)
    def mean(self, *dims, keep_dims=False): return self._reduce(jnp.mean, dims, keep_dims)
    def prod(self, *dims, keep_dims=False): return self._reduce(jnp.prod, dims, keep_dims)
    def max(self, *dims, keep_dims=False): return self._reduce(jnp.max, dims, keep_dims)
    def min(self, *dims, keep_dims=False): return self._reduce(jnp.min, dims, keep_dims)

    def std(self, *dims, bias_corrected=True, keep_dims=False):
        ddof = 1 if bias_corrected else 0
        return self._reduce(
            lambda d, axis, keepdims: jnp.std(d, axis=axis, ddof=ddof, keepdims=keepdims),
            dims, keep_dims)

    def var(self, *dims, bias_corrected=True, keep_dims=False):
        ddof = 1 if bias_corrected else 0
        return self._reduce(
            lambda d, axis, keepdims: jnp.var(d, axis=axis, ddof=ddof, keepdims=keepdims),
            dims, keep_dims)

    def argmax(self, *dims):
        ax = dims[0] if dims else None
        return NDArray(jnp.argmax(self.data, axis=ax))

    def argmin(self, *dims):
        ax = dims[0] if dims else None
        return NDArray(jnp.argmin(self.data, axis=ax))

    def norm1(self, *dims): return self._reduce(lambda d, axis, keepdims: jnp.sum(jnp.abs(d), axis=axis, keepdims=keepdims), dims)
    def norm2(self, *dims): return self._reduce(lambda d, axis, keepdims: jnp.sqrt(jnp.sum(d * d, axis=axis, keepdims=keepdims)), dims)
    def normmax(self, *dims): return self._reduce(lambda d, axis, keepdims: jnp.max(jnp.abs(d), axis=axis, keepdims=keepdims), dims)

    def cumsum(self, axis=None): return NDArray(jnp.cumsum(self.data, axis=axis))
    def cumprod(self, axis=None): return NDArray(jnp.cumprod(self.data, axis=axis))

    def entropy(self, *dims):
        # zero-probability entries contribute 0 (0*log(0) -> 0), matching
        # shannon_entropy's clamp — not NaN
        return self._reduce(
            lambda d, axis, keepdims: -jnp.sum(
                d * jnp.log(jnp.maximum(d, 1e-30)), axis=axis,
                keepdims=keepdims), dims)

    def shannon_entropy(self, *dims):
        """-sum(p * log2(p)) (reference: INDArray.shannonEntropy)."""
        return self._reduce(
            lambda d, axis, keepdims: -jnp.sum(
                d * jnp.log2(jnp.maximum(d, 1e-30)), axis=axis,
                keepdims=keepdims), dims)

    def log_entropy(self, *dims):
        """log(entropy) (reference: INDArray.logEntropy)."""
        e = self.entropy(*dims)
        return NDArray(jnp.log(_as_jax(e)))

    def prod_number(self) -> float:
        return float(jnp.prod(self.data))

    def eps(self, other, eps: float = 1e-5) -> "NDArray":
        """Elementwise |a-b| < eps (reference: INDArray.eps — the Eps
        pairwise bool op)."""
        return NDArray(jnp.abs(self.data - _as_jax(other)) < eps)

    def take(self, indices, axis: int = 0) -> "NDArray":
        """Gather along an axis (reference: Nd4j.pullRows / the gather
        op surface on INDArray)."""
        idx = _as_jax(indices).astype(jnp.int32)
        return NDArray(jnp.take(self.data, idx, axis=axis))

    def scan_all(self) -> dict:
        """Summary stats (reference: SummaryStats ops family)."""
        d = self.data.astype(jnp.float32)
        return {
            "mean": float(jnp.mean(d)), "std": float(jnp.std(d, ddof=1) if d.size > 1 else 0.0),
            "min": float(jnp.min(d)), "max": float(jnp.max(d)),
            "nan": int(jnp.sum(jnp.isnan(d))), "inf": int(jnp.sum(jnp.isinf(d))),
        }

    # ------------------------------------------------------------------
    # round-5 INDArray surface wave (round-4 Weak #9): conditional
    # replace/get (BooleanIndexing), row/column-vector broadcast ops,
    # tensors-along-dimension, scalar reducers, distances, exporters
    # ------------------------------------------------------------------
    def replace_where(self, value, condition) -> "NDArray":
        """In-place ``x[cond] = value`` (reference: INDArray.replaceWhere
        / BooleanIndexing.replaceWhere). ``condition`` is a Conditions
        factory result, callable, or boolean mask; ``value`` a scalar or
        broadcastable array."""
        from deeplearning4j_tpu.ndarray.conditions import resolve
        mask = resolve(condition)(self.data)
        v = _as_jax(value, self.data.dtype)
        self._set_data(jnp.where(mask, v, self.data))
        return self

    def get_where(self, comp, condition) -> "NDArray":
        """Elements where cond(comp or self) holds, flattened (reference:
        INDArray.getWhere). NOTE: data-dependent size — eager-only."""
        from deeplearning4j_tpu.ndarray.conditions import resolve
        src = _as_jax(comp) if comp is not None else self.data
        mask = np.asarray(resolve(condition)(src))
        return NDArray(jnp.asarray(np.asarray(self.data)[mask]))

    def put_where(self, condition, source) -> "NDArray":
        """x[cond] = source[cond] (reference: INDArray.putWhere)."""
        from deeplearning4j_tpu.ndarray.conditions import resolve
        mask = resolve(condition)(self.data)
        s = _as_jax(source, self.data.dtype)
        self._set_data(jnp.where(mask, jnp.broadcast_to(s, self.shape),
                                 self.data))
        return self

    def match_condition(self, condition) -> "NDArray":
        """Boolean mask of matches (reference: MatchConditionTransform)."""
        from deeplearning4j_tpu.ndarray.conditions import resolve
        return NDArray(resolve(condition)(self.data))

    def condition_count(self, condition) -> int:
        """(reference: MatchCondition accumulation)"""
        from deeplearning4j_tpu.ndarray.conditions import resolve
        return int(jnp.sum(resolve(condition)(self.data)))

    # -- row/column vector broadcast arithmetic (reference:
    # INDArray.addRowVector/.addiRowVector etc.) -----------------------
    def _row_op(self, vec, op):
        v = _as_jax(vec).reshape(1, -1)
        return NDArray(op(self.data, v.astype(self.data.dtype)))

    def _col_op(self, vec, op):
        v = _as_jax(vec).reshape(-1, 1)
        return NDArray(op(self.data, v.astype(self.data.dtype)))

    def add_row_vector(self, v):
        return self._row_op(v, jnp.add)

    def sub_row_vector(self, v):
        return self._row_op(v, jnp.subtract)

    def mul_row_vector(self, v):
        return self._row_op(v, jnp.multiply)

    def div_row_vector(self, v):
        return self._row_op(v, jnp.divide)

    def add_column_vector(self, v):
        return self._col_op(v, jnp.add)

    def sub_column_vector(self, v):
        return self._col_op(v, jnp.subtract)

    def mul_column_vector(self, v):
        return self._col_op(v, jnp.multiply)

    def div_column_vector(self, v):
        return self._col_op(v, jnp.divide)

    def addi_row_vector(self, v):
        self._set_data(self.add_row_vector(v).data)
        return self

    def subi_row_vector(self, v):
        self._set_data(self.sub_row_vector(v).data)
        return self

    def muli_row_vector(self, v):
        self._set_data(self.mul_row_vector(v).data)
        return self

    def divi_row_vector(self, v):
        self._set_data(self.div_row_vector(v).data.astype(self.data.dtype))
        return self

    def addi_column_vector(self, v):
        self._set_data(self.add_column_vector(v).data)
        return self

    def subi_column_vector(self, v):
        self._set_data(self.sub_column_vector(v).data)
        return self

    def muli_column_vector(self, v):
        self._set_data(self.mul_column_vector(v).data)
        return self

    def divi_column_vector(self, v):
        self._set_data(
            self.div_column_vector(v).data.astype(self.data.dtype))
        return self

    # -- tensors along dimension (reference: INDArray.
    # tensorAlongDimension / tensorsAlongDimension) --------------------
    def num_tensors_along_dimension(self, *dims) -> int:
        kept = int(np.prod([self.shape[d] for d in dims])) or 1
        return (self.length // kept) if kept else 0

    def tensor_along_dimension(self, index: int, *dims) -> "NDArray":
        dims = tuple(d % self.rank for d in dims)
        others = [d for d in range(self.rank) if d not in dims]
        perm = others + list(dims)
        moved = jnp.transpose(self.data, perm)
        lead = int(np.prod([self.shape[d] for d in others])) or 1
        tad_shape = tuple(self.shape[d] for d in dims)
        return NDArray(moved.reshape((lead,) + tad_shape)[index])

    def vector_along_dimension(self, index: int, dim: int) -> "NDArray":
        return self.tensor_along_dimension(index, dim)

    def slice_at(self, i: int, dim: int = 0) -> "NDArray":
        """(reference: INDArray.slice(i, dimension)) — a view."""
        idx = [slice(None)] * self.rank
        idx[dim] = i
        return self[tuple(idx)]

    def put_slice(self, i: int, value, dim: int = 0) -> "NDArray":
        idx = [slice(None)] * self.rank
        idx[dim] = i
        self[tuple(idx)] = value
        return self

    def repmat(self, *reps) -> "NDArray":
        """(reference: INDArray.repmat) — tile() with varargs."""
        if len(reps) == 1 and isinstance(reps[0], (tuple, list)):
            reps = tuple(reps[0])
        return self.tile(reps)

    def broadcast(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.broadcast_to(shape)

    # -- scalar reducers (reference: maxNumber/minNumber/...) ----------
    def max_number(self) -> float:
        return float(jnp.max(self.data))

    def min_number(self) -> float:
        return float(jnp.min(self.data))

    def mean_number(self) -> float:
        return float(jnp.mean(self.data))

    def sum_number(self) -> float:
        return float(jnp.sum(self.data))

    def std_number(self, bias_corrected: bool = True) -> float:
        return float(jnp.std(
            self.data, ddof=1 if bias_corrected and self.length > 1 else 0))

    def var_number(self, bias_corrected: bool = True) -> float:
        return float(jnp.var(
            self.data, ddof=1 if bias_corrected and self.length > 1 else 0))

    def median_number(self) -> float:
        return float(jnp.median(self.data))

    def percentile_number(self, q: float) -> float:
        return float(jnp.percentile(self.data, q))

    def norm1_number(self) -> float:
        return float(jnp.sum(jnp.abs(self.data)))

    def norm2_number(self) -> float:
        return float(jnp.sqrt(jnp.sum(self.data * self.data)))

    def ammean(self) -> float:
        """Mean of absolute values (reference: amean)."""
        return float(jnp.mean(jnp.abs(self.data)))

    # -- distances (reference: INDArray.distance1/distance2/
    # squaredDistance; Transforms.cosineSim) ---------------------------
    def distance1(self, other) -> float:
        return float(jnp.sum(jnp.abs(self.data - _as_jax(other))))

    def distance2(self, other) -> float:
        d = self.data - _as_jax(other)
        return float(jnp.sqrt(jnp.sum(d * d)))

    def squared_distance(self, other) -> float:
        d = self.data - _as_jax(other)
        return float(jnp.sum(d * d))

    def cosine_similarity(self, other) -> float:
        o = _as_jax(other)
        num = jnp.sum(self.data * o)
        den = jnp.sqrt(jnp.sum(self.data ** 2)) * jnp.sqrt(jnp.sum(o ** 2))
        return float(num / jnp.maximum(den, 1e-30))

    # -- exporters (reference: toIntVector/toFloatMatrix/...) ----------
    def to_int_vector(self):
        return np.asarray(self.data).astype(np.int32).reshape(-1).tolist()

    def to_long_vector(self):
        return np.asarray(self.data).astype(np.int64).reshape(-1).tolist()

    def to_float_vector(self):
        return np.asarray(self.data).astype(np.float32).reshape(-1).tolist()

    def to_double_vector(self):
        return np.asarray(self.data).astype(np.float64).reshape(-1).tolist()

    def to_int_matrix(self):
        return np.asarray(self.data).astype(np.int32).tolist()

    def to_float_matrix(self):
        return np.asarray(self.data).astype(np.float32).tolist()

    def to_double_matrix(self):
        return np.asarray(self.data).astype(np.float64).tolist()

    # -- shape predicates (reference: isRowVector/isColumnVector) ------
    @property
    def is_row_vector(self) -> bool:
        return self.rank == 1 or (self.rank == 2 and self.shape[0] == 1)

    @property
    def is_column_vector(self) -> bool:
        return self.rank == 2 and self.shape[1] == 1

    @property
    def is_square(self) -> bool:
        return self.rank == 2 and self.shape[0] == self.shape[1]

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    def __len__(self) -> int:
        if self.rank == 0:
            raise TypeError("len() of a rank-0 NDArray")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self) -> str:
        return f"NDArray(shape={self.shape}, dtype={self.dtype.name})\n{np.asarray(self.data)}"

    def __format__(self, spec):
        return format(np.asarray(self.data), spec)

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self.data

    def __bool__(self):
        if self.length != 1:
            raise ValueError("truth value of a non-scalar NDArray is ambiguous")
        return bool(self.data.reshape(()))


# ----------------------------------------------------------------------
# camelCase aliases so reference (nd4j) users find familiar method names
# ----------------------------------------------------------------------
_ALIASES = {
    "toNumpy": "to_numpy", "castTo": "cast_to", "getDouble": "get_double",
    "getInt": "get_int", "getRow": "get_row", "getColumn": "get_column",
    "getRows": "get_rows", "getColumns": "get_columns", "putRow": "put_row",
    "putColumn": "put_column", "putScalar": "put_scalar",
    "swapAxes": "swap_axes", "tensorMmul": "tensor_mmul",
    "isScalar": "is_scalar", "isVector": "is_vector", "isMatrix": "is_matrix",
    "isEmpty": "is_empty", "isView": "is_view",
    "replaceWhere": "replace_where", "getWhere": "get_where",
    "putWhere": "put_where", "matchCondition": "match_condition",
    "addRowVector": "add_row_vector", "subRowVector": "sub_row_vector",
    "mulRowVector": "mul_row_vector", "divRowVector": "div_row_vector",
    "addColumnVector": "add_column_vector",
    "subColumnVector": "sub_column_vector",
    "mulColumnVector": "mul_column_vector",
    "divColumnVector": "div_column_vector",
    "addiRowVector": "addi_row_vector", "subiRowVector": "subi_row_vector",
    "muliRowVector": "muli_row_vector", "diviRowVector": "divi_row_vector",
    "addiColumnVector": "addi_column_vector",
    "subiColumnVector": "subi_column_vector",
    "muliColumnVector": "muli_column_vector",
    "diviColumnVector": "divi_column_vector",
    "tensorAlongDimension": "tensor_along_dimension",
    "vectorAlongDimension": "vector_along_dimension",
    "tensorsAlongDimension": "num_tensors_along_dimension",
    "putSlice": "put_slice", "maxNumber": "max_number",
    "minNumber": "min_number", "meanNumber": "mean_number",
    "sumNumber": "sum_number", "stdNumber": "std_number",
    "varNumber": "var_number", "medianNumber": "median_number",
    "percentileNumber": "percentile_number", "norm1Number": "norm1_number",
    "norm2Number": "norm2_number", "squaredDistance": "squared_distance",
    "toIntVector": "to_int_vector", "toLongVector": "to_long_vector",
    "toFloatVector": "to_float_vector", "toDoubleVector": "to_double_vector",
    "toIntMatrix": "to_int_matrix", "toFloatMatrix": "to_float_matrix",
    "toDoubleMatrix": "to_double_matrix",
    "shannonEntropy": "shannon_entropy", "logEntropy": "log_entropy",
    "prodNumber": "prod_number",
}
for _camel, _snake in _ALIASES.items():
    setattr(NDArray, _camel, getattr(NDArray, _snake))
