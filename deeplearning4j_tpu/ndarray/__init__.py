from deeplearning4j_tpu.ndarray.dtype import DataType, default_float, set_default_float
from deeplearning4j_tpu.ndarray.ndarray import NDArray

__all__ = ["DataType", "NDArray", "default_float", "set_default_float"]
