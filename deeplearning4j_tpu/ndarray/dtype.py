"""Data type system.

Reference parity: libnd4j/include/array/DataType.h (dtype enum bool..utf8) and
org.nd4j.linalg.api.buffer.DataType. UTF8/compressed types are represented at
the framework level only (numpy object arrays are host-side); device dtypes map
onto XLA element types. BFLOAT16 is first-class on TPU (MXU-native).
"""
from __future__ import annotations

import enum

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    # name -> (jnp dtype or None for host-only types)
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    HALF = "float16"
    BFLOAT16 = "bfloat16"
    FLOAT = "float32"
    DOUBLE = "float64"
    UTF8 = "utf8"  # host-only

    # ------------------------------------------------------------------
    @property
    def jnp(self):
        if self is DataType.UTF8:
            raise TypeError("UTF8 is a host-only data type")
        return jnp.dtype(self.value)

    @property
    def np(self):
        if self is DataType.UTF8:
            return np.dtype(object)
        return np.dtype(self.value)

    # reference: DataType.isFPType / isIntType / width()
    def is_fp(self) -> bool:
        return self in (DataType.HALF, DataType.BFLOAT16, DataType.FLOAT, DataType.DOUBLE)

    def is_int(self) -> bool:
        return self in (
            DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64,
            DataType.UINT8, DataType.UINT16, DataType.UINT32, DataType.UINT64,
        )

    def is_signed(self) -> bool:
        return self.is_fp() or self in (
            DataType.INT8, DataType.INT16, DataType.INT32, DataType.INT64)

    def width(self) -> int:
        """Bytes per element."""
        if self is DataType.UTF8:
            return 0
        return self.np.itemsize

    # ------------------------------------------------------------------
    @staticmethod
    def from_any(x) -> "DataType":
        if isinstance(x, DataType):
            return x
        if isinstance(x, str):
            s = x.lower()
            alias = {
                "float": "float32", "double": "float64", "half": "float16",
                "long": "int64", "int": "int32", "short": "int16", "byte": "int8",
                "ubyte": "uint8",
            }
            s = alias.get(s, s)
            for dt in DataType:
                if dt.value == s or dt.name.lower() == x.lower():
                    return dt
            raise ValueError(f"Unknown data type: {x}")
        # numpy / jax dtype objects
        name = np.dtype(x).name
        for dt in DataType:
            if dt.value == name:
                return dt
        raise ValueError(f"Unknown data type: {x}")


# Global default dtype — reference: Nd4j.defaultFloatingPointType() /
# ND4JSystemProperties "dtype". On TPU we keep float32 as the default user
# dtype; matmul-heavy paths downcast to bfloat16 where configured.
_DEFAULT_FLOAT = DataType.FLOAT


def default_float() -> DataType:
    return _DEFAULT_FLOAT


def set_default_float(dt) -> None:
    global _DEFAULT_FLOAT
    dt = DataType.from_any(dt)
    if not dt.is_fp():
        raise ValueError("default float type must be a floating point type")
    _DEFAULT_FLOAT = dt
