"""Conditions for boolean indexing (reference:
nd4j-api indexing/conditions/Conditions.java — the factory the reference
uses with BooleanIndexing.replaceWhere / INDArray.replaceWhere).

A Condition is a callable array -> bool mask; factories mirror the
reference names (snake_cased, camelCase aliases kept).
"""
from __future__ import annotations

import jax.numpy as jnp


class Condition:
    def __init__(self, fn, desc: str):
        self._fn = fn
        self.desc = desc

    def __call__(self, x):
        return self._fn(x)

    def __repr__(self):
        return f"Condition({self.desc})"


class Conditions:
    @staticmethod
    def greater_than(v) -> Condition:
        return Condition(lambda x: x > v, f"> {v}")

    @staticmethod
    def less_than(v) -> Condition:
        return Condition(lambda x: x < v, f"< {v}")

    @staticmethod
    def greater_than_or_equal(v) -> Condition:
        return Condition(lambda x: x >= v, f">= {v}")

    @staticmethod
    def less_than_or_equal(v) -> Condition:
        return Condition(lambda x: x <= v, f"<= {v}")

    @staticmethod
    def equals(v) -> Condition:
        return Condition(lambda x: x == v, f"== {v}")

    @staticmethod
    def not_equals(v) -> Condition:
        return Condition(lambda x: x != v, f"!= {v}")

    @staticmethod
    def epsilon_equals(v, eps: float = 1e-5) -> Condition:
        return Condition(lambda x: jnp.abs(x - v) < eps, f"~= {v}")

    @staticmethod
    def is_nan() -> Condition:
        return Condition(jnp.isnan, "isnan")

    @staticmethod
    def is_infinite() -> Condition:
        return Condition(jnp.isinf, "isinf")

    @staticmethod
    def is_finite() -> Condition:
        return Condition(jnp.isfinite, "isfinite")

    @staticmethod
    def not_finite() -> Condition:
        return Condition(lambda x: ~jnp.isfinite(x), "notfinite")

    @staticmethod
    def absolute_greater_than(v) -> Condition:
        return Condition(lambda x: jnp.abs(x) > v, f"|x| > {v}")

    @staticmethod
    def absolute_less_than(v) -> Condition:
        return Condition(lambda x: jnp.abs(x) < v, f"|x| < {v}")

    # reference camelCase aliases
    greaterThan = greater_than
    lessThan = less_than
    greaterThanOrEqual = greater_than_or_equal
    lessThanOrEqual = less_than_or_equal
    notEquals = not_equals
    epsEquals = epsilon_equals
    isNan = is_nan
    isInfinite = is_infinite
    absGreaterThan = absolute_greater_than
    absLessThan = absolute_less_than


def resolve(cond) -> Condition:
    """Accept a Condition, a callable mask fn, or a boolean array."""
    if isinstance(cond, Condition):
        return cond
    if callable(cond):
        return Condition(cond, "custom")
    mask = jnp.asarray(cond)
    return Condition(lambda x: jnp.broadcast_to(mask.astype(bool), x.shape),
                     "mask")
