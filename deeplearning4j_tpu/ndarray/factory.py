"""Static ndarray factory — the ``Nd4j`` equivalent.

Reference parity: org.nd4j.linalg.factory.Nd4j (nd4j-api
.../linalg/factory/Nd4j.java — create/zeros/ones/rand/randn/linspace/eye/
concat/stack/...). The reference routes creation through a backend-selected
NDArrayFactory; here every constructor materialises a ``jax.Array`` on the
default device, and the global RNG mirrors ``Nd4j.getRandom()``'s settable
seed via a counter-based (threefry) key that splits per draw.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtype import DataType, default_float
from deeplearning4j_tpu.ndarray.ndarray import NDArray, _as_jax


# ----------------------------------------------------------------------
# global RNG (reference: Nd4j.getRandom(), nd4j NativeRandom/RandomGenerator —
# libnd4j graph/RandomGenerator.h is counter-based; threefry is the TPU-native
# counter-based equivalent)
# ----------------------------------------------------------------------
class Random:
    """Stateful wrapper over jax's splittable PRNG."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._key = jax.random.key(seed)

    def set_seed(self, seed: int) -> None:
        with self._lock:
            self._key = jax.random.key(seed)

    setSeed = set_seed

    def next_key(self) -> jax.Array:
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub


_RANDOM = Random(np.random.SeedSequence().entropy % (2**31))


def get_random() -> Random:
    return _RANDOM


getRandom = get_random


def _dt(dtype) -> jnp.dtype:
    return DataType.from_any(dtype).jnp if dtype is not None else default_float().jnp


# ----------------------------------------------------------------------
# creation
# ----------------------------------------------------------------------
def create(data=None, shape=None, dtype=None) -> NDArray:
    """Nd4j.create(...) — from nested lists/numpy, or uninitialised by shape."""
    if data is None:
        if shape is None:
            raise ValueError("create() needs data or shape")
        return NDArray(jnp.zeros(tuple(shape), dtype=_dt(dtype)))
    if shape is not None:
        arr = jnp.asarray(data, dtype=DataType.from_any(dtype).jnp if dtype is not None else None)
        if dtype is None and arr.dtype == jnp.float64:
            arr = arr.astype(default_float().jnp)
        return NDArray(arr.reshape(tuple(shape)))
    arr = _as_jax(data)
    if dtype is not None:
        arr = arr.astype(_dt(dtype))
    elif arr.dtype == jnp.float64:
        arr = arr.astype(default_float().jnp)
    return NDArray(arr)


def zeros(*shape, dtype=None) -> NDArray:
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return NDArray(jnp.zeros(shape, dtype=_dt(dtype)))


def ones(*shape, dtype=None) -> NDArray:
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    return NDArray(jnp.ones(shape, dtype=_dt(dtype)))


def zeros_like(arr) -> NDArray:
    return NDArray(jnp.zeros_like(_as_jax(arr)))


def ones_like(arr) -> NDArray:
    return NDArray(jnp.ones_like(_as_jax(arr)))


def value_array_of(shape, value, dtype=None) -> NDArray:
    return NDArray(jnp.full(tuple(shape), value, dtype=_dt(dtype)))


valueArrayOf = value_array_of


def scalar(value, dtype=None) -> NDArray:
    return NDArray(jnp.asarray(value, dtype=_dt(dtype) if dtype is not None or not isinstance(value, (bool, int)) else None))


def eye(n: int, dtype=None) -> NDArray:
    return NDArray(jnp.eye(n, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None) -> NDArray:
    return NDArray(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def arange(*args, dtype=None) -> NDArray:
    return NDArray(jnp.arange(*args, dtype=DataType.from_any(dtype).jnp if dtype else None))


def empty(dtype=None) -> NDArray:
    return NDArray(jnp.zeros((0,), dtype=_dt(dtype)))


# ----------------------------------------------------------------------
# random  (reference: Nd4j.rand / randn / Nd4j.getExecutioner random ops)
# ----------------------------------------------------------------------
def rand(*shape, dtype=None, seed: Optional[int] = None) -> NDArray:
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    key = jax.random.key(seed) if seed is not None else _RANDOM.next_key()
    return NDArray(jax.random.uniform(key, shape, dtype=_dt(dtype)))


def randn(*shape, dtype=None, seed: Optional[int] = None) -> NDArray:
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    key = jax.random.key(seed) if seed is not None else _RANDOM.next_key()
    return NDArray(jax.random.normal(key, shape, dtype=_dt(dtype)))


def rand_int(maxval, shape, minval=0, seed: Optional[int] = None) -> NDArray:
    key = jax.random.key(seed) if seed is not None else _RANDOM.next_key()
    return NDArray(jax.random.randint(key, tuple(shape), minval, maxval, dtype=jnp.int32))


def bernoulli(p, shape, dtype=None, seed: Optional[int] = None) -> NDArray:
    key = jax.random.key(seed) if seed is not None else _RANDOM.next_key()
    return NDArray(jax.random.bernoulli(key, p, tuple(shape)).astype(_dt(dtype)))


def shuffle(arr: NDArray, seed: Optional[int] = None) -> NDArray:
    """In-place first-axis shuffle (reference: Nd4j.shuffle mutates its arg)."""
    key = jax.random.key(seed) if seed is not None else _RANDOM.next_key()
    shuffled = jax.random.permutation(key, _as_jax(arr), axis=0)
    if isinstance(arr, NDArray):
        arr._set_data(shuffled)
        return arr
    return NDArray(shuffled)


# ----------------------------------------------------------------------
# combination / splitting
# ----------------------------------------------------------------------
def concat(dimension: int, *arrs) -> NDArray:
    if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
        arrs = tuple(arrs[0])
    return NDArray(jnp.concatenate([_as_jax(a) for a in arrs], axis=dimension))


def hstack(*arrs) -> NDArray:
    if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
        arrs = tuple(arrs[0])
    return NDArray(jnp.hstack([_as_jax(a) for a in arrs]))


def vstack(*arrs) -> NDArray:
    if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
        arrs = tuple(arrs[0])
    return NDArray(jnp.vstack([_as_jax(a) for a in arrs]))


def stack(dimension: int, *arrs) -> NDArray:
    if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
        arrs = tuple(arrs[0])
    return NDArray(jnp.stack([_as_jax(a) for a in arrs], axis=dimension))


def split(arr, num_or_sections, axis=0):
    return [NDArray(a) for a in jnp.split(_as_jax(arr), num_or_sections, axis=axis)]


def tile(arr, reps) -> NDArray:
    return NDArray(jnp.tile(_as_jax(arr), reps))


def repeat(arr, repeats, axis=None) -> NDArray:
    return NDArray(jnp.repeat(_as_jax(arr), repeats, axis=axis))


def where(cond, x=None, y=None):
    if x is None:
        return [NDArray(w) for w in jnp.where(_as_jax(cond))]
    return NDArray(jnp.where(_as_jax(cond), _as_jax(x), _as_jax(y)))


def sort(arr, axis=-1, descending=False) -> NDArray:
    s = jnp.sort(_as_jax(arr), axis=axis)
    return NDArray(jnp.flip(s, axis=axis) if descending else s)


def argsort(arr, axis=-1) -> NDArray:
    return NDArray(jnp.argsort(_as_jax(arr), axis=axis))


# ----------------------------------------------------------------------
# linalg conveniences (reference: Nd4j.gemm / matmul)
# ----------------------------------------------------------------------
def gemm(a, b, transpose_a=False, transpose_b=False, alpha=1.0, beta=0.0, c=None) -> NDArray:
    A = _as_jax(a).T if transpose_a else _as_jax(a)
    B = _as_jax(b).T if transpose_b else _as_jax(b)
    r = alpha * jnp.matmul(A, B)
    if c is not None and beta != 0.0:
        r = r + beta * _as_jax(c)
    return NDArray(r)


def matmul(a, b) -> NDArray:
    return NDArray(jnp.matmul(_as_jax(a), _as_jax(b)))


def exec_op(op_name: str, *args, **kwargs):
    """Execute a registered named op (reference: Nd4j.exec(DynamicCustomOp))."""
    try:
        from deeplearning4j_tpu.ops.registry import exec_op as _exec
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "the ops registry is not available in this build") from e
    return _exec(op_name, *args, **kwargs)


# camelCase aliases
zerosLike = zeros_like
onesLike = ones_like
randInt = rand_int
execOp = exec_op
