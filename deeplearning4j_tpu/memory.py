"""Memory observability: allocation tracking and HBM watermarks.

Reference parity: org.nd4j.linalg.api.memory.AllocationsTracker (live
per-device allocation accounting) and the workspace debug listeners.

TPU-native redesign: XLA owns allocation, so tracking reads the PJRT
client's per-device counters (``device.memory_stats()``: bytes_in_use,
peak_bytes_in_use, num_allocs, largest_alloc_size) plus the Python-side
live-buffer view (``jax.live_arrays()``). The watermark context manager
is the per-fit HBM accounting the reference gets from
AllocationsTracker.getInstance() around training calls. On backends
whose PJRT client exposes no stats (CPU), live-array accounting is the
fallback so the API stays total.

The live-telemetry half (``{"type": "memory"}`` records at listener
flush boundaries, compiled-program memory plans, the ``/memory`` route,
OOM forensics) lives in :mod:`deeplearning4j_tpu.monitor.memstats` and
samples this module — see docs/observability.md ("Memory
observability").
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass
class DeviceMemoryState:
    """One device's counters at a point in time."""
    device: str
    bytes_in_use: int = 0
    peak_bytes: int = 0
    num_allocs: int = 0
    largest_alloc: int = 0
    bytes_limit: int = 0
    source: str = "pjrt"        # "pjrt" | "live_arrays"
    skipped_arrays: int = 0     # live-array fallback only: arrays the
    #                             census could not size (deleted/donated)


def _live_array_bytes_by_device() -> Tuple[Dict[str, int], int]:
    """Python-side live-buffer accounting: per-device bytes of every
    addressable ``jax.live_arrays()`` shard, plus the count of arrays
    that could NOT be sized. An array can be un-sizable for two
    legitimate reasons — it was ``delete()``d but the tracking list has
    not dropped it yet, or its buffer was DONATED into a running
    computation (reading shards then raises RuntimeError). Those are
    skipped and **counted**, never silently dropped: a fallback total
    that silently undercounts would masquerade as headroom."""
    import jax
    by_dev: Dict[str, int] = {}
    skipped = 0
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                skipped += 1
                continue
        except Exception:
            pass        # not every array type exposes is_deleted()
        try:
            for shard in a.addressable_shards:
                d = str(shard.device)
                by_dev[d] = by_dev.get(d, 0) + int(shard.data.nbytes)
        except RuntimeError:
            # deleted/donated between the is_deleted() check and the
            # shard read (the race is real: the async dispatch thread
            # consumes donated buffers concurrently)
            skipped += 1
    return by_dev, skipped


def snapshot() -> List[DeviceMemoryState]:
    """Per-device memory counters (reference:
    AllocationsTracker.getInstance() device reports)."""
    import jax
    out: List[DeviceMemoryState] = []
    live = None
    live_skipped = 0
    for dev in jax.local_devices():
        ms = None
        try:
            ms = dev.memory_stats()
        except Exception:
            ms = None
        if ms:
            out.append(DeviceMemoryState(
                device=str(dev),
                bytes_in_use=int(ms.get("bytes_in_use", 0)),
                peak_bytes=int(ms.get("peak_bytes_in_use", 0)),
                num_allocs=int(ms.get("num_allocs", 0)),
                largest_alloc=int(ms.get("largest_alloc_size", 0)),
                bytes_limit=int(ms.get("bytes_limit", 0)),
                source="pjrt"))
        else:
            if live is None:
                live, live_skipped = _live_array_bytes_by_device()
            out.append(DeviceMemoryState(
                device=str(dev),
                bytes_in_use=live.get(str(dev), 0),
                source="live_arrays",
                skipped_arrays=live_skipped))
    return out


def total_bytes_in_use() -> int:
    return sum(s.bytes_in_use for s in snapshot())


def live_array_count() -> int:
    import jax
    return len(jax.live_arrays())


def live_census(top_n: int = 12) -> Dict[str, Any]:
    """The live-array census for OOM forensics: the ``top_n`` biggest
    live arrays (shape/dtype/nbytes/device) plus aggregate counts —
    what is actually holding HBM when an allocation fails."""
    import jax
    rows: List[dict] = []
    total = 0
    skipped = 0
    count = 0
    for a in jax.live_arrays():
        count += 1
        try:
            if a.is_deleted():
                skipped += 1
                continue
            nbytes = int(a.nbytes)
            dev = str(next(iter(a.devices()), "?")) \
                if hasattr(a, "devices") else "?"
            rows.append({"shape": list(a.shape), "dtype": str(a.dtype),
                         "nbytes": nbytes, "device": dev})
            total += nbytes
        except Exception:
            skipped += 1
    rows.sort(key=lambda r: -r["nbytes"])
    return {"arrays": count, "skipped": skipped,
            "total_bytes": total, "top": rows[:max(0, int(top_n))]}


def device_memory_report() -> str:
    """Human-readable per-device table (reference: AllocationsTracker
    + Nd4j memory info dumps)."""
    lines = ["device memory report"]
    for s in snapshot():
        mb = s.bytes_in_use / 2**20
        line = f"  {s.device}: {mb:.1f} MiB in use"
        if s.source == "pjrt":
            line += (f", peak {s.peak_bytes / 2**20:.1f} MiB, "
                     f"{s.num_allocs} allocs, largest "
                     f"{s.largest_alloc / 2**20:.1f} MiB")
            if s.bytes_limit:
                line += f", limit {s.bytes_limit / 2**20:.1f} MiB"
        else:
            line += " (live-array accounting; PJRT stats unavailable"
            if s.skipped_arrays:
                line += f"; {s.skipped_arrays} arrays unsized"
            line += ")"
        lines.append(line)
    return "\n".join(lines)


class MemoryWatermark:
    """Context manager recording the HBM watermark across a block —
    the per-fit accounting the reference gets from AllocationsTracker
    around training runs.

    with MemoryWatermark() as wm:
        net.fit(...)
    wm.peak_bytes / wm.delta_bytes / wm.report()
    """

    def __init__(self):
        self.before: List[DeviceMemoryState] = []
        self.after: List[DeviceMemoryState] = []

    def __enter__(self) -> "MemoryWatermark":
        self.before = snapshot()
        return self

    def __exit__(self, *exc) -> None:
        self.after = snapshot()

    @property
    def peak_bytes(self) -> int:
        """Max peak across devices during/after the block (PJRT peaks are
        process-lifetime; delta vs `before` isolates this block only when
        the block's peak exceeded the prior peak)."""
        if not self.after:
            self.after = snapshot()
        return max((s.peak_bytes or s.bytes_in_use) for s in self.after)

    @property
    def delta_bytes(self) -> int:
        if not self.after:
            self.after = snapshot()
        b = {s.device: s.bytes_in_use for s in self.before}
        return sum(s.bytes_in_use - b.get(s.device, 0) for s in self.after)

    def report(self) -> str:
        """Per-device peaks (not just the max — a lopsided mesh shows
        one device pinned at the limit while the fleet average looks
        healthy), then the net delta and the live device table."""
        if not self.after:
            self.after = snapshot()
        lines = [f"memory watermark: peak {self.peak_bytes / 2**20:.1f} "
                 f"MiB, net delta {self.delta_bytes / 2**20:+.1f} MiB"]
        before = {s.device: s for s in self.before}
        for s in self.after:
            peak = s.peak_bytes or s.bytes_in_use
            b = before.get(s.device)
            delta = s.bytes_in_use - (b.bytes_in_use if b else 0)
            line = (f"  {s.device}: peak {peak / 2**20:.1f} MiB, "
                    f"delta {delta / 2**20:+.1f} MiB")
            if s.bytes_limit:
                line += (f", headroom "
                         f"{(s.bytes_limit - s.bytes_in_use) / 2**20:.1f}"
                         f" MiB")
            lines.append(line)
        lines.append(device_memory_report())
        return "\n".join(lines)


class AllocationsTracker:
    """Counting tracker for explicit instrumentation points (reference:
    AllocationsTracker.allocate/release accounting API). The framework's
    own allocations go through XLA, so this tracks what callers tag —
    today the window stager's H2D staging (``h2d_stage``) and the
    checkpoint writer's D2H capture (``checkpoint_d2h``), both cumulative
    transfer totals surfaced in ``{"type": "memory"}`` records.

    Thread-safe: the checkpoint writer thread, the window-stager thread
    and the training thread all hit the same singleton. ``release``
    clamps at zero — an unmatched release (a tag released more than it
    allocated, e.g. across a ``reset()``) must not drive a lifetime
    total negative and silently cancel later allocations."""

    _instance: Optional["AllocationsTracker"] = None

    def __init__(self):
        self._lock = threading.Lock()
        self._tracked: Dict[str, int] = {}
        self._counts: Dict[str, int] = {}

    @classmethod
    def get_instance(cls) -> "AllocationsTracker":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def allocate(self, tag: str, nbytes: int) -> None:
        with self._lock:
            self._tracked[tag] = self._tracked.get(tag, 0) + int(nbytes)
            self._counts[tag] = self._counts.get(tag, 0) + 1

    def release(self, tag: str, nbytes: int) -> None:
        with self._lock:
            self._tracked[tag] = max(
                0, self._tracked.get(tag, 0) - int(nbytes))

    def bytes_tracked(self, tag: str) -> int:
        with self._lock:
            return self._tracked.get(tag, 0)

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._tracked)

    def counts(self) -> Dict[str, int]:
        """Per-tag event counts (how many tagged transfers/allocations
        happened, independent of their byte totals)."""
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            self._tracked.clear()
            self._counts.clear()


class MemoryExhaustedError(RuntimeError):
    """A device allocation failed (``RESOURCE_EXHAUSTED``) — with
    forensics attached, instead of the raw backend crash.

    Carries the last per-device :func:`snapshot`, a :func:`live_census`
    of what holds HBM, and the active compiled program's memory plan
    (``monitor/memstats.py``) when one is known. Deliberately **not**
    part of ``faults.retryable_errors()``: a rollback replays the same
    program against the same HBM — it cannot shrink the footprint —
    so ``FaultTolerantFit`` publishes the ``{"type": "faults",
    "event": "oom"}`` diagnosis and aborts instead of burning the
    retry budget (docs/fault_tolerance.md)."""

    def __init__(self, message: str, *, program: Optional[str] = None,
                 step: Optional[int] = None, epoch: Optional[int] = None,
                 snapshot: Optional[List[DeviceMemoryState]] = None,
                 census: Optional[dict] = None,
                 plan: Optional[dict] = None):
        super().__init__(message)
        self.program = program
        self.step = step
        self.epoch = epoch
        self.snapshot = list(snapshot or [])
        self.census = census
        self.plan = plan
        self.cause = "oom"

    def provenance(self) -> Dict[str, Any]:
        """Machine-readable view for ``{"type": "faults"}`` records —
        same shape as ``faults.FaultError.provenance()``."""
        return {"error": type(self).__name__, "cause": "oom",
                "step": self.step, "epoch": self.epoch,
                "program": self.program}

    def forensics(self) -> Dict[str, Any]:
        """The full diagnosis: per-device usage, live-array census,
        the active program's memory plan."""
        return {**self.provenance(),
                "devices": [dataclasses.asdict(s) for s in self.snapshot],
                "census": self.census, "plan": self.plan}

    def __str__(self) -> str:  # noqa: D105 — the postmortem one-pager
        parts = [super().__str__()]
        if self.program:
            parts.append(f"active program: {self.program}")
        for s in self.snapshot:
            line = (f"{s.device}: {s.bytes_in_use / 2**20:.1f} MiB in "
                    f"use, peak {(s.peak_bytes or 0) / 2**20:.1f} MiB")
            if s.bytes_limit:
                line += f", limit {s.bytes_limit / 2**20:.1f} MiB"
            parts.append(line)
        if self.plan:
            parts.append(
                f"program plan: temp "
                f"{self.plan.get('temp_bytes', 0) / 2**20:.1f} MiB + args "
                f"{self.plan.get('argument_bytes', 0) / 2**20:.1f} MiB + "
                f"out {self.plan.get('output_bytes', 0) / 2**20:.1f} MiB")
        if self.census:
            parts.append(f"live arrays: {self.census.get('arrays', 0)} "
                         f"({self.census.get('total_bytes', 0) / 2**20:.1f}"
                         f" MiB); top: " + ", ".join(
                             f"{r['shape']}:{r['dtype']}"
                             f"={r['nbytes'] / 2**20:.1f}MiB"
                             for r in self.census.get("top", [])[:4]))
        return "\n  ".join(parts)


class MemoryHeadroomError(RuntimeError):
    """A guarded operation (serving hot reload, warmup of a new bucket)
    was REFUSED because its projected footprint exceeds the device's
    remaining HBM headroom — raised *before* the backend OOMs, so the
    server keeps serving what it served (docs/serving.md "Resilience")."""

    def __init__(self, message: str, *, required_bytes: int = 0,
                 headroom_bytes: int = 0):
        super().__init__(message)
        self.required_bytes = int(required_bytes)
        self.headroom_bytes = int(headroom_bytes)
