"""Memory observability: allocation tracking and HBM watermarks.

Reference parity: org.nd4j.linalg.api.memory.AllocationsTracker (live
per-device allocation accounting) and the workspace debug listeners.

TPU-native redesign: XLA owns allocation, so tracking reads the PJRT
client's per-device counters (``device.memory_stats()``: bytes_in_use,
peak_bytes_in_use, num_allocs, largest_alloc_size) plus the Python-side
live-buffer view (``jax.live_arrays()``). The watermark context manager
is the per-fit HBM accounting the reference gets from
AllocationsTracker.getInstance() around training calls. On backends
whose PJRT client exposes no stats (CPU), live-array accounting is the
fallback so the API stays total.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class DeviceMemoryState:
    """One device's counters at a point in time."""
    device: str
    bytes_in_use: int = 0
    peak_bytes: int = 0
    num_allocs: int = 0
    largest_alloc: int = 0
    bytes_limit: int = 0
    source: str = "pjrt"        # "pjrt" | "live_arrays"


def _live_array_bytes_by_device() -> Dict[str, int]:
    import jax
    by_dev: Dict[str, int] = {}
    for a in jax.live_arrays():
        try:
            for shard in a.addressable_shards:
                d = str(shard.device)
                by_dev[d] = by_dev.get(d, 0) + int(shard.data.nbytes)
        except Exception:
            pass
    return by_dev


def snapshot() -> List[DeviceMemoryState]:
    """Per-device memory counters (reference:
    AllocationsTracker.getInstance() device reports)."""
    import jax
    out: List[DeviceMemoryState] = []
    live = None
    for dev in jax.local_devices():
        ms = None
        try:
            ms = dev.memory_stats()
        except Exception:
            ms = None
        if ms:
            out.append(DeviceMemoryState(
                device=str(dev),
                bytes_in_use=int(ms.get("bytes_in_use", 0)),
                peak_bytes=int(ms.get("peak_bytes_in_use", 0)),
                num_allocs=int(ms.get("num_allocs", 0)),
                largest_alloc=int(ms.get("largest_alloc_size", 0)),
                bytes_limit=int(ms.get("bytes_limit", 0)),
                source="pjrt"))
        else:
            if live is None:
                live = _live_array_bytes_by_device()
            out.append(DeviceMemoryState(
                device=str(dev),
                bytes_in_use=live.get(str(dev), 0),
                source="live_arrays"))
    return out


def total_bytes_in_use() -> int:
    return sum(s.bytes_in_use for s in snapshot())


def live_array_count() -> int:
    import jax
    return len(jax.live_arrays())


def device_memory_report() -> str:
    """Human-readable per-device table (reference: AllocationsTracker
    + Nd4j memory info dumps)."""
    lines = ["device memory report"]
    for s in snapshot():
        mb = s.bytes_in_use / 2**20
        line = f"  {s.device}: {mb:.1f} MiB in use"
        if s.source == "pjrt":
            line += (f", peak {s.peak_bytes / 2**20:.1f} MiB, "
                     f"{s.num_allocs} allocs, largest "
                     f"{s.largest_alloc / 2**20:.1f} MiB")
            if s.bytes_limit:
                line += f", limit {s.bytes_limit / 2**20:.1f} MiB"
        else:
            line += " (live-array accounting; PJRT stats unavailable)"
        lines.append(line)
    return "\n".join(lines)


class MemoryWatermark:
    """Context manager recording the HBM watermark across a block —
    the per-fit accounting the reference gets from AllocationsTracker
    around training runs.

    with MemoryWatermark() as wm:
        net.fit(...)
    wm.peak_bytes / wm.delta_bytes / wm.report()
    """

    def __init__(self):
        self.before: List[DeviceMemoryState] = []
        self.after: List[DeviceMemoryState] = []

    def __enter__(self) -> "MemoryWatermark":
        self.before = snapshot()
        return self

    def __exit__(self, *exc) -> None:
        self.after = snapshot()

    @property
    def peak_bytes(self) -> int:
        """Max peak across devices during/after the block (PJRT peaks are
        process-lifetime; delta vs `before` isolates this block only when
        the block's peak exceeded the prior peak)."""
        if not self.after:
            self.after = snapshot()
        return max((s.peak_bytes or s.bytes_in_use) for s in self.after)

    @property
    def delta_bytes(self) -> int:
        if not self.after:
            self.after = snapshot()
        b = {s.device: s.bytes_in_use for s in self.before}
        return sum(s.bytes_in_use - b.get(s.device, 0) for s in self.after)

    def report(self) -> str:
        return (f"memory watermark: peak {self.peak_bytes / 2**20:.1f} "
                f"MiB, net delta {self.delta_bytes / 2**20:+.1f} MiB\n"
                + device_memory_report())


class AllocationsTracker:
    """Counting tracker for explicit instrumentation points (reference:
    AllocationsTracker.allocate/release accounting API). The framework's
    own allocations go through XLA, so this tracks what callers tag."""

    _instance: Optional["AllocationsTracker"] = None

    def __init__(self):
        self._tracked: Dict[str, int] = {}
        self._counts: Dict[str, int] = {}

    @classmethod
    def get_instance(cls) -> "AllocationsTracker":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def allocate(self, tag: str, nbytes: int) -> None:
        self._tracked[tag] = self._tracked.get(tag, 0) + int(nbytes)
        self._counts[tag] = self._counts.get(tag, 0) + 1

    def release(self, tag: str, nbytes: int) -> None:
        self._tracked[tag] = self._tracked.get(tag, 0) - int(nbytes)

    def bytes_tracked(self, tag: str) -> int:
        return self._tracked.get(tag, 0)

    def totals(self) -> Dict[str, int]:
        return dict(self._tracked)

    def reset(self) -> None:
        self._tracked.clear()
        self._counts.clear()
