"""PipelineState: the seekable position of a streaming input pipeline.

Everything a resumed process needs to continue a disk-backed fit
MID-EPOCH, bit-exact, without replaying the pass:

- ``pass_index``   — the pass (epoch) in progress; the pass's shuffle
  permutation is a pure function of ``(seed, pass_index, host)``, so
  the index IS the shuffle RNG state;
- ``cursor``       — the next PLAN batch of that pass (plan = the
  pass's permutation chunked into batches); seeking = recomputing the
  permutation and starting at ``cursor``, O(1) in records read vs the
  O(n) reset-and-fast-forward a plain iterator needs;
- ``yielded``      — batches DELIVERED to the trainer at the same
  point (differs from ``cursor`` only when fully-quarantined batches
  were skipped); the capture-time bridge between the trainer's
  iteration counter and the plan cursor;
- ``seed`` / ``passes_started`` — the shuffle base seed and the fresh-
  pass counter (so post-resume epochs continue the uninterrupted run's
  pass sequence);
- ``quarantined_records`` / ``pass_quarantine_base`` — the corrupt-row
  quarantine set now, and as of the pass's start (the permutation is
  computed over the BASE set — a row quarantined mid-pass must not
  change the order of batches already consumed);
- ``quarantined_shards`` / ``pass_shard_base`` — shards withheld after
  their read budget, now and as of the pass's start (same reasoning:
  the permutation is computed over the pass-start shard set, so a
  shard quarantined mid-pass withholds rows without re-planning the
  pass a resume would then mis-seek into);
- ``batch_size`` / ``shuffle`` / ``host_index`` / ``host_count`` — the
  plan-shaping configuration at capture time. ``cursor`` is
  denominated in plan batches of THIS configuration; restoring into a
  pipeline with a different one would silently seek to different
  records, so ``restore_state`` checks and raises.

Serialized as plain JSON-able dicts inside
``TrainingState.metadata["datapipe"]`` (checkpoint/state.py captures
it at every checkpoint flush; faults.FaultTolerantFit restores it on
rollback). See docs/data_pipeline.md for what is and is not bit-exact
across a resume.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass
class PipelineState:
    pass_index: int = 0
    cursor: int = 0
    yielded: int = 0
    seed: int = 0
    passes_started: int = 0
    quarantined_records: List[int] = dataclasses.field(default_factory=list)
    pass_quarantine_base: List[int] = dataclasses.field(
        default_factory=list)
    quarantined_shards: List[int] = dataclasses.field(default_factory=list)
    pass_shard_base: List[int] = dataclasses.field(default_factory=list)
    # plan-shaping configuration (None = unknown, e.g. an old state:
    # restore then skips the check)
    batch_size: Optional[int] = None
    shuffle: Optional[bool] = None
    host_index: Optional[int] = None
    host_count: Optional[int] = None

    def to_json(self) -> dict:
        return {"pass_index": int(self.pass_index),
                "cursor": int(self.cursor),
                "yielded": int(self.yielded),
                "seed": int(self.seed),
                "passes_started": int(self.passes_started),
                "quarantined_records": sorted(
                    int(i) for i in self.quarantined_records),
                "pass_quarantine_base": sorted(
                    int(i) for i in self.pass_quarantine_base),
                "quarantined_shards": sorted(
                    int(i) for i in self.quarantined_shards),
                "pass_shard_base": sorted(
                    int(i) for i in self.pass_shard_base),
                "batch_size": self.batch_size,
                "shuffle": self.shuffle,
                "host_index": self.host_index,
                "host_count": self.host_count}

    @staticmethod
    def from_json(data: dict) -> "PipelineState":
        def _opt(key, cast):
            v = data.get(key)
            return None if v is None else cast(v)

        return PipelineState(
            pass_index=int(data.get("pass_index", 0)),
            cursor=int(data.get("cursor", 0)),
            yielded=int(data.get("yielded", data.get("cursor", 0))),
            seed=int(data.get("seed", 0)),
            passes_started=int(data.get("passes_started", 0)),
            quarantined_records=[int(i) for i in
                                 data.get("quarantined_records", [])],
            pass_quarantine_base=[int(i) for i in
                                  data.get("pass_quarantine_base", [])],
            quarantined_shards=[int(i) for i in
                                data.get("quarantined_shards", [])],
            pass_shard_base=[int(i) for i in
                             data.get("pass_shard_base",
                                      data.get("quarantined_shards",
                                               []))],
            batch_size=_opt("batch_size", int),
            shuffle=_opt("shuffle", bool),
            host_index=_opt("host_index", int),
            host_count=_opt("host_count", int))


__all__ = ["PipelineState"]
