"""ShardedRecordReader: verified, retrying, quarantining shard access.

The read path is ONE seam — :func:`_read_file_bytes` — so the chaos
harness can inject flaky/slow IO exactly where production IO happens,
and so verification hashes THE BYTES THAT WERE READ (a verify-the-file-
then-load-the-file sequence would race bit-rot between the two opens).

Failure discipline (detect → decide → recover, applied to IO):

- a shard whose bytes fail verification (size, sha256, record count,
  unreadable npz) raises a typed, RETRYABLE
  :class:`~deeplearning4j_tpu.faults.errors.ShardCorruptError` with
  shard + offset provenance;
- transient read errors (``OSError``) and verification failures are
  retried up to ``read_retries`` times with bounded exponential
  backoff — flaky NFS heals on the re-read;
- a shard that exhausts its retry budget ``quarantine_budget`` times
  is QUARANTINED: its records drop out of ``record_ids()`` (loudly —
  a ``shard_quarantined`` event carries the lost-record count), and
  further reads of it fail fast. Bit-rot costs one shard, not the job.

Reads are whole-shard (one sequential read + one hash per shard
content version, cached by ``(path, mtime_ns, size)``) with an LRU of
decoded shards, so a shuffled pass touching a shard from many batches
decodes it once.
"""
from __future__ import annotations

import hashlib
import io
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datapipe.manifest import (ShardInfo, ShardManifest,
                                                  load_manifest,
                                                  shard_assignment,
                                                  verify_shard_bytes)
from deeplearning4j_tpu.faults.errors import ShardCorruptError


def _read_file_bytes(path: str) -> bytes:
    """THE shard-IO seam: every byte the reader consumes flows through
    here (chaos.flaky_read / chaos.slow_reader patch this)."""
    with open(path, "rb") as fh:
        return fh.read()


class ShardedRecordReader:
    """Verified access to a committed dataset directory's shards.

    ``host_index``/``host_count`` select this process's shard subset
    (disjoint-and-total round-robin, manifest.shard_assignment);
    record ids stay GLOBAL so multihost quarantine/seek state is
    host-portable. Thread-safe: prefetch workers call
    :meth:`read_rows` concurrently.
    """

    def __init__(self, directory: str, host_index: int = 0,
                 host_count: int = 1, verify: bool = True,
                 read_retries: int = 3, backoff_base_s: float = 0.0,
                 backoff_max_s: float = 1.0, quarantine_budget: int = 2,
                 cache_shards: int = 4,
                 on_event: Optional[Callable[[dict], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.directory = os.fspath(directory)
        self.manifest: ShardManifest = load_manifest(self.directory)
        self.assigned: List[int] = shard_assignment(
            len(self.manifest.shards), host_index, host_count)
        # the manifest is immutable after load: precompute the shard
        # offset table once (read_rows maps ids -> shards per batch on
        # the hot worker path)
        self._offsets = np.array([s.offset for s in self.manifest.shards],
                                 dtype=np.int64)
        self.verify = bool(verify)
        self.read_retries = max(0, int(read_retries))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.quarantine_budget = max(1, int(quarantine_budget))
        self._sleep = sleep
        self._on_event = on_event
        self._lock = threading.Lock()
        # per-shard in-flight guards: concurrent workers wanting the
        # same uncached shard load it ONCE (the second finds the cache
        # populated) — without this, every cold shard pays duplicate
        # read+hash+decode at n_workers>1, and a transiently-corrupt
        # shard has its quarantine budget double-counted by the racing
        # workers' simultaneously-exhausted retry loops
        self._shard_locks: Dict[int, threading.Lock] = {}
        # decoded-shard LRU + per-content verification memo
        self._cache: "OrderedDict[int, Dict[str, np.ndarray]]" = \
            OrderedDict()
        self._cache_cap = max(1, int(cache_shards))
        self._verified: Dict[int, tuple] = {}     # idx -> (mtime_ns, size)
        self._failures: Dict[int, int] = {}       # idx -> exhausted budgets
        self.quarantined_shards: set = set()
        # observability counters (datapipe telemetry reads these)
        self.read_retries_total = 0
        self.shard_reads_total = 0
        self.bytes_read_total = 0

    # ------------------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event({"type": "faults", "event": kind,
                            "t": time.time(), **fields})

    def shard(self, index: int) -> ShardInfo:
        return self.manifest.shards[index]

    def quarantined_shards_snapshot(self) -> set:
        """Locked copy of the quarantine set — workers mutate the live
        set under the reader lock, so cross-thread consumers (pipeline
        pass planning, checkpoint capture) must read a snapshot, not
        iterate the live set."""
        with self._lock:
            return set(self.quarantined_shards)

    def quarantine_shards(self, indices) -> None:
        """Locked bulk add (restore_state re-arms a snapshot's set)."""
        with self._lock:
            self.quarantined_shards.update(int(i) for i in indices)

    def record_ids(self, exclude_shards=None) -> np.ndarray:
        """This host's GLOBAL record ids, excluded shards removed
        (sorted ascending — the permutation's stable input).
        ``exclude_shards`` defaults to the LIVE quarantine set; the
        pipeline passes each pass's FROZEN pass-start set instead, so a
        shard quarantined mid-pass withholds rows without re-planning
        the pass a seek-resume would then mis-enter."""
        if exclude_shards is None:
            exclude_shards = self.quarantined_shards
        parts = []
        for i in self.assigned:
            if i in exclude_shards:
                continue
            s = self.manifest.shards[i]
            parts.append(np.arange(s.offset, s.offset + s.records,
                                   dtype=np.int64))
        return np.concatenate(parts) if parts else \
            np.empty(0, dtype=np.int64)

    def shard_of(self, record_id: int) -> int:
        """Global record id -> owning shard index."""
        if not 0 <= record_id < self.manifest.record_count:
            raise IndexError(f"record id {record_id} outside the "
                             f"dataset's {self.manifest.record_count} "
                             f"records")
        return int(np.searchsorted(self._offsets, record_id,
                                   side="right") - 1)

    # ------------------------------------------------------------------
    def _load_verified(self, index: int) -> Dict[str, np.ndarray]:
        """Read + verify + decode one shard's bytes (no retry here —
        one attempt; the caller owns the budget)."""
        info = self.manifest.shards[index]
        path = os.path.join(self.directory, info.file)
        try:
            data = _read_file_bytes(path)
        except OSError as e:
            raise ShardCorruptError(
                f"shard {info.file}: read failed: {e!r}",
                shard=info.file, offset=info.offset, cause="io") from e
        with self._lock:
            self.shard_reads_total += 1
            self.bytes_read_total += len(data)
        if self.verify:
            problems = verify_shard_bytes(info, data)
            if problems:
                raise ShardCorruptError(
                    f"shard {info.file}: {'; '.join(problems)} — "
                    f"bit-rot or a torn write (records "
                    f"[{info.offset}, {info.offset + info.records}))",
                    shard=info.file, offset=info.offset)
        try:
            with np.load(io.BytesIO(data)) as npz:
                arrays = {k: npz[k] for k in npz.files}
        except Exception as e:   # zipfile/np decode of damaged bytes
            raise ShardCorruptError(
                f"shard {info.file}: undecodable npz: {e!r}",
                shard=info.file, offset=info.offset) from e
        lens = {len(a) for a in arrays.values()}
        if not arrays or lens != {info.records}:
            raise ShardCorruptError(
                f"shard {info.file}: {sorted(lens)} rows decoded but the "
                f"manifest records {info.records}",
                shard=info.file, offset=info.offset)
        return arrays

    def _shard_lock(self, index: int) -> threading.Lock:
        with self._lock:
            lk = self._shard_locks.get(index)
            if lk is None:
                lk = self._shard_locks[index] = threading.Lock()
            return lk

    def _get_shard(self, index: int) -> Dict[str, np.ndarray]:
        """Cached, retrying shard load; quarantines the shard after
        ``quarantine_budget`` exhausted retry budgets. Serialized per
        shard (distinct shards still load in parallel)."""
        with self._shard_lock(index):
            return self._get_shard_locked(index)

    def _get_shard_locked(self, index: int) -> Dict[str, np.ndarray]:
        info = self.manifest.shards[index]
        path = os.path.join(self.directory, info.file)
        with self._lock:
            if index in self.quarantined_shards:
                raise ShardCorruptError(
                    f"shard {info.file} is quarantined "
                    f"({info.records} records withheld)",
                    shard=info.file, offset=info.offset,
                    cause="shard_quarantined")
            try:
                st = os.stat(path)
                token = (st.st_mtime_ns, st.st_size)
            except OSError:
                token = None
            cached = self._cache.get(index)
            if cached is not None and self._verified.get(index) == token \
                    and token is not None:
                self._cache.move_to_end(index)
                return cached
        last: Optional[ShardCorruptError] = None
        for attempt in range(self.read_retries + 1):
            try:
                arrays = self._load_verified(index)
                with self._lock:
                    self._cache[index] = arrays
                    self._cache.move_to_end(index)
                    while len(self._cache) > self._cache_cap:
                        self._cache.popitem(last=False)
                    try:
                        st = os.stat(path)
                        self._verified[index] = (st.st_mtime_ns,
                                                 st.st_size)
                    except OSError:
                        self._verified.pop(index, None)
                return arrays
            except ShardCorruptError as e:
                last = e
                with self._lock:
                    self.read_retries_total += 1
                self._event("read_retry", shard=info.file, attempt=attempt,
                            error=repr(e))
                if attempt < self.read_retries and self.backoff_base_s > 0:
                    self._sleep(min(self.backoff_max_s,
                                    self.backoff_base_s * (2 ** attempt)))
        # budget spent on this open: count it toward the shard's
        # quarantine budget and surface the typed, retryable error
        with self._lock:
            self._failures[index] = self._failures.get(index, 0) + 1
            exhausted = self._failures[index]
            if exhausted >= self.quarantine_budget:
                self.quarantined_shards.add(index)
                quarantined = True
            else:
                quarantined = False
        if quarantined:
            self._event("shard_quarantined", shard=info.file,
                        records=info.records,
                        failures=exhausted, error=repr(last))
        raise last

    # ------------------------------------------------------------------
    def read_rows(self, record_ids: np.ndarray) -> Dict[str, np.ndarray]:
        """Gather GLOBAL record ids (any shards, any order) into one
        row-aligned column dict — the vectorized read a prefetch worker
        issues per batch. Preserves the id order given (the shuffled
        batch composition)."""
        ids = np.asarray(record_ids, dtype=np.int64)
        offsets = self._offsets
        shard_idx = np.searchsorted(offsets, ids, side="right") - 1
        out_parts: Dict[str, List[np.ndarray]] = {}
        order: List[np.ndarray] = []
        for si in np.unique(shard_idx):
            mask = shard_idx == si
            local = ids[mask] - offsets[si]
            arrays = self._get_shard(int(si))
            for name, a in arrays.items():
                out_parts.setdefault(name, []).append(a[local])
            order.append(np.flatnonzero(mask))
        if not order:
            return {}
        # reassemble in the requested (shuffled) id order
        perm = np.concatenate(order)
        inv = np.empty(len(ids), dtype=np.int64)
        inv[perm] = np.arange(len(ids))
        return {name: np.concatenate(parts)[inv]
                for name, parts in out_parts.items()}

    def stats(self) -> dict:
        with self._lock:
            return {"shard_reads": self.shard_reads_total,
                    "read_retries": self.read_retries_total,
                    "bytes_read": self.bytes_read_total,
                    "quarantined_shards": len(self.quarantined_shards)}


__all__ = ["ShardedRecordReader", "_read_file_bytes"]
