"""datapipe/ — the fault-tolerant streaming data plane.

The reference dedicates a whole layer (L6: datavec ``RecordReader`` →
``TransformProcess`` → ``DataSetIterator``) to ETL; this package is
that layer rebuilt with the detect→decide→recover discipline the
compute rails (faults/, serving/) already have, applied to IO:

- ``manifest``  : checksummed shard directories with the checkpoint/
  staged-commit protocol (``write_dataset`` / ``load_manifest`` /
  ``verify_dataset``) + per-host ``shard_assignment``
- ``reader``    : ``ShardedRecordReader`` — open-time sha256
  verification, transient-IO retry with bounded backoff, typed
  retryable ``ShardCorruptError``, shard quarantine after a budget
- ``prefetch``  : ``SupervisedPrefetcher`` — supervised worker pool
  (exactly-once requeue of a dead worker's batch, bounded-backoff
  respawn, read-timeout backup requests, in-order delivery)
- ``pipeline``  : ``StreamingDataPipeline`` — the DataSetIterator
  gluing it together, with record-level corrupt-row quarantine and
  seekable deterministic per-pass state
- ``state``     : ``PipelineState`` — the mid-epoch position captured
  into checkpoints and restored by ``faults.FaultTolerantFit``

See docs/data_pipeline.md.
"""
from deeplearning4j_tpu.datapipe.manifest import (ShardInfo, ShardManifest,
                                                  load_manifest,
                                                  shard_assignment,
                                                  verify_dataset,
                                                  write_dataset)
from deeplearning4j_tpu.datapipe.pipeline import (StreamingDataPipeline,
                                                  find_pipeline)
from deeplearning4j_tpu.datapipe.prefetch import (SupervisedPrefetcher,
                                                  WorkItem)
from deeplearning4j_tpu.datapipe.reader import ShardedRecordReader
from deeplearning4j_tpu.datapipe.state import PipelineState
from deeplearning4j_tpu.faults.errors import ShardCorruptError

__all__ = ["PipelineState", "ShardCorruptError", "ShardInfo",
           "ShardManifest", "ShardedRecordReader",
           "StreamingDataPipeline", "SupervisedPrefetcher", "WorkItem",
           "find_pipeline", "load_manifest", "shard_assignment",
           "verify_dataset", "write_dataset"]
