"""Supervised parallel prefetch: the worker pool behind the pipeline.

The serving resilience rail's ``WorkerSupervisor`` shape (PR 9) applied
to data loading: prefetch workers are SUPERVISED, not immortal —

- a worker claims one :class:`WorkItem` (a batch's record-id range) at
  a time through an :class:`InflightSlot`-style claim window; a dead
  worker's claimed item is requeued at the FRONT **exactly once**
  (``WorkItem.requeues``; an item lost to two crashed workers fails
  in-stream with a typed ``DataPipelineError`` instead of ping-ponging)
  and the worker is respawned with bounded exponential backoff;
- a read exceeding ``read_timeout_s`` gets a BACKUP: the supervisor
  requeues the item (its own one-hedge budget — a timeout is not a
  loss and never poisons) so another worker re-reads it while the
  straggler finishes — first result wins, late duplicates are
  discarded (content is deterministic, so either copy is identical).
  The classic tail-latency hedge, here for a hung NFS read;
- STRUCTURED loader errors (``DataPipelineError`` and its
  ``ShardCorruptError`` subtype — the reader's post-retry verdicts)
  travel IN-STREAM as a poisoned result at the right batch index (the
  ``AsyncDataSetIterator`` convention), so the consumer raises them in
  order and an epoch can never end silently short; any OTHER exception
  is a worker crash and takes the supervision path above.

Results are re-ordered: the consumer iterates batches in plan order
regardless of which worker finished first, with a bounded reorder
window (``depth``) so a slow head batch backpressures the pool instead
of letting it race ahead unboundedly.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.faults.errors import DataPipelineError

#: chaos seam (faults/chaos.py worker_killer): {"at_index", "left",
#: "log"} — a claiming worker whose item index matches raises an
#: UNSTRUCTURED error, i.e. a worker crash, exercising the
#: exactly-once requeue + respawn path. None = no injection.
_CHAOS_KILL: Optional[dict] = None


class WorkItem:
    """One batch's worth of work: plan index + the global record ids
    composing it. ``requeues`` counts CRASH losses (exactly-once
    budget); ``hedges`` counts read-timeout backup requests (at most
    one — a timeout is not a loss, the straggler is still working, so
    it must never consume the crash budget or poison the item)."""

    __slots__ = ("index", "record_ids", "requeues", "hedges")

    def __init__(self, index: int, record_ids: np.ndarray):
        self.index = int(index)
        self.record_ids = record_ids
        self.requeues = 0
        self.hedges = 0


class _WorkerSlot:
    """Per-worker claim window (serving/resilience.InflightSlot shape):
    what the supervisor requeues when the worker dies or stalls
    mid-read. Plain attribute writes (atomic under the GIL)."""

    def __init__(self):
        self.claimed: Optional[WorkItem] = None
        self.read_started: Optional[float] = None
        self.timeout_fired = False
        self.exited = False
        self.crashed: Optional[BaseException] = None
        self.busy_s = 0.0              # cumulative read seconds


class _Poison:
    """In-stream structured failure at a batch index."""

    __slots__ = ("error",)

    def __init__(self, error: BaseException):
        self.error = error


class SupervisedPrefetcher:
    """Run ``items`` through ``read_item`` on a supervised worker pool;
    iterate the results in plan order.

    ``read_item(item) -> batch`` runs on worker threads (the verified
    shard read + the vectorized transform). ``on_event`` receives one
    dict per supervision decision (also folded into ``stats()``).
    """

    def __init__(self, items: List[WorkItem],
                 read_item: Callable[[WorkItem], object],
                 n_workers: int = 2, depth: int = 4,
                 read_timeout_s: Optional[float] = None,
                 backoff_base_s: float = 0.01, backoff_max_s: float = 1.0,
                 poll_s: float = 0.01,
                 on_event: Optional[Callable[[dict], None]] = None):
        self._queue: "deque[WorkItem]" = deque(items)
        # items carry ABSOLUTE plan indices (a seek-resumed pass starts
        # mid-plan); emission runs [first, end) in index order
        self._first = items[0].index if items else 0
        self._end = items[-1].index + 1 if items else 0
        self._read_item = read_item
        self._depth = max(1, int(depth))
        self._read_timeout_s = read_timeout_s
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.poll_s = float(poll_s)
        self._on_event = on_event
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._results: Dict[int, object] = {}
        self._next_emit = self._first
        self._stopping = False
        self._started = time.monotonic()
        # counters (datapipe telemetry)
        self.restarts_total = 0
        self.requeues_total = 0
        self.slow_reads_total = 0
        self.items_served = 0
        self._entries: List[dict] = []
        for i in range(max(1, int(n_workers))):
            slot = _WorkerSlot()
            self._entries.append({"index": i, "slot": slot,
                                  "thread": self._spawn(i, slot),
                                  "restarts": 0, "consecutive": 0,
                                  "busy_s": 0.0})
        self._supervisor = threading.Thread(
            target=self._supervise, name="DatapipeSupervisor", daemon=True)
        self._supervisor.start()

    # -- events ---------------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event({"type": "faults", "event": kind,
                            "t": time.time(), **fields})

    # -- worker side ----------------------------------------------------
    def _spawn(self, index: int, slot: _WorkerSlot) -> threading.Thread:
        t = threading.Thread(target=self._worker, args=(index, slot),
                             name=f"DatapipeWorker-{index}", daemon=True)
        t.start()
        return t

    def _claim(self, slot: _WorkerSlot) -> Optional[WorkItem]:
        """Pop the head work item once it is inside the reorder window
        (head.index < next_emit + depth); None = work exhausted or
        shutdown."""
        with self._cond:
            while not self._stopping:
                if self._queue and (self._queue[0].index
                                    < self._next_emit + self._depth):
                    item = self._queue.popleft()
                    slot.claimed = item
                    slot.read_started = time.monotonic()
                    slot.timeout_fired = False
                    return item
                if not self._queue and self._all_resolved_locked():
                    return None
                self._cond.wait(timeout=0.05)
            return None

    def _all_resolved_locked(self) -> bool:
        if self._next_emit >= self._end:
            return True
        # anything still claimed may yet produce a result
        return not any(e["slot"].claimed is not None
                       for e in self._entries) and not self._queue \
            and all(i in self._results
                    for i in range(self._next_emit, self._end))

    def _deliver(self, item: WorkItem, result: object) -> None:
        with self._cond:
            if item.index not in self._results and \
                    item.index >= self._next_emit:
                self._results[item.index] = result
            # a late straggler/backup duplicate is silently dropped:
            # the first arrival already owns the index (identical bytes)
            self._cond.notify_all()

    def _worker(self, index: int, slot: _WorkerSlot) -> None:
        try:
            while True:
                item = self._claim(slot)
                if item is None:
                    slot.exited = True
                    return
                kill = _CHAOS_KILL
                if kill is not None and kill.get("left", 0) > 0 and \
                        item.index == kill.get("at_index"):
                    kill["left"] -= 1
                    kill.setdefault("log", []).append(
                        {"event": "worker_killed", "batch_index":
                         item.index, "worker": index, "t": time.time()})
                    raise RuntimeError(
                        f"chaos: prefetch worker {index} killed at "
                        f"batch {item.index}")
                t0 = time.monotonic()
                try:
                    batch = self._read_item(item)
                except DataPipelineError as e:
                    # structured loader verdict: poison in-stream at the
                    # right index — the consumer raises it in order
                    if e.batch_index is None:
                        e.batch_index = item.index
                    self._deliver(item, _Poison(e))
                    slot.claimed = None
                    slot.read_started = None
                    continue
                finally:
                    slot.busy_s += time.monotonic() - t0
                self._deliver(item, batch)
                slot.claimed = None
                slot.read_started = None
        except BaseException as e:      # worker crash → supervision path
            # record and RETURN (no re-raise: the supervisor owns the
            # episode, and threading's excepthook would spray the
            # injected chaos traceback over every drill's stderr)
            slot.crashed = e

    # -- supervisor -----------------------------------------------------
    def _requeue(self, item: WorkItem, why: str, worker: int) -> None:
        with self._cond:
            already = item.index in self._results or \
                item.index < self._next_emit
            if already:
                return
            if why == "read_timeout":
                # a timeout is a HEDGE, not a loss: the straggler still
                # owns a live claim and may deliver. At most one backup
                # per item, and timeouts never poison (a same-shard
                # backup serialized behind the straggler's shard lock
                # would otherwise "lose" the batch twice while both
                # readers are healthy)
                if item.hedges >= 1:
                    return
                item.hedges += 1
            elif item.requeues >= 1:
                # exactly-once: a batch lost to two CRASHED workers
                # fails its slot with a typed in-stream error instead
                # of ping-ponging
                self._results[item.index] = _Poison(DataPipelineError(
                    f"batch {item.index} lost to {why} twice; giving up",
                    batch_index=item.index, cause=why))
                self._cond.notify_all()
                return
            else:
                item.requeues += 1
            self.requeues_total += 1
            self._queue.appendleft(item)
            self._cond.notify_all()
        self._event("prefetch_requeue", batch_index=item.index,
                    cause=why, worker=worker)

    def _handle_crash(self, entry: dict) -> None:
        slot: _WorkerSlot = entry["slot"]
        entry["busy_s"] += slot.busy_s
        slot.busy_s = 0.0       # folded; a skipped respawn (shutdown)
        #                         must not count this slot twice
        item = slot.claimed
        self.restarts_total += 1
        entry["restarts"] += 1
        entry["consecutive"] += 1
        self._event("worker_crash", worker=entry["index"],
                    error=repr(slot.crashed) if slot.crashed else None,
                    batch_index=item.index if item else None)
        if item is not None:
            self._requeue(item, "worker_crash", entry["index"])
            slot.claimed = None    # requeued; the dead slot must not
            #                        read as in-flight work
        backoff = min(self.backoff_max_s, self.backoff_base_s *
                      (2 ** (entry["consecutive"] - 1)))
        # respawn is a DEADLINE checked by the supervise loop, never an
        # inline sleep: blocking here would suspend crash detection and
        # timeout hedging for every OTHER worker for the whole backoff
        entry["respawn_at"] = time.monotonic() + backoff
        entry["backoff_s"] = backoff
        entry["thread"] = None

    def _maybe_respawn(self, entry: dict) -> None:
        if self._stopping or time.monotonic() < entry["respawn_at"]:
            return
        new_slot = _WorkerSlot()
        entry["slot"] = new_slot
        entry["thread"] = self._spawn(entry["index"], new_slot)
        self._event("worker_restart", worker=entry["index"],
                    restarts=entry["restarts"],
                    backoff_s=round(entry["backoff_s"], 4))

    def _supervise(self) -> None:
        while not self._stopping:
            for entry in self._entries:
                t, slot = entry["thread"], entry["slot"]
                if t is None:                 # dead, awaiting respawn
                    self._maybe_respawn(entry)
                    continue
                if t.is_alive():
                    if entry["consecutive"] and slot.claimed is None \
                            and slot.busy_s > 0:
                        entry["consecutive"] = 0    # served work again
                    item = slot.claimed
                    if item is not None and not slot.timeout_fired and \
                            self._read_timeout_s is not None and \
                            slot.read_started is not None and \
                            time.monotonic() - slot.read_started \
                            > self._read_timeout_s:
                        # straggler read: hedge with a backup worker;
                        # the late original result will be discarded
                        slot.timeout_fired = True
                        self.slow_reads_total += 1
                        self._event("slow_read", worker=entry["index"],
                                    batch_index=item.index,
                                    timeout_s=self._read_timeout_s)
                        self._requeue(item, "read_timeout",
                                      entry["index"])
                    continue
                if slot.exited or self._stopping:
                    continue
                self._handle_crash(entry)
            with self._cond:
                if self._next_emit >= self._end:
                    return
            time.sleep(self.poll_s)

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        try:
            while True:
                with self._cond:
                    while self._next_emit < self._end and \
                            self._next_emit not in self._results and \
                            not self._stopping:
                        self._cond.wait(timeout=0.1)
                    if self._stopping or self._next_emit >= self._end:
                        return
                    result = self._results.pop(self._next_emit)
                    self._next_emit += 1
                    self.items_served += 1
                    self._cond.notify_all()
                if isinstance(result, _Poison):
                    raise result.error
                yield result
        finally:
            self.close()

    def close(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        self._supervisor.join(timeout=5)
        for entry in self._entries:
            if entry["thread"] is not None:   # None = awaiting respawn
                entry["thread"].join(timeout=5)

    # -- observability --------------------------------------------------
    def worker_busy_seconds(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for entry in self._entries:
            out[entry["index"]] = entry["busy_s"] + entry["slot"].busy_s
        return out

    def stats(self) -> dict:
        return {"workers": len(self._entries),
                "worker_restarts": self.restarts_total,
                "requeues": self.requeues_total,
                "slow_reads": self.slow_reads_total,
                "items_served": self.items_served,
                "wall_s": time.monotonic() - self._started,
                "worker_busy_s": self.worker_busy_seconds()}


__all__ = ["SupervisedPrefetcher", "WorkItem"]
