"""ShardManifest: checksummed, staged-commit dataset shards on disk.

The checkpoint/ commit protocol (stage everything under ``<dir>.tmp``,
fsync, write a manifest + COMMIT marker, ``os.replace`` the directory
into place) applied to TRAINING DATA: a dataset directory is either
fully committed — every shard present with the recorded size, sha256
and record count — or it is not a dataset, and the reader says so with
a typed :class:`~deeplearning4j_tpu.faults.errors.ShardCorruptError`
instead of an exception from deep inside ``np.load``.

Layout of a committed dataset directory::

    dataset/
      MANIFEST.json      {"format_version", "record_count", "layout",
                          "shards": [{"file", "records", "size",
                                      "sha256"}, ...]}
      COMMIT             marker, written after the manifest
      shard_00000.npz    {"features": (n, ...), "labels": (n, ...)}
      shard_00001.npz    ... (or one array per named column with
                          layout="columns")

Record ids are GLOBAL: shard ``i`` holds records
``[offset_i, offset_i + records_i)`` where ``offset_i`` is the sum of
the record counts of shards ``0..i-1`` — the id space the streaming
pipeline's shuffle, quarantine and seek state all live in.

Reference parity: datavec's ``InputSplit``/``FileSplit`` enumerate
files and trust them completely; here every byte the training loop
will consume is covered by a digest, the same guarantee checkpoints
already have (checkpoint/manifest.py).
"""
from __future__ import annotations

import io
import json
import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.checkpoint.atomic import fsync_dir
from deeplearning4j_tpu.checkpoint.manifest import sha256_file
from deeplearning4j_tpu.faults.errors import ShardCorruptError

MANIFEST_NAME = "MANIFEST.json"
COMMIT_NAME = "COMMIT"
SHARD_FMT = "shard_{i:05d}.npz"
FORMAT_VERSION = 1

#: shard payload layouts: "arrays" = features/labels arrays per shard;
#: "columns" = one named 1-D array per schema column (the
#: TransformProcess-streaming form)
LAYOUTS = ("arrays", "columns")


@dataclass
class ShardInfo:
    """One shard's manifest entry."""
    file: str
    records: int
    size: int
    sha256: str
    offset: int = 0          # global id of this shard's first record

    def to_json(self) -> dict:
        return {"file": self.file, "records": int(self.records),
                "size": int(self.size), "sha256": self.sha256}


@dataclass
class ShardManifest:
    """The committed dataset's table of contents."""
    shards: List[ShardInfo] = field(default_factory=list)
    record_count: int = 0
    layout: str = "arrays"

    def __post_init__(self):
        off = 0
        for s in self.shards:
            s.offset = off
            off += int(s.records)
        if not self.record_count:
            self.record_count = off

    def to_json(self) -> dict:
        return {"format_version": FORMAT_VERSION,
                "record_count": int(self.record_count),
                "layout": self.layout,
                "shards": [s.to_json() for s in self.shards]}

    @staticmethod
    def from_json(data: dict) -> "ShardManifest":
        shards = [ShardInfo(file=e["file"], records=int(e["records"]),
                            size=int(e["size"]), sha256=e["sha256"])
                  for e in data.get("shards", [])]
        return ShardManifest(shards=shards,
                             record_count=int(data.get("record_count", 0)),
                             layout=str(data.get("layout", "arrays")))


def _write_durable(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def write_dataset(directory: str, features=None, labels=None, *,
                  columns: Optional[Dict[str, np.ndarray]] = None,
                  shard_size: int = 1024,
                  overwrite: bool = False) -> ShardManifest:
    """Commit a dataset directory of checksummed shards.

    Either ``features``/``labels`` (row-aligned arrays; layout
    ``"arrays"``) or ``columns`` (a dict of row-aligned 1-D/2-D column
    arrays; layout ``"columns"`` — the form a ``TransformProcess``
    consumes) — not both. Everything is staged under
    ``<directory>.tmp`` and published with one atomic ``os.replace``,
    so a writer killed mid-build can never leave a half-dataset that a
    reader would mistake for the real thing (the checkpoint/ commit
    discipline)."""
    if (features is None) == (columns is None):
        raise ValueError("pass features/labels OR columns=, not both")
    if columns is not None:
        parts = {str(k): np.asarray(v) for k, v in columns.items()}
        layout = "columns"
    else:
        parts = {"features": np.asarray(features),
                 "labels": np.asarray(labels)}
        layout = "arrays"
    lens = {len(a) for a in parts.values()}
    if len(lens) != 1:
        raise ValueError(f"all arrays must share the leading length; "
                         f"got {sorted(lens)}")
    n = lens.pop()
    shard_size = max(1, int(shard_size))
    directory = os.fspath(directory)
    if os.path.exists(directory) and not overwrite:
        raise FileExistsError(f"{directory} exists "
                              f"(pass overwrite=True)")
    tmp = directory + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    shards: List[ShardInfo] = []
    for i, start in enumerate(range(0, n, shard_size)):
        name = SHARD_FMT.format(i=i)
        payload = {k: a[start:start + shard_size]
                   for k, a in parts.items()}
        data = _npz_bytes(payload)
        path = os.path.join(tmp, name)
        _write_durable(path, data)
        shards.append(ShardInfo(
            file=name, records=len(next(iter(payload.values()))),
            size=len(data),
            sha256=sha256_file(path)))
    manifest = ShardManifest(shards=shards, record_count=n, layout=layout)
    _write_durable(os.path.join(tmp, MANIFEST_NAME),
                   json.dumps(manifest.to_json(), indent=1,
                              sort_keys=True).encode())
    _write_durable(os.path.join(tmp, COMMIT_NAME), b"committed\n")
    # the full checkpoint/atomic discipline: fsync the staged dir's
    # ENTRIES, publish with one rename, fsync the parent so the rename
    # itself survives a crash — without these a power cut after return
    # can unjournal the commit the module header promises
    fsync_dir(tmp)
    # the previous dataset (overwrite=True) survives until the
    # replacement is FULLY staged: deleting it up front would leave NO
    # dataset for the whole build if the writer crashes mid-shard —
    # this narrows the loss window to the delete-rename gap below
    if os.path.exists(directory):
        shutil.rmtree(directory)
    os.replace(tmp, directory)
    fsync_dir(os.path.dirname(os.path.abspath(directory)) or ".")
    return manifest


def load_manifest(directory: str) -> ShardManifest:
    """Load and structurally validate a committed dataset directory.
    Raises :class:`ShardCorruptError` (typed, retryable) for every
    failure mode a torn writer or bit-rot can produce — a missing
    COMMIT marker, an unreadable/truncated manifest, a manifest whose
    shard list is malformed."""
    directory = os.fspath(directory)
    if not os.path.isdir(directory):
        raise ShardCorruptError(f"{directory}: not a dataset directory",
                                shard=None)
    if not os.path.isfile(os.path.join(directory, COMMIT_NAME)):
        raise ShardCorruptError(
            f"{directory}: missing COMMIT marker — the dataset was "
            f"never committed (torn writer?)", shard=None)
    mpath = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(mpath, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        manifest = ShardManifest.from_json(data)
    except (OSError, ValueError, KeyError, TypeError) as e:
        raise ShardCorruptError(
            f"{directory}: unreadable manifest: {e!r}",
            shard=MANIFEST_NAME) from e
    if not manifest.shards:
        raise ShardCorruptError(f"{directory}: manifest lists no shards",
                                shard=MANIFEST_NAME)
    if manifest.layout not in LAYOUTS:
        raise ShardCorruptError(
            f"{directory}: unknown shard layout "
            f"{manifest.layout!r} (have {LAYOUTS})", shard=MANIFEST_NAME)
    return manifest


def verify_shard_bytes(info: ShardInfo, data: bytes) -> List[str]:
    """Integrity problems of one shard's bytes vs its manifest entry
    (empty = intact). Hashing the bytes actually read — not the file a
    second time — closes the verify-then-read race."""
    import hashlib
    problems: List[str] = []
    if len(data) != info.size:
        problems.append(f"size {len(data)} != {info.size}")
        return problems            # a truncated file will not hash either
    digest = hashlib.sha256(data).hexdigest()
    if digest != info.sha256:
        problems.append(f"sha256 mismatch ({digest[:12]}… != "
                        f"{info.sha256[:12]}…)")
    return problems


def verify_dataset(directory: str, full: bool = True) -> List[str]:
    """Whole-dataset integrity scan: structural manifest checks plus
    (with ``full=True``) a re-hash of every shard. Returns the problem
    list (empty = committed & intact) — the cheap pre-flight a job can
    run before pointing a fleet at a dataset."""
    try:
        manifest = load_manifest(directory)
    except ShardCorruptError as e:
        return [str(e)]
    problems: List[str] = []
    for info in manifest.shards:
        path = os.path.join(directory, info.file)
        if not os.path.isfile(path):
            problems.append(f"{info.file}: missing")
            continue
        size = os.path.getsize(path)
        if size != info.size:
            problems.append(f"{info.file}: size {size} != {info.size}")
            continue
        if full and sha256_file(path) != info.sha256:
            problems.append(f"{info.file}: sha256 mismatch")
    return problems


def shard_assignment(n_shards: int, host_index: int,
                     host_count: int) -> List[int]:
    """Deterministic per-host shard partition: shard ``i`` belongs to
    host ``i % host_count``. Disjoint and total by construction — the
    union over hosts covers every shard exactly once (pinned in
    tests/test_datapipe.py), the same round-robin
    ``checkpoint.state.shard_names`` uses for array shards."""
    host_index, host_count = int(host_index), int(host_count)
    if host_count <= 0:
        raise ValueError("host_count must be positive")
    if not 0 <= host_index < host_count:
        raise ValueError(f"host_index {host_index} outside "
                         f"[0, {host_count})")
    return [i for i in range(int(n_shards)) if i % host_count == host_index]


__all__ = ["LAYOUTS", "ShardInfo", "ShardManifest", "load_manifest",
           "shard_assignment", "verify_dataset", "verify_shard_bytes",
           "write_dataset"]
