"""StreamingDataPipeline: the fault-tolerant disk→device input pipeline.

One `DataSetIterator` that composes the whole datapipe/ rail (the L6
datavec role — RecordReader → TransformProcess → DataSetIterator — with
the detect→decide→recover discipline of faults/ and serving/ applied
to IO):

- **sharded, checksummed source** — a committed
  :mod:`~deeplearning4j_tpu.datapipe.manifest` directory read through
  :class:`~deeplearning4j_tpu.datapipe.reader.ShardedRecordReader`
  (open-time sha256 verification, transient-IO retry, per-host shard
  assignment for multihost, shard quarantine after a bounded budget);
- **supervised parallel prefetch** —
  :class:`~deeplearning4j_tpu.datapipe.prefetch.SupervisedPrefetcher`
  workers read + transform batches ahead of the trainer (vectorized
  NumPy, optionally a ``TransformProcess``), with exactly-once requeue
  of a dead worker's claimed batch, bounded-backoff respawn, and
  read-timeout backup requests; the batches feed ``fit()``'s existing
  ``WindowStager`` H2D double-buffer unchanged;
- **record-level corrupt-row quarantine** — non-finite rows are
  dropped where the untrusted bytes enter (before the transform), the
  ids quarantined PERSISTENTLY (later passes exclude them up front),
  composing with ``faults.RetryingIterator``'s batch-level semantics
  one level up;
- **seekable deterministic state** — each pass's order is a pure
  function of ``(seed, pass_index, host)``, so
  :meth:`export_state`/:meth:`restore_state`/:meth:`seek_batches`
  reposition the pipeline mid-pass in O(1) instead of replaying it.
  ``SameDiff.fit`` registers the pipeline, checkpoint captures embed
  the :class:`~deeplearning4j_tpu.datapipe.state.PipelineState` at
  flush boundaries, and a resumed/rolled-back fit seeks — bit-exact vs
  the uninterrupted run (docs/data_pipeline.md).

::

    write_dataset(path, X, Y, shard_size=1024)
    pipe = StreamingDataPipeline(path, batch_size=128, seed=7,
                                 n_workers=2)
    ftf = FaultTolerantFit(net, CheckpointManager(ckpt_dir))
    ftf.fit(pipe, epochs=10)      # survives torn shards, dead workers,
                                  # flaky reads; resumes by seeking
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.dataset.iterators import DataSetIterator
from deeplearning4j_tpu.datapipe.prefetch import (SupervisedPrefetcher,
                                                  WorkItem)
from deeplearning4j_tpu.datapipe.reader import ShardedRecordReader
from deeplearning4j_tpu.datapipe.state import PipelineState
from deeplearning4j_tpu.faults.errors import (DataPipelineError,
                                              ShardCorruptError)

#: wrapper-attribute chain find_pipeline() walks (RetryingIterator and
#: the utility iterators expose ``_wrapped``)
_UNWRAP_ATTRS = ("_wrapped", "_source")


def find_pipeline(iterator, max_depth: int = 8):
    """The seekable pipeline inside an iterator wrapper chain (or
    None): the object exposing ``export_state`` — what fit() registers
    for checkpoint capture and FaultTolerantFit seeks on rollback."""
    probe = iterator
    for _ in range(max_depth):
        if probe is None:
            return None
        if hasattr(probe, "export_state"):
            return probe
        nxt = None
        for attr in _UNWRAP_ATTRS:
            nxt = getattr(probe, attr, None)
            if nxt is not None:
                break
        probe = nxt
    return None


class StreamingDataPipeline(DataSetIterator):
    """Disk-backed streaming batches with supervised prefetch and
    seekable mid-epoch state.

    ``transform``: vectorized callable ``(features, labels) ->
    (features, labels)`` run on worker threads (layout ``"arrays"``).
    ``transform_process``: an ``etl.TransformProcess`` applied per
    batch over the shard columns (layout ``"columns"``; steps must be
    row-count-preserving — a filter step would break the global
    record-id accounting the quarantine/seek state lives in);
    ``label_column``/``num_classes`` then split columns into
    (features, one-hot labels) exactly like
    ``RecordReaderDataSetIterator``.

    Each ``iter()`` starts the next PASS; ``shuffle=True`` draws the
    pass permutation from ``(seed, pass_index, host_index)`` — fresh
    order every epoch, yet reproducible and therefore seekable.
    """

    def __init__(self, directory: str, batch_size: int = 32,
                 shuffle: bool = True, seed: int = 0,
                 transform: Optional[Callable] = None,
                 transform_process=None, label_column=None,
                 num_classes: Optional[int] = None,
                 n_workers: int = 2, prefetch_depth: int = 4,
                 host_index: Optional[int] = None,
                 host_count: Optional[int] = None,
                 verify: bool = True, read_retries: int = 3,
                 read_backoff_base_s: float = 0.0,
                 read_timeout_s: Optional[float] = None,
                 shard_quarantine_budget: int = 2,
                 quarantine_corrupt_rows: bool = True,
                 drop_remainder: bool = False,
                 on_event: Optional[Callable[[dict], None]] = None):
        if host_index is None or host_count is None:
            try:
                import jax
                host_index = jax.process_index() if host_index is None \
                    else host_index
                host_count = jax.process_count() if host_count is None \
                    else host_count
            except Exception:   # jax not initialized: single-host
                host_index, host_count = host_index or 0, host_count or 1
        self.host_index, self.host_count = int(host_index), int(host_count)
        self.events: List[dict] = []
        self._subscribers: List[Callable[[dict], None]] = []
        if on_event is not None:
            self._subscribers.append(on_event)
        self._reader = ShardedRecordReader(
            directory, host_index=self.host_index,
            host_count=self.host_count, verify=verify,
            read_retries=read_retries,
            backoff_base_s=read_backoff_base_s,
            quarantine_budget=shard_quarantine_budget,
            on_event=self._emit_event)
        self._batch = int(batch_size)
        self._shuffle = bool(shuffle)
        self._seed = int(seed)
        self._transform = transform
        self._tp = transform_process
        if self._tp is not None:
            if self._reader.manifest.layout != "columns":
                raise ValueError(
                    "transform_process= needs a columns-layout dataset "
                    "(write_dataset(columns=...))")
            for st in self._tp.steps:
                if getattr(st, "changes_row_count", False):
                    raise ValueError(
                        f"{type(st).__name__.lstrip('_')} steps are not "
                        f"streamable: changing the row count would break "
                        f"the global record-id space the quarantine and "
                        f"seek state live in — filter at dataset-build "
                        f"time instead")
        if self._reader.manifest.layout == "columns" and \
                label_column is None:
            raise ValueError("columns-layout datasets need label_column=")
        self._label_column = label_column
        self._num_classes = num_classes
        self._n_workers = max(1, int(n_workers))
        self._depth = max(1, int(prefetch_depth))
        self._read_timeout_s = read_timeout_s
        self._quarantine_rows = bool(quarantine_corrupt_rows)
        self._drop_remainder = bool(drop_remainder)
        self._lock = threading.Lock()
        # persistent-across-passes state
        self._quarantined_records: set = set()
        self._passes_started = 0
        self._pending_seek: Optional[dict] = None
        # current-pass state
        self._current_pass: Optional[int] = None
        self._pass_quarantine_base: frozenset = frozenset()
        self._pass_shard_base: frozenset = frozenset()
        self._pass_anchor = 0
        self._pass_complete = False
        self._plan_cursor = 0
        self._yield_counter = 0
        self._gen_yield_base = 0
        self._yield_plan: Dict[int, int] = {}
        self._pass_start_iteration: Optional[int] = None
        self._pass_start_epoch: Optional[int] = None
        self._iteration_source: Optional[Callable[[], int]] = None
        self._epoch_source: Optional[Callable[[], int]] = None
        self._live_prefetcher: Optional[SupervisedPrefetcher] = None
        # telemetry counters
        self._records_delivered = 0
        self._batches_delivered = 0
        self._rows_quarantined = 0
        self._records_withheld = 0
        self._pf_totals = {"worker_restarts": 0, "requeues": 0,
                           "slow_reads": 0}
        self._pf_busy: Dict[int, float] = {}

    # -- events ---------------------------------------------------------
    def _emit_event(self, ev: dict) -> None:
        self.events.append(ev)
        del self.events[:-1000]             # bounded
        for fn in list(self._subscribers):
            try:
                fn(ev)
            except Exception:   # noqa: BLE001 — a raising subscriber
                # (user callback, chaos healer doing file IO) must not
                # kill the supervisor/worker thread that emitted the
                # event: a dead supervisor turns the next worker crash
                # into a silent hang instead of a typed failure
                pass

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        """Attach an event listener (stats storage ``put``, chaos
        injectors' heal triggers, tests)."""
        self._subscribers.append(fn)

    # -- DataSetIterator protocol ---------------------------------------
    def reset(self) -> None:
        """No-op by design: a PASS begins at ``iter()`` (each one gets
        the next pass's permutation), so the double reset the fit tiers
        + RetryingIterator issue per epoch cannot double-advance the
        pass counter."""

    def batch_size(self) -> int:
        return self._batch

    @property
    def record_count(self) -> int:
        return int(self._reader.manifest.record_count)

    # -- deterministic pass plan ----------------------------------------
    def _pass_permutation(self, pass_index: int,
                          quarantine_base: frozenset,
                          shard_base: frozenset) -> np.ndarray:
        ids = self._reader.record_ids(exclude_shards=shard_base)
        if quarantine_base:
            ids = ids[~np.isin(ids, np.fromiter(
                quarantine_base, dtype=np.int64,
                count=len(quarantine_base)))]
        if self._shuffle:
            rng = np.random.default_rng(
                (self._seed, int(pass_index), self.host_index))
            return rng.permutation(ids)
        return ids

    def _plan_items(self, perm: np.ndarray) -> List[WorkItem]:
        items = []
        for j, start in enumerate(range(0, len(perm), self._batch)):
            chunk = perm[start:start + self._batch]
            if self._drop_remainder and len(chunk) < self._batch:
                break
            items.append(WorkItem(j, chunk))
        return items

    # -- worker-side read + transform + row quarantine ------------------
    def _assemble(self, cols: Dict[str, np.ndarray]):
        """Column dict -> (features, labels), vectorized."""
        if self._reader.manifest.layout == "arrays":
            feats, labels = cols["features"], cols["labels"]
            if self._transform is not None:
                feats, labels = self._transform(feats, labels)
            return np.asarray(feats), np.asarray(labels)
        # columns layout: TransformProcess steps, then feature/label split
        if self._tp is not None:
            s = self._tp.initial_schema
            for st in self._tp.steps:
                cols = st.apply(s, cols)
                s = st.apply_schema(s)
            names = list(s.names())
        else:
            names = list(cols)
        label_name = names[self._label_column] \
            if isinstance(self._label_column, int) else self._label_column
        feat_names = [n for n in names if n != label_name]
        feats = np.stack([np.asarray(cols[n], np.float32)
                          for n in feat_names], axis=1)
        lab = cols[label_name]
        if self._num_classes is not None:
            labels = np.eye(self._num_classes, dtype=np.float32)[
                np.asarray(lab).astype(np.int64)]
        else:
            labels = np.asarray(lab, np.float32).reshape(-1, 1)
        return feats, labels

    @staticmethod
    def _corrupt_rows(cols: Dict[str, np.ndarray], n: int) -> np.ndarray:
        """Row mask of non-finite values across the RAW columns — the
        scan happens where untrusted bytes enter, before any transform
        can turn a NaN into a crash."""
        bad = np.zeros(n, dtype=bool)
        for a in cols.values():
            if isinstance(a, np.ndarray) and \
                    np.issubdtype(a.dtype, np.floating):
                bad |= ~np.isfinite(a.reshape(n, -1)).all(axis=1)
        return bad

    def _read_item(self, item: WorkItem) -> dict:
        ids = np.asarray(item.record_ids, dtype=np.int64)
        try:
            cols = self._reader.read_rows(ids)
        except ShardCorruptError as e:
            if e.cause != "shard_quarantined":
                raise
            # the shard was already quarantined (its first failure was
            # raised loudly): withhold its rows, keep the rest
            bad_shards = self._reader.quarantined_shards_snapshot()
            offsets = [(s.offset, s.offset + s.records, i) for i, s in
                       enumerate(self._reader.manifest.shards)
                       if i in bad_shards]
            withheld = np.zeros(len(ids), dtype=bool)
            for lo, hi, _ in offsets:
                withheld |= (ids >= lo) & (ids < hi)
            n_withheld = int(withheld.sum())
            with self._lock:
                self._records_withheld += n_withheld
            self._emit_event({"type": "faults",
                              "event": "records_withheld",
                              "t": time.time(), "records": n_withheld,
                              "batch_index": item.index})
            ids = ids[~withheld]
            if not len(ids):
                return {"index": item.index, "batch": None, "rows": 0}
            cols = self._reader.read_rows(ids)
        keep = np.ones(len(ids), dtype=bool)
        with self._lock:
            quarantined = self._quarantined_records
            if quarantined:
                keep &= ~np.isin(ids, np.fromiter(
                    quarantined, dtype=np.int64, count=len(quarantined)))
        if self._quarantine_rows:
            bad = self._corrupt_rows(cols, len(ids))
            fresh = bad & keep
            if fresh.any():
                fresh_ids = [int(i) for i in ids[fresh]]
                with self._lock:
                    self._quarantined_records.update(fresh_ids)
                    self._rows_quarantined += len(fresh_ids)
                self._emit_event({
                    "type": "faults", "event": "record_quarantine",
                    "t": time.time(), "records": len(fresh_ids),
                    "batch_index": item.index,
                    "record_ids": fresh_ids[:16]})
            keep &= ~bad
        if not keep.all():
            ids = ids[keep]
            cols = {k: a[keep] for k, a in cols.items()}
        if not len(ids):
            return {"index": item.index, "batch": None, "rows": 0}
        feats, labels = self._assemble(cols)
        return {"index": item.index, "batch": (feats, labels),
                "rows": len(ids)}

    # -- iteration ------------------------------------------------------
    def __iter__(self):
        return self._iterate()

    def _iterate(self):
        with self._lock:
            if self._pending_seek is not None:
                st = self._pending_seek
                self._pending_seek = None
                pass_index = st["pass_index"]
                plan_start = st["cursor"]
                yield_base = st["yielded"]
                base = frozenset(st["base"])
                shard_base = frozenset(st["shard_base"])
                # the consumer-pass anchor (what a wrapper's absolute
                # per-pass batch index is relative to) moves only on a
                # NEW consumer timeline (fresh pass / restore), never on
                # an intra-pass seek — RetryingIterator keeps counting
                # from its pass start across repeated recoveries
                anchor = st.get("anchor", yield_base)
                self._passes_started = max(self._passes_started,
                                           pass_index + 1)
            else:
                pass_index = self._passes_started
                self._passes_started += 1
                plan_start, yield_base, anchor = 0, 0, 0
                base = frozenset(self._quarantined_records)
                shard_base = frozenset(
                    self._reader.quarantined_shards_snapshot())
            self._current_pass = pass_index
            self._pass_quarantine_base = base
            self._pass_shard_base = shard_base
            self._pass_anchor = anchor
            self._pass_complete = False
            self._plan_cursor = plan_start
            self._yield_counter = yield_base
            self._gen_yield_base = yield_base
            self._yield_plan = {k: v for k, v in self._yield_plan.items()
                                if k < yield_base} if yield_base else {}
            src = self._iteration_source
            self._pass_start_iteration = (int(src()) - yield_base) \
                if src is not None else None
            esrc = self._epoch_source
            self._pass_start_epoch = int(esrc()) if esrc is not None \
                else None
        perm = self._pass_permutation(pass_index, base, shard_base)
        plan = self._plan_items(perm)
        if plan_start > len(plan):
            raise DataPipelineError(
                f"seek cursor {plan_start} beyond the pass's "
                f"{len(plan)} batches — the source shrank since the "
                f"state was captured", batch_index=plan_start,
                cause="source_shrank")
        pf = SupervisedPrefetcher(
            plan[plan_start:], self._read_item,
            n_workers=self._n_workers, depth=self._depth,
            read_timeout_s=self._read_timeout_s,
            on_event=self._emit_event)
        with self._lock:
            self._live_prefetcher = pf
        try:
            for out in pf:
                if out["batch"] is None:        # fully-quarantined batch
                    with self._lock:
                        self._plan_cursor = out["index"] + 1
                    continue
                # plan-cursor advance and yield bookkeeping in ONE lock
                # block: a checkpoint capture on the training thread
                # between the two would read a cursor past a batch the
                # yield map doesn't cover yet — a resume from that
                # snapshot would seek over (never train) the in-flight
                # batch
                with self._lock:
                    self._yield_plan[self._yield_counter] = out["index"]
                    self._yield_counter += 1
                    self._plan_cursor = out["index"] + 1
                    self._records_delivered += out["rows"]
                    self._batches_delivered += 1
                yield out["batch"]
            with self._lock:
                self._pass_complete = True
        finally:
            self._fold_prefetcher(pf)
            pf.close()

    def _fold_prefetcher(self, pf: SupervisedPrefetcher) -> None:
        with self._lock:
            if self._live_prefetcher is pf:
                self._live_prefetcher = None
            self._pf_totals["worker_restarts"] += pf.restarts_total
            self._pf_totals["requeues"] += pf.requeues_total
            self._pf_totals["slow_reads"] += pf.slow_reads_total
            for w, s in pf.worker_busy_seconds().items():
                self._pf_busy[w] = self._pf_busy.get(w, 0.0) + s

    # -- seekable state --------------------------------------------------
    def bind_iteration_source(self, fn: Callable[[], int]) -> None:
        """Register the trainer's absolute-iteration reader (fit()
        wires ``tc.iteration_count``). With it bound, pass starts are
        anchored to iterations and :meth:`export_state` can map a
        checkpoint's iteration to the exact plan cursor."""
        self._iteration_source = fn

    def bind_epoch_source(self, fn: Callable[[], int]) -> None:
        """Register the trainer's completed-epoch reader
        (``tc.epoch_count``). It disambiguates the one position the
        iteration alone cannot: a checkpoint captured EXACTLY at a pass
        boundary. Before ``on_epoch_end`` counts the epoch, the resume
        must re-enter the finished pass at its end (an empty epoch that
        absorbs the pending count); after, it must start the next fresh
        pass — exporting the wrong one trains a pass twice or not at
        all."""
        self._epoch_source = fn

    def export_state(self, iteration: Optional[int] = None
                     ) -> dict:
        """The JSON-able :class:`PipelineState` at ``iteration`` (the
        checkpointed step) — or at everything-delivered when no
        iteration anchor exists. Called by
        ``checkpoint.capture_training_state`` at flush boundaries."""
        with self._lock:
            quarantined = sorted(self._quarantined_records)
            shards = sorted(self._reader.quarantined_shards_snapshot())
            config = {"seed": self._seed,
                      "batch_size": self._batch,
                      "shuffle": self._shuffle,
                      "host_index": self.host_index,
                      "host_count": self.host_count}
            if self._pending_seek is not None:
                # an armed-but-not-yet-consumed seek (restore_state
                # before the next pass begins) IS the position: a
                # snapshot taken now — e.g. FaultTolerantFit's step-0
                # rollback-target save right after resume_latest — must
                # re-export it, not a fresh next pass that would skip
                # the rest of the interrupted one
                st = self._pending_seek
                return PipelineState(
                    pass_index=st["pass_index"], cursor=st["cursor"],
                    yielded=st["yielded"],
                    passes_started=self._passes_started,
                    quarantined_records=quarantined,
                    pass_quarantine_base=sorted(st["base"]),
                    quarantined_shards=shards,
                    pass_shard_base=sorted(st["shard_base"]),
                    **config).to_json()
            if self._current_pass is None:
                # before the first pass: resume = start pass 0 fresh
                return PipelineState(
                    pass_index=self._passes_started, cursor=0, yielded=0,
                    passes_started=self._passes_started,
                    quarantined_records=quarantined,
                    pass_quarantine_base=quarantined,
                    quarantined_shards=shards,
                    pass_shard_base=shards, **config).to_json()
            if iteration is not None and \
                    self._pass_start_iteration is not None:
                y = max(0, min(int(iteration) - self._pass_start_iteration,
                               self._yield_counter))
            else:
                y = self._yield_counter
            if self._pass_complete and y >= self._yield_counter:
                # the checkpoint sits EXACTLY on a pass boundary. Two
                # distinct resumes hide here, told apart by whether
                # on_epoch_end already counted the pass's epoch:
                counted = (self._epoch_source is not None
                           and self._pass_start_epoch is not None
                           and int(self._epoch_source())
                           > self._pass_start_epoch)
                if counted:
                    # counted (epoch-cadence snapshot): the restored
                    # epoch budget excludes this pass → next fresh pass
                    return PipelineState(
                        pass_index=self._passes_started, cursor=0,
                        yielded=0,
                        passes_started=self._passes_started,
                        quarantined_records=quarantined,
                        pass_quarantine_base=quarantined,
                        quarantined_shards=shards,
                        pass_shard_base=shards, **config).to_json()
                # NOT counted (iteration-cadence snapshot fired at the
                # last flush of the epoch): the restored epoch budget
                # still includes this pass, so the resume re-enters it
                # AT ITS END — an empty epoch that absorbs the pending
                # on_epoch_end count without retraining a single batch
                return PipelineState(
                    pass_index=self._current_pass,
                    cursor=self._plan_cursor, yielded=int(y),
                    passes_started=self._passes_started,
                    quarantined_records=quarantined,
                    pass_quarantine_base=sorted(
                        self._pass_quarantine_base),
                    quarantined_shards=shards,
                    pass_shard_base=sorted(self._pass_shard_base),
                    **config).to_json()
            cursor = self._yield_plan.get(y, self._plan_cursor)
            return PipelineState(
                pass_index=self._current_pass, cursor=int(cursor),
                yielded=int(y),
                passes_started=self._passes_started,
                quarantined_records=quarantined,
                pass_quarantine_base=sorted(self._pass_quarantine_base),
                quarantined_shards=shards,
                pass_shard_base=sorted(self._pass_shard_base),
                **config).to_json()

    def restore_state(self, state) -> None:
        """Arm the pipeline so its NEXT pass resumes exactly where
        ``state`` points: same pass permutation, plan cursor, and
        quarantine sets. Accepts the dict :meth:`export_state`
        produced (what ``TrainingState.metadata['datapipe']`` holds)
        or a :class:`PipelineState`."""
        st = state if isinstance(state, PipelineState) \
            else PipelineState.from_json(dict(state))
        if st.seed != self._seed:
            raise DataPipelineError(
                f"PipelineState was captured with shuffle seed "
                f"{st.seed}, this pipeline uses {self._seed} — the "
                f"replayed pass orders would differ silently",
                cause="seed_mismatch")
        # the cursor is denominated in plan batches of the CAPTURING
        # configuration: restoring into a differently-shaped plan would
        # seek to different records with no error (None = old state
        # without the field: check skipped)
        for field, mine in (("batch_size", self._batch),
                            ("shuffle", self._shuffle),
                            ("host_index", self.host_index),
                            ("host_count", self.host_count)):
            theirs = getattr(st, field)
            if theirs is not None and theirs != mine:
                raise DataPipelineError(
                    f"PipelineState was captured with {field}="
                    f"{theirs}, this pipeline uses {mine} — the plan "
                    f"cursor would seek to different records silently",
                    cause="config_mismatch")
        with self._lock:
            self._quarantined_records = set(st.quarantined_records)
            self._reader.quarantine_shards(st.quarantined_shards)
            # the snapshot's pass counter is AUTHORITATIVE, not merged:
            # an in-process rollback rolls the timeline (and therefore
            # the fresh-pass numbering) BACK — keeping the live counter
            # would skip the abandoned pass's permutation on retry and
            # train different data than the uninterrupted run
            self._passes_started = st.passes_started
            self._pending_seek = {"pass_index": st.pass_index,
                                  "cursor": st.cursor,
                                  "yielded": st.yielded,
                                  "anchor": st.yielded,
                                  "base": list(st.pass_quarantine_base),
                                  "shard_base":
                                  list(st.pass_shard_base)}
            self._current_pass = None
            self._pass_complete = False

    def seek_batches(self, skip: int):
        """Re-open the CURRENT pass positioned after ``skip`` batches
        already delivered to the consumer — the O(1) recovery hook
        ``faults.RetryingIterator`` uses instead of reset-and-fast-
        forward. Returns the positioned iterator. Raises a
        ``source_shrank`` :class:`DataPipelineError` when ``skip``
        exceeds what this pass can deliver."""
        with self._lock:
            if self._current_pass is None:
                raise DataPipelineError(
                    "seek_batches: no pass in progress (iterate first)",
                    cause="seek")
            # ``skip`` is the CONSUMER's absolute per-pass batch count
            # (RetryingIterator never resets its index across repeated
            # recoveries), so it is relative to the pass ANCHOR — not
            # to the current generator, which may itself be the product
            # of an earlier seek
            y = self._pass_anchor + max(0, int(skip))
            if y > self._yield_counter:
                raise DataPipelineError(
                    f"seek_batches: {skip} batches requested but only "
                    f"{self._yield_counter - self._pass_anchor} were "
                    f"delivered this pass — the source shrank",
                    batch_index=int(skip), cause="source_shrank")
            cursor = self._yield_plan.get(y, self._plan_cursor)
            self._pending_seek = {"pass_index": self._current_pass,
                                  "cursor": int(cursor), "yielded": y,
                                  "anchor": self._pass_anchor,
                                  "base":
                                  sorted(self._pass_quarantine_base),
                                  "shard_base":
                                  sorted(self._pass_shard_base)}
        return self._iterate()

    # -- observability ---------------------------------------------------
    @property
    def quarantined_records(self) -> set:
        with self._lock:
            return set(self._quarantined_records)

    def stats(self) -> dict:
        """Cumulative pipeline counters (monotonic — the monitor
        listener publishes per-flush deltas as ``{"type": "datapipe"}``
        records)."""
        with self._lock:
            pf = self._live_prefetcher
            totals = dict(self._pf_totals)
            busy = dict(self._pf_busy)
            out = {"records": self._records_delivered,
                   "batches": self._batches_delivered,
                   "rows_quarantined": self._rows_quarantined,
                   "records_withheld": self._records_withheld,
                   "passes_started": self._passes_started,
                   "workers": self._n_workers}
            # fold the LIVE prefetcher inside the lock: _fold_prefetcher
            # (also under it) must not land between the snapshot and the
            # merge, or the same pass would count twice
            if pf is not None:
                totals["worker_restarts"] += pf.restarts_total
                totals["requeues"] += pf.requeues_total
                totals["slow_reads"] += pf.slow_reads_total
                for w, s in pf.worker_busy_seconds().items():
                    busy[w] = busy.get(w, 0.0) + s
        out.update(totals)
        out["worker_busy_s"] = {str(k): round(v, 6)
                                for k, v in sorted(busy.items())}
        out.update(self._reader.stats())
        return out


__all__ = ["StreamingDataPipeline", "find_pipeline"]
