"""Early stopping suite: termination conditions, score calculators,
model savers, and the trainer that drives them.

Reference parity: org.deeplearning4j.earlystopping —
EarlyStoppingConfiguration + EarlyStoppingTrainer + EarlyStoppingResult
(earlystopping/EarlyStoppingTrainer.java, trainer/BaseEarlyStoppingTrainer.java),
epoch termination conditions {MaxEpochs, ScoreImprovementEpoch,
BestScoreEpoch}, iteration termination conditions {MaxTime, MaxScore,
InvalidScore}, score calculators (DataSetLossCalculator,
ClassificationScoreCalculator), and model savers
{InMemoryModelSaver, LocalFileModelSaver}.

TPU-native difference: the trainer drives whole epochs through the
model's compiled fit path (one jitted step, scanned epochs) and computes
holdout scores from batched device inference — there is no per-iteration
Java loop to interleave, so iteration conditions are checked between
epochs on the epoch's mean loss and wall clock.
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# termination conditions

class MaxEpochsTerminationCondition:
    """(reference: termination/MaxEpochsTerminationCondition)"""

    uses_score = False       # epoch-count only; safe on any cadence

    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without improvement of MORE than
    ``min_improvement`` (reference:
    termination/ScoreImprovementEpochTerminationCondition.java:62-64 —
    improvement counts only when best - score is strictly greater than
    minImprovement; an unchanged score is not improvement)."""

    #: the internal streak counter must only advance on epochs that
    #: produced a fresh score — the trainer skips it otherwise
    requires_fresh_score = True

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = min_improvement
        self.initialize()

    def initialize(self):
        self._since_best = 0
        self._best = None

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        # strict >: an unchanged score is NOT improvement (reference
        # ScoreImprovementEpochTerminationCondition.java:62-64)
        if self._best is None or \
                self._best - score > self.min_improvement:
            self._best = score if self._best is None \
                else min(self._best, score)
            self._since_best = 0
        else:
            self._since_best += 1
        return self._since_best > self.patience

    def __repr__(self):
        return (f"ScoreImprovementEpochTerminationCondition"
                f"({self.patience})")


class BestScoreEpochTerminationCondition:
    """Stop once the score is at least as good as a target (reference:
    termination/BestScoreEpochTerminationCondition)."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = best_expected_score

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        # strict <: merely REACHING the target is not beating it
        # (reference BestScoreEpochTerminationCondition.java uses
        # score < bestExpectedScore when lesser is better)
        return score < self.best_expected_score

    def __repr__(self):
        return (f"BestScoreEpochTerminationCondition"
                f"({self.best_expected_score})")


class MaxTimeTerminationCondition:
    """Wall-clock budget (reference:
    termination/MaxTimeIterationTerminationCondition)."""

    uses_score = False       # wall-clock only; judged on every epoch

    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        if self._start is None:
            self.initialize()
        return time.perf_counter() - self._start > self.max_seconds

    def __repr__(self):
        return f"MaxTimeTerminationCondition({self.max_seconds}s)"


class MaxScoreTerminationCondition:
    """Abort when the score explodes above a bound (reference:
    termination/MaxScoreIterationTerminationCondition)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        return score > self.max_score

    def __repr__(self):
        return f"MaxScoreTerminationCondition({self.max_score})"


class InvalidScoreTerminationCondition:
    """Abort on NaN/Inf (reference:
    termination/InvalidScoreIterationTerminationCondition)."""

    def terminate(self, epoch: int, score: float, improved: bool) -> bool:
        return math.isnan(score) or math.isinf(score)

    def __repr__(self):
        return "InvalidScoreTerminationCondition()"


# ---------------------------------------------------------------------------
# score calculators

class DataSetLossCalculator:
    """Mean loss over a holdout iterator (reference:
    scorecalc/DataSetLossCalculator). Uses the model's inference outputs
    and recomputes the configured loss on host — the holdout pass never
    touches training state."""

    def __init__(self, iterator, loss: str = "mcxent", eps: float = 1e-7):
        self.iterator = iterator
        self.loss = loss.lower()
        self.eps = eps

    def _batch_loss(self, preds: np.ndarray, labels: np.ndarray) -> float:
        p = np.asarray(preds, np.float64)
        y = np.asarray(labels, np.float64)
        if self.loss == "mcxent":
            p = np.clip(p, self.eps, 1.0)
            return float(-(y * np.log(p)).sum(axis=-1).mean())
        if self.loss == "mse":
            return float(((p - y) ** 2).mean())
        raise ValueError(f"unknown loss {self.loss!r}")

    def calculate_score(self, model) -> float:
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total, n = 0.0, 0
        for batch in self.iterator:
            if hasattr(batch, "features"):
                feats, labs = batch.features, batch.labels
            else:
                feats, labs = batch
            out = model.output(feats)
            if isinstance(out, list):
                out = out[0]
            out = out.to_numpy() if hasattr(out, "to_numpy") else \
                np.asarray(getattr(out, "data", out))
            b = len(out)
            total += self._batch_loss(out, labs) * b
            n += b
        return total / max(n, 1)


class ClassificationScoreCalculator:
    """1 - accuracy on a holdout iterator, so lower is better like a loss
    (reference: scorecalc/ClassificationScoreCalculator with
    Evaluation.Metric.ACCURACY)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.evaluation import Evaluation
        ev = Evaluation()
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        model.evaluate(self.iterator, evaluation=ev)
        return 1.0 - ev.accuracy()


class TrainingLossCalculator:
    """Scores with the epoch's own mean training loss — no holdout
    (the implicit behavior when the reference is configured without a
    score calculator)."""

    def calculate_score(self, model) -> float:
        raise RuntimeError("TrainingLossCalculator is resolved by the "
                           "trainer from the epoch history")


# ---------------------------------------------------------------------------
# model savers

class InMemoryModelSaver:
    """Keep the best model's arrays in memory (reference:
    saver/InMemoryModelSaver)."""

    def __init__(self):
        self.best_params: Optional[Dict[str, np.ndarray]] = None
        self.best_epoch = -1
        self.best_score = float("inf")
        self.latest_params: Optional[Dict[str, np.ndarray]] = None
        self.latest_epoch = -1

    def save_best(self, model, epoch: int, score: float) -> None:
        sd = model.samediff if hasattr(model, "samediff") else model
        self.best_params = {n: np.asarray(a)
                            for n, a in sd._arrays.items()}
        self.best_epoch = epoch
        self.best_score = score

    def save_latest(self, model, epoch: int, score: float) -> None:
        sd = model.samediff if hasattr(model, "samediff") else model
        self.latest_params = {n: np.asarray(a)
                              for n, a in sd._arrays.items()}
        self.latest_epoch = epoch

    def restore_best(self, model):
        if self.best_params is None:
            return model
        import jax.numpy as jnp
        sd = model.samediff if hasattr(model, "samediff") else model
        for n, a in self.best_params.items():
            if n in sd._arrays:
                sd._arrays[n] = jnp.asarray(a)
        if hasattr(model, "_sync_infer"):
            model._sync_infer()
        return model


class LocalFileModelSaver:
    """Save the best model as a zip in a directory (reference:
    saver/LocalFileModelSaver — bestModel.bin).

    Writes are atomic (checkpoint/atomic.py): a crash during an
    improvement save cannot corrupt the previously saved best model —
    bestModel.zip is either the old complete artifact or the new one.
    """

    def __init__(self, directory: str):
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.best_path = None
        self.best_epoch = -1
        self.best_score = float("inf")
        self.latest_path = None
        self.latest_epoch = -1

    @staticmethod
    def _atomic_model_save(model, path) -> None:
        from deeplearning4j_tpu.checkpoint.atomic import atomic_write_via
        atomic_write_via(path, model.save)

    def save_best(self, model, epoch: int, score: float) -> None:
        import os
        path = os.path.join(self.directory, "bestModel.zip")
        self._atomic_model_save(model, path)
        self.best_path = path
        self.best_epoch = epoch
        self.best_score = score

    def save_latest(self, model, epoch: int, score: float) -> None:
        import os
        path = os.path.join(self.directory, "latestModel.zip")
        self._atomic_model_save(model, path)
        self.latest_path = path
        self.latest_epoch = epoch

    def restore_best(self, model):
        if self.best_path is None:
            return model
        return type(model).load(self.best_path)


# ---------------------------------------------------------------------------

class EarlyStoppingConfiguration:
    """(reference: EarlyStoppingConfiguration + .Builder)"""

    def __init__(self, epoch_termination_conditions: Sequence = (),
                 iteration_termination_conditions: Sequence = (),
                 score_calculator=None, model_saver=None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.epoch_conditions = list(epoch_termination_conditions)
        self.iteration_conditions = list(iteration_termination_conditions)
        self.score_calculator = score_calculator
        self.model_saver = model_saver or InMemoryModelSaver()
        self.evaluate_every_n_epochs = max(int(evaluate_every_n_epochs), 1)
        self.save_last_model = save_last_model

    class Builder:
        def __init__(self):
            self._kw = dict(epoch_termination_conditions=[],
                            iteration_termination_conditions=[])

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"] = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"] = list(conds)
            return self

        def score_calculator(self, sc):
            self._kw["score_calculator"] = sc; return self

        def model_saver(self, saver):
            self._kw["model_saver"] = saver; return self

        def save_last_model(self, v: bool = True):
            self._kw["save_last_model"] = v; return self

        def evaluate_every_n_epochs(self, n: int):
            self._kw["evaluate_every_n_epochs"] = n; return self

        def build(self) -> "EarlyStoppingConfiguration":
            return EarlyStoppingConfiguration(**self._kw)

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()


class EarlyStoppingResult:
    """(reference: EarlyStoppingResult — termination reason + details +
    best epoch/score + the best model)"""

    EPOCH_TERMINATION = "EpochTerminationCondition"
    ITERATION_TERMINATION = "IterationTerminationCondition"
    MAX_EPOCHS = "MaxEpochsExceeded"

    def __init__(self, reason, details, best_epoch, best_score,
                 total_epochs, best_model, score_by_epoch):
        self.termination_reason = reason
        self.termination_details = details
        self.best_model_epoch = best_epoch
        self.best_model_score = best_score
        self.total_epochs = total_epochs
        self.best_model = best_model
        self.score_vs_epoch = score_by_epoch

    def __repr__(self):
        return (f"EarlyStoppingResult(reason={self.termination_reason}, "
                f"details={self.termination_details}, "
                f"best_epoch={self.best_model_epoch}, "
                f"best_score={self.best_model_score:.6f}, "
                f"epochs={self.total_epochs})")


class EarlyStoppingTrainer:
    """Drives epoch-at-a-time training with score-based termination
    (reference: trainer/BaseEarlyStoppingTrainer.fit)."""

    def __init__(self, config: EarlyStoppingConfiguration, model,
                 train_data):
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self, max_epochs: int = 1000) -> EarlyStoppingResult:
        cfg = self.config
        for c in list(cfg.iteration_conditions) + list(cfg.epoch_conditions):
            if hasattr(c, "initialize"):
                c.initialize()
        best_score = float("inf")
        best_epoch = -1
        last_score = None
        score_by_epoch: Dict[int, float] = {}
        reason, details = EarlyStoppingResult.MAX_EPOCHS, \
            f"no termination condition fired in {max_epochs} epochs"
        epoch = -1
        for epoch in range(max_epochs):
            if hasattr(self.train_data, "reset"):
                self.train_data.reset()
            history = self.model.fit(self.train_data, epochs=1)
            train_loss = history.final_loss()

            # iteration-class conditions watch the raw training signal
            fired = None
            for c in cfg.iteration_conditions:
                if c.terminate(epoch, train_loss, False):
                    fired = c
                    break
            if fired is not None:
                reason = EarlyStoppingResult.ITERATION_TERMINATION
                details = repr(fired)
                score_by_epoch[epoch] = train_loss
                break

            # scoring + best-model tracking on the evaluation cadence;
            # epoch conditions are checked EVERY epoch (a MaxEpochs limit
            # must not overshoot because evaluation is sparse) with the
            # most recent score. Score-improvement counting only advances
            # on epochs that produced a fresh score.
            scored = (epoch + 1) % cfg.evaluate_every_n_epochs == 0
            improved = False
            if scored:
                if cfg.score_calculator is not None and not isinstance(
                        cfg.score_calculator, TrainingLossCalculator):
                    score = cfg.score_calculator.calculate_score(self.model)
                else:
                    score = train_loss
                score_by_epoch[epoch] = score
                improved = score < best_score
                if improved:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best(self.model, epoch, score)
                last_score = score
            score = last_score if last_score is not None else train_loss
            # is `score` the configured metric, or a train-loss stand-in
            # because the calculator hasn't run yet?
            score_is_real = (scored or last_score is not None
                             or cfg.score_calculator is None
                             or isinstance(cfg.score_calculator,
                                           TrainingLossCalculator))
            fired = None
            for c in cfg.epoch_conditions:
                if getattr(c, "requires_fresh_score", False) and not scored:
                    continue           # streak counters only see new scores
                if getattr(c, "uses_score", True) and not score_is_real:
                    continue           # never judge thresholds on stand-ins
                if c.terminate(epoch, score, improved):
                    fired = c
                    break
            if fired is not None:
                reason = EarlyStoppingResult.EPOCH_TERMINATION
                details = repr(fired)
                break

        if cfg.save_last_model and epoch >= 0:
            # reference: saver.saveLatestModel — persisted BEFORE the
            # best-model restore overwrites the in-memory final state
            cfg.model_saver.save_latest(
                self.model, epoch, score_by_epoch.get(epoch, float("nan")))
        best_model = cfg.model_saver.restore_best(self.model)
        return EarlyStoppingResult(reason, details, best_epoch, best_score,
                                   epoch + 1, best_model, score_by_epoch)
