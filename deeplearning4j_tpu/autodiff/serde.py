"""SameDiff serialization: save/load graph + values + updater state.

Reference parity: the FlatBuffers SameDiff file format (ADR
0001-SameDiff_File_Format.md; SameDiff.java:1583 save / 5849 asFlatBuffers /
6114 fromFlatBuffers), which stores graph structure, variable values,
training config and updater state in one artifact.

TPU-native format: a zip containing
- ``graph.json``   — variables (name/type/shape/dtype), ops (op name,
  inputs/outputs/attrs), loss variables, training config;
- ``arrays.npz``   — VARIABLE/CONSTANT values;
- ``updater.npz``  — flattened updater state (optional).

JSON+npz rather than FlatBuffers because the graph here is *names + attrs*
(the compiled artifact is XLA's concern, rebuilt at load time); there are no
opaque buffers to describe. Checkpoint round-trip includes updater state so
training resumes bit-exact, matching the reference's
``save(..., saveUpdaterState=true)``.
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 1


def _attrs_to_json(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            out[k] = {"__ndarray__": np.asarray(v).tolist(),
                      "dtype": str(np.asarray(v).dtype)}
        elif isinstance(v, tuple):
            out[k] = {"__tuple__": list(v)}
        else:
            out[k] = v
    return out


def _attrs_from_json(attrs: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.asarray(v["__ndarray__"], dtype=v["dtype"])
        elif isinstance(v, dict) and "__tuple__" in v:
            out[k] = tuple(tuple(x) if isinstance(x, list) else x
                           for x in v["__tuple__"])
        elif isinstance(v, list):
            out[k] = tuple(tuple(x) if isinstance(x, list) else x for x in v)
        else:
            out[k] = v
    return out


def save(sd, path, include_updater_state: bool = True) -> None:
    from deeplearning4j_tpu.autodiff.variable import VariableType

    graph = {
        "format_version": FORMAT_VERSION,
        "variables": [
            {"name": v.name, "type": v.var_type.value,
             "shape": list(v._shape) if v._shape is not None else None,
             "dtype": v._dtype}
            for v in sd._vars.values()
        ],
        "ops": [
            {"name": n.name, "op": n.op, "inputs": n.inputs,
             "outputs": n.outputs, "attrs": _attrs_to_json(n.attrs),
             "random": n.random,
             **({"group": n.group} if n.group else {})}
            for n in sd.ops()
        ],
        "loss_variables": sd.loss_variables,
        "state_vars": sorted(sd._state_var_names),
        "state_updates": dict(sd._state_updates),
        "training_config": sd.training_config.to_json()
        if sd.training_config else None,
    }

    arrays = {name: np.asarray(arr) for name, arr in sd._arrays.items()}

    # crash-safe: assemble in a temp file, atomically rename into place
    # (checkpoint/atomic.py) — a killed process never tears the artifact
    from deeplearning4j_tpu.checkpoint.atomic import atomic_output_file
    with atomic_output_file(path) as tmp:
        with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(graph, indent=1))
            buf = io.BytesIO()
            np.savez(buf, **arrays)
            zf.writestr("arrays.npz", buf.getvalue())
            if include_updater_state and sd._updater_state is not None:
                leaves, treedef = jax.tree_util.tree_flatten(
                    sd._updater_state)
                buf = io.BytesIO()
                np.savez(buf, **{f"leaf_{i}": np.asarray(l)
                                 for i, l in enumerate(leaves)})
                zf.writestr("updater.npz", buf.getvalue())


def load(path):
    from deeplearning4j_tpu.autodiff.samediff import SameDiff, OpNode
    from deeplearning4j_tpu.autodiff.variable import SDVariable, VariableType
    from deeplearning4j_tpu.autodiff.training import TrainingConfig

    with zipfile.ZipFile(path, "r") as zf:
        graph = json.loads(zf.read("graph.json"))
        with np.load(io.BytesIO(zf.read("arrays.npz"))) as npz:
            arrays = {k: jnp.asarray(npz[k]) for k in npz.files}
        updater_leaves = None
        if "updater.npz" in zf.namelist():
            with np.load(io.BytesIO(zf.read("updater.npz"))) as npz:
                updater_leaves = [jnp.asarray(npz[f"leaf_{i}"])
                                  for i in range(len(npz.files))]

    sd = SameDiff()
    for vd in graph["variables"]:
        v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                       tuple(vd["shape"]) if vd["shape"] is not None else None,
                       vd["dtype"])
        # placeholder batch dims round-trip as -1; ARRAY shapes re-infer
        if v.var_type == VariableType.ARRAY:
            v._shape = None
        sd._vars[v.name] = v
    sd._arrays = arrays
    for od in graph["ops"]:
        node = OpNode(name=od["name"], op=od["op"], inputs=list(od["inputs"]),
                      outputs=list(od["outputs"]),
                      attrs=_attrs_from_json(od["attrs"]),
                      random=od.get("random", False),
                      group=od.get("group"))
        sd._ops[node.name] = node
        sd._op_order.append(node.name)
        for on in node.outputs:
            sd._producer[on] = node.name
    # keep future remat_scope ids distinct from loaded ones
    sd._group_counter = sum(1 for od in graph["ops"] if od.get("group"))
    sd.loss_variables = list(graph.get("loss_variables", []))
    sd._state_var_names = set(graph.get("state_vars", []))
    sd._state_updates = dict(graph.get("state_updates", {}))
    if graph.get("training_config"):
        sd.training_config = TrainingConfig.from_json(graph["training_config"])
        if updater_leaves is not None:
            # rebuild the state treedef from a fresh init, then pour leaves in
            params = sd.trainable_params()
            template = sd.training_config.updater.init(params)
            treedef = jax.tree_util.tree_structure(template)
            sd._updater_state = jax.tree_util.tree_unflatten(
                treedef, updater_leaves)
    sd._mutated()
    return sd
