"""Training configuration, history and listener API for SameDiff.

Reference parity:
- TrainingConfig (org.nd4j.autodiff.samediff.TrainingConfig.java:42):
  updater + L1/L2 + dataSetFeatureMapping/dataSetLabelMapping.
- Listener (org.nd4j.autodiff.listeners.Listener) and the History/LossCurve
  records (org.nd4j.autodiff.listeners.records).
- ScoreIterationListener / PerformanceListener
  (deeplearning4j optimize/listeners/) — throughput metrics use the same
  samples/sec & batches/sec definitions (PerformanceListener.java:46-118).

The listener surface is host-side: it observes per-iteration scalars after
the compiled step returns. It can NOT inject code into the XLA computation
(the reference's listeners run between per-op JNI dispatches; here there is
nothing between ops — that is the point).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.learning.updaters import IUpdater
from deeplearning4j_tpu.learning.regularization import Regularization


@dataclasses.dataclass
class TrainingConfig:
    updater: IUpdater
    data_set_feature_mapping: Sequence[str] = ()
    data_set_label_mapping: Sequence[str] = ()
    regularization: Sequence[Regularization] = ()
    grad_clip_value: Optional[float] = None
    minibatch: bool = True
    iteration_count: int = 0
    epoch_count: int = 0

    def to_json(self) -> dict:
        return {
            "updater": self.updater.to_json(),
            "data_set_feature_mapping": list(self.data_set_feature_mapping),
            "data_set_label_mapping": list(self.data_set_label_mapping),
            "regularization": [r.to_json() for r in self.regularization],
            "grad_clip_value": self.grad_clip_value,
            "minibatch": self.minibatch,
            "iteration_count": self.iteration_count,
            "epoch_count": self.epoch_count,
        }

    @staticmethod
    def from_json(d: dict) -> "TrainingConfig":
        return TrainingConfig(
            updater=IUpdater.from_json(d["updater"]),
            data_set_feature_mapping=d.get("data_set_feature_mapping", []),
            data_set_label_mapping=d.get("data_set_label_mapping", []),
            regularization=[Regularization.from_json(r)
                            for r in d.get("regularization", [])],
            grad_clip_value=d.get("grad_clip_value"),
            minibatch=d.get("minibatch", True),
            iteration_count=d.get("iteration_count", 0),
            epoch_count=d.get("epoch_count", 0),
        )

    class Builder:
        """Fluent builder matching the reference's TrainingConfig.Builder."""

        def __init__(self):
            self._kw: Dict[str, Any] = {}

        def updater(self, u):             self._kw["updater"] = u; return self
        def data_set_feature_mapping(self, *names):
            self._kw["data_set_feature_mapping"] = list(names); return self
        def data_set_label_mapping(self, *names):
            self._kw["data_set_label_mapping"] = list(names); return self
        def regularization(self, *regs):  self._kw["regularization"] = list(regs); return self
        def grad_clip_value(self, v):     self._kw["grad_clip_value"] = v; return self
        def minibatch(self, b):           self._kw["minibatch"] = b; return self
        def build(self) -> "TrainingConfig":
            return TrainingConfig(**self._kw)

    @staticmethod
    def builder() -> "TrainingConfig.Builder":
        return TrainingConfig.Builder()


class LossCurve:
    """Per-epoch mean loss (reference: listeners.records.LossCurve)."""

    def __init__(self):
        self.epochs: List[int] = []
        self.losses: List[float] = []

    def add(self, epoch: int, loss: float):
        self.epochs.append(epoch)
        self.losses.append(loss)

    def mean_loss(self, epoch: int) -> float:
        return self.losses[self.epochs.index(epoch)]

    def last(self) -> float:
        return self.losses[-1] if self.losses else float("nan")


class History:
    """Training run record (reference: listeners.records.History)."""

    def __init__(self):
        self.loss_curve = LossCurve()

    def add_epoch(self, epoch: int, mean_loss: float):
        self.loss_curve.add(epoch, mean_loss)

    def final_loss(self) -> float:
        return self.loss_curve.last()


class Listener:
    """Training listener (reference: autodiff.listeners.Listener /
    dl4j TrainingListener). Return False from on_epoch_end to stop."""

    def on_training_start(self, sd): ...
    def on_training_end(self, sd): ...
    def on_epoch_start(self, sd, epoch: int): ...
    def on_epoch_end(self, sd, epoch: int, mean_loss: float): ...
    def iteration_done(self, sd, epoch: int, iteration: int, loss: float): ...


class ScoreIterationListener(Listener):
    """Print score every N iterations (reference:
    optimize/listeners/ScoreIterationListener)."""

    def __init__(self, print_every: int = 10, print_fn=print):
        self.print_every = print_every
        self.print_fn = print_fn

    def iteration_done(self, sd, epoch, iteration, loss):
        if iteration % self.print_every == 0:
            self.print_fn(f"Score at iteration {iteration} is {loss}")


class PerformanceListener(Listener):
    """Throughput metrics: samples/sec, batches/sec (reference:
    optimize/listeners/PerformanceListener.java:46-118)."""

    def __init__(self, frequency: int = 10, print_fn=print):
        self.frequency = frequency
        self.print_fn = print_fn
        self.batch_size = None  # auto-filled by fit() from the first batch
        self._last_time = None
        self._last_iter = None
        self.samples_per_sec = float("nan")
        self.batches_per_sec = float("nan")

    def iteration_done(self, sd, epoch, iteration, loss):
        now = time.perf_counter()
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            n_batches = iteration - self._last_iter
            self.batches_per_sec = n_batches / dt
            if self.batch_size:
                self.samples_per_sec = self.batch_size * self.batches_per_sec
            if iteration % self.frequency == 0:
                self.print_fn(
                    f"iteration {iteration}: {self.batches_per_sec:.1f} batches/sec"
                    + (f", {self.samples_per_sec:.1f} samples/sec"
                       if self.batch_size else ""))
        self._last_time = now
        self._last_iter = iteration


class CheckpointListener(Listener):
    """Periodic model save (reference: optimize/listeners/CheckpointListener
    + autodiff/listeners/checkpoint/CheckpointListener): keep-last-N,
    every-N-epochs."""

    def __init__(self, save_dir, every_n_epochs: int = 1, keep_last: int = 3):
        import os
        self.save_dir = str(save_dir)
        self.every_n_epochs = every_n_epochs
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(self.save_dir, exist_ok=True)

    def on_epoch_end(self, sd, epoch, mean_loss):
        import os
        if (epoch + 1) % self.every_n_epochs != 0:
            return
        path = os.path.join(self.save_dir, f"checkpoint_epoch_{epoch}.zip")
        sd.save(path, include_updater_state=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def last_checkpoint(self) -> Optional[str]:
        return self._saved[-1] if self._saved else None


class EarlyStoppingListener(Listener):
    """Stop when the score stops improving (reference: earlystopping/
    EarlyStoppingTrainer + termination conditions, compressed into a
    listener since fit() owns the loop here)."""

    def __init__(self, patience: int = 5, min_delta: float = 0.0,
                 max_epochs: Optional[int] = None):
        self.patience = patience
        self.min_delta = min_delta
        self.max_epochs = max_epochs
        self.best_loss = float("inf")
        self.best_epoch = -1
        self.stopped_epoch = None

    def on_epoch_end(self, sd, epoch, mean_loss):
        if mean_loss < self.best_loss - self.min_delta:
            self.best_loss = mean_loss
            self.best_epoch = epoch
            return None
        if epoch - self.best_epoch >= self.patience or \
                (self.max_epochs is not None and epoch + 1 >= self.max_epochs):
            self.stopped_epoch = epoch
            return False
        return None
