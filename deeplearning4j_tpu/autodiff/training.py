"""Training configuration, history and listener API for SameDiff.

Reference parity:
- TrainingConfig (org.nd4j.autodiff.samediff.TrainingConfig.java:42):
  updater + L1/L2 + dataSetFeatureMapping/dataSetLabelMapping.
- Listener (org.nd4j.autodiff.listeners.Listener) and the History/LossCurve
  records (org.nd4j.autodiff.listeners.records).
- ScoreIterationListener / PerformanceListener
  (deeplearning4j optimize/listeners/) — throughput metrics use the same
  samples/sec & batches/sec definitions (PerformanceListener.java:46-118).

The listener surface is host-side: it observes per-iteration scalars after
the compiled step returns. It can NOT inject code into the XLA computation
(the reference's listeners run between per-op JNI dispatches; here there is
nothing between ops — that is the point).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.learning.updaters import IUpdater
from deeplearning4j_tpu.learning.regularization import Regularization


def _env():
    from deeplearning4j_tpu.environment import environment
    return environment()


@dataclasses.dataclass
class MixedPrecision:
    """Mixed-precision training policy: compute in ``compute_dtype``
    (bf16 → the MXU's native input format), keep float32 master params.

    The reference has no analogue (its DataType plumbing switches the
    whole net's dtype); this is the TPU-native design: the train step
    casts params + inputs to the compute dtype at the top of the
    forward trace, XLA fuses the casts into the producing/consuming
    ops, gradients flow back through the casts as float32 into the
    updater, and loss-sensitive reductions (loss ops, BN statistics)
    stay float32 internally. ``loss_scale`` is optional static loss
    scaling (rarely needed with bf16 — same exponent range as f32).

    ``softmax_dtype`` (alias ``ce_tail_dtype``) relaxes the one upcast
    that dominates LM steps: by default the softmax-CE losses run their
    log-softmax tail in f32 even under bf16 compute, which on a 32k
    vocab materializes the largest f32 tensor in the step (PROFILE.md
    round 5 names it the top delta to hand-written JAX). Setting
    ``softmax_dtype="bfloat16"`` keeps that [batch..., vocab] tail in
    bf16 — the per-example losses still reduce to the scalar loss in
    f32, so the training signal accumulates at full precision. Default
    ``None`` preserves the f32 tail bit-exactly
    (docs/training_performance.md).
    """
    compute_dtype: str = "bfloat16"
    loss_scale: Optional[float] = None
    softmax_dtype: Optional[str] = None
    ce_tail_dtype: dataclasses.InitVar[Optional[str]] = None

    def __post_init__(self, ce_tail_dtype: Optional[str]) -> None:
        if ce_tail_dtype is not None:
            if (self.softmax_dtype is not None
                    and self.softmax_dtype != ce_tail_dtype):
                raise ValueError(
                    f"softmax_dtype={self.softmax_dtype!r} and its alias "
                    f"ce_tail_dtype={ce_tail_dtype!r} disagree — pass one")
            self.softmax_dtype = ce_tail_dtype

    def to_json(self) -> dict:
        return {"compute_dtype": self.compute_dtype,
                "loss_scale": self.loss_scale,
                "softmax_dtype": self.softmax_dtype}

    @staticmethod
    def from_json(d) -> "Optional[MixedPrecision]":
        if d is None:
            return None
        return MixedPrecision(compute_dtype=d.get("compute_dtype", "bfloat16"),
                              loss_scale=d.get("loss_scale"),
                              softmax_dtype=d.get("softmax_dtype",
                                                  d.get("ce_tail_dtype")))


# ce_tail_dtype is BOTH a constructor alias (the InitVar above) and a
# read alias of softmax_dtype; the property is attached after class
# creation because defining it in the body would shadow the InitVar's
# class-attribute default and feed the property object to __post_init__
MixedPrecision.ce_tail_dtype = property(lambda self: self.softmax_dtype)


@dataclasses.dataclass
class TrainingConfig:
    updater: IUpdater
    data_set_feature_mapping: Sequence[str] = ()
    data_set_label_mapping: Sequence[str] = ()
    regularization: Sequence[Regularization] = ()
    grad_clip_value: Optional[float] = None
    minibatch: bool = True
    iteration_count: int = 0
    epoch_count: int = 0
    mixed_precision: Optional[MixedPrecision] = None
    # gradient normalization mode (reference:
    # BaseMultiLayerUpdater.preApply :395 / GradientNormalization enum):
    # None | "clip_element_wise_absolute_value" | "clip_l2_per_layer" |
    # "clip_l2_global" | "renormalize_l2_per_layer"
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    # unroll factor for the scanned whole-epoch fit path (compile-time
    # cost vs fewer while-loop iterations; runtime-tuning knob, not serde)
    scan_unroll: int = 1
    # fused training windows (autodiff/window.py): K consecutive train
    # steps execute as ONE compiled lax.scan dispatch, with per-step
    # losses buffered on device and flushed to listeners at window
    # boundaries. 1 = per-step dispatch (the legacy tier). Works with
    # listeners AND host-streaming iterators — unlike the scanned
    # whole-epoch tier, which needs neither.
    fused_steps: int = 1
    # gradient accumulation: micro-batch grads accumulate in the window
    # scan carry and the updater applies every ``accum_steps``-th
    # micro-step (grads averaged — an effective batch of
    # accum_steps * batch). 1 = update every step.
    accum_steps: int = 1
    # NaN/Inf panic (reference: DefaultOpExecutioner ProfilingMode
    # NAN_PANIC/INF_PANIC): fit() checks fetched losses and raises
    # NumericsException naming the iteration; localize the producing op
    # with sd.exec_debug(). Step-internal per-op checks are impossible
    # under whole-graph jit, so the check granularity is the loss fetch.
    # Defaults from the runtime Environment ($DL4J_TPU_NAN_PANIC /
    # $DL4J_TPU_DEBUG, reference: Environment.h debug mode).
    nan_panic: bool = dataclasses.field(default_factory=lambda: bool(
        _env().get("nan_panic") or _env().get("debug")))
    # device-side divergence sentinel (faults/sentinels.py): the compiled
    # step additionally emits isfinite(loss) AND an isfinite check over
    # EVERY gradient leaf (SameDiff._sentinel_ok — deliberately not a
    # sampled leaf); fused windows fold it into the scan carry (one
    # extra scalar per window, no per-step host sync) and the fit tiers
    # raise a structured faults.TrainingDivergedError naming
    # step/epoch/batch. Parameter math is untouched — sentinel-on
    # training is bit-identical.
    sentinel: bool = False
    # declarative mesh sharding (parallel.ShardingSpec, serde'd like
    # every other field): when set, SameDiff.fit places params/state on
    # the spec's device mesh and shards input batches before tier
    # selection, so DP/TP training composes with fused windows, the
    # sentinel carry and AOT precompile without the ParallelTrainer
    # front end. The spec carries INTENT (axis sizes with one -1 fill,
    # rule preset, per-layer rules); the strategy binds to whatever
    # devices the process has — the elastic-resume contract
    # (docs/elastic_training.md).
    sharding: Optional[Any] = None
    # in-graph per-layer tensor statistics (monitor/tensorstats.py):
    # True (defaults) or a TensorStatsConfig. The compiled step
    # additionally summarizes gradients/updates/params per layer (L2,
    # mean|x|, min/max, nonfinite count, fixed log2-magnitude
    # histogram) every Nth step, folded into the scan carry like the
    # sentinel and fetched at the flush boundaries the host already
    # syncs on. Requires the listener rail (per-step or fused-window
    # tier with listeners) to deliver {"type": "tensorstats"} records;
    # parameter math is untouched — stats-on training is bit-identical.
    tensorstats: Optional[Any] = None
    # bitwise state fingerprints (integrity/fingerprint.py): the
    # compiled window additionally emits one uint32 digest of
    # params + state vars + optimizer state (a word-sum folded in
    # like the sentinel — one extra int per window), read at the
    # flush boundaries the host already syncs on. Checkpoint captures
    # compare it against the host bytes and stamp the snapshot;
    # restores re-verify the stamp; mismatch raises a typed
    # faults.SilentCorruptionError. Parameter math is untouched —
    # fingerprints-on training is bit-identical (bench.py
    # integrity_overhead, ≤2% bar with the stall watchdog armed too).
    fingerprints: bool = False
    # replay probe cadence (windows): every Nth window is re-dispatched
    # from a stashed carry and the two digests compared — genuine
    # in-dispatch SDC/nondeterminism disagrees. Costs 1/N extra
    # compute; 0 = off.
    fingerprint_replay_every: int = 0
    # cross-replica agreement cadence (flushes): every Nth listener
    # flush compares per-replica digests of DP-sharded params bitwise
    # (integrity.check_replica_agreement). 0 = off.
    fingerprint_replica_every: int = 0
    # pre-compile static analysis (analyze/, docs/static_analysis.md):
    # fit()/precompile() walk the graph + this config WITHOUT compiling
    # and surface structured findings (shape mismatches with producer
    # chains, numerics hazards, sharding/cadence/mapping lint). True =
    # error-severity findings warn (GraphAnalysisWarning) and the fit
    # proceeds; "strict" = raise GraphAnalysisError BEFORE any XLA
    # compile; False = off. Analysis runs once per graph version, so
    # its cost never touches the warm dispatch path (bench.py
    # analyze_overhead).
    analyze: Any = True

    def __post_init__(self):
        if self.tensorstats is not None:
            from deeplearning4j_tpu.monitor.tensorstats import normalize
            self.tensorstats = normalize(self.tensorstats)

    def clip_gradients(self, grads):
        """Apply elementwise clip + the configured normalization mode to a
        gradient pytree (traced inside the compiled train step)."""
        import jax
        import jax.numpy as jnp
        if self.grad_clip_value is not None:
            c = self.grad_clip_value
            grads = jax.tree_util.tree_map(lambda g: jnp.clip(g, -c, c),
                                           grads)
        mode = (self.gradient_normalization or "none").lower()
        if mode in ("none", ""):
            return grads
        t = self.gradient_normalization_threshold
        eps = 1e-8
        if mode == "clip_element_wise_absolute_value":
            return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), grads)
        if mode == "clip_l2_per_layer":
            def _clip(g):
                n = jnp.sqrt(jnp.sum(jnp.square(g)))
                return g * jnp.minimum(1.0, t / (n + eps))
            return jax.tree_util.tree_map(_clip, grads)
        if mode in ("clip_l2_global", "clip_by_global_norm"):
            leaves = jax.tree_util.tree_leaves(grads)
            gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))
            scale = jnp.minimum(1.0, t / (gn + eps))
            return jax.tree_util.tree_map(lambda g: g * scale, grads)
        if mode == "renormalize_l2_per_layer":
            return jax.tree_util.tree_map(
                lambda g: g / (jnp.sqrt(jnp.sum(jnp.square(g))) + eps), grads)
        raise ValueError(f"unknown gradient_normalization {mode!r}")

    def to_json(self) -> dict:
        return {
            "updater": self.updater.to_json(),
            "data_set_feature_mapping": list(self.data_set_feature_mapping),
            "data_set_label_mapping": list(self.data_set_label_mapping),
            "regularization": [r.to_json() for r in self.regularization],
            "grad_clip_value": self.grad_clip_value,
            "minibatch": self.minibatch,
            "iteration_count": self.iteration_count,
            "epoch_count": self.epoch_count,
            "mixed_precision": (self.mixed_precision.to_json()
                                if self.mixed_precision else None),
            "gradient_normalization": self.gradient_normalization,
            "gradient_normalization_threshold":
                self.gradient_normalization_threshold,
            "fused_steps": self.fused_steps,
            "accum_steps": self.accum_steps,
            "sentinel": self.sentinel,
            # the fit path also accepts a live ShardingStrategy here;
            # serialize it through its declarative to_spec() form
            "sharding": (None if self.sharding is None
                         else (self.sharding
                               if hasattr(self.sharding, "to_json")
                               else self.sharding.to_spec()).to_json()),
            "tensorstats": (None if self.tensorstats is None
                            else self.tensorstats.to_json()),
            "fingerprints": self.fingerprints,
            "fingerprint_replay_every": self.fingerprint_replay_every,
            "fingerprint_replica_every": self.fingerprint_replica_every,
            "analyze": (self.analyze if isinstance(self.analyze,
                                                   (bool, str))
                        else bool(self.analyze)),
        }

    @staticmethod
    def from_json(d: dict) -> "TrainingConfig":
        sharding = None
        if d.get("sharding") is not None:
            from deeplearning4j_tpu.parallel.sharding import ShardingSpec
            sharding = ShardingSpec.from_json(d["sharding"])
        tensorstats = None
        if d.get("tensorstats") is not None:
            from deeplearning4j_tpu.monitor.tensorstats import \
                TensorStatsConfig
            tensorstats = TensorStatsConfig.from_json(d["tensorstats"])
        return TrainingConfig(
            updater=IUpdater.from_json(d["updater"]),
            data_set_feature_mapping=d.get("data_set_feature_mapping", []),
            data_set_label_mapping=d.get("data_set_label_mapping", []),
            regularization=[Regularization.from_json(r)
                            for r in d.get("regularization", [])],
            grad_clip_value=d.get("grad_clip_value"),
            minibatch=d.get("minibatch", True),
            iteration_count=d.get("iteration_count", 0),
            epoch_count=d.get("epoch_count", 0),
            mixed_precision=MixedPrecision.from_json(d.get("mixed_precision")),
            gradient_normalization=d.get("gradient_normalization"),
            gradient_normalization_threshold=d.get(
                "gradient_normalization_threshold", 1.0),
            fused_steps=d.get("fused_steps", 1),
            accum_steps=d.get("accum_steps", 1),
            sentinel=d.get("sentinel", False),
            sharding=sharding,
            tensorstats=tensorstats,
            fingerprints=d.get("fingerprints", False),
            fingerprint_replay_every=d.get("fingerprint_replay_every", 0),
            fingerprint_replica_every=d.get("fingerprint_replica_every",
                                            0),
            analyze=d.get("analyze", True),
        )

    class Builder:
        """Fluent builder matching the reference's TrainingConfig.Builder."""

        def __init__(self):
            self._kw: Dict[str, Any] = {}

        def updater(self, u):             self._kw["updater"] = u; return self
        def data_set_feature_mapping(self, *names):
            self._kw["data_set_feature_mapping"] = list(names); return self
        def data_set_label_mapping(self, *names):
            self._kw["data_set_label_mapping"] = list(names); return self
        def regularization(self, *regs):  self._kw["regularization"] = list(regs); return self
        def grad_clip_value(self, v):     self._kw["grad_clip_value"] = v; return self
        def minibatch(self, b):           self._kw["minibatch"] = b; return self
        def mixed_precision(self, mp):
            if mp is True:
                mp = MixedPrecision()
            self._kw["mixed_precision"] = mp; return self
        def gradient_normalization(self, mode, threshold: float = 1.0):
            self._kw["gradient_normalization"] = mode
            self._kw["gradient_normalization_threshold"] = threshold
            return self
        def fused_steps(self, k: int):
            self._kw["fused_steps"] = int(k); return self
        def accum_steps(self, n: int):
            self._kw["accum_steps"] = int(n); return self
        def sentinel(self, on: bool = True):
            self._kw["sentinel"] = bool(on); return self
        def sharding(self, spec):
            self._kw["sharding"] = spec; return self
        def tensorstats(self, cfg=True):
            self._kw["tensorstats"] = cfg; return self
        def fingerprints(self, on: bool = True, replay_every: int = 0,
                         replica_every: int = 0):
            """Bitwise state fingerprints (integrity/): capture/restore
            verification plus the optional replay-probe and
            cross-replica-agreement cadences."""
            self._kw["fingerprints"] = bool(on)
            self._kw["fingerprint_replay_every"] = int(replay_every)
            self._kw["fingerprint_replica_every"] = int(replica_every)
            return self
        def analyze(self, mode=True):
            """Pre-compile static analysis: True (warn), "strict"
            (raise GraphAnalysisError before any compile), False."""
            self._kw["analyze"] = mode; return self
        def build(self) -> "TrainingConfig":
            return TrainingConfig(**self._kw)

    @staticmethod
    def builder() -> "TrainingConfig.Builder":
        return TrainingConfig.Builder()


class LossCurve:
    """Per-epoch mean loss (reference: listeners.records.LossCurve)."""

    def __init__(self):
        self.epochs: List[int] = []
        self.losses: List[float] = []

    def add(self, epoch: int, loss: float):
        self.epochs.append(epoch)
        self.losses.append(loss)

    def mean_loss(self, epoch: int) -> float:
        return self.losses[self.epochs.index(epoch)]

    def last(self) -> float:
        return self.losses[-1] if self.losses else float("nan")


class History:
    """Training run record (reference: listeners.records.History)."""

    def __init__(self):
        self.loss_curve = LossCurve()

    def add_epoch(self, epoch: int, mean_loss: float):
        self.loss_curve.add(epoch, mean_loss)

    def final_loss(self) -> float:
        return self.loss_curve.last()


class Listener:
    """Training listener (reference: autodiff.listeners.Listener /
    dl4j TrainingListener). Return False from on_epoch_end to stop.

    Loss scalars live on device; forcing one to a python float costs a
    device round-trip that serializes the dispatch pipeline. fit()
    therefore buffers per-step losses and delivers them in bursts via
    ``iterations_done`` every ``frequency`` steps (ONE transfer per
    burst). The default implementation replays ``iteration_done`` per
    step, so simple listeners just implement that."""

    #: how often (in iterations) this listener needs scalars delivered
    frequency: int = 10

    def on_training_start(self, sd): ...
    def on_training_end(self, sd): ...
    def on_epoch_start(self, sd, epoch: int): ...
    def on_epoch_end(self, sd, epoch: int, mean_loss: float): ...
    def iteration_done(self, sd, epoch: int, iteration: int, loss: float): ...

    def iterations_done(self, sd, epoch: int, iterations: Sequence[int],
                        losses: Sequence[float]):
        for it, lo in zip(iterations, losses):
            self.iteration_done(sd, epoch, it, lo)

    def tensorstats_done(self, sd, epoch: int,
                         records: Sequence[dict]):
        """Per-layer tensor-statistics delivery (``TrainingConfig.
        tensorstats``, monitor/tensorstats.py): fit() calls this right
        after ``iterations_done`` at each flush whose burst contained
        sampled stats, with the fetched ``{"type": "tensorstats"}``
        records. Default: ignore."""


class ScoreIterationListener(Listener):
    """Print score every N iterations (reference:
    optimize/listeners/ScoreIterationListener)."""

    def __init__(self, print_every: int = 10, print_fn=print):
        self.print_every = print_every
        self.frequency = print_every
        self.print_fn = print_fn

    def iteration_done(self, sd, epoch, iteration, loss):
        if iteration % self.print_every == 0:
            self.print_fn(f"Score at iteration {iteration} is {loss}")


class PerformanceListener(Listener):
    """Throughput metrics: samples/sec, batches/sec (reference:
    optimize/listeners/PerformanceListener.java:46-118)."""

    def __init__(self, frequency: int = 10, print_fn=print):
        self.frequency = frequency
        self.print_fn = print_fn
        self.batch_size = None  # auto-filled by fit() from the first batch
        self._last_time = None
        self._last_iter = None
        self._last_print_iter = None
        self.samples_per_sec = float("nan")
        self.batches_per_sec = float("nan")

    def iteration_done(self, sd, epoch, iteration, loss):
        self.iterations_done(sd, epoch, [iteration], [loss])

    def iterations_done(self, sd, epoch, iterations, losses):
        # burst delivery: timing spans the whole burst, so rates stay
        # honest — and the listener no longer forces per-step syncs
        now = time.perf_counter()
        iteration = iterations[-1]
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            n_batches = iteration - self._last_iter
            self.batches_per_sec = n_batches / dt
            if self.batch_size:
                self.samples_per_sec = self.batch_size * self.batches_per_sec
            # bursts may arrive more often than this listener's frequency
            # (the fit loop flushes at the MIN frequency across listeners) —
            # keep printing on our own cadence
            if self._last_print_iter is None or \
                    iteration - self._last_print_iter >= self.frequency:
                self._last_print_iter = iteration
                self.print_fn(
                    f"iteration {iteration}: {self.batches_per_sec:.1f} batches/sec"
                    + (f", {self.samples_per_sec:.1f} samples/sec"
                       if self.batch_size else ""))
        self._last_time = now
        self._last_iter = iteration


class CheckpointListener(Listener):
    """Periodic model save (reference: optimize/listeners/CheckpointListener
    + autodiff/listeners/checkpoint/CheckpointListener): keep-last-N,
    every-N-epochs.

    Legacy whole-model-zip variant. Production checkpointing lives in
    ``deeplearning4j_tpu.checkpoint`` (``checkpoint.CheckpointListener``):
    asynchronous writes, atomic commits with integrity manifests,
    iteration/seconds cadences, retention policies, and bit-exact
    resume including updater/RNG state."""

    def __init__(self, save_dir, every_n_epochs: int = 1, keep_last: int = 3):
        import os
        self.save_dir = str(save_dir)
        self.every_n_epochs = every_n_epochs
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(self.save_dir, exist_ok=True)

    def on_epoch_end(self, sd, epoch, mean_loss):
        import os
        if (epoch + 1) % self.every_n_epochs != 0:
            return
        path = os.path.join(self.save_dir, f"checkpoint_epoch_{epoch}.zip")
        sd.save(path, include_updater_state=True)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            if os.path.exists(old):
                os.remove(old)

    def last_checkpoint(self) -> Optional[str]:
        return self._saved[-1] if self._saved else None


class EarlyStoppingListener(Listener):
    """Stop when the score stops improving (reference: earlystopping/
    EarlyStoppingTrainer + termination conditions, compressed into a
    listener since fit() owns the loop here)."""

    def __init__(self, patience: int = 5, min_delta: float = 0.0,
                 max_epochs: Optional[int] = None):
        self.patience = patience
        self.min_delta = min_delta
        self.max_epochs = max_epochs
        self.best_loss = float("inf")
        self.best_epoch = -1
        self.stopped_epoch = None

    def on_epoch_end(self, sd, epoch, mean_loss):
        if mean_loss < self.best_loss - self.min_delta:
            self.best_loss = mean_loss
            self.best_epoch = epoch
            return None
        if epoch - self.best_epoch >= self.patience or \
                (self.max_epochs is not None and epoch + 1 >= self.max_epochs):
            self.stopped_epoch = epoch
            return False
        return None


class FailureTestingListener(Listener):
    """Fault injection for robustness testing (reference:
    optimize/listeners/FailureTestingListener.java:19 — FailureMode
    {OOM, SYSTEM_EXIT_1, ILLEGAL_STATE, INFINITE_SLEEP} x CallType
    trigger points). TPU-native subset: raising and sleeping; process
    exit/OOM are not simulated in-process (the elastic-restart test
    kills training with the EXCEPTION mode instead, see
    parallel/multihost.ElasticTrainer).

    failure_mode: "exception" | "illegal_state" | "sleep"
    trigger: "epoch_start" | "epoch_end" | "iteration" | "training_start"
    at: epoch or iteration number that fires the fault (-1 = first call)
    sleep_seconds: used by the sleep mode
    """

    class InjectedFailure(RuntimeError):
        pass

    #: deliver scalars every iteration — a fault at iteration N must fire
    #: before N+1 trains, not at the next burst flush
    frequency = 1

    def __init__(self, failure_mode: str = "exception",
                 trigger: str = "iteration", at: int = -1,
                 sleep_seconds: float = 0.1):
        self.failure_mode = failure_mode.lower()
        self.trigger = trigger.lower()
        self.at = at
        self.sleep_seconds = sleep_seconds
        self.fired = False

    def _fire(self, where: str):
        self.fired = True
        if self.failure_mode == "sleep":
            time.sleep(self.sleep_seconds)
            return
        if self.failure_mode == "illegal_state":
            raise RuntimeError(
                f"FailureTestingListener: injected illegal state at {where}")
        raise FailureTestingListener.InjectedFailure(
            f"FailureTestingListener: injected failure at {where}")

    def _should(self, n: int) -> bool:
        return not self.fired and (self.at < 0 or n == self.at)

    def on_training_start(self, sd):
        if self.trigger == "training_start" and self._should(0):
            self._fire("training start")

    def on_epoch_start(self, sd, epoch):
        if self.trigger == "epoch_start" and self._should(epoch):
            self._fire(f"epoch {epoch} start")

    def on_epoch_end(self, sd, epoch, mean_loss):
        if self.trigger == "epoch_end" and self._should(epoch):
            self._fire(f"epoch {epoch} end")

    def iteration_done(self, sd, epoch, iteration, loss):
        if self.trigger == "iteration" and self._should(iteration):
            self._fire(f"iteration {iteration}")
