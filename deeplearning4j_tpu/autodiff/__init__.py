"""SameDiff-equivalent autodiff (reference: org.nd4j.autodiff).

Define-then-run graphs compiled whole (forward + backward + updater) into
single XLA computations — see samediff.py for the design contrast with the
reference's op-by-op Java interpreter.
"""
from deeplearning4j_tpu.autodiff.samediff import SameDiff, OpNode
from deeplearning4j_tpu.autodiff.variable import SDVariable, VariableType
from deeplearning4j_tpu.autodiff.training import (
    TrainingConfig, MixedPrecision, History, Listener,
    ScoreIterationListener, PerformanceListener, CheckpointListener,
    EarlyStoppingListener,
)

__all__ = [
    "SameDiff", "SDVariable", "VariableType", "OpNode", "TrainingConfig",
    "MixedPrecision", "History", "Listener", "ScoreIterationListener",
    "PerformanceListener", "CheckpointListener", "EarlyStoppingListener",
]
