"""SameDiff-equivalent autodiff (reference: org.nd4j.autodiff).

Define-then-run graphs compiled whole (forward + backward + updater) into
single XLA computations — see samediff.py for the design contrast with the
reference's op-by-op Java interpreter.
"""
from deeplearning4j_tpu.autodiff.samediff import SameDiff, OpNode
from deeplearning4j_tpu.autodiff.variable import SDVariable, VariableType
from deeplearning4j_tpu.autodiff.training import (
    TrainingConfig, MixedPrecision, History, Listener,
    ScoreIterationListener, PerformanceListener, CheckpointListener,
    EarlyStoppingListener,
)
from deeplearning4j_tpu.autodiff.listeners_ext import (
    EvaluativeListener, SleepyListener, TimeIterationListener)
from deeplearning4j_tpu.autodiff.earlystopping import (
    BestScoreEpochTerminationCondition, ClassificationScoreCalculator,
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingResult,
    EarlyStoppingTrainer, InMemoryModelSaver, InvalidScoreTerminationCondition,
    LocalFileModelSaver, MaxEpochsTerminationCondition,
    MaxScoreTerminationCondition, MaxTimeTerminationCondition,
    ScoreImprovementEpochTerminationCondition)

__all__ = [
    "SameDiff", "SDVariable", "VariableType", "OpNode", "TrainingConfig",
    "MixedPrecision", "History", "Listener", "ScoreIterationListener",
    "PerformanceListener", "CheckpointListener", "EarlyStoppingListener",
    "EvaluativeListener", "TimeIterationListener", "SleepyListener",
    "EarlyStoppingConfiguration", "EarlyStoppingTrainer",
    "EarlyStoppingResult", "MaxEpochsTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition", "MaxTimeTerminationCondition",
    "MaxScoreTerminationCondition", "InvalidScoreTerminationCondition",
    "DataSetLossCalculator", "ClassificationScoreCalculator",
    "InMemoryModelSaver", "LocalFileModelSaver",
]
