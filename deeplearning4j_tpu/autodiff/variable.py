"""SDVariable — symbolic variable in a SameDiff-equivalent graph.

Reference parity: org.nd4j.autodiff.samediff.SDVariable (SDVariable.java:46)
and VariableType (VariableType.java). A variable is a named node:

- VARIABLE    : trainable parameter (has a value; receives gradients)
- CONSTANT    : fixed value (no gradient)
- PLACEHOLDER : fed at execution time
- ARRAY       : output of an op (computed, never stored)

Unlike the reference — where SDVariable wraps an INDArray that the Java
interpreter materializes per-op — here a variable is purely a graph name;
values only exist inside the single compiled XLA computation (or in the
parameter store for VARIABLE/CONSTANT).
"""
from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Optional, Sequence, Tuple, Union

if TYPE_CHECKING:
    from deeplearning4j_tpu.autodiff.samediff import SameDiff


class VariableType(enum.Enum):
    VARIABLE = "VARIABLE"
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"


class SDVariable:
    __slots__ = ("sd", "name", "var_type", "_shape", "_dtype")

    def __init__(self, sd: "SameDiff", name: str, var_type: VariableType,
                 shape: Optional[Tuple[int, ...]] = None, dtype: str = "float32"):
        self.sd = sd
        self.name = name
        self.var_type = var_type
        self._shape = tuple(shape) if shape is not None else None
        self._dtype = dtype

    # ------------------------------------------------------------------
    def __repr__(self):
        return (f"SDVariable(name={self.name!r}, type={self.var_type.value}, "
                f"shape={self._shape}, dtype={self._dtype})")

    @property
    def shape(self) -> Optional[Tuple[int, ...]]:
        if self._shape is None:
            self._shape = self.sd.infer_shape(self.name)
        return self._shape

    @property
    def dtype(self) -> str:
        return self._dtype

    def rank(self) -> int:
        s = self.shape
        return len(s) if s is not None else -1

    # value access ------------------------------------------------------
    def eval(self, placeholders=None):
        """Evaluate this variable (reference: SDVariable.eval())."""
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def get_arr(self):
        """Stored value for VARIABLE/CONSTANT (reference: SDVariable.getArr())."""
        return self.sd.get_arr_for_var(self.name)

    def set_arr(self, value):
        self.sd.set_arr_for_var(self.name, value)

    def rename(self, new_name: str) -> "SDVariable":
        return self.sd.rename_variable(self.name, new_name)

    def mark_as_loss(self) -> "SDVariable":
        if self.name not in self.sd.loss_variables:
            self.sd.set_loss_variables(
                list(self.sd.loss_variables) + [self.name])
        return self

    def convert_to_constant(self) -> "SDVariable":
        return self.sd.convert_to_constant(self)

    def convert_to_variable(self) -> "SDVariable":
        return self.sd.convert_to_variable(self)

    # op sugar ----------------------------------------------------------
    def _op(self, op_name: str, *others, name: Optional[str] = None, **attrs):
        inputs = [self] + [self.sd._lift(o) for o in others]
        return self.sd.invoke(op_name, inputs, attrs, name=name)

    # arithmetic
    def add(self, other, name=None):  return self._op("add", other, name=name)
    def sub(self, other, name=None):  return self._op("subtract", other, name=name)
    def mul(self, other, name=None):  return self._op("multiply", other, name=name)
    def div(self, other, name=None):  return self._op("divide", other, name=name)
    def rsub(self, other, name=None): return self.sd._lift(other)._op("subtract", self, name=name)
    def rdiv(self, other, name=None): return self.sd._lift(other)._op("divide", self, name=name)
    def pow(self, other, name=None):  return self._op("pow", other, name=name)
    def neg(self, name=None):         return self._op("neg", name=name)
    def fmod(self, other, name=None): return self._op("fmod", other, name=name)

    __add__ = add
    __sub__ = sub
    __mul__ = mul
    __truediv__ = div
    __pow__ = pow
    __neg__ = neg
    def __radd__(self, other): return self.sd._lift(other).add(self)
    def __rsub__(self, other): return self.sd._lift(other).sub(self)
    def __rmul__(self, other): return self.sd._lift(other).mul(self)
    def __rtruediv__(self, other): return self.sd._lift(other).div(self)

    # comparisons (return numeric mask like the reference)
    def gt(self, other, name=None):  return self._op("greater", other, name=name)
    def gte(self, other, name=None): return self._op("greater_equal", other, name=name)
    def lt(self, other, name=None):  return self._op("less", other, name=name)
    def lte(self, other, name=None): return self._op("less_equal", other, name=name)
    def eq(self, other, name=None):  return self._op("equals", other, name=name)
    def neq(self, other, name=None): return self._op("not_equals", other, name=name)

    # linalg
    def mmul(self, other, name=None):
        return self._op("matmul", other, name=name)

    def dot(self, other, name=None):
        return self._op("matmul", other, name=name)

    def tensordot(self, other, axes_a, axes_b, name=None):
        return self._op("tensordot", other, name=name, axes_a=axes_a, axes_b=axes_b)

    # reductions
    def _red(self, op_name, dims, keep_dims, name):
        attrs = {"keep_dims": keep_dims}
        if dims is not None:
            attrs["axis"] = tuple(dims) if isinstance(dims, (list, tuple)) else (dims,)
        return self._op(op_name, name=name, **attrs)

    def sum(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_sum", dims, keep_dims, name)

    def mean(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_mean", dims, keep_dims, name)

    def max(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_max", dims, keep_dims, name)

    def min(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_min", dims, keep_dims, name)

    def prod(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_prod", dims, keep_dims, name)

    def std(self, dims=None, keep_dims=False, bias_corrected=True, name=None):
        attrs = {"keep_dims": keep_dims, "bias_corrected": bias_corrected}
        if dims is not None:
            attrs["axis"] = tuple(dims) if isinstance(dims, (list, tuple)) else (dims,)
        return self._op("reduce_stdev", name=name, **attrs)

    def var(self, dims=None, keep_dims=False, bias_corrected=True, name=None):
        attrs = {"keep_dims": keep_dims, "bias_corrected": bias_corrected}
        if dims is not None:
            attrs["axis"] = tuple(dims) if isinstance(dims, (list, tuple)) else (dims,)
        return self._op("reduce_variance", name=name, **attrs)

    def norm1(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_norm1", dims, keep_dims, name)

    def norm2(self, dims=None, keep_dims=False, name=None):
        return self._red("reduce_norm2", dims, keep_dims, name)

    def argmax(self, dim=-1, name=None):
        return self._op("argmax", name=name, axis=dim)

    def argmin(self, dim=-1, name=None):
        return self._op("argmin", name=name, axis=dim)

    # shape ops
    def reshape(self, *shape, name=None):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return self._op("reshape", name=name, shape=shape)

    def permute(self, *dims, name=None):
        if len(dims) == 1 and isinstance(dims[0], (list, tuple)):
            dims = tuple(dims[0])
        return self._op("permute", name=name, axes=dims)

    def transpose(self, name=None):
        return self._op("transpose", name=name)

    def squeeze(self, axis=None, name=None):
        return self._op("squeeze", name=name, axis=axis)

    def expand_dims(self, axis, name=None):
        return self._op("expand_dims", name=name, axis=axis)

    def cast(self, dtype, name=None):
        return self._op("cast", name=name, dtype=str(dtype))

    def get(self, begin, end, strides=None, name=None):
        """Static slice (reference: SDVariable.get(SDIndex...))."""
        return self._op("strided_slice", name=name, begin=tuple(begin),
                        end=tuple(end), strides=tuple(strides) if strides else None)

    # common math sugar
    def abs(self, name=None):     return self._op("abs", name=name)
    def exp(self, name=None):     return self._op("exp", name=name)
    def log(self, name=None):     return self._op("log", name=name)
    def sqrt(self, name=None):    return self._op("sqrt", name=name)
    def square(self, name=None):  return self._op("square", name=name)
    def sigmoid(self, name=None): return self._op("sigmoid", name=name)
    def tanh(self, name=None):    return self._op("tanh", name=name)
    def relu(self, name=None):    return self._op("relu", name=name)
    def softmax(self, axis=-1, name=None):
        return self._op("softmax", name=name, axis=axis)
