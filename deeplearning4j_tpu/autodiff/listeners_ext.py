"""Listener breadth: EvaluativeListener, TimeIterationListener,
SleepyListener.

Reference parity: org.deeplearning4j.optimize.listeners —
EvaluativeListener.java (periodic holdout evaluation during fit),
TimeIterationListener.java (remaining-time ETA logging), and
SleepyTrainingListener.java (deliberate throttling at chosen points).
All hook the same burst-aware listener bus as the core listeners
(autodiff/training.Listener).
"""
from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.autodiff.training import Listener


class EvaluativeListener(Listener):
    """Evaluate on a holdout iterator every N epochs or iterations
    (reference: EvaluativeListener.java — InvocationType
    {EPOCH_END, ITERATION_END}).

    ``model`` must expose ``output``/``evaluate`` (MultiLayerNetwork,
    ComputationGraph); evaluations accumulate into ``results`` and the
    freshest one is in ``last_evaluation``.
    """

    def __init__(self, model, iterator, frequency: int = 1,
                 invocation: str = "epoch_end", evaluation_factory=None,
                 print_fn: Optional[Callable] = None):
        if invocation not in ("epoch_end", "iteration_end"):
            raise ValueError(f"unknown invocation {invocation!r}")
        from deeplearning4j_tpu.evaluation import Evaluation
        self.model = model
        self.iterator = iterator
        self.invocation = invocation
        self.eval_every = max(int(frequency), 1)
        # bus burst size (Listener.frequency) is a DIFFERENT axis than the
        # eval interval: epoch-end evaluation must not force per-iteration
        # loss flushes, so it leaves the bus cadence effectively unbounded
        if invocation == "iteration_end":
            self.frequency = self.eval_every
            # mid-epoch evaluation reads model params — fit() syncs them
            # into the graph at each flush when this is set
            self.needs_params = True
        else:
            self.frequency = 1_000_000_000
        self.evaluation_factory = evaluation_factory or Evaluation
        self.print_fn = print_fn
        self.results = []               # (epoch_or_iter, evaluation)
        self.last_evaluation = None

    def _evaluate(self, tag: int):
        ev = self.evaluation_factory()
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        self.model.evaluate(self.iterator, evaluation=ev)
        self.last_evaluation = ev
        self.results.append((tag, ev))
        if self.print_fn is not None:
            acc = ev.accuracy() if hasattr(ev, "accuracy") else None
            self.print_fn(f"EvaluativeListener at {self.invocation} {tag}: "
                          + (f"accuracy={acc:.4f}" if acc is not None
                             else repr(ev)))

    def on_epoch_end(self, sd, epoch, mean_loss):
        if self.invocation == "epoch_end" and \
                (epoch + 1) % self.eval_every == 0:
            self._evaluate(epoch)

    def iteration_done(self, sd, epoch, iteration, loss):
        if self.invocation == "iteration_end" and \
                iteration % self.eval_every == 0:
            self._evaluate(iteration)

    def iterations_done(self, sd, epoch, iterations, losses):
        if self.invocation != "iteration_end":
            return
        # bursts may span several eval points; evaluate once per burst if
        # any iteration in it crossed the interval
        if any(i % self.eval_every == 0 for i in iterations):
            self._evaluate(iterations[-1])


class TimeIterationListener(Listener):
    """Log estimated remaining training time (reference:
    TimeIterationListener.java — linear extrapolation from elapsed time
    over completed iterations toward ``total_iterations``)."""

    def __init__(self, total_iterations: int, frequency: int = 50,
                 print_fn=print):
        self.total_iterations = int(total_iterations)
        self.frequency = max(int(frequency), 1)
        self.print_fn = print_fn
        self.start_time = None
        self._last_print = 0
        self.remaining_seconds = float("nan")

    def on_training_start(self, sd):
        self.start_time = time.perf_counter()

    def iteration_done(self, sd, epoch, iteration, loss):
        # prints on elapsed-iteration count, not modulo — burst sizes set
        # by OTHER listeners must not be able to starve the ETA line
        if self.start_time is None:
            self.start_time = time.perf_counter()
            return
        done = iteration + 1
        if done - self._last_print < self.frequency:
            return
        self._last_print = done
        elapsed = time.perf_counter() - self.start_time
        rate = elapsed / max(done, 1)
        self.remaining_seconds = rate * max(
            self.total_iterations - done, 0)
        mins, secs = divmod(int(self.remaining_seconds), 60)
        self.print_fn(f"iteration {done}/{self.total_iterations}: "
                      f"estimated {mins}m{secs:02d}s remaining")

    def iterations_done(self, sd, epoch, iterations, losses):
        self.iteration_done(sd, epoch, iterations[-1], losses[-1])


class SleepyListener(Listener):
    """Throttle training by sleeping at chosen points (reference:
    SleepyTrainingListener.java — per-callback sleep durations used to
    simulate slow hosts / pace device submission in tests)."""

    frequency = 1           # sleeps must fire per-iteration, not per-burst

    def __init__(self, on_iteration_ms: float = 0.0,
                 on_epoch_start_ms: float = 0.0,
                 on_epoch_end_ms: float = 0.0):
        self.on_iteration_ms = on_iteration_ms
        self.on_epoch_start_ms = on_epoch_start_ms
        self.on_epoch_end_ms = on_epoch_end_ms
        self.sleep_count = 0

    def _sleep(self, ms: float):
        if ms > 0:
            self.sleep_count += 1
            time.sleep(ms / 1000.0)

    def iteration_done(self, sd, epoch, iteration, loss):
        self._sleep(self.on_iteration_ms)

    def on_epoch_start(self, sd, epoch):
        self._sleep(self.on_epoch_start_ms)

    def on_epoch_end(self, sd, epoch, mean_loss):
        self._sleep(self.on_epoch_end_ms)
