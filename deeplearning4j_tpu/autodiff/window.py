"""Fused multi-step training windows: K train steps per compiled dispatch.

BENCH_r05 showed the per-step fit tier is host-dispatch-bound on small
models (lenet_mnist ~1.4 ms/step at ~1.8% MFU): the device finishes the
step long before the host can enqueue the next one, and the scanned
whole-epoch tier that fixes this was only reachable with zero listeners
and a fully device-cached dataset. This module makes the fused path work
under PRODUCTION constraints:

- **K steps, one dispatch** — ``SameDiff.make_train_window`` scans the
  train-step body over a ``(K, batch, ...)`` stacked window, so the
  per-epoch dispatch count drops from ``steps`` to ``ceil(steps / K)``.
- **listeners keep working** — per-step losses accumulate in the scan's
  device-side ``(K,)`` output buffer; the burst-flush machinery from the
  per-step tier delivers them via ``Listener.iterations_done`` at window
  boundaries (one device→host transfer per flush). Checkpoint flushes
  stay bit-exact: params + updater state + the iteration counter sync at
  window boundaries, which is exactly the granularity the checkpoint/
  listener contract records (a saved step is always a window boundary).
  Exception: the gradient-accumulation carry is NOT part of the
  checkpoint schema — with ``accum_steps > 1`` use a checkpoint cadence
  that is a multiple of ``accum_steps`` (docs/training_performance.md).
- **streaming data keeps working** — a background ``WindowStager`` thread
  stacks the NEXT window's batches and enqueues its host→HBM transfer
  while the current window computes (double buffering, queue depth 2).
- **ragged final windows stay fused** — a tail of ``r < K`` steps is
  decomposed into power-of-two buckets (serving-style shape bucketing:
  at most ``log2(K)+1`` compiled window lengths EVER, vs one compile per
  distinct tail if dispatched raw, vs per-step dispatch if not fused).
- **gradient accumulation rides along** — ``TrainingConfig.accum_steps``
  accumulates micro-batch grads in the scan carry and applies the
  updater every N-th micro-step (see ``make_train_window``); the accum
  carry threads BETWEEN windows, so accumulation cycles may span window
  boundaries.

The reference has no analogue: DL4J dispatched per-op, its
GradientsAccumulator shared grads across workers but never fused steps.
This is the lax.scan generalization of the whole-epoch tier (SURVEY
L3/L4) to the listener + streaming-ETL workloads production runs have.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.compilecache.aot import ph_shape_sig
from deeplearning4j_tpu.integrity.watchdog import guard as _wd_guard
from deeplearning4j_tpu.monitor import memstats
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer


def pow2_buckets(r: int) -> List[int]:
    """Binary decomposition of a ragged tail length into descending
    powers of two — the bounded compiled-shape set (serving/ bucketing
    idiom applied to window lengths). ``pow2_buckets(13) == [8, 4, 1]``."""
    out = []
    b = 1
    while r > 0:
        if r & 1:
            out.append(b)
        r >>= 1
        b <<= 1
    return out[::-1]


class WindowStager:
    """Background double-buffering window stager.

    Pulls raw ``{placeholder: array}`` batch dicts from ``source``,
    stacks ``window`` of them on a new leading axis, finalizes the stack
    (dtype coercion + device placement — this is where the host→HBM
    transfer of the NEXT window is enqueued while the CURRENT one
    computes), and hands ``(k, stacked)`` pairs to the consumer through
    a bounded queue (``depth=2`` → classic double buffering).

    Stacking happens host-side (one ``np.stack`` + ONE transfer per
    window) when the batches are host arrays, and device-side
    (``jnp.stack`` of resident slices) when they already live in HBM
    (DeviceCachedIterator, pre-sharded batches).

    Shutdown is leak-proof: ``close()`` (also called by ``__iter__``'s
    ``finally``) sets a stop flag, drains the queue to unblock the
    worker's bounded put, and joins the thread — abandoning the
    iterator mid-epoch cannot strand a blocked thread.
    """

    _END = object()

    def __init__(self, source, window: int, finalize=None, depth: int = 2):
        self._source = source
        self._window = max(1, int(window))
        self._finalize = finalize or (lambda d: d)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    # -- worker side ----------------------------------------------------
    def _put(self, item) -> bool:
        """Bounded put that aborts when the consumer is gone."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _stack(self, batches: List[Dict[str, object]]):
        # the H2D stage of the window pipeline: stacking + the enqueue
        # of the next window's host→HBM transfer, on the stager thread
        # (its own swimlane in the chrome trace — overlap with the
        # consumer's dispatch lane is the double-buffering working)
        with _tracer.span("h2d_stage", cat="train", k=len(batches)):
            names = batches[0].keys()
            stacked = {}
            h2d_bytes = 0
            for n in names:
                items = [b[n] for b in batches]
                if all(isinstance(a, np.ndarray) for a in items):
                    stacked[n] = np.stack(items)
                    h2d_bytes += stacked[n].nbytes
                else:
                    stacked[n] = jnp.stack([jnp.asarray(a) for a in items])
            if h2d_bytes:
                # tagged host→HBM transfer accounting: the staging
                # bytes surface in {"type": "memory"} records
                # (memory.AllocationsTracker is thread-safe — this runs
                # on the stager thread)
                from deeplearning4j_tpu.memory import AllocationsTracker
                AllocationsTracker.get_instance().allocate(
                    "h2d_stage", h2d_bytes)
            return len(batches), self._finalize(stacked)

    def _emit_bucketed(self, buf) -> bool:
        i = 0
        for k in pow2_buckets(len(buf)):
            if not self._put(self._stack(buf[i:i + k])):
                return False
            i += k
        return True

    @staticmethod
    def _sig(batch) -> tuple:
        return tuple(sorted((n, tuple(np.shape(v)))
                            for n, v in batch.items()))

    def _worker(self):
        try:
            buf: List[Dict[str, object]] = []
            sig = None
            for b in self._source:
                if self._stop.is_set():
                    return
                # only same-shaped batches stack into one window: a
                # ragged final BATCH (fewer rows than the rest) flushes
                # the current buffer and forms its own (smaller-shape)
                # window — the same extra compiled shape the per-step
                # tier pays for it
                bsig = self._sig(b)
                if buf and bsig != sig:
                    if not self._emit_bucketed(buf):
                        return
                    buf = []
                if not buf:
                    sig = bsig
                buf.append(b)
                if len(buf) == self._window:
                    if not self._put(self._stack(buf)):
                        return
                    buf = []
            # ragged tail → bounded power-of-two buckets
            if buf and not self._emit_bucketed(buf):
                return
        except BaseException as e:     # propagate to the consumer
            self._err = e
        finally:
            # close a closeable source (generators) from THIS thread —
            # the one that iterated it: an abandoned mid-epoch stager
            # then deterministically releases whatever the source holds
            # (a streaming pipeline's prefetch workers, datapipe/)
            # instead of waiting for GC to run its finally
            close = getattr(self._source, "close", None)
            if callable(close):
                try:
                    close()
                except Exception:      # noqa: BLE001 — shutdown path;
                    pass               # the consumer's error (if any)
                #                        is already in self._err
            self._put(self._END)

    # -- consumer side --------------------------------------------------
    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._END:
                    break
                yield item
        finally:
            self.close()
        if self._err is not None:
            raise self._err

    def close(self):
        self._stop.set()
        while True:                    # unblock a worker stuck on put
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5)


def window_trace_set(sd, accum_steps: int, sentinel: bool,
                     ts_key=None, fingerprint: bool = False) -> set:
    """The per-(graph version, accum, sentinel, tensorstats,
    fingerprint) set of window trace signatures already compiled. This
    is the ONE key construction, shared by the executor's compile
    accounting below and ``SameDiff.precompile()``'s pre-registration —
    if the key shape changed in only one place, precompiled sigs would
    land in a set fit never reads and ``window_compiles`` would
    silently report nonzero after a precompile (the same drift
    ``ph_shape_sig`` was unified to prevent for the signature itself).
    ``ts_key`` is ``TensorStatsConfig.key()`` or None (stats-free)."""
    return sd.__dict__.setdefault("_window_traces", {}) \
        .setdefault((sd._version, accum_steps, sentinel, ts_key,
                     bool(fingerprint)), set())


def fit_windowed(sd, dataset_iterator, epochs: int = 1, listeners=()):
    """The fused-window fit tier (``TrainingConfig.fused_steps`` /
    ``accum_steps``). Called by ``SameDiff.fit`` — see its docstring for
    the tier contract. Structure mirrors the per-step loop; the unit of
    dispatch is a window instead of a step."""
    from deeplearning4j_tpu.autodiff.samediff import (NumericsException,
                                                      _split_batch)
    from deeplearning4j_tpu.autodiff.training import History

    tc = sd.training_config
    K = max(1, int(getattr(tc, "fused_steps", 1) or 1))
    A = max(1, int(getattr(tc, "accum_steps", 1) or 1))
    use_sentinel = bool(getattr(tc, "sentinel", False))
    # bitwise state fingerprints (integrity/fingerprint.py): one extra
    # uint32 output per window, read only at flush boundaries; the
    # optional replay probe re-dispatches every Nth window from a
    # stashed carry and compares digests, and the optional replica
    # check compares per-replica digests every Nth flush
    fp_on = bool(getattr(tc, "fingerprints", False))
    probe_every = int(getattr(tc, "fingerprint_replay_every", 0) or 0) \
        if fp_on else 0
    replica_every = int(getattr(tc, "fingerprint_replica_every", 0) or 0) \
        if fp_on else 0
    sd._device_fingerprint = None
    if fp_on:
        from deeplearning4j_tpu.integrity.fingerprint import (
            check_probes, check_replica_agreement)
    # in-graph tensor statistics (monitor/tensorstats.py): only with
    # listeners — the records ride the listener rail; a listener-free
    # fit dispatches the stats-free window
    ts_cfg = getattr(tc, "tensorstats", None) if listeners else None
    window_fn = sd.make_train_window(accum_steps=A, sentinel=use_sentinel,
                                     tensorstats=ts_cfg,
                                     fingerprint=fp_on)
    # window_fn donates param/state buffers; work on copies so the
    # graph's stored arrays stay valid for output()/save() mid-fit
    params = jax.tree_util.tree_map(jnp.copy, sd.trainable_params())
    svars = jax.tree_util.tree_map(jnp.copy, sd.state_vars_map())
    if sd._updater_state is not None and \
            set(sd._updater_state.keys()) == set(params.keys()):
        state = jax.tree_util.tree_map(jnp.copy, sd._updater_state)
    else:
        state = tc.updater.init(params)
    constants = sd.constants_map()
    iteration = int(getattr(tc, "iteration_count", 0))
    it_dev = jnp.asarray(iteration, jnp.int32)
    accum = None
    if A > 1:
        # resume a mid-cycle accumulation from the previous fit: the
        # apply phase is (iteration+1) % A on the ABSOLUTE iteration, so
        # a fit ending mid-cycle leaves partial grads that the next fit
        # must continue from (otherwise those micro-batches are lost)
        prev = getattr(sd, "_grad_accum", None)
        if prev is not None and set(prev.keys()) == set(params.keys()) \
                and iteration % A != 0:
            accum = jax.tree_util.tree_map(jnp.copy, prev)
        else:
            accum = jax.tree_util.tree_map(jnp.zeros_like, params)
    # resumable RNG contract (checkpoint/state.py): per-step keys are
    # fold_in(key(base_seed), absolute_iteration)
    sd._fit_base_seed = sd._seed
    base_key = jax.random.key(sd._seed)
    sd._seed += 1
    history = History()
    deferred_means = []                # device scalars, fetched at fit end
    panic = sd._nan_panic_active(tc)
    for l in listeners:
        l.on_training_start(sd)
    flush_every = min((max(1, int(getattr(l, "frequency", 10)))
                       for l in listeners), default=0)
    # next absolute iteration whose crossing triggers a listener flush
    next_flush = (iteration // flush_every + 1) * flush_every \
        if flush_every else 0
    sync_params_on_flush = any(getattr(l, "needs_params", False)
                               for l in listeners)
    # compiled window lengths (jit retraces per leading-dim K): tracked
    # per (graph version, accum) so stats report real compile counts
    seen_sizes = window_trace_set(
        sd, A, use_sentinel, ts_cfg.key() if ts_cfg is not None else None,
        fp_on)
    # last window's device digest (a device scalar until fetched at a
    # flush / fit end) + probe/replica bookkeeping shared across epochs
    last_fp_box: List[Optional[jax.Array]] = [None]
    replica_mark = [0]
    win_count = 0
    probes_total = 0
    if ts_cfg is not None:
        from deeplearning4j_tpu.monitor.tensorstats import layer_names
        ts_names = layer_names(params)
    else:
        ts_names = ()

    def _name_batch(batch):
        if isinstance(batch, dict):
            # dict keys may be SDVariables (same contract as the
            # per-step tier's _prep_placeholders)
            from deeplearning4j_tpu.autodiff.variable import SDVariable
            return {k.name if isinstance(k, SDVariable) else k: v
                    for k, v in batch.items()}
        feats, labels = _split_batch(batch)
        ph = dict(zip(tc.data_set_feature_mapping, feats))
        ph.update(zip(tc.data_set_label_mapping, labels))
        return ph

    window_sharding = getattr(dataset_iterator, "window_sharding", None)
    # sharding specs are a pure function of rank: build each ONCE here
    # (stager setup) instead of per window per tensor — at post-fusion
    # window times the repeated PartitionSpec/NamedSharding construction
    # was measurable host work between dispatches (monitor/ steptime
    # attributes it to data_wait)
    _sharding_by_rank: Dict[int, object] = {}

    def _window_spec(ndim):
        spec = _sharding_by_rank.get(ndim)
        if spec is None:
            spec = _sharding_by_rank[ndim] = window_sharding(ndim)
        return spec

    def _finalize(stacked):
        ph = sd._prep_placeholders(stacked)
        if window_sharding is not None:
            ph = {k: jax.device_put(v, _window_spec(v.ndim))
                  for k, v in ph.items()}
        return ph

    # device-cached source (stacked_batches): the window content is
    # identical every epoch, so build the window list ONCE as device
    # slices of the pre-stacked arrays and reuse it — no stager thread,
    # no per-epoch re-stack/re-upload churn
    cached_windows = None
    if hasattr(dataset_iterator, "stacked_batches"):
        feats, labels = dataset_iterator.stacked_batches()
        stacked = _finalize(dict(
            list(zip(tc.data_set_feature_mapping, feats)) +
            list(zip(tc.data_set_label_mapping, labels))))
        n_steps = next(iter(stacked.values())).shape[0]
        parts, i = [], 0
        while n_steps - i >= K:
            parts.append((i, K))
            i += K
        for k in pow2_buckets(n_steps - i):
            parts.append((i, k))
            i += k
        cached_windows = [(k, {nm: a[j:j + k] for nm, a in stacked.items()})
                          for j, k in parts]

    stop = False
    for epoch in range(epochs):
        epoch_losses: List[float] = []       # floats (listener path)
        epoch_loss_bufs: List[jax.Array] = []  # device (K,) buffers
        pending = []                         # (start_iter, k, (k,) losses)
        pending_bads: List[jax.Array] = []   # sentinel scalars, device
        epoch_bads: List[jax.Array] = []     # ... for the listener-free path
        pending_stats: List[tuple] = []      # (stats pytree, at) device
        pending_probes: List[tuple] = []     # (start_iter, fp, fp_replay)
        epoch_probes: List[tuple] = []       # ... listener-free variant
        epoch_start_iter = iteration
        dispatches = 0
        compiles = 0
        sizes: Dict[int, int] = {}     # window length -> dispatch count

        def _check_bads(bads):
            """Device-sentinel verdicts for a burst of windows: ONE
            stacked fetch; the first non-negative entry is the absolute
            iteration of the diverged step (faults/sentinels.py)."""
            if not bads:
                return
            from deeplearning4j_tpu.faults.sentinels import check_bad_steps
            fetched = np.asarray(jnp.stack(bads))
            bads.clear()
            check_bad_steps(fetched, epoch, epoch_start_iter)

        def _fetch_flush():
            """The device-sync half of a listener flush: fetch the loss
            burst (+ sentinel verdicts), sync training state. Returns
            the (iters, vals) burst for :func:`_deliver`, or None. Split
            from delivery so the ``flush`` span records the WINDOW
            boundary's device wait (as a child of the window span that
            triggered it) while listener callbacks run outside it."""
            if not pending:
                return None
            iters: List[int] = []
            for start, k, _ in pending:
                iters.extend(range(start, start + k))
            ts_recs: List[dict] = []
            with _tracer.span("flush", cat="train", steps=len(iters)):
                losses_cat = jnp.concatenate([lv for _, _, lv in pending])
                # losses + sentinel verdicts + sampled tensorstats +
                # fingerprints/probe digests in ONE device→host
                # transfer; poisoned windows must not feed listeners/
                # checkpoints, so verdicts are checked (and may raise)
                # before the burst is delivered
                bads_stack = jnp.stack(pending_bads) if pending_bads \
                    else None
                stats_burst = list(pending_stats)
                pending_stats.clear()
                probes = list(pending_probes)
                pending_probes.clear()
                probes_stack = jnp.stack(
                    [jnp.stack((a, b)) for _, a, b in probes]) \
                    if probes else None
                fp_dev = last_fp_box[0] if fp_on else None
                try:
                    with _wd_guard("flush"):
                        vals_arr, bads, stats_host, fp_host, probes_host \
                            = jax.device_get(
                                (losses_cat, bads_stack, stats_burst,
                                 fp_dev, probes_stack))
                except Exception as e:
                    # async dispatch: an allocation failure inside a
                    # window often surfaces HERE, at the first sync
                    memstats.reraise_oom(e, step=iters[-1] if iters
                                         else None, epoch=epoch)
                    raise
                if bads is not None:
                    from deeplearning4j_tpu.faults.sentinels import \
                        check_bad_steps
                    pending_bads.clear()
                    check_bad_steps(np.asarray(bads), epoch,
                                    epoch_start_iter)
                if fp_host is not None:
                    # the boundary digest a checkpoint capture at this
                    # flush verifies its host bytes against
                    sd._device_fingerprint = {"iteration": iters[-1] + 1,
                                              "fp": int(fp_host)}
                if probes:
                    # replay-probe verdicts gate delivery like the
                    # sentinel: a corrupted window's losses must not
                    # reach listeners/checkpoints
                    check_probes(np.asarray(probes_host),
                                 [s for s, _, _ in probes])
                if replica_every:
                    replica_mark[0] += 1
                    if replica_mark[0] % replica_every == 0:
                        check_replica_agreement({**params, **svars})
                if stats_burst:
                    # windows with no sample point carry at = -1 (zeros
                    # payload) and are dropped here
                    from deeplearning4j_tpu.monitor.tensorstats import \
                        build_record
                    ts_recs = [build_record(ts_names, s, int(at), epoch,
                                            ts_cfg)
                               for s, at in stats_host if int(at) >= 0]
            vals = [float(v) for v in vals_arr]
            epoch_losses.extend(vals)
            if sync_params_on_flush:
                # the FULL training state at the window boundary: a
                # checkpoint taken at this flush captures params, updater
                # state and the iteration counter of the LAST completed
                # window — bit-exact resume (checkpoint/listener.py)
                for n, p in {**params, **svars}.items():
                    sd._arrays[n] = jnp.copy(p)
                sd._updater_state = jax.tree_util.tree_map(jnp.copy, state)
                tc.iteration_count = iters[-1] + 1
            if panic:
                for it, v in zip(iters, vals):
                    if not np.isfinite(v):
                        raise NumericsException(
                            f"non-finite loss {v} at iteration {it} "
                            f"(nan_panic); localize the producing op with "
                            f"sd.exec_debug(placeholders)")
            pending.clear()
            return iters, vals, ts_recs

        def _deliver(flushed):
            if flushed is None:
                return
            iters, vals, ts_recs = flushed
            for l in listeners:
                l.iterations_done(sd, epoch, iters, vals)
            if ts_recs:
                for l in listeners:
                    hook = getattr(l, "tensorstats_done", None)
                    if hook is not None:
                        hook(sd, epoch, ts_recs)

        def _flush():
            _deliver(_fetch_flush())

        for l in listeners:
            l.on_epoch_start(sd, epoch)
        if cached_windows is not None:
            stager, source = None, cached_windows
        else:
            if hasattr(dataset_iterator, "reset"):
                dataset_iterator.reset()
            # a real generator expression (not map()): the stager closes
            # its source on shutdown, and generator .close() propagates
            # GeneratorExit into a streaming pipeline's generator —
            # releasing its prefetch workers deterministically
            # (map objects have no close())
            stager = WindowStager(
                (_name_batch(b) for b in iter(dataset_iterator)),
                K, finalize=_finalize)
            source = stager
        _END_OF_DATA = object()
        src_iter = iter(source)
        try:
            while True:
                # one "window" span per dispatch unit, with data_wait /
                # dispatch (and, when this window crosses a listener
                # cadence, flush) children — the trace rows ui/report's
                # step-time breakdown and monitor/steptime.py attribute
                flushed = None
                with _tracer.span("window", cat="train") as wspan:
                    with _tracer.span("data_wait", cat="train"):
                        item = next(src_iter, _END_OF_DATA)
                    if item is _END_OF_DATA:
                        wspan.discard()
                        break
                    k, win = item
                    wspan.set(k=k, iteration=iteration)
                    for l in listeners:
                        if getattr(l, "batch_size", -1) is None:
                            l.batch_size = next(iter(win.values())).shape[1]
                    # jit retraces per full placeholder shape set (a
                    # ragged final BATCH recompiles even at an
                    # already-seen k); the signature is the same key
                    # AOT dispatch uses, so shapes prebuilt by
                    # sd.precompile() count as already-seen
                    trace_sig = ph_shape_sig(win)
                    first_dispatch = trace_sig not in seen_sizes
                    if first_dispatch:
                        seen_sizes.add(trace_sig)
                        compiles += 1
                        sd._verbose_log(f"fit: compiling window length {k}")
                    bad = None
                    with _tracer.span("dispatch", cat="train", k=k):
                        # positional output layout (make_train_window):
                        # p, sv, st, [accum], it, losses, [bad],
                        # [stats, at], [fp]
                        if A > 1:
                            args = (params, svars, state, accum, it_dev,
                                    constants, win, base_key)
                        else:
                            args = (params, svars, state, it_dev,
                                    constants, win, base_key)
                        # replay probe (integrity/fingerprint.py): stash
                        # copies of the donated carry BEFORE the main
                        # dispatch so the window can be re-dispatched
                        # from identical inputs and the two digests
                        # compared at the next flush
                        probe_this = probe_every and \
                            win_count % probe_every == probe_every - 1
                        if probe_this:
                            stash = jax.tree_util.tree_map(
                                jnp.copy, args[:5 if A > 1 else 4])
                        win_count += 1
                        if first_dispatch:
                            # with plan capture armed (MonitorListener),
                            # a new shape compiles through the AOT path
                            # so its memory plan is captured — same
                            # lowering, one compile either way, outputs
                            # bit-identical (tests/test_memory_obs.py)
                            memstats.promote_dispatch(
                                window_fn, args, trace_sig,
                                f"window_k{k}", steps=k, graph=sd)
                        try:
                            with _wd_guard("window_dispatch",
                                           first=first_dispatch):
                                out = window_fn(*args)
                        except Exception as e:
                            memstats.reraise_oom(e,
                                                 program=f"window_k{k}",
                                                 step=iteration,
                                                 epoch=epoch)
                            raise
                        memstats.note_dispatch(trace_sig, steps=k)
                        if A > 1:
                            params, svars, state, accum = out[:4]
                            i = 4
                        else:
                            params, svars, state = out[:3]
                            i = 3
                        it_dev = out[i]
                        losses = out[i + 1]
                        i += 2
                        if use_sentinel:
                            bad = out[i]
                            i += 1
                        if ts_cfg is not None:
                            pending_stats.append((out[i], out[i + 1]))
                            i += 2
                        if fp_on:
                            last_fp_box[0] = out[i]
                            i += 1
                        if probe_this:
                            # second dispatch of the SAME window from
                            # the stash (which it donates); only its
                            # digest is kept — compared at the flush
                            with _tracer.span("integrity.replay_probe",
                                              cat="integrity", k=k), \
                                    _wd_guard("window_dispatch"):
                                out2 = window_fn(*stash, constants, win,
                                                 base_key)
                            probes_total += 1
                            # fp is the LAST window output by layout
                            (pending_probes if listeners
                             else epoch_probes).append(
                                (iteration, out[-1], out2[-1]))
                    dispatches += 1
                    sizes[k] = sizes.get(k, 0) + 1
                    if bad is not None:
                        (pending_bads if listeners
                         else epoch_bads).append(bad)
                    if listeners:
                        pending.append((iteration, k, losses))
                        iteration += k
                        # flush at the FIRST window boundary at-or-after
                        # each multiple of the listener cadence (absolute
                        # iterations), so an every-N listener sees its
                        # burst as soon as a boundary crosses N — not
                        # only when a full N steps have buffered
                        # (docs/checkpointing.md)
                        if iteration >= next_flush:
                            flushed = _fetch_flush()
                            next_flush = (iteration // flush_every + 1) \
                                * flush_every
                    else:
                        epoch_loss_bufs.append(losses)
                        iteration += k
                # listener callbacks run OUTSIDE the window span: their
                # cost is user code, not executor time
                _deliver(flushed)
        finally:
            if stager is not None:
                stager.close()
        # listener-free sentinel path: one stacked verdict fetch per epoch
        _check_bads(epoch_bads)
        if epoch_probes:
            # listener-free replay probes: one stacked digest fetch
            fetched = np.asarray(jnp.stack(
                [jnp.stack((a, b)) for _, a, b in epoch_probes]))
            starts = [s for s, _, _ in epoch_probes]
            epoch_probes.clear()
            check_probes(fetched, starts)
        if listeners:
            _flush()
            if flush_every:
                next_flush = (iteration // flush_every + 1) * flush_every
            mean_loss = float(np.mean(epoch_losses)) \
                if epoch_losses else float("nan")
        elif panic:
            mean_loss = float(jnp.mean(jnp.concatenate(epoch_loss_bufs))) \
                if epoch_loss_bufs else float("nan")
            if epoch_loss_bufs and not np.isfinite(mean_loss):
                raise NumericsException(
                    f"non-finite epoch-{epoch} mean loss {mean_loss} "
                    f"(nan_panic); localize with sd.exec_debug()")
        else:
            # mean on device, fetch deferred to fit end (one transfer)
            mean_loss = None
            deferred_means.append(
                jnp.mean(jnp.concatenate(epoch_loss_bufs))
                if epoch_loss_bufs else jnp.asarray(float("nan")))
        history.add_epoch(epoch, mean_loss)
        tc.epoch_count = getattr(tc, "epoch_count", 0) + 1
        sd.last_fit_stats = {
            "tier": "windowed", "fused_steps": K, "accum_steps": A,
            "steps_per_epoch": iteration - epoch_start_iter,
            "dispatches_per_epoch": dispatches,
            "window_sizes": sizes, "window_compiles": compiles,
            "sentinel": use_sentinel, "fingerprints": fp_on,
            "replay_probes": probes_total}
        if listeners:
            # sync current training state into the graph (copies — the
            # next window donates the working buffers)
            for n, p in {**params, **svars}.items():
                sd._arrays[n] = jnp.copy(p)
            sd._updater_state = jax.tree_util.tree_map(jnp.copy, state)
            tc.iteration_count = iteration
        for l in listeners:
            if l.on_epoch_end(sd, epoch, mean_loss) is False:
                stop = True
        if stop:
            break
    if deferred_means:
        fetched = np.asarray(jnp.stack(deferred_means))
        history.loss_curve.losses = [float(v) for v in fetched]
    # write trained params back into the graph
    for n, p in {**params, **svars}.items():
        sd._arrays[n] = p
    sd._updater_state = state
    sd._grad_accum = accum         # partial accumulation survives the fit
    tc.iteration_count = iteration
    if fp_on and last_fp_box[0] is not None:
        cur = sd._device_fingerprint
        if cur is None or cur.get("iteration") != iteration:
            # listener-free (or post-final-flush) boundary digest for
            # checkpoint captures taken after this fit
            sd._device_fingerprint = {
                "iteration": int(iteration),
                "fp": int(jax.device_get(last_fp_box[0]))}
    for l in listeners:
        l.on_training_end(sd)
    return history
