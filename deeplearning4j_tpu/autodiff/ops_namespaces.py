"""Op namespaces on SameDiff: sd.math, sd.nn, sd.cnn, sd.rnn, sd.loss, …

Reference parity: the generated namespace classes SDMath/SDNN/SDCNN/SDRNN/
SDLoss/SDImage/SDLinalg/SDRandom/SDBitwise (nd4j autodiff/samediff/ops/,
produced by the codegen module from the op DSL). The reference generates
~5k lines of Java per namespace; here namespaces are *views over the op
registry* — every registered op is exposed as a method that records a graph
node, so new ops appear in the API the moment they are registered.

Method call convention: positional SDVariable args become graph inputs;
positional non-variables are bound to the op function's parameter names as
static attributes (the reference's iArgs/tArgs/bArgs); keyword args are
static attributes. For arithmetic categories, bare scalars/arrays are
lifted to CONSTANT variables (so ``sd.math.subtract(1.0, x)`` works like
the reference's rsub).
"""
from __future__ import annotations

import inspect
from typing import Dict, Optional

from deeplearning4j_tpu.autodiff.variable import SDVariable
from deeplearning4j_tpu.ops import registry

# ops whose jax function returns a tuple
MULTI_OUTPUT = {
    "batchnorm_train": 3, "gru_layer": 2, "lstm_cell": 2, "lstm_layer": 3,
    "lu": 2, "moments": 2, "non_max_suppression": 2, "normalize_moments": 2,
    "simple_rnn_layer": 2, "sufficient_statistics": 3, "top_k": 2, "unique": 2,
}

# categories where bare numeric positional args are operands, not attrs
_LIFT_CATEGORIES = {"pairwise", "elementwise", "bitwise", "linalg", "reduce"}

# variable-output ops: attrs key giving the output count
_VARIADIC_OUT = {"split": "num_split", "dynamic_partition": "num_partitions"}


def _signature_info(fn):
    """(positional param names, has *args)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return [], False
    names = [p.name for p in sig.parameters.values()
             if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                           inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    varargs = any(p.kind == inspect.Parameter.VAR_POSITIONAL
                  for p in sig.parameters.values())
    return names, varargs


class OpCaller:
    __slots__ = ("_sd", "_op")

    def __init__(self, sd, op: registry.Op):
        self._sd = sd
        self._op = op

    def __call__(self, *args, name: Optional[str] = None,
                 n_outputs: Optional[int] = None, **attrs):
        sd, o = self._sd, self._op
        pos_names, varargs = _signature_info(o.fn)
        inputs = []
        static = dict(attrs)
        for i, a in enumerate(args):
            if isinstance(a, SDVariable):
                inputs.append(a)
            elif o.category in _LIFT_CATEGORIES:
                inputs.append(sd._lift(a))
            elif varargs:
                # *xs ops (concat/stack/...): every positional is an operand;
                # attrs like axis must be keywords — binding by index would
                # silently misassign them
                raise TypeError(
                    f"op {o.name!r} takes variadic tensor inputs; pass "
                    f"non-tensor argument {a!r} as a keyword (e.g. axis=...)")
            else:
                pname = pos_names[i] if i < len(pos_names) else f"arg{i}"
                static[pname] = a
        if n_outputs is None:
            n_outputs = MULTI_OUTPUT.get(o.name, 1)
            if o.name in _VARIADIC_OUT and _VARIADIC_OUT[o.name] in static:
                n_outputs = int(static[_VARIADIC_OUT[o.name]])
            elif o.name == "unstack":
                # output count = extent of the unstacked axis
                shape = inputs[0].shape
                if shape is None:
                    raise ValueError("unstack needs a statically-known input "
                                     "shape (or pass n_outputs=)")
                n_outputs = shape[int(static.get("axis", 0))]
        return sd.invoke(o.name, inputs, static, name=name, n_outputs=n_outputs)


class OpNamespace:
    """One namespace (e.g. sd.math); methods resolve lazily from the registry."""

    def __init__(self, sd, label: str, categories):
        self._sd = sd
        self._label = label
        self._categories = frozenset(categories)

    def _resolve(self, item: str) -> registry.Op:
        for cand in (item, f"random_{item}" if self._label == "random" else None):
            if cand and registry.has_op(cand):
                o = registry.get_op(cand)
                if o.category in self._categories:
                    return o
        raise AttributeError(
            f"no op {item!r} in namespace {self._label} "
            f"(categories {sorted(self._categories)})")

    def __getattr__(self, item: str):
        if item.startswith("_"):
            raise AttributeError(item)
        return OpCaller(self._sd, self._resolve(item))

    def __dir__(self):
        names = []
        for cat, ops in registry.ops_by_category().items():
            if cat in self._categories:
                names.extend(ops)
        return sorted(names)


def make_namespaces(sd) -> Dict[str, OpNamespace]:
    nn_like = ("nn",)
    return {
        "math": OpNamespace(sd, "math", ("elementwise", "pairwise", "reduce")),
        "nn": OpNamespace(sd, "nn", nn_like + ("elementwise", "loss")),
        "cnn": OpNamespace(sd, "cnn", nn_like + ("image",)),
        "rnn": OpNamespace(sd, "rnn", nn_like),
        "loss": OpNamespace(sd, "loss", ("loss",)),
        "image": OpNamespace(sd, "image", ("image", "nn")),
        "linalg": OpNamespace(sd, "linalg", ("linalg",)),
        "random": OpNamespace(sd, "random", ("random",)),
        "bitwise": OpNamespace(sd, "bitwise", ("bitwise",)),
        "shape": OpNamespace(sd, "shape", ("shape",)),
    }
