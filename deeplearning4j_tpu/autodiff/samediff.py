"""SameDiff-equivalent define-then-run autodiff graph.

Reference parity: org.nd4j.autodiff.samediff.SameDiff (SameDiff.java) — the
graph is a map of variables + ops; training/inference walk it. The reference
executes **op-by-op in a Java interpreter** with per-op JNI dispatch
(InferenceSession.java:690, TrainingSession.java:74); gradients come from a
separately-built grad graph via per-op doDiff (SameDiff.java:4999
createGradFunction).

TPU-native redesign (SURVEY.md §7 stage 4): the graph records op *names*
from the registry; execution *traces* the pruned DAG into a pure jax
function and compiles it ONCE with jax.jit. Gradients come from jax.grad of
that traced function — no hand-maintained grad graph, no per-op dispatch at
runtime, and the whole training step (forward + backward + updater) is a
single XLA computation in which the compiler fuses elementwise chains into
matmuls and schedules the MXU. Parameters are donated across steps so HBM
holds one copy.

Execution caches are keyed by (graph version, output set, placeholder
shapes/dtypes) — the analogue of the reference's per-thread InferenceSession
map (SameDiff.java:126), except a cache hit costs a dict lookup instead of
an interpreter pass.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.autodiff.variable import SDVariable, VariableType
from deeplearning4j_tpu.compilecache.aot import (AOTDispatch,
                                                 AOTOutput as _AOTOutput,
                                                 ph_shape_sig)
from deeplearning4j_tpu.monitor import memstats
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.ndarray.dtype import DataType
from deeplearning4j_tpu.ndarray.ndarray import NDArray
from deeplearning4j_tpu.ops import registry


class NumericsException(ArithmeticError):
    """Raised by numerics panic modes (reference: the ND4JIllegalState
    thrown by DefaultOpExecutioner NAN_PANIC/INF_PANIC checks)."""


def _to_jnp(value, dtype=None):
    if isinstance(value, NDArray):
        value = value.data
    arr = jnp.asarray(value)
    if dtype is not None:
        arr = arr.astype(DataType.from_any(dtype).jnp)
    return arr


@dataclasses.dataclass
class OpNode:
    """One recorded op (reference: samediff.internal.SameDiffOp)."""
    name: str                 # unique node name
    op: str                   # registry op name
    inputs: List[str]         # input variable names
    outputs: List[str]        # output variable names
    attrs: Dict[str, Any]     # static attributes (iArgs/tArgs/bArgs analogue)
    random: bool = False      # needs a PRNG key threaded at trace time
    group: Optional[str] = None  # remat group id (see SameDiff.remat_scope)


class SameDiff:
    """Define-then-run graph with whole-graph XLA compilation."""

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._arrays: Dict[str, jax.Array] = {}   # VARIABLE/CONSTANT values
        self._ops: Dict[str, OpNode] = {}
        self._op_order: List[str] = []            # creation order = topo order
        self._producer: Dict[str, str] = {}       # var name -> op node name
        self._name_counter: Dict[str, int] = {}
        self.loss_variables: List[str] = []
        # non-trainable state vars (e.g. BN running stats): carried through
        # the compiled step, updated from graph outputs, never given to the
        # updater (reference: BatchNormalization's self-updated mean/var
        # params, excluded from the gradient view)
        self._state_var_names: set = set()
        self._state_updates: Dict[str, str] = {}  # state var -> source output
        self._version = 0                         # bump on any mutation
        self._fn_cache: Dict[Any, Any] = {}
        self._active_group: Optional[str] = None  # current remat_scope id
        self._group_counter = 0
        self.training_config = None
        self._updater_state = None
        self._seed = 0
        # pre-compile static analysis (analyze/): the last
        # AnalysisReport fit()/precompile() produced, plus the cache
        # key (graph version + context) that makes repeat fits pay a
        # dict lookup, not a re-analysis
        self.last_analysis = None
        self._analysis_key = None
        # dispatch/compile accounting of the most recent fit() epoch
        # (tier, dispatches_per_epoch, window sizes/compiles) — consumed
        # by ui/stats StatsListener and bench.py
        self.last_fit_stats = None
        # op namespaces (reference: SDMath/SDNN/... generated classes)
        from deeplearning4j_tpu.autodiff.ops_namespaces import make_namespaces
        for ns_name, ns in make_namespaces(self).items():
            setattr(self, ns_name, ns)

    # ------------------------------------------------------------------
    # naming
    def _unique_name(self, base: str) -> str:
        if base not in self._vars and base not in self._ops:
            return base
        while True:
            i = self._name_counter.get(base, 0) + 1
            self._name_counter[base] = i
            cand = f"{base}_{i}"
            if cand not in self._vars and cand not in self._ops:
                return cand

    def _mutated(self):
        self._version += 1
        self._fn_cache.clear()

    @property
    def training_config(self):
        return self._training_config

    @training_config.setter
    def training_config(self, tc):
        # assigning a new config must invalidate compiled train steps — the
        # closure bakes in updater/regularization/clip hyperparameters
        self._training_config = tc
        self._mutated()

    # ------------------------------------------------------------------
    # variable creation (reference: SameDiff.var/constant/placeHolder)
    def var(self, name: str = "var", shape: Optional[Sequence[int]] = None,
            dtype: str = "float32", value=None,
            weight_init: Optional[Callable] = None) -> SDVariable:
        """Trainable VARIABLE. Provide ``value`` or ``shape`` (+ optional
        ``weight_init(shape) -> array``)."""
        name = self._unique_name(name)
        if value is not None:
            arr = _to_jnp(value, dtype)
        elif shape is not None:
            if weight_init is not None:
                arr = _to_jnp(weight_init(tuple(shape)), dtype)
            else:
                arr = jnp.zeros(tuple(shape), DataType.from_any(dtype).jnp)
        else:
            raise ValueError("var() needs value= or shape=")
        v = SDVariable(self, name, VariableType.VARIABLE, arr.shape,
                       str(arr.dtype))
        self._vars[name] = v
        self._arrays[name] = arr
        self._mutated()
        return v

    def constant(self, value, name: str = "const", dtype=None) -> SDVariable:
        name = self._unique_name(name)
        arr = _to_jnp(value, dtype)
        v = SDVariable(self, name, VariableType.CONSTANT, arr.shape,
                       str(arr.dtype))
        self._vars[name] = v
        self._arrays[name] = arr
        self._mutated()
        return v

    def placeholder(self, name: str, shape: Optional[Sequence[int]] = None,
                    dtype: str = "float32") -> SDVariable:
        """PLACEHOLDER fed at exec time; -1/None dims = batch dims."""
        name = self._unique_name(name)
        shp = tuple(-1 if (d is None or d == -1) else int(d) for d in shape) \
            if shape is not None else None
        v = SDVariable(self, name, VariableType.PLACEHOLDER, None, dtype)
        v._shape = shp
        self._vars[name] = v
        self._mutated()
        return v

    # alias matching the reference API
    place_holder = placeholder

    def zero(self, name, shape, dtype="float32"):
        return self.constant(jnp.zeros(tuple(shape), DataType.from_any(dtype).jnp), name)

    def one(self, name, shape, dtype="float32"):
        return self.constant(jnp.ones(tuple(shape), DataType.from_any(dtype).jnp), name)

    def _lift(self, value) -> SDVariable:
        """Coerce a python scalar/array into a CONSTANT variable."""
        if isinstance(value, SDVariable):
            if value.sd is not self:
                raise ValueError("variable belongs to a different SameDiff")
            return value
        return self.constant(value)

    # ------------------------------------------------------------------
    # graph access
    def variables(self) -> List[SDVariable]:
        return list(self._vars.values())

    def get_variable(self, name: str) -> SDVariable:
        return self._vars[name]

    def has_variable(self, name: str) -> bool:
        return name in self._vars

    def ops(self) -> List[OpNode]:
        return [self._ops[n] for n in self._op_order]

    def trainable_params(self) -> Dict[str, jax.Array]:
        return {n: self._arrays[n] for n, v in self._vars.items()
                if v.var_type == VariableType.VARIABLE
                and n not in self._state_var_names}

    def state_var(self, name: str, value, dtype: str = "float32") -> SDVariable:
        """Non-trainable state variable (e.g. BN running mean): updated via
        update_state(), not by the updater."""
        v = self.var(name, value=value, dtype=dtype)
        self._state_var_names.add(v.name)
        return v

    def update_state(self, state_var: Union[str, SDVariable],
                     new_value: Union[str, SDVariable]) -> None:
        """Declare that ``state_var`` takes the value of graph output
        ``new_value`` after each training step."""
        sn = state_var.name if isinstance(state_var, SDVariable) else state_var
        nn_ = new_value.name if isinstance(new_value, SDVariable) else new_value
        if sn not in self._state_var_names:
            raise ValueError(f"{sn!r} is not a state var")
        self._state_updates[sn] = nn_
        self._mutated()

    def state_vars_map(self) -> Dict[str, jax.Array]:
        return {n: self._arrays[n] for n in self._state_var_names}

    def constants_map(self) -> Dict[str, jax.Array]:
        return {n: self._arrays[n] for n, v in self._vars.items()
                if v.var_type == VariableType.CONSTANT}

    def placeholders(self) -> List[str]:
        return [n for n, v in self._vars.items()
                if v.var_type == VariableType.PLACEHOLDER]

    def get_arr_for_var(self, name: str):
        return NDArray(self._arrays[name]) if name in self._arrays else None

    def set_arr_for_var(self, name: str, value):
        v = self._vars[name]
        if v.var_type not in (VariableType.VARIABLE, VariableType.CONSTANT):
            raise ValueError(f"{name} is {v.var_type.value}; has no stored array")
        self._arrays[name] = _to_jnp(value)  # values are runtime args; no retrace

    def set_loss_variables(self, names: Sequence[Union[str, SDVariable]]):
        self.loss_variables = [n.name if isinstance(n, SDVariable) else n
                               for n in names]

    def rename_variable(self, old: str, new: str) -> SDVariable:
        if new in self._vars:
            raise ValueError(f"variable {new!r} already exists")
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._arrays:
            self._arrays[new] = self._arrays.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for node in self._ops.values():
            node.inputs = [new if i == old else i for i in node.inputs]
            node.outputs = [new if o == old else o for o in node.outputs]
        self.loss_variables = [new if n == old else n for n in self.loss_variables]
        if old in self._state_var_names:
            self._state_var_names.discard(old)
            self._state_var_names.add(new)
        self._state_updates = {
            (new if k == old else k): (new if s == old else s)
            for k, s in self._state_updates.items()}
        self._mutated()
        return v

    def convert_to_constant(self, v: SDVariable) -> SDVariable:
        if v.var_type != VariableType.VARIABLE:
            raise ValueError("only VARIABLE can convert to constant")
        v.var_type = VariableType.CONSTANT
        self._mutated()
        return v

    def convert_to_variable(self, v: SDVariable) -> SDVariable:
        if v.var_type != VariableType.CONSTANT:
            raise ValueError("only CONSTANT can convert to variable")
        v.var_type = VariableType.VARIABLE
        self._mutated()
        return v

    # ------------------------------------------------------------------
    # op recording (reference: DynamicCustomOp registration into the graph)
    def invoke(self, op_name: str, inputs: Sequence[SDVariable],
               attrs: Optional[Dict[str, Any]] = None,
               name: Optional[str] = None, n_outputs: int = 1) -> Union[SDVariable, List[SDVariable]]:
        """Record a registry op; returns its output variable(s)."""
        o = registry.get_op(op_name)
        attrs = dict(attrs or {})
        node_name = self._unique_name(name or op_name)
        is_random = o.needs_key    # op() folds category=="random" into it
        out_names = []
        for i in range(n_outputs):
            base = node_name if n_outputs == 1 else f"{node_name}:{i}"
            out_name = self._unique_name(base)
            ov = SDVariable(self, out_name, VariableType.ARRAY, None, "float32")
            self._vars[out_name] = ov
            out_names.append(out_name)
        node = OpNode(name=node_name, op=o.name,
                      inputs=[v.name for v in inputs], outputs=out_names,
                      attrs=attrs, random=is_random,
                      group=self._active_group)
        self._ops[node_name] = node
        self._op_order.append(node_name)
        for on in out_names:
            self._producer[on] = node_name
        self._mutated()
        outs = [self._vars[n] for n in out_names]
        return outs[0] if n_outputs == 1 else outs

    # ------------------------------------------------------------------
    # control flow (reference: AbstractSession.java:46-101 executes
    # Enter/Exit/Switch/Merge frames host-side; redesigned per ADR 0020's
    # invokable-subgraph direction, lowered to lax.while_loop/cond/scan —
    # see ops/control_flow.py for semantics + differentiability)
    @staticmethod
    def _var_shape(v) -> Optional[Tuple[int, ...]]:
        """Best-effort static shape: the .shape property runs lazy
        inference for ARRAY vars (derived op outputs), so control-flow
        bodies see real shapes, not just placeholder declarations."""
        try:
            return v.shape
        except Exception:
            return None

    def _record_subgraph(self, fn, arg_vars, arg_shapes=None,
                         prefix: str = "p"):
        from deeplearning4j_tpu.ops import control_flow as cf
        sub = SameDiff()
        phs = []
        for i, v in enumerate(arg_vars):
            shape = (arg_shapes[i] if arg_shapes is not None
                     else self._var_shape(v))
            ph = sub.placeholder(f"{prefix}{i}", shape=shape,
                                 dtype=getattr(v, "dtype", "float32"))
            phs.append(ph)
        res = fn(sub, *phs)
        if isinstance(res, SDVariable):
            res = [res]
        if not res:
            raise ValueError("control-flow subgraph returned no outputs")
        return cf.subgraph_to_json(sub, [p.name for p in phs],
                                   [r.name for r in res])

    def while_loop(self, cond_fn, body_fn, loop_vars, captures=(),
                   name: str = "while"):
        """Data-dependent loop: ``cond_fn(sub, *loop_vars, *captures) ->
        scalar bool var``, ``body_fn(sub, *loop_vars, *captures) -> new
        loop vars``. Returns the final loop vars. Lowered to
        ``lax.while_loop`` (forward-only; use scan() for gradients)."""
        loop_vars, captures = list(loop_vars), list(captures)
        allv = loop_vars + captures
        cg = self._record_subgraph(cond_fn, allv)
        bg = self._record_subgraph(body_fn, allv)
        if len(bg["outputs"]) != len(loop_vars):
            raise ValueError(
                f"while_loop body returned {len(bg['outputs'])} values "
                f"for {len(loop_vars)} loop vars")
        return self.invoke("while_loop", allv,
                           {"cond_graph": cg, "body_graph": bg,
                            "n_loop": len(loop_vars)},
                           name=name, n_outputs=len(loop_vars))

    def cond(self, pred, true_fn, false_fn, operands, name: str = "cond"):
        """Branch: ``true_fn/false_fn(sub, *operands) -> same-shaped
        outputs``. Lowered to ``lax.cond`` (differentiable)."""
        operands = list(operands)
        tg = self._record_subgraph(true_fn, operands)
        fg = self._record_subgraph(false_fn, operands)
        if len(tg["outputs"]) != len(fg["outputs"]):
            raise ValueError("cond branches must return the same arity")
        return self.invoke("cond_branch", [pred, *operands],
                           {"true_graph": tg, "false_graph": fg},
                           name=name, n_outputs=len(tg["outputs"]))

    def scan(self, body_fn, carries, scanned=(), captures=(),
             length: Optional[int] = None, reverse: bool = False,
             name: str = "scan"):
        """Static-trip recurrence: ``body_fn(sub, *carries, *x_slices,
        *captures) -> (new_carries..., per_step_outputs...)``; scanned
        vars are consumed along their leading axis. Returns final
        carries + stacked per-step outputs. Lowered to ``lax.scan`` —
        fully reverse-mode differentiable (the trainable-RNN path)."""
        carries, scanned, captures = (list(carries), list(scanned),
                                      list(captures))
        shapes = [self._var_shape(v) for v in carries]
        for v in scanned:
            s = self._var_shape(v)
            shapes.append(tuple(s[1:]) if s else None)
        shapes += [self._var_shape(v) for v in captures]
        bg = self._record_subgraph(body_fn, carries + scanned + captures,
                                   arg_shapes=shapes)
        n_out = len(bg["outputs"])
        if n_out < len(carries):
            raise ValueError("scan body must return at least the carries")
        return self.invoke("scan_loop", carries + scanned + captures,
                           {"body_graph": bg, "n_carry": len(carries),
                            "n_scan": len(scanned), "length": length,
                            "reverse": reverse},
                           name=name, n_outputs=n_out)

    def remat_scope(self, name: str = "remat"):
        """Context manager: ops recorded inside form a rematerialized
        (gradient-checkpointed) group — at trace time the group becomes one
        ``jax.checkpoint`` call, so its internal activations are NOT saved
        for the backward pass but recomputed from the group's inputs.

        The TPU-native memory/workspace lever (SURVEY §2.1 memory &
        workspaces): where the reference manages activation memory with
        workspaces + MemoryManager, here HBM held-live set is traded for
        FLOPs at the XLA level. Typical use: one scope per transformer
        layer, which drops activation memory from O(layers) to
        O(sqrt-ish) and lets batch/seq grow to MXU-saturating sizes::

            for i in range(num_layers):
                with sd.remat_scope(f"layer{i}"):
                    x = block(sd, x, ...)

        Nesting records the innermost scope only (one checkpoint level).
        """
        import contextlib

        @contextlib.contextmanager
        def _scope():
            prev = self._active_group
            self._group_counter += 1
            self._active_group = f"{name}#{self._group_counter}"
            try:
                yield
            finally:
                self._active_group = prev

        return _scope()

    # ------------------------------------------------------------------
    # tracing: graph -> pure jax function
    def _prune(self, outputs: Sequence[str]) -> List[OpNode]:
        """Subgraph of ops needed for ``outputs``, in recorded (topo) order.

        Reference: AbstractSession subgraph build (AbstractSession.java:140+).
        """
        needed_vars = set(outputs)
        needed_ops = set()
        for op_name in reversed(self._op_order):
            node = self._ops[op_name]
            if any(o in needed_vars for o in node.outputs):
                needed_ops.add(op_name)
                needed_vars.update(node.inputs)
        return [self._ops[n] for n in self._op_order if n in needed_ops]

    def _trace_fn(self, outputs: Tuple[str, ...]) -> Callable:
        """Build fn(params, constants, placeholders, key) -> {name: array}.

        Consecutive ops sharing a remat group (recorded under
        ``remat_scope``) execute inside one ``jax.checkpoint`` region:
        the group's boundary values are the only activations XLA keeps
        live for the backward pass."""
        order = self._prune(outputs)
        out_set = set(outputs)

        # segment the topo order into (group, [(global_idx, node), ...])
        segments: List[Tuple[Optional[str], List[Tuple[int, OpNode]]]] = []
        for idx, node in enumerate(order):
            g = node.group
            if segments and segments[-1][0] == g and g is not None:
                segments[-1][1].append((idx, node))
            else:
                segments.append((g, [(idx, node)]))

        def _run_nodes(nodes, env, key):
            for idx, node in nodes:
                o = registry.get_op(node.op)
                attrs = dict(node.attrs)
                if node.random:
                    attrs["key"] = jax.random.fold_in(key, idx)
                try:
                    args = [env[i] for i in node.inputs]
                except KeyError as e:
                    raise KeyError(
                        f"op {node.name!r} needs variable {e.args[0]!r} — "
                        f"missing placeholder?") from None
                res = o.fn(*args, **attrs)
                if isinstance(res, (tuple, list)):
                    for out_name, r in zip(node.outputs, res):
                        env[out_name] = r
                else:
                    env[node.outputs[0]] = res

        # per remat segment: external inputs (read, not produced inside)
        # and external outputs (produced inside, consumed later/returned)
        seg_specs = []
        for si, (g, nodes) in enumerate(segments):
            if g is None:
                seg_specs.append((None, nodes, None, None))
                continue
            produced = {o for _, n in nodes for o in n.outputs}
            ext_in, seen = [], set()
            for _, n in nodes:
                for i in n.inputs:
                    if i not in produced and i not in seen:
                        seen.add(i)
                        ext_in.append(i)
            later = set()
            for _, nodes2 in segments[si + 1:]:
                for _, n2 in nodes2:
                    later.update(n2.inputs)
            ext_out = [o for _, n in nodes for o in n.outputs
                       if o in later or o in out_set]
            seg_specs.append((g, nodes, ext_in, ext_out))

        def fn(params: Dict[str, jax.Array], constants: Dict[str, jax.Array],
               placeholders: Dict[str, jax.Array], key) -> Dict[str, jax.Array]:
            env: Dict[str, jax.Array] = {}
            env.update(constants)
            env.update(params)
            env.update(placeholders)
            for g, nodes, ext_in, ext_out in seg_specs:
                if g is None:
                    _run_nodes(nodes, env, key)
                    continue

                def seg_fn(k, *args, _nodes=nodes, _ein=ext_in,
                           _eout=ext_out):
                    local = dict(zip(_ein, args))
                    _run_nodes(_nodes, local, k)
                    return tuple(local[o] for o in _eout)

                try:
                    args = [env[i] for i in ext_in]
                except KeyError as e:
                    raise KeyError(
                        f"remat group {g!r} needs variable {e.args[0]!r} — "
                        f"missing placeholder?") from None
                res = jax.checkpoint(seg_fn)(key, *args)
                env.update(zip(ext_out, res))
            missing = [o for o in outputs if o not in env]
            if missing:
                raise KeyError(f"outputs not computable: {missing}")
            return {o: env[o] for o in outputs}

        return fn

    def _ph_sig(self, placeholders: Dict[str, jax.Array]):
        return tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in placeholders.items()))

    def _output_cache_key(self, out_names, ph):
        """The execution-cache key for an inference program — shared by
        output() and precompile_output() so an AOT executable installed
        by serving warmup is found by the exact lazy lookup (a drift
        between the two would silently reintroduce the first-request
        compile warmup exists to kill)."""
        return ("output", self._version, tuple(out_names),
                self._ph_sig(ph))

    def _prep_placeholders(self, placeholders) -> Dict[str, jax.Array]:
        out = {}
        for k, v in (placeholders or {}).items():
            if isinstance(k, SDVariable):
                k = k.name
            out[k] = _to_jnp(v, self._vars[k].dtype if k in self._vars else None)
        return out

    # ------------------------------------------------------------------
    # inference (reference: SameDiff.output, SameDiff.java:2568)
    def output(self, placeholders=None, outputs: Optional[Sequence[Union[str, SDVariable]]] = None,
               key=None) -> Dict[str, NDArray]:
        if outputs is None:
            outputs = self.outputs()
        out_names = tuple(o.name if isinstance(o, SDVariable) else o
                          for o in outputs)
        ph = self._prep_placeholders(placeholders)
        cache_key = self._output_cache_key(out_names, ph)
        compiled = self._fn_cache.get(cache_key)
        if compiled is None:
            fn = self._trace_fn(out_names)
            compiled = jax.jit(fn)
            self._fn_cache[cache_key] = compiled
        if key is None:
            key = jax.random.key(self._seed)
            self._seed += 1
        res = compiled({**self.trainable_params(), **self.state_vars_map()},
                       self.constants_map(), ph, key)
        return {k: NDArray(v) for k, v in res.items()}

    # reference names
    exec = output
    batch_output = output

    def exec_debug(self, placeholders=None, outputs=None, key=None,
                   check: str = "nan_inf"):
        """Eager op-by-op execution with per-op numerics checks — the
        NAN_PANIC/INF_PANIC diagnosis path (reference:
        DefaultOpExecutioner.java:397-437 checkForAny/checkForNaN).

        Under jit there is nothing between ops to hook, so panic-mode
        LOCALIZATION runs the pruned graph eagerly (one tiny XLA program
        per op) and raises NumericsException at the first op whose output
        goes non-finite, naming the op, its inputs and their stats. Slow
        by design; use after fit() flags a non-finite loss
        (TrainingConfig.nan_panic)."""
        import numpy as _np
        if outputs is None:
            outputs = self.outputs()
        out_names = tuple(o.name if isinstance(o, SDVariable) else o
                          for o in outputs)
        ph = self._prep_placeholders(placeholders)
        if key is None:
            key = jax.random.key(0)
        env: Dict[str, jax.Array] = {}
        env.update(self.constants_map())
        env.update({**self.trainable_params(), **self.state_vars_map()})
        env.update(ph)

        def _bad(a):
            a = _np.asarray(a)
            if not _np.issubdtype(a.dtype, _np.floating):
                return None
            if check in ("nan", "nan_inf") and _np.isnan(a).any():
                return "NaN"
            if check in ("inf", "nan_inf") and _np.isinf(a).any():
                return "Inf"
            return None

        for name, arr in env.items():
            kind = _bad(arr)
            if kind:
                raise NumericsException(f"input/parameter {name!r} already "
                                        f"contains {kind}")
        for idx, node in enumerate(self._prune(out_names)):
            o = registry.get_op(node.op)
            attrs = dict(node.attrs)
            if node.random:
                attrs["key"] = jax.random.fold_in(key, idx)
            try:
                args = [env[i] for i in node.inputs]
            except KeyError as e:
                raise KeyError(
                    f"exec_debug: op {node.name!r} needs variable "
                    f"{e.args[0]!r} — pass it in placeholders=") from None
            res = o.fn(*args, **attrs)
            results = list(res) if isinstance(res, (tuple, list)) else [res]
            for out_name, r in zip(node.outputs, results):
                env[out_name] = r
                kind = _bad(r)
                if kind:
                    stats = "; ".join(
                        f"{i}: shape {tuple(_np.shape(env[i]))}, "
                        f"range [{float(_np.nanmin(_np.asarray(env[i]))):.4g}"
                        f", {float(_np.nanmax(_np.asarray(env[i]))):.4g}]"
                        for i in node.inputs)
                    raise NumericsException(
                        f"{kind} produced by op {node.op!r} (node "
                        f"{node.name!r}) in output {out_name!r}; "
                        f"inputs: {stats}")
        return {o: NDArray(env[o]) for o in out_names}

    def outputs(self) -> List[str]:
        """Graph outputs = ARRAY vars consumed by no op (reference:
        SameDiff.outputs())."""
        consumed = set()
        for node in self._ops.values():
            consumed.update(node.inputs)
        outs = [n for n, v in self._vars.items()
                if v.var_type == VariableType.ARRAY and n not in consumed]
        return outs

    def infer_shape(self, name: str) -> Optional[Tuple[int, ...]]:
        """Shape inference via jax.eval_shape over the pruned subgraph —
        the analogue of calculateOutputShapes2 (NativeOps.h), done by the
        tracer instead of per-op C++ shape functions."""
        v = self._vars[name]
        if name in self._arrays:
            return tuple(self._arrays[name].shape)
        if v.var_type == VariableType.PLACEHOLDER:
            return v._shape
        fn = self._trace_fn((name,))
        ph_specs = {}
        faked_dims = False
        for pn in self.placeholders():
            pv = self._vars[pn]
            if pv._shape is None or any(d == -1 for d in pv._shape):
                shape = tuple(1 if d == -1 else d for d in (pv._shape or (1,)))
                faked_dims = True
            else:
                shape = pv._shape
            ph_specs[pn] = jax.ShapeDtypeStruct(shape, DataType.from_any(pv.dtype).jnp)
        try:
            out = jax.eval_shape(fn,
                                 {**self.trainable_params(),
                                  **self.state_vars_map()},
                                 self.constants_map(),
                                 ph_specs, jax.random.key(0))
            return tuple(out[name].shape)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # ops with structural-tensor args (tf_compat Reshape etc.) need
            # concrete values the abstract tracer can't provide — the shape
            # is genuinely not statically inferable here.
            return None
        except (TypeError, ValueError):
            if faked_dims:
                # unknown placeholder dims were substituted with 1 to make
                # abstract eval possible; a shape-compat failure is then an
                # artifact of the fake dims, not a user bug
                return None
            # fully-known shapes that still fail to trace = a real graph
            # error the caller must see (round-2 Weak #3: don't swallow)
            raise

    # ------------------------------------------------------------------
    # gradients (reference: createGradFunction + calculateGradients,
    # SameDiff.java:4999,5013 — replaced by jax.grad of the traced fn)
    def calculate_gradients(self, placeholders=None,
                            wrt: Optional[Sequence[Union[str, SDVariable]]] = None,
                            loss: Optional[Union[str, SDVariable]] = None,
                            key=None) -> Dict[str, NDArray]:
        wrt_names = tuple(w.name if isinstance(w, SDVariable) else w
                          for w in (wrt or self.trainable_params().keys()))
        loss_names = self._resolve_loss(loss)
        ph = self._prep_placeholders(placeholders)
        cache_key = ("grad", self._version, wrt_names, loss_names, self._ph_sig(ph))
        compiled = self._fn_cache.get(cache_key)
        if compiled is None:
            fn = self._trace_fn(loss_names)

            def loss_fn(wrt_params, other_params, constants, phv, k):
                params = {**other_params, **wrt_params}
                outs = fn(params, constants, phv, k)
                return sum(jnp.sum(outs[ln]) for ln in loss_names)

            compiled = jax.jit(jax.grad(loss_fn))
            self._fn_cache[cache_key] = compiled
        params = {**self.trainable_params(), **self.state_vars_map()}
        wrt_params = {n: params[n] for n in wrt_names}
        other = {n: p for n, p in params.items() if n not in wrt_names}
        if key is None:
            key = jax.random.key(self._seed)
            self._seed += 1
        grads = compiled(wrt_params, other, self.constants_map(), ph, key)
        return {k: NDArray(v) for k, v in grads.items()}

    def _resolve_loss(self, loss=None) -> Tuple[str, ...]:
        if loss is not None:
            return (loss.name if isinstance(loss, SDVariable) else loss,)
        if self.loss_variables:
            return tuple(self.loss_variables)
        # fall back: single graph output
        outs = self.outputs()
        if len(outs) == 1:
            return (outs[0],)
        raise ValueError("no loss variable set; call set_loss_variables()")

    # ------------------------------------------------------------------
    # training (reference: SameDiff.fit → TrainingSession.java:74; here the
    # step — forward+backward+updater+param update — is ONE jitted fn with
    # donated param/state buffers)
    def _build_step_parts(self):
        """The two halves of the train step, separated so gradient
        accumulation (autodiff/window.py) can run the gradient half every
        micro-step and the apply half every ``accum_steps``-th:

        - ``grad_fn(params, svars, iteration, constants, phv, base_key)
          -> (grads, new_svars, data_loss)`` — forward + backward with
          the optional mixed-precision policy applied (cast params/inputs
          to the compute dtype inside the trace; gradients flow back
          through the casts as float32 master-param grads);
        - ``apply_fn(params, grads, state, iteration)
          -> (new_params, new_state)`` — regularization + clipping +
          updater + parameter update.
        """
        tc = self.training_config
        if tc is None:
            raise ValueError("set sd.training_config = TrainingConfig(...) first")
        loss_names = self._resolve_loss()
        state_updates = dict(self._state_updates)
        trace_outputs = loss_names + tuple(state_updates.values())
        fn = self._trace_fn(trace_outputs)
        updater = tc.updater
        regs = tc.regularization or []

        from deeplearning4j_tpu.learning.schedules import resolve_lr
        pre_regs = [r for r in regs if r.apply_step == "BEFORE_UPDATER"]
        post_regs = [r for r in regs if r.apply_step == "POST_UPDATER"]

        mp = getattr(tc, "mixed_precision", None)
        if mp is not None:
            cdt = DataType.from_any(mp.compute_dtype).jnp
            loss_scale = mp.loss_scale

            def _cast(tree):
                return jax.tree_util.tree_map(
                    lambda x: x.astype(cdt)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)
        else:
            loss_scale = None
            _cast = None
        # CE-tail precision policy (MixedPrecision.softmax_dtype): the
        # scope is consulted by the loss ops at TRACE time, so it wraps
        # the graph fn's execution inside loss_fn below
        _ce_dt = getattr(mp, "softmax_dtype", None) if mp is not None \
            else None

        def _ce_scope():
            if _ce_dt is None:
                import contextlib
                return contextlib.nullcontext()
            from deeplearning4j_tpu.ops.loss import softmax_dtype_scope
            return softmax_dtype_scope(_ce_dt)

        def grad_fn(params, svars, iteration, constants, phv, base_key):
            # per-step key derived ON DEVICE (a host-side jax.random.key per
            # step costs a tunnel round-trip; fold_in is free inside the jit)
            key = jax.random.fold_in(base_key, iteration)

            def loss_fn(p):
                with _ce_scope():
                    if _cast is not None:
                        # bf16 compute: params/inputs/constants cast at
                        # the top of the trace (XLA fuses the casts);
                        # state vars (BN running stats) stay f32 — the
                        # norm ops keep their statistics math in f32 and
                        # emit x-dtype activations
                        outs = fn({**_cast(p),
                                   **jax.lax.stop_gradient(svars)},
                                  _cast(constants), _cast(phv), key)
                    else:
                        outs = fn({**p, **jax.lax.stop_gradient(svars)},
                                  constants, phv, key)
                loss = sum(jnp.sum(outs[ln]).astype(jnp.float32)
                           for ln in loss_names)
                if loss_scale is not None:
                    return loss * loss_scale, (outs, loss)
                return loss, (outs, loss)

            (_, (outs, data_loss)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if loss_scale is not None:
                grads = jax.tree_util.tree_map(
                    lambda g: g / loss_scale, grads)
            # chaos harness (faults/chaos.py): deterministic NaN-gradient
            # injection at one absolute iteration, traced into the
            # program — fires inside fused windows/scans too. A None
            # spec (production) leaves the trace untouched.
            _chaos = getattr(tc, "_chaos_spec", None)
            _nan_at = getattr(_chaos, "nan_grads_at", None) \
                if _chaos is not None else None
            if _nan_at is not None:
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.where(iteration == int(_nan_at),
                                        jnp.full_like(g, jnp.nan), g),
                    grads)
            new_svars = {sn: outs[src].astype(svars[sn].dtype)
                         for sn, src in state_updates.items()}
            # state vars with no declared update carry over unchanged
            new_svars = {**svars, **new_svars}
            return grads, new_svars, data_loss

        def apply_fn(params, grads, state, iteration):
            lr = resolve_lr(getattr(updater, "learning_rate", 0.0), iteration, 0)
            # L1/L2 modify the gradient pre-updater; WeightDecay modifies the
            # update post-updater (reference: BaseMultiLayerUpdater.update)
            for r in pre_regs:
                grads = jax.tree_util.tree_map(
                    lambda p, g: r.apply(p, g, lr), params, grads)
            grads = tc.clip_gradients(grads)
            updates, new_state = updater.apply(grads, state, iteration)
            for r in post_regs:
                updates = jax.tree_util.tree_map(
                    lambda p, u: r.apply(p, u, lr), params, updates)
            new_params = jax.tree_util.tree_map(
                lambda p, u: p - u, params, updates)
            return new_params, new_state

        return grad_fn, apply_fn, loss_names

    def _ts_stats_fn(self, tensorstats):
        """The traced tensorstats sampler (monitor/tensorstats.py):
        ``stats_fn(iteration, params, new_params, grads) -> stats`` —
        the configured per-layer summaries under a ``lax.cond`` that
        fires only on sampled steps (zeros otherwise; shape-stable).
        Layer order is the sorted trainable-param names, the SAME order
        the host-side record builder uses."""
        from deeplearning4j_tpu.monitor.tensorstats import (compute_stats,
                                                            layer_names,
                                                            zeros_stats)
        ts = tensorstats
        names = layer_names(self.trainable_params())

        def stats_fn(take, params, new_params, grads):
            def _sampled():
                updates = jax.tree_util.tree_map(
                    lambda a, b: a - b, params, new_params) \
                    if "updates" in ts.families else None
                return compute_stats(
                    ts, names,
                    grads=grads if "grads" in ts.families else None,
                    updates=updates,
                    params=new_params if "params" in ts.families else None)

            return jax.lax.cond(take, _sampled,
                                lambda: zeros_stats(len(names), ts))

        return stats_fn, names

    def _build_step_body(self, sentinel: bool = False, tensorstats=None):
        """One full train step (forward + backward + updater + param
        update) composed from _build_step_parts — shared by the per-batch
        step, the fused-window step and the scanned whole-epoch step.

        ``sentinel=True`` (TrainingConfig.sentinel, faults/sentinels.py)
        makes the body additionally emit one boolean from
        ``_sentinel_ok``: finite loss AND finite global gradient norm.
        ``tensorstats`` (TrainingConfig.tensorstats, monitor/
        tensorstats.py) appends the sampled per-layer stats pytree
        (zeros on unsampled steps — the host keeps only sampled ones).
        Both are computed from values the step already produces;
        parameter math is untouched (training with either rail on is
        bit-identical to off)."""
        grad_fn, apply_fn, loss_names = self._build_step_parts()
        if tensorstats is not None:
            from deeplearning4j_tpu.monitor.tensorstats import sample_mask
            stats_fn, _ = self._ts_stats_fn(tensorstats)

        def step_body(params, svars, state, iteration, constants, phv,
                      base_key):
            grads, new_svars, data_loss = grad_fn(params, svars, iteration,
                                                  constants, phv, base_key)
            new_params, new_state = apply_fn(params, grads, state, iteration)
            # iteration advances on device — no per-step int transfer
            out = [new_params, new_svars, new_state, iteration + 1,
                   data_loss]
            if sentinel:
                out.append(self._sentinel_ok(data_loss, grads))
            if tensorstats is not None:
                out.append(stats_fn(sample_mask(iteration, tensorstats),
                                    params, new_params, grads))
            return tuple(out)

        return step_body, loss_names

    def make_train_step(self, donate: bool = True, sentinel: bool = False,
                        tensorstats=None):
        step_body, loss_names = self._build_step_body(
            sentinel=sentinel, tensorstats=tensorstats)
        cache_key = ("train_step", self._version, loss_names, donate,
                     bool(sentinel),
                     tensorstats.key() if tensorstats is not None else None)
        compiled = self._fn_cache.get(cache_key)
        if compiled is None:
            self._verbose_log(f"compiling train step (graph v{self._version}, "
                              f"{len(self._ops)} ops, donate={donate})")
            compiled = AOTDispatch(
                jax.jit(step_body,
                        donate_argnums=(0, 1, 2, 3) if donate else ()),
                ph_arg=5)
            self._fn_cache[cache_key] = compiled
        return compiled

    @staticmethod
    def _sentinel_ok(data_loss, grads):
        """The divergence sentinel's per-step verdict: finite loss AND a
        finite global gradient L1 norm. The norm touches every gradient
        leaf — NO sampling — because a where-based op (relu, dropout
        masks) can launder NaN activations into a FINITE loss while one
        weight's gradient (``x^T @ delta`` with NaN x) silently poisons
        that parameter forever; only a reduction over all leaves sees
        it. The check is a boolean ``isfinite``-AND reduce (not a float
        norm accumulation): XLA fuses the elementwise ``isfinite`` into
        each gradient's producer and the AND-reduce has no serial float
        dependency chain — measured noise-level next to the step's
        matmuls (bench.py sentinel_overhead tracks it)."""
        ok = jnp.isfinite(data_loss)
        for g in jax.tree_util.tree_leaves(grads):
            ok = ok & jnp.all(jnp.isfinite(g))
        return ok

    @staticmethod
    def _nan_panic_active(tc) -> bool:
        """Loss checking is on when the config asks for it OR the runtime
        Environment is in debug mode — debug set after a TrainingConfig
        was built must still take effect at fit time."""
        if getattr(tc, "nan_panic", False):
            return True
        from deeplearning4j_tpu.environment import environment
        return environment().is_debug()

    @staticmethod
    def _verbose_log(msg: str) -> None:
        """Environment verbose mode (reference: Environment.h verbose —
        the runtime narrates compile/dispatch events)."""
        from deeplearning4j_tpu.environment import environment
        env = environment()
        if env.is_verbose() or env.is_debug():
            print(f"[deeplearning4j_tpu] {msg}")

    def make_train_epoch(self, donate: bool = True, unroll: int = 1,
                         sentinel: bool = False, fingerprint: bool = False):
        """Whole-epoch train step: lax.scan of the step body over batches
        stacked on a leading steps axis. ONE device dispatch per epoch —
        on a tunneled/host-bottlenecked chip this removes the per-step
        dispatch latency that dominates small models (no reference
        analogue; the reference pays per-OP dispatch, SURVEY §3.2).
        ``unroll`` unrolls the scan body (fewer while-loop iterations at
        the cost of compile time; the runtime's per-iteration sync can
        dominate small step bodies).

        An epoch IS a window of length n_steps — this delegates to
        make_train_window."""
        return self.make_train_window(donate=donate, unroll=unroll,
                                      sentinel=sentinel,
                                      fingerprint=fingerprint)

    def make_train_window(self, accum_steps: int = 1, donate: bool = True,
                          unroll: int = 1, sentinel: bool = False,
                          tensorstats=None, fingerprint: bool = False):
        """Fused-window train step: K consecutive steps in ONE compiled
        dispatch — a lax.scan of the step body over a (K, batch, ...)
        stacked window of placeholders. Per-step losses come back as a
        device-side (K,) buffer, so listeners cost one transfer per
        flush, not one per step (autodiff/window.py owns the loop).

        The returned jitted fn specializes per window length K (the
        leading dim of the stacked placeholders), so ONE cache entry
        serves the full window and every ragged-tail bucket.

        With ``accum_steps > 1``, micro-batch gradients accumulate in the
        scan carry and the updater applies every ``accum_steps``-th
        micro-step on the AVERAGED gradient (effective batch =
        accum_steps * batch). The updater sees the update count
        (``iteration // accum_steps``) so schedules/bias-correction step
        per update, while RNG keys still fold the absolute micro-step
        iteration. Signature then gains an ``accum`` carry (zeros_like
        params) threaded between windows — an accumulation cycle may
        span window boundaries.

        ``sentinel=True`` (TrainingConfig.sentinel) adds ONE extra int32
        output: the absolute iteration of the first step in the window
        whose loss or gradients went non-finite (-1 = clean). The
        flag folds into the scan carry, so the window still syncs with
        the host only at its boundaries (faults/sentinels.py).

        ``tensorstats`` (TrainingConfig.tensorstats, monitor/
        tensorstats.py) folds the sampled per-layer stats into the scan
        carry the same way: TWO extra outputs — the stats pytree of the
        LAST sampled step in the window (zeros when none) and the int32
        iteration it was sampled at (-1 = no sample point). The host
        fetches both at flush boundaries in the same device_get burst
        as losses and sentinel verdicts; no per-step sync.

        ``fingerprint=True`` (TrainingConfig.fingerprints, integrity/
        fingerprint.py) appends ONE extra uint32 output: the bitwise
        word-sum digest of the window's final params + state vars +
        optimizer state — the silent-corruption sentinel. Computed once
        per window on the final carry (not per step), order-independent
        so the host can recompute it from captured bytes; parameter
        math is untouched.
        """
        ts = tensorstats
        if ts is not None:
            from deeplearning4j_tpu.monitor.tensorstats import (sample_mask,
                                                                zeros_stats)
            ts_n_layers = len(self.trainable_params())
        if fingerprint:
            from deeplearning4j_tpu.integrity.fingerprint import \
                tree_fingerprint as _tree_fp
        if accum_steps <= 1:
            step_body, loss_names = self._build_step_body(
                sentinel=sentinel, tensorstats=ts)

            def window_fn(params, svars, state, iteration, constants,
                          stacked_phv, base_key):
                def body(carry, phv):
                    # carry layout: p, sv, st, it [, bad] [, stats, at]
                    p, sv, st, it = carry[:4]
                    i = 4
                    if sentinel:
                        bad = carry[i]; i += 1
                    if ts is not None:
                        stats_c, stats_at = carry[i], carry[i + 1]
                    res = step_body(p, sv, st, it, constants, phv,
                                    base_key)
                    p, sv, st, it2, loss = res[:5]
                    out = [p, sv, st, it2]
                    r = 5
                    if sentinel:
                        ok = res[r]; r += 1
                        # absolute iteration of the FIRST bad step in
                        # the window; -1 = clean (faults/sentinels.py)
                        bad = jnp.where((bad < 0) & jnp.logical_not(ok),
                                        it, bad)
                        out.append(bad)
                    if ts is not None:
                        # keep the LAST sampled step's stats (step_body
                        # already gated the compute under lax.cond; the
                        # selects below touch only the small stat
                        # arrays)
                        take = sample_mask(it, ts)
                        stats_c = jax.tree_util.tree_map(
                            lambda n, o: jnp.where(take, n, o), res[r],
                            stats_c)
                        out.extend([stats_c,
                                    jnp.where(take, it, stats_at)])
                    return tuple(out), loss

                carry0 = [params, svars, state, iteration]
                if sentinel:
                    carry0.append(jnp.asarray(-1, jnp.int32))
                if ts is not None:
                    carry0.extend([zeros_stats(ts_n_layers, ts),
                                   jnp.asarray(-1, jnp.int32)])
                carry, losses = jax.lax.scan(body, tuple(carry0),
                                             stacked_phv, unroll=unroll)
                out = list(carry[:4]) + [losses] + list(carry[4:])
                if fingerprint:
                    # digest of the window's FINAL state, once per
                    # window on the post-scan carry — not per step
                    out.append(_tree_fp(carry[0], carry[1], carry[2]))
                return tuple(out)

            donate_args = (0, 1, 2, 3)
        else:
            grad_fn, apply_fn, loss_names = self._build_step_parts()
            n_accum = int(accum_steps)
            if ts is not None:
                stats_fn, _ = self._ts_stats_fn(ts)

            def window_fn(params, svars, state, accum, iteration, constants,
                          stacked_phv, base_key):
                def body(carry, phv):
                    # carry layout: p, sv, st, acc, it [, bad] [, stats,
                    # at]
                    p, sv, st, acc, it = carry[:5]
                    i = 5
                    if sentinel:
                        bad = carry[i]; i += 1
                    if ts is not None:
                        stats_c, stats_at = carry[i], carry[i + 1]
                    grads, sv, loss = grad_fn(p, sv, it, constants, phv,
                                              base_key)
                    acc = jax.tree_util.tree_map(jnp.add, acc, grads)

                    def do_apply(args):
                        p_, st_, acc_ = args
                        mean_g = jax.tree_util.tree_map(
                            lambda g: g / n_accum, acc_)
                        p_, st_ = apply_fn(p_, mean_g, st_, it // n_accum)
                        return (p_, st_, jax.tree_util.tree_map(
                            jnp.zeros_like, acc_))

                    p_pre = p
                    p, st, acc = jax.lax.cond(
                        (it + 1) % n_accum == 0, do_apply, lambda a: a,
                        (p, st, acc))
                    out = [p, sv, st, acc, it + 1]
                    if sentinel:
                        # the MICRO-step grads, pre-accumulation: the bad
                        # step is named, not its whole cycle
                        ok = self._sentinel_ok(loss, grads)
                        bad = jnp.where((bad < 0) & jnp.logical_not(ok),
                                        it, bad)
                        out.append(bad)
                    if ts is not None:
                        # sampling aligns to apply boundaries
                        # (sample_mask with accum_steps): the updates
                        # family always describes a real parameter
                        # delta, never a mid-cycle zero
                        take = sample_mask(it, ts, accum_steps=n_accum)
                        stats_c = jax.tree_util.tree_map(
                            lambda n, o: jnp.where(take, n, o),
                            stats_fn(take, p_pre, p, grads), stats_c)
                        out.extend([stats_c,
                                    jnp.where(take, it, stats_at)])
                    return tuple(out), loss

                carry0 = [params, svars, state, accum, iteration]
                if sentinel:
                    carry0.append(jnp.asarray(-1, jnp.int32))
                if ts is not None:
                    carry0.extend([zeros_stats(ts_n_layers, ts),
                                   jnp.asarray(-1, jnp.int32)])
                carry, losses = jax.lax.scan(body, tuple(carry0),
                                             stacked_phv, unroll=unroll)
                out = list(carry[:5]) + [losses] + list(carry[5:])
                if fingerprint:
                    # params/svars/updater state only: the accum carry
                    # is NOT part of the checkpoint schema, so it stays
                    # outside the digest too (autodiff/window.py)
                    out.append(_tree_fp(carry[0], carry[1], carry[2]))
                return tuple(out)

            donate_args = (0, 1, 2, 3, 4)
        cache_key = ("train_window", self._version, loss_names,
                     int(accum_steps), donate, int(unroll), bool(sentinel),
                     ts.key() if ts is not None else None,
                     bool(fingerprint))
        compiled = self._fn_cache.get(cache_key)
        if compiled is None:
            self._verbose_log(
                f"compiling fused-window step (graph v{self._version}, "
                f"accum_steps={accum_steps}, donate={donate})")
            compiled = AOTDispatch(
                jax.jit(window_fn,
                        donate_argnums=donate_args if donate else ()),
                ph_arg=6 if accum_steps > 1 else 5)
            self._fn_cache[cache_key] = compiled
        return compiled

    # ------------------------------------------------------------------
    # pre-compile static analysis (analyze/ — docs/static_analysis.md)
    def _maybe_analyze(self, has_listeners=None, context="fit"):
        """Run the static analyzer per ``TrainingConfig.analyze``
        (True = warn on error findings and proceed; "strict" = raise
        GraphAnalysisError BEFORE any compile; False = off). Cached on
        the graph version + fit context, so only the first fit of a
        given graph pays the walk — warm dispatches see a dict lookup
        (bench.py analyze_overhead)."""
        tc = self.training_config
        mode = getattr(tc, "analyze", True) if tc is not None else False
        if not mode:
            return None
        # content fingerprint, not id(tc): the config is mutable and
        # the common pattern is in-place mutation (tc.sharding = ...,
        # fused_steps set by fit kwargs) — an identity key would serve
        # a stale clean report for exactly the knob that changed.
        # loss_variables rides the key too: set_loss_variables does
        # not bump the graph version.
        key = (self._version, has_listeners,
               tuple(self.loss_variables), self._tc_fingerprint(tc))
        if self._analysis_key == key and self.last_analysis is not None:
            report = self.last_analysis
            fresh = False
        else:
            from deeplearning4j_tpu.analyze import analyze_training
            # a cache hit keeps the first producer's context — only a
            # FRESH analysis stamps the entry point that ran it
            report = analyze_training(self, tc,
                                      has_listeners=has_listeners,
                                      device_count=jax.device_count(),
                                      context=context)
            self.last_analysis = report
            self._analysis_key = key
            fresh = True
            self._verbose_log(
                f"static analysis ({report.context}): "
                + ", ".join(f"{n} {s}"
                            for s, n in report.counts().items())
                + f" in {report.seconds:.3f}s")
        errs = report.errors()
        if errs:
            # strict enforcement applies on EVERY call — a cached
            # report of a still-broken graph must keep refusing, not
            # just the fit that first analyzed it
            if str(mode).lower() == "strict":
                report.raise_if_errors()
            if fresh:
                from deeplearning4j_tpu.analyze import \
                    GraphAnalysisWarning
                import warnings as _warnings
                _warnings.warn(
                    f"static analysis found {len(errs)} error(s) — "
                    f"the compile will likely fail; "
                    f"sd.last_analysis.render() has the located "
                    f"diagnostics (docs/static_analysis.md):\n"
                    + "\n".join(f.render() for f in errs[:5]),
                    GraphAnalysisWarning, stacklevel=3)
        return report

    @staticmethod
    def _tc_fingerprint(tc):
        """Cheap content key of the analysis-relevant TrainingConfig
        fields (NOT iteration/epoch counters, which advance every
        fit and would defeat the cache)."""
        import json as _json
        mp = getattr(tc, "mixed_precision", None)
        sh = getattr(tc, "sharding", None)
        if sh is not None:
            sh = (sh if hasattr(sh, "to_json") else sh.to_spec()) \
                .to_json()
        ts = getattr(tc, "tensorstats", None)
        return (tuple(getattr(tc, "data_set_feature_mapping", ()) or ()),
                tuple(getattr(tc, "data_set_label_mapping", ()) or ()),
                max(1, int(getattr(tc, "fused_steps", 1) or 1)),
                max(1, int(getattr(tc, "accum_steps", 1) or 1)),
                None if mp is None
                else tuple(sorted(mp.to_json().items())),
                None if sh is None
                else _json.dumps(sh, sort_keys=True, default=str),
                (ts.key() if hasattr(ts, "key") else bool(ts))
                if ts is not None else None,
                getattr(tc, "_chaos_spec", None) is not None,
                str(getattr(tc, "analyze", True)))

    # ------------------------------------------------------------------
    # AOT precompilation (compilecache/ — docs/cold_start.md)
    def _placeholder_specs(self, names=None, batch_size=None,
                           batch_shapes=None) -> Dict[str, Any]:
        """Abstract ``ShapeDtypeStruct``s for placeholders: declared
        shapes with ``-1`` batch dims resolved from ``batch_size``, or
        overridden wholesale per name via ``batch_shapes``."""
        specs = {}
        for pn in (names if names else self.placeholders()):
            v = self._vars[pn]
            shape = v._shape
            if batch_shapes and pn in batch_shapes:
                shape = tuple(int(d) for d in batch_shapes[pn])
            if shape is None:
                raise ValueError(
                    f"placeholder {pn!r} has no declared shape; pass "
                    f"batch_shapes={{{pn!r}: (...)}} to precompile")
            if any(d == -1 for d in shape):
                if batch_size is None:
                    raise ValueError(
                        f"placeholder {pn!r} has batch dims {shape}; pass "
                        f"batch_size= (or batch_shapes=) to precompile")
                shape = tuple(int(batch_size) if d == -1 else int(d)
                              for d in shape)
            specs[pn] = jax.ShapeDtypeStruct(
                tuple(shape), DataType.from_any(v.dtype).jnp)
        return specs

    def precompile(self, batch_size: Optional[int] = None,
                   batch_shapes: Optional[Dict[str, Sequence[int]]] = None,
                   epoch_steps: Optional[int] = None,
                   tiers: Optional[Sequence[str]] = None) -> dict:
        """AOT-compile the training programs from ABSTRACT shapes, before
        the first batch exists — ``fit()`` then dispatches straight into
        the prebuilt executables instead of paying XLA inside its first
        window (compilecache/, docs/cold_start.md).

        What gets built follows ``training_config``: with
        ``fused_steps``/``accum_steps`` > 1 the fused-window fn at the
        full window length K **plus every pow2 ragged-tail bucket**
        (all powers of two ≤ K-1 — the complete set the window executor
        can ever dispatch for full-size batches; log2(K)+1 shapes for a
        pow2 K); otherwise the per-step
        train fn, plus — when ``epoch_steps`` is given — the scanned
        whole-epoch fn. Placeholder batch dims resolve from
        ``batch_size``/``batch_shapes``. With a persistent compilation
        cache configured (``Environment compilation_cache_dir``), the
        builds themselves become cache hits on a warm restart, so
        restart-to-first-step approaches data-loading time.

        Returns a summary dict (targets built/reused, wall seconds, and
        the process-wide backend-compile / cache-hit / cache-miss deltas
        this call produced). Precompiled executables live in the same
        version-keyed cache as lazy compiles: any graph mutation
        invalidates them, and unpredicted shapes (a ragged final BATCH)
        still compile lazily exactly as before — outputs are
        bit-identical either way (tests/test_cold_start.py).
        """
        import time as _time
        from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                     install_compile_watcher)
        from deeplearning4j_tpu.environment import environment
        tc = self.training_config
        if tc is None:
            raise ValueError("precompile() needs sd.training_config "
                             "(use precompile_output() for inference "
                             "graphs)")
        environment().apply_compilation_cache()
        install_compile_watcher()
        # static analysis gates AOT builds too: a strict config fails
        # with named diagnostics before paying any lowering/compile
        # (listener presence unknown at precompile time)
        self._maybe_analyze(has_listeners=None, context="precompile")
        K = max(1, int(getattr(tc, "fused_steps", 1) or 1))
        A = max(1, int(getattr(tc, "accum_steps", 1) or 1))
        sentinel = bool(getattr(tc, "sentinel", False))
        # tensorstats rides the listener rail; precompile builds the
        # stats-enabled signature fit() will dispatch when listeners are
        # attached (a listener-free fused fit compiles the stats-free
        # variant lazily — docs/observability.md)
        ts = getattr(tc, "tensorstats", None)
        names = list(tc.data_set_feature_mapping) + \
            list(tc.data_set_label_mapping)
        ph = self._placeholder_specs(names or None, batch_size,
                                     batch_shapes)
        if tiers is None:
            tiers = ["window"] if (K > 1 or A > 1) else ["step"]
            if epoch_steps and K <= 1 and A <= 1:
                tiers.append("epoch")
        params_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                      for n, a in self.trainable_params().items()}
        svars_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                     for n, a in self.state_vars_map().items()}
        consts_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                      for n, a in self.constants_map().items()}
        state_abs = jax.eval_shape(tc.updater.init, params_abs)
        it_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = jax.random.key(0)   # concrete — only its aval reaches lower()

        mark = COMPILE_STATS.mark()
        t0 = _time.perf_counter()
        built = reused = 0

        def _build(disp, args, sig, label, seen=None, steps=1):
            nonlocal built, reused
            if sig in disp.aot:
                reused += 1
                return
            with _tracer.span("compile.precompile", cat="compile",
                              target=label):
                disp.aot[sig] = disp.lower(*args).compile()
            # static memory & compute plan (monitor/memstats.py): the
            # executable exists — reading memory_analysis/cost_analysis
            # here is free observability
            memstats.capture_plan(label, sig, compiled=disp.aot[sig],
                                  steps=steps, graph=self)
            if seen is not None:
                # pre-register the trace signature so the window
                # executor's compile accounting reports 0 for shapes
                # precompiled here
                seen.add(sig)
            built += 1
            self._verbose_log(f"precompiled {label}")

        def _window_args(k, with_accum):
            sphv = {n: jax.ShapeDtypeStruct((k,) + tuple(s.shape), s.dtype)
                    for n, s in ph.items()}
            base = (params_abs, svars_abs, state_abs)
            if with_accum:
                base = base + (params_abs,)   # accum carry ≅ zeros_like
            return base + (it_abs, consts_abs, sphv, key), \
                ph_shape_sig(sphv)

        # donation is NOT a parameter here: fit() always builds its
        # dispatchers with the donate=True default, and the _fn_cache
        # key includes donate — a divergent value would AOT-compile
        # executables fit() never consults (silently useless work)
        if "step" in tiers:
            disp = self.make_train_step(sentinel=sentinel, tensorstats=ts)
            _build(disp, (params_abs, svars_abs, state_abs, it_abs,
                          consts_abs, ph, key),
                   ph_shape_sig(ph), "train_step", steps=1)
        fp_on = bool(getattr(tc, "fingerprints", False))
        if "window" in tiers:
            disp = self.make_train_window(accum_steps=A, sentinel=sentinel,
                                          tensorstats=ts,
                                          fingerprint=fp_on)
            from deeplearning4j_tpu.autodiff.window import window_trace_set
            seen = window_trace_set(self, A, sentinel,
                                    ts.key() if ts is not None else None,
                                    fp_on)
            # every pow2 the tail decomposition can emit: a ragged tail
            # of r < K steps uses buckets up to the largest pow2 ≤ r,
            # so cover all powers of two ≤ K-1 (for pow2 K this is the
            # log2(K)+1-shape set; a non-pow2 K needs one more)
            sizes = {K} | {1 << i for i in range((K - 1).bit_length())}
            for k in sorted(sizes, reverse=True):
                args, sig = _window_args(k, with_accum=A > 1)
                _build(disp, args, sig, f"window_k{k}", seen=seen,
                       steps=k)
        if "epoch" in tiers:
            if not epoch_steps:
                raise ValueError("the scanned-epoch tier needs "
                                 "epoch_steps= (batches per epoch)")
            unroll = int(getattr(tc, "scan_unroll", 1) or 1)
            disp = self.make_train_epoch(unroll=unroll, sentinel=sentinel,
                                         fingerprint=fp_on)
            args, sig = _window_args(int(epoch_steps), with_accum=False)
            _build(disp, args, sig, f"epoch_{epoch_steps}",
                   steps=int(epoch_steps))
        delta = COMPILE_STATS.delta(mark)
        info = {"compiled": built, "reused": reused,
                "seconds": round(_time.perf_counter() - t0, 4),
                "backend_compiles": delta["backend_compiles"],
                "cache_hits": delta["cache_hits"],
                "cache_misses": delta["cache_misses"]}
        # remembered so FaultTolerantFit can re-AOT after a retrace
        # (lr_rescale) instead of paying the compile inside the first
        # retry window (faults/recovery.py)
        self._precompile_spec = {"batch_size": batch_size,
                                 "batch_shapes": batch_shapes,
                                 "epoch_steps": epoch_steps,
                                 "tiers": tuple(tiers)}
        self.last_precompile = info
        self._verbose_log(f"precompile: {info}")
        return info

    def precompile_output(self, placeholders, outputs=None):
        """AOT-compile an inference program for the given placeholder
        shapes (``{name: shape tuple | ShapeDtypeStruct | array}``) and
        install it in the execution cache, so the matching ``output()``
        call runs without compiling — the serving warmup path
        (``ParallelInference(warmup_buckets=...)``). Idempotent per
        shape set; bit-identical to the lazily-compiled path."""
        from deeplearning4j_tpu.compilecache import install_compile_watcher
        from deeplearning4j_tpu.environment import environment
        environment().apply_compilation_cache()
        install_compile_watcher()
        if outputs is None:
            outputs = self.outputs()
        out_names = tuple(o.name if isinstance(o, SDVariable) else o
                          for o in outputs)
        ph_specs = {}
        for k, v in placeholders.items():
            name = k.name if isinstance(k, SDVariable) else k
            shape = tuple(int(d) for d in
                          (v.shape if hasattr(v, "shape") else v))
            # dtype from the DECLARED placeholder — the dtype
            # _prep_placeholders casts live inputs to — NOT from a
            # sample array: a float64 numpy sample would install the
            # executable under a cache key output()'s float32-cast
            # lookup never finds (warmup compiles, first request
            # compiles AGAIN)
            var = self._vars.get(name)
            if var is not None and var.dtype is not None:
                dt = DataType.from_any(var.dtype).jnp
            elif hasattr(v, "dtype"):
                dt = v.dtype
            else:
                raise KeyError(f"unknown placeholder {name!r} and no "
                               f"dtype on its sample value")
            ph_specs[name] = jax.ShapeDtypeStruct(shape, dt)
        cache_key = self._output_cache_key(out_names, ph_specs)
        existing = self._fn_cache.get(cache_key)
        if isinstance(existing, _AOTOutput):
            return existing       # already an AOT executable
        fn = self._trace_fn(out_names)
        params_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                      for n, a in {**self.trainable_params(),
                                   **self.state_vars_map()}.items()}
        consts_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                      for n, a in self.constants_map().items()}
        jit_fn = jax.jit(fn)
        with _tracer.span("compile.precompile", cat="compile",
                          target="output"):
            compiled = _AOTOutput(
                jit_fn,
                jit_fn.lower(params_abs, consts_abs, ph_specs,
                             jax.random.key(0)).compile())
        # per-bucket serving memory plan (monitor/memstats.py): label
        # carries the row count so /report can show the footprint
        # ladder across warmup buckets
        rows = next(iter(ph_specs.values())).shape
        rows = rows[0] if rows else 1
        memstats.capture_plan(f"output_b{rows}", ph_shape_sig(ph_specs),
                              compiled=compiled.compiled, graph=self)
        self._fn_cache[cache_key] = compiled
        return compiled

    def fit(self, dataset_iterator, epochs: int = 1, listeners=()):
        """Train (reference: SameDiff.fit(DataSetIterator, epochs),
        SameDiff.java:1833). ``dataset_iterator`` yields objects with
        ``features``/``labels`` (DataSet) or (features, labels) tuples.

        THREE execution tiers (this is a documented contract, not an
        internal detail — see docs/training_performance.md):

        - **scanned fast path** — zero listeners AND an iterator exposing
          ``stacked_batches`` (``DeviceCachedIterator``): the whole epoch
          compiles to ONE lax.scan dispatch. Use this for benchmarking
          and small models, where per-step dispatch latency dominates.
        - **fused windows** — ``TrainingConfig.fused_steps > 1`` (or
          ``accum_steps > 1``): K steps per compiled dispatch with
          device-buffered losses flushed to listeners at window
          boundaries and a background stager double-buffering the next
          window's host→HBM transfer. Works with listeners AND
          host-streaming iterators — the production default fast path.
        - **per-step path** — the legacy tier: one dispatch per step
          with burst loss delivery. Expect ~ms-scale extra latency per
          step on a tunneled chip.

        Environment verbose mode announces which tier each fit() took.
        """
        from deeplearning4j_tpu.autodiff.training import History, LossCurve
        tc = self.training_config
        if tc is None:
            raise ValueError("set sd.training_config = TrainingConfig(...) first")
        # pre-compile static analysis (analyze/): named diagnostics
        # BEFORE tier selection, mesh placement, or any XLA compile —
        # strict mode raises here (docs/static_analysis.md)
        self._maybe_analyze(has_listeners=bool(listeners))
        # seekable streaming pipeline (datapipe/): register it on the
        # graph so checkpoint captures embed its PipelineState at flush
        # boundaries and anchor its pass starts to absolute iterations —
        # a mid-epoch restore then SEEKS instead of replaying the pass
        # (docs/data_pipeline.md). Cleared (None) for plain iterators so
        # a previous fit's pipeline can't leak into this fit's snapshots.
        from deeplearning4j_tpu.datapipe.pipeline import find_pipeline
        _dp = find_pipeline(dataset_iterator)
        self._active_datapipe = _dp
        if _dp is not None and hasattr(_dp, "bind_iteration_source"):
            _dp.bind_iteration_source(
                lambda: int(getattr(tc, "iteration_count", 0) or 0))
            _dp.bind_epoch_source(
                lambda: int(getattr(tc, "epoch_count", 0) or 0))
        if getattr(tc, "sharding", None) is not None:
            # declarative mesh sharding: place params/state on the
            # spec's mesh and pre-shard batches BEFORE tier selection,
            # so every tier below (scanned / fused windows / per-step)
            # trains under the mesh. A ParallelTrainer front end arrives
            # here with an already-sharded iterator (its explicit
            # strategy wins) and this is a no-op.
            from deeplearning4j_tpu.parallel.trainer import ensure_sharded
            wrapped = ensure_sharded(self, tc.sharding, dataset_iterator)
            if wrapped is not dataset_iterator:
                self._verbose_log(
                    f"fit: sharded over mesh "
                    f"{dict(wrapped._strategy.mesh.mesh.shape)} "
                    f"(TrainingConfig.sharding)")
            dataset_iterator = wrapped
        fused = max(1, int(getattr(tc, "fused_steps", 1) or 1))
        accum = max(1, int(getattr(tc, "accum_steps", 1) or 1))
        if not listeners and hasattr(dataset_iterator, "stacked_batches") \
                and fused <= 1 and accum <= 1:
            self._verbose_log("fit: scanned whole-epoch path "
                              "(one dispatch per epoch)")
            return self._fit_scanned(dataset_iterator, epochs)
        if fused > 1 or accum > 1:
            from deeplearning4j_tpu.autodiff.window import fit_windowed
            self._verbose_log(
                f"fit: fused-window path (fused_steps={fused}, "
                f"accum_steps={accum} — ceil(steps/{fused}) dispatches "
                f"per epoch)")
            return fit_windowed(self, dataset_iterator, epochs,
                                listeners=listeners)
        why = ("listeners need per-iteration scalars" if listeners
               else "iterator has no stacked_batches (use "
                    "DeviceCachedIterator for the scanned path)")
        self._verbose_log(f"fit: per-step path — {why} "
                          f"(set TrainingConfig.fused_steps>1 for fused "
                          f"windows)")
        use_sentinel = bool(getattr(tc, "sentinel", False))
        # in-graph tensor statistics need the listener rail to deliver
        # their records; a listener-free fit builds the stats-free step
        # (monitor/tensorstats.py)
        ts_cfg = getattr(tc, "tensorstats", None) if listeners else None
        step = self.make_train_step(sentinel=use_sentinel,
                                    tensorstats=ts_cfg)
        # bitwise state fingerprints (integrity/): the per-step tier
        # does not thread the digest through the step body — a tiny
        # separate digest program dispatches at the flush boundaries
        # (and once at fit end), fetched in the same burst
        fp_on = bool(getattr(tc, "fingerprints", False))
        self._device_fingerprint = None
        if fp_on:
            from deeplearning4j_tpu.integrity.fingerprint import \
                make_fingerprint_fn
            fp_fn = make_fingerprint_fn(self)
        from deeplearning4j_tpu.integrity.watchdog import guard as _wd_guard
        # step() donates param/state buffers; work on copies so the graph's
        # stored arrays stay valid for output()/save() during training
        params = jax.tree_util.tree_map(jnp.copy, self.trainable_params())
        svars = jax.tree_util.tree_map(jnp.copy, self.state_vars_map())
        # restored state only reusable if the trainable set hasn't changed
        # (e.g. convert_to_constant between fits); otherwise re-init
        if self._updater_state is not None and \
                set(self._updater_state.keys()) == set(params.keys()):
            state = jax.tree_util.tree_map(jnp.copy, self._updater_state)
        else:
            state = tc.updater.init(params)
        constants = self.constants_map()
        iteration = getattr(tc, "iteration_count", 0)
        it_dev = jnp.asarray(iteration, jnp.int32)    # one transfer per fit
        # the base seed is part of the resumable training state: per-step
        # keys are fold_in(key(base_seed), absolute_iteration), so a
        # checkpoint capturing this seed + the iteration counter resumes
        # the exact key sequence (checkpoint/state.py)
        self._fit_base_seed = self._seed
        base_key = jax.random.key(self._seed)          # one key per fit
        self._seed += 1
        history = History()
        deferred_means = []   # device scalars, fetched once at fit end
        for l in listeners:
            l.on_training_start(self)

        def _prep_batch(batch):
            if isinstance(batch, dict):
                ph = dict(batch)  # keys are placeholder names
            else:
                feats, labels = _split_batch(batch)
                ph = dict(zip(tc.data_set_feature_mapping, feats))
                ph.update(zip(tc.data_set_label_mapping, labels))
            return self._prep_placeholders(ph)

        # listeners get loss scalars in BURSTS: per-step losses stay on
        # device and one stacked fetch every flush_every steps feeds
        # iterations_done — the listener path no longer serializes the
        # dispatch pipeline with a float() per step (one round-trip per
        # burst instead of per iteration)
        flush_every = min((max(1, int(getattr(l, "frequency", 10)))
                           for l in listeners), default=0)
        # listeners that evaluate/save mid-epoch need current params in
        # self._arrays at each flush (params otherwise sync at epoch end)
        sync_params_on_flush = any(getattr(l, "needs_params", False)
                                   for l in listeners)

        if ts_cfg is not None:
            from deeplearning4j_tpu.monitor.tensorstats import (
                layer_names, sample_mask)
            ts_names = layer_names(params)
        else:
            ts_names = ()
        # memory-plan capture (monitor/memstats.py): with capture armed
        # a new shape's first compile goes through the AOT path so its
        # memory plan is observable; the sig work is skipped entirely
        # when the rail is off (the common case on this legacy tier)
        mem_on = memstats.plan_capture_enabled() or len(memstats.PLANS)
        mem_sigs: set = set()
        for epoch in range(epochs):
            epoch_losses = []
            epoch_oks: List[jax.Array] = []   # sentinel flags, device-side
            epoch_start_iter = iteration
            pending: List[Tuple[int, jax.Array]] = []
            pending_oks: List[Tuple[int, jax.Array]] = []
            pending_stats: List[Tuple[int, Any]] = []  # sampled stats

            def _flush(pending):
                if not pending:
                    return
                iters = [it for it, _ in pending]
                ts_recs: List[dict] = []
                with _tracer.span("flush", cat="train", steps=len(iters)):
                    # losses + sentinel verdicts + sampled tensorstats in
                    # ONE device->host transfer; verdicts are checked
                    # (and may raise) BEFORE the burst reaches listeners
                    oks_stack = jnp.stack([o for _, o in pending_oks]) \
                        if pending_oks else None
                    stats_burst = list(pending_stats)
                    pending_stats.clear()
                    fp_dev = fp_fn(params, svars, state) if fp_on else None
                    try:
                        with _wd_guard("flush"):
                            vals_arr, oks, stats_host, fp_host = \
                                jax.device_get(
                                    (jnp.stack([lv for _, lv in pending]),
                                     oks_stack,
                                     [s for _, s in stats_burst], fp_dev))
                    except Exception as e:
                        # async dispatch: an allocation failure often
                        # surfaces at the first sync, not the dispatch
                        memstats.reraise_oom(e, program="train_step",
                                             step=iters[-1], epoch=epoch)
                        raise
                    if fp_host is not None:
                        self._device_fingerprint = {
                            "iteration": iters[-1] + 1,
                            "fp": int(fp_host)}
                    if oks is not None:
                        from deeplearning4j_tpu.faults.sentinels import \
                            check_ok_flags
                        ok_iters = [it for it, _ in pending_oks]
                        pending_oks.clear()
                        check_ok_flags(np.asarray(oks), ok_iters, epoch,
                                       epoch_start_iter)
                    if stats_burst:
                        from deeplearning4j_tpu.monitor.tensorstats import \
                            build_record
                        ts_recs = [
                            build_record(ts_names, s, it_, epoch, ts_cfg)
                            for (it_, _), s in zip(stats_burst,
                                                   stats_host)]
                vals = [float(v) for v in vals_arr]
                epoch_losses.extend(vals)
                if sync_params_on_flush:
                    # the FULL training state, not just params: a
                    # checkpoint taken at this flush must capture updater
                    # state and the iteration counter too (mid-epoch
                    # snapshots resume bit-exact, checkpoint/listener.py)
                    for n, p in {**params, **svars}.items():
                        self._arrays[n] = jnp.copy(p)
                    self._updater_state = jax.tree_util.tree_map(
                        jnp.copy, state)
                    tc.iteration_count = iters[-1] + 1
                if self._nan_panic_active(tc):
                    for it, v in zip(iters, vals):
                        if not np.isfinite(v):
                            raise NumericsException(
                                f"non-finite loss {v} at iteration {it} "
                                f"(nan_panic); localize the producing op "
                                f"with sd.exec_debug(placeholders)")
                for l in listeners:
                    l.iterations_done(self, epoch, iters, vals)
                if ts_recs:
                    for l in listeners:
                        hook = getattr(l, "tensorstats_done", None)
                        if hook is not None:
                            hook(self, epoch, ts_recs)
                pending.clear()

            for l in listeners:
                l.on_epoch_start(self, epoch)
            if hasattr(dataset_iterator, "reset"):
                dataset_iterator.reset()
            # one-batch-ahead prefetch: enqueue the NEXT batch's host→HBM
            # transfer before stepping on the current one, so transfers
            # overlap compute (reference: AsyncDataSetIterator's prefetch
            # thread, MultiLayerNetwork.java:1678)
            batch_iter = iter(dataset_iterator)
            ph = next((_prep_batch(b) for b in batch_iter), None)
            while ph is not None:
                # one "step" span per dispatch (the per-step tier's
                # window of k=1) with data_wait/dispatch children;
                # listener flushes record outside it (monitor/steptime)
                with _tracer.span("step", cat="train", k=1,
                                  iteration=iteration):
                    with _tracer.span("data_wait", cat="train"):
                        nxt = next((_prep_batch(b) for b in batch_iter),
                                   None)
                    for l in listeners:
                        if getattr(l, "batch_size", -1) is None:
                            l.batch_size = next(iter(ph.values())).shape[0]
                    with _tracer.span("dispatch", cat="train"):
                        if mem_on:
                            step_sig = ph_shape_sig(ph)
                            if step_sig not in mem_sigs:
                                mem_sigs.add(step_sig)
                                memstats.promote_dispatch(
                                    step, (params, svars, state, it_dev,
                                           constants, ph, base_key),
                                    step_sig, "train_step", steps=1,
                                    graph=self)
                            memstats.note_dispatch(step_sig, steps=1)
                        try:
                            with _wd_guard("step_dispatch"):
                                res = step(params, svars, state, it_dev,
                                           constants, ph, base_key)
                        except Exception as e:
                            memstats.reraise_oom(e, program="train_step",
                                                 step=iteration,
                                                 epoch=epoch)
                            raise
                        params, svars, state, it_dev, loss_val = res[:5]
                        r = 5
                        if use_sentinel:
                            ok = res[r]; r += 1
                            if listeners:
                                pending_oks.append((iteration, ok))
                            else:
                                epoch_oks.append(ok)
                        if ts_cfg is not None and \
                                sample_mask(iteration, ts_cfg):
                            # host-side gate is THE traced predicate on
                            # a host int — the same construction, so it
                            # can never disagree with the in-graph
                            # lax.cond (unsampled steps return zeros
                            # that are simply never retained)
                            pending_stats.append((iteration, res[r]))
                    # without listeners, never force a device sync: losses
                    # stay async device scalars (a scalar fetch = tunnel
                    # round-trip)
                    if listeners:
                        pending.append((iteration, loss_val))
                    else:
                        epoch_losses.append(loss_val)
                    iteration += 1
                if pending and len(pending) >= flush_every:
                    _flush(pending)
                ph = nxt
            if epoch_oks:
                # sentinel without listeners: ONE stacked verdict fetch
                # per epoch (the rail's only extra sync on this path)
                from deeplearning4j_tpu.faults.sentinels import \
                    check_ok_flags
                oks = np.asarray(jnp.stack(epoch_oks))
                epoch_oks.clear()
                check_ok_flags(oks, range(epoch_start_iter,
                                          epoch_start_iter + len(oks)),
                               epoch, epoch_start_iter)
            if listeners:
                _flush(pending)
                mean_loss = float(np.mean(epoch_losses)) \
                    if epoch_losses else float("nan")
            elif self._nan_panic_active(tc):
                # panic mode: fetch the epoch mean NOW (one sync per epoch)
                mean_loss = float(jnp.mean(jnp.stack(epoch_losses))) \
                    if epoch_losses else float("nan")
                if epoch_losses and not np.isfinite(mean_loss):
                    raise NumericsException(
                        f"non-finite epoch-{epoch} mean loss {mean_loss} "
                        f"(nan_panic); localize with sd.exec_debug()")
            else:
                # mean on device, fetch deferred to fit end (one transfer)
                mean_loss = None
                deferred_means.append(
                    jnp.mean(jnp.stack(epoch_losses)) if epoch_losses
                    else jnp.asarray(float("nan")))
            history.add_epoch(epoch, mean_loss)
            tc.epoch_count = getattr(tc, "epoch_count", 0) + 1
            # dispatch accounting (ui/stats 'dispatch' records, bench.py)
            self.last_fit_stats = {
                "tier": "per_step", "fused_steps": 1, "accum_steps": 1,
                "steps_per_epoch": iteration - epoch_start_iter,
                "dispatches_per_epoch": iteration - epoch_start_iter,
                "window_sizes": {1: iteration - epoch_start_iter},
                "window_compiles": 0}
            if listeners:
                # sync current params/state into the graph (copies — the next
                # step donates the working buffers) so listeners can save/eval
                for n, p in {**params, **svars}.items():
                    self._arrays[n] = jnp.copy(p)
                self._updater_state = jax.tree_util.tree_map(jnp.copy, state)
                tc.iteration_count = iteration
            stop = False
            for l in listeners:
                if l.on_epoch_end(self, epoch, mean_loss) is False:
                    stop = True
            if stop:
                break
        if deferred_means:
            fetched = np.asarray(jnp.stack(deferred_means))
            history.loss_curve.losses = [float(v) for v in fetched]
        # write trained params back into the graph
        for n, p in {**params, **svars}.items():
            self._arrays[n] = p
        self._updater_state = state
        tc.iteration_count = iteration
        if fp_on:
            # final boundary digest: a checkpoint captured after this
            # fit verifies its host bytes against it
            self._device_fingerprint = {
                "iteration": int(iteration),
                "fp": int(jax.device_get(fp_fn(params, svars, state)))}
        for l in listeners:
            l.on_training_end(self)
        return history

    def _fit_scanned(self, dataset_iterator, epochs: int):
        """fit() fast path: epochs of lax.scan over device-stacked batches."""
        from deeplearning4j_tpu.autodiff.training import History
        tc = self.training_config
        use_sentinel = bool(getattr(tc, "sentinel", False))
        fp_on = bool(getattr(tc, "fingerprints", False))
        self._device_fingerprint = None
        epoch_step = self.make_train_epoch(
            unroll=getattr(tc, "scan_unroll", 1) or 1,
            sentinel=use_sentinel, fingerprint=fp_on)
        params = jax.tree_util.tree_map(jnp.copy, self.trainable_params())
        svars = jax.tree_util.tree_map(jnp.copy, self.state_vars_map())
        if self._updater_state is not None and \
                set(self._updater_state.keys()) == set(params.keys()):
            state = jax.tree_util.tree_map(jnp.copy, self._updater_state)
        else:
            state = tc.updater.init(params)
        constants = self.constants_map()
        iteration = getattr(tc, "iteration_count", 0)
        it_dev = jnp.asarray(iteration, jnp.int32)
        self._fit_base_seed = self._seed    # resumable RNG state, see fit()
        base_key = jax.random.key(self._seed)
        self._seed += 1
        feats, labels = dataset_iterator.stacked_batches()
        stacked = {}
        for name, arr in list(zip(tc.data_set_feature_mapping, feats)) + \
                list(zip(tc.data_set_label_mapping, labels)):
            dt = self._vars[name].dtype if name in self._vars else None
            stacked[name] = _to_jnp(arr, dt)
        n_steps = next(iter(stacked.values())).shape[0]
        # memory-plan capture + OOM forensics for the scanned tier: one
        # signature per fit, promoted to an AOT compile when capture is
        # armed so /report can show the whole-epoch program's footprint
        scan_label = f"scanned_epoch_{n_steps}"
        scan_sig = ph_shape_sig(stacked)
        memstats.promote_dispatch(
            epoch_step, (params, svars, state, it_dev, constants,
                         stacked, base_key), scan_sig, scan_label,
            steps=n_steps, graph=self)
        memstats.note_dispatch(scan_sig, steps=n_steps)
        history = History()
        epoch_means = []
        last_fp = None                 # device uint32, fetched at fit end
        panic = self._nan_panic_active(tc)
        for epoch in range(epochs):
            try:
                res = epoch_step(params, svars, state, it_dev,
                                 constants, stacked, base_key)
            except Exception as e:
                memstats.reraise_oom(e, program=scan_label,
                                     step=iteration, epoch=epoch)
                raise
            # positional layout (make_train_window): p, sv, st, it,
            # losses [, bad] [, fp]
            params, svars, state, it_dev, losses = res[:5]
            r = 5
            if use_sentinel:
                bad = int(res[r])  # one scalar sync per scanned epoch
                r += 1
                if bad >= 0:
                    from deeplearning4j_tpu.faults.sentinels import \
                        raise_diverged
                    # epoch = this fit's loop index, matching the
                    # per-step and windowed tiers' provenance
                    raise_diverged(bad, epoch, iteration)
            if fp_on:
                last_fp = res[r]
                r += 1
            m = jnp.mean(losses)
            if panic and not np.isfinite(float(m)):
                raise NumericsException(
                    f"non-finite mean loss {float(m)} in scanned epoch "
                    f"(nan_panic); localize with sd.exec_debug()")
            epoch_means.append(m)
            iteration += n_steps
            self.last_fit_stats = {
                "tier": "scanned_epoch", "fused_steps": n_steps,
                "accum_steps": 1, "steps_per_epoch": n_steps,
                "dispatches_per_epoch": 1, "window_sizes": {n_steps: 1},
                "window_compiles": 0}
        # ONE device fetch for all epoch means at fit end
        fetched = np.asarray(jnp.stack(epoch_means))
        for e in range(epochs):
            history.add_epoch(e, float(fetched[e]))
        for n, p in {**params, **svars}.items():
            self._arrays[n] = p
        self._updater_state = state
        tc.iteration_count = iteration
        tc.epoch_count = getattr(tc, "epoch_count", 0) + epochs
        if last_fp is not None:
            # the boundary digest a checkpoint capture after this fit
            # verifies against (integrity/fingerprint.py)
            self._device_fingerprint = {"iteration": int(iteration),
                                        "fp": int(last_fp)}
        return history

    # ------------------------------------------------------------------
    # serde (reference: SameDiff.save/fromFlatBuffers, SameDiff.java:1583)
    def save(self, path, include_updater_state: bool = True):
        from deeplearning4j_tpu.autodiff import serde
        serde.save(self, path, include_updater_state)

    @staticmethod
    def load(path) -> "SameDiff":
        from deeplearning4j_tpu.autodiff import serde
        return serde.load(path)

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._ops)} ops"]
        for n, v in self._vars.items():
            if v.var_type != VariableType.ARRAY:
                lines.append(f"  {v.var_type.value:<11} {n:<24} {v._shape}")
        for node in self.ops():
            lines.append(f"  OP {node.op:<20} {node.inputs} -> {node.outputs}")
        return "\n".join(lines)


def _split_batch(batch):
    """Accept DataSet-like or (features, labels) batches (dict batches are
    handled in fit() — their keys are placeholder names directly)."""
    if hasattr(batch, "features") and hasattr(batch, "labels"):
        f, l = batch.features, batch.labels
        feats = f if isinstance(f, (list, tuple)) else [f]
        labels = l if isinstance(l, (list, tuple)) else [l]
        return feats, labels
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        f, l = batch
        feats = f if isinstance(f, (list, tuple)) else [f]
        labels = l if isinstance(l, (list, tuple)) else [l]
        return feats, labels
    raise TypeError(f"cannot interpret batch of type {type(batch)}")
