"""SLO-headroom autoscaling: start/drain replicas on TTFT pressure.

The scaling signal is the same admission math every replica sheds on
(docs/serving.md "Admission math"): estimated TTFT on a replica is
``(queue_depth + 1) × rolling p99 decode-step ms``. The fleet-level
p99 TTFT estimate is the WORST ready replica's estimate — a router
places on the least-loaded replica, but under sustained pressure the
worst replica is where the next unlucky request lands.

- **scale up** when estimated TTFT eats past ``scale_up_headroom`` of
  the SLO (default: est > 70% of the deadline), or mean queue depth
  reaches ``queue_high``, or the queue trend is strictly rising from a
  nonzero base (pressure building faster than the fleet drains it).
- **scale down** when estimated TTFT is below ``scale_down_headroom``
  of the SLO AND queues are empty — capacity is provably idle.
- **hysteresis** — a signal must repeat ``hysteresis`` consecutive
  evaluations before acting, and ``cooldown_s`` must have elapsed
  since the last action; flapping traffic changes the signal, not the
  fleet.
- **bounds** — never below ``min_replicas`` or above ``max_replicas``.

Scale-down drains through the replica's existing quiesce + drain-on-
shutdown path: the victim (the least-loaded ready replica) leaves the
routing set, finishes its in-flight work, then stops — zero failed
requests, same as a deploy drain.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from deeplearning4j_tpu.serving.fleet.metrics import FleetMetrics
from deeplearning4j_tpu.serving.fleet.replica import FleetReplica, ReplicaLoad
from deeplearning4j_tpu.serving.fleet.router import FleetRouter


class FleetAutoscaler:
    """Evaluate the SLO-headroom signal and act on a router's fleet.

    ``factory(name) -> FleetReplica`` builds (and starts) a fresh
    replica for scale-up. ``evaluate`` is side-effect-free given a
    loads dict (tests drive it with synthetic loads); ``step`` applies
    hysteresis/cooldown/bounds and actually scales."""

    def __init__(self, router: FleetRouter,
                 factory: Callable[[str], FleetReplica], *,
                 ttft_slo_ms: float = 500.0,
                 min_replicas: int = 1, max_replicas: int = 8,
                 scale_up_headroom: float = 0.7,
                 scale_down_headroom: float = 0.2,
                 queue_high: int = 4, hysteresis: int = 2,
                 cooldown_s: float = 10.0,
                 drain_timeout_s: float = 30.0,
                 metrics: Optional[FleetMetrics] = None,
                 clock: Callable[[], float] = time.monotonic):
        if not 0.0 < scale_down_headroom < scale_up_headroom:
            raise ValueError("need 0 < scale_down_headroom < "
                             "scale_up_headroom")
        self.router = router
        self.factory = factory
        self.ttft_slo_ms = float(ttft_slo_ms)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_headroom = float(scale_up_headroom)
        self.scale_down_headroom = float(scale_down_headroom)
        self.queue_high = int(queue_high)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.metrics = metrics if metrics is not None else router.metrics
        self._clock = clock
        self._streak_signal = "hold"
        self._streak = 0
        self._last_action_t = float("-inf")
        self._prev_mean_queue: Optional[float] = None
        self._next_id = 0

    # -- signal ---------------------------------------------------------
    def fleet_ttft_estimate_ms(self,
                               loads: Dict[str, ReplicaLoad]) -> float:
        """Worst ready replica's ``(queue_depth + 1) × p99 step``."""
        ests = [(l.queue_depth + 1) * l.p99_decode_step_ms
                for l in loads.values() if l.ready]
        return max(ests) if ests else float("inf")

    def evaluate(self,
                 loads: Optional[Dict[str, ReplicaLoad]] = None) -> str:
        """``scale_up`` / ``scale_down`` / ``hold`` from the current
        (or given) loads. Pure in ``loads`` apart from the queue-trend
        memory."""
        if loads is None:
            loads = self.router.snapshot_loads()
        ready = [l for l in loads.values() if l.ready]
        if not ready:
            return "scale_up"           # nothing can serve: grow or die
        est = self.fleet_ttft_estimate_ms(loads)
        mean_queue = sum(l.queue_depth for l in ready) / len(ready)
        prev = self._prev_mean_queue
        self._prev_mean_queue = mean_queue
        rising = prev is not None and prev > 0 and mean_queue > prev
        if (est > self.scale_up_headroom * self.ttft_slo_ms
                or mean_queue >= self.queue_high or rising):
            return "scale_up"
        if (est < self.scale_down_headroom * self.ttft_slo_ms
                and mean_queue == 0):
            return "scale_down"
        return "hold"

    # -- actuation ------------------------------------------------------
    def _n_live(self) -> int:
        with self.router._lock:
            return sum(1 for r in self.router.replicas.values()
                       if r.alive)

    def step(self,
             loads: Optional[Dict[str, ReplicaLoad]] = None) -> dict:
        """One control-loop tick: evaluate, apply hysteresis/cooldown/
        bounds, act. Returns ``{"signal", "acted", "replicas", ...}``."""
        signal = self.evaluate(loads)
        if signal == self._streak_signal:
            self._streak += 1
        else:
            self._streak_signal, self._streak = signal, 1
        out = {"signal": signal, "acted": False,
               "streak": self._streak, "replicas": self._n_live()}
        if signal == "hold" or self._streak < self.hysteresis:
            return out
        if (self._clock() - self._last_action_t) < self.cooldown_s:
            out["reason"] = "cooldown"
            return out
        n = self._n_live()
        if signal == "scale_up":
            if n >= self.max_replicas:
                out["reason"] = "at max_replicas"
                return out
            name = self._fresh_name()
            replica = self.factory(name)
            replica.start()
            self.router.add_replica(replica)
            self.metrics.inc("scale_up_events")
            out.update(acted=True, replica=name, replicas=n + 1)
        else:
            if n <= self.min_replicas:
                out["reason"] = "at min_replicas"
                return out
            victim = self._pick_victim(loads)
            if victim is None:
                out["reason"] = "no drainable replica"
                return out
            victim.quiesce(timeout_s=self.drain_timeout_s)
            self.router.remove_replica(victim.name)
            victim.stop(drain=True)
            self.metrics.inc("scale_down_events")
            out.update(acted=True, replica=victim.name, replicas=n - 1)
        self._last_action_t = self._clock()
        self._streak = 0
        return out

    def _fresh_name(self) -> str:
        with self.router._lock:
            taken = set(self.router.replicas)
        while True:
            name = f"scaled-{self._next_id}"
            self._next_id += 1
            if name not in taken:
                return name

    def _pick_victim(self,
                     loads: Optional[Dict[str, ReplicaLoad]] = None
                     ) -> Optional[FleetReplica]:
        """Least-loaded ready replica — cheapest to drain."""
        if loads is None:
            loads = self.router.snapshot_loads()
        with self.router._lock:
            candidates = [(r, loads.get(r.name))
                          for r in self.router.replicas.values()
                          if r.routable]
        candidates = [(r, l) for r, l in candidates
                      if l is not None and l.ready]
        if not candidates:
            return None
        return min(candidates, key=lambda rl: rl[1].score())[0]


__all__ = ["FleetAutoscaler"]
