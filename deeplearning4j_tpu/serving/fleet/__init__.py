"""serving.fleet — the multi-replica front door (ROADMAP item 3).

The cluster tier over ``PagedGenerativeServer``/``GenerativeServer``
replicas — the reference's ``ParallelInference`` fan-out role scaled
from threads-in-one-JVM to a fleet of serving processes:

- ``replica``: :class:`FleetReplica` — one server + its telemetry as a
  fleet citizen: scrapeable load (``/readyz`` + the merged ``load``
  sub-dict), lifecycle (start / quiesce / stop / kill), hot reload
  with snapshot/rollback.
- ``router``: :class:`FleetRouter` — least-loaded-among-ready dispatch
  with staleness cutoffs, rendezvous prefix-affinity routing keyed on
  the SAME chain hashes the paged prefix cache uses, and retry-on-
  shed/death honoring the typed ``retry_after_s`` contract within a
  per-request budget (permanent errors never retried).
- ``deploy``: :class:`RollingDeploy` — canary → shadow-eval token-match
  gate → one-at-a-time roll, drain-before-reload, snapshot rollback on
  any failed gate; zero in-flight failures by construction.
- ``autoscale``: :class:`FleetAutoscaler` — SLO-headroom signal (fleet
  p99 TTFT estimate vs deadline + queue trend) starting/draining
  replicas with hysteresis, cooldown and min/max bounds.
- ``durable``: :class:`RequestJournal` / :class:`StreamCursor` /
  :class:`DurabilityMetrics` — the write-ahead journal, exactly-once
  streaming and resume-from-emitted-prefix rail the router's
  ``generate``/``recover`` compose (docs/serving.md "Durability").
- ``metrics``: :class:`FleetMetrics` — ``{"type": "fleet"}`` records →
  ``dl4j_fleet_*`` gauges (``registry.fold_fleet``) and the ui/report
  "Fleet" panel.

See docs/serving.md ("Fleet") for semantics and the retry table.
"""
from deeplearning4j_tpu.serving.fleet.autoscale import FleetAutoscaler
from deeplearning4j_tpu.serving.fleet.deploy import (RollingDeploy,
                                                     rolling_deploy)
from deeplearning4j_tpu.serving.fleet.durable import (DURABILITY_COUNTERS,
                                                      DurabilityMetrics,
                                                      JournalCorruptError,
                                                      RequestJournal,
                                                      StreamCursor)
from deeplearning4j_tpu.serving.fleet.metrics import (FLEET_COUNTERS,
                                                      FleetMetrics)
from deeplearning4j_tpu.serving.fleet.replica import (REPLICA_STATES,
                                                      FleetReplica,
                                                      ReplicaLoad)
from deeplearning4j_tpu.serving.fleet.router import (FleetResult,
                                                     FleetRouter,
                                                     FleetUnavailableError)

__all__ = [
    "DurabilityMetrics", "DURABILITY_COUNTERS",
    "FleetAutoscaler",
    "FleetMetrics", "FLEET_COUNTERS",
    "JournalCorruptError", "RequestJournal", "StreamCursor",
    "FleetReplica", "ReplicaLoad", "REPLICA_STATES",
    "FleetResult", "FleetRouter", "FleetUnavailableError",
    "RollingDeploy", "rolling_deploy",
]
