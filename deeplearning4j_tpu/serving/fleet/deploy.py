"""Rolling canaried deploys: reload a fleet one replica at a time.

The checkpoint-follows-training story at fleet scale. A deploy never
takes the front door down and never fails an in-flight request:

1. **canary** — ONE replica is quiesced (drain-before-reload: the
   router stops placing work on it, every queued + in-flight
   generation finishes), its parameters snapshotted, then reloaded via
   ``update_model`` (prefix-cache fencing included on paged servers).
2. **gate** — the canary must scrape healthy+ready AND pass the
   shadow-eval probes: each probe prompt is generated on the canary
   and token-matched against its expected tokens (when given) —
   greedy decode is deterministic, so one mismatched token means the
   new parameters changed behavior. Probes without expected tokens
   capture the canary's output as the fleet reference: every later
   replica must match the canary bit-exactly, or the fleet would serve
   two models at once.
3. **roll** — the remaining replicas repeat quiesce → reload → gate
   one at a time; the rest of the fleet keeps serving throughout.
4. **rollback** — any failed gate restores that replica's snapshot
   (``restore_params`` — the paged server re-fences its prefix cache)
   and aborts the deploy with a typed report. Already-rolled replicas
   keep the new parameters; the report says exactly how far the roll
   got (``rolled``/``failed_at``/``reason``) so an operator — or a
   retry loop — can decide.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.fleet.metrics import FleetMetrics
from deeplearning4j_tpu.serving.fleet.replica import FleetReplica
from deeplearning4j_tpu.serving.fleet.router import FleetRouter


class RollingDeploy:
    """Drive a canaried rolling reload over a router's replicas.

    ``probes`` is a sequence of ``(prompt, max_new_tokens,
    expected_tokens_or_None)``. ``drain_timeout_s`` bounds each
    replica's quiesce; a replica that cannot drain in time aborts the
    deploy with NOTHING reloaded on it (it resumes serving the old
    parameters)."""

    def __init__(self, router: FleetRouter,
                 probes: Sequence[Tuple] = (),
                 drain_timeout_s: float = 30.0,
                 probe_timeout_s: float = 60.0,
                 metrics: Optional[FleetMetrics] = None):
        self.router = router
        self.probes = [(p, int(n), None if exp is None else
                        [int(t) for t in exp]) for p, n, exp in probes]
        self.drain_timeout_s = float(drain_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.metrics = metrics if metrics is not None else router.metrics

    # ------------------------------------------------------------------
    def _gate(self, replica: FleetReplica,
              reference: List[Optional[List[int]]]) -> Tuple[bool, str]:
        """Health + shadow-eval check on a freshly reloaded (still
        draining) replica. Mutates ``reference`` in place: probes with
        no expected tokens adopt this replica's output as the fleet
        reference (the canary defines truth for the roll)."""
        load = replica.scrape()
        if not load.healthy:
            return False, "unhealthy after reload"
        for i, (prompt, n_new, expected) in enumerate(self.probes):
            try:
                handle = replica.submit(prompt, max_new_tokens=n_new)
                got = [int(t) for t in
                       handle.result(timeout=self.probe_timeout_s)]
            except Exception as e:      # noqa: BLE001 — any raise fails the gate
                return False, f"probe {i} raised {type(e).__name__}: {e}"
            want = expected if expected is not None else reference[i]
            if want is not None and got != want:
                return False, (f"probe {i} token mismatch: "
                               f"got {got[:8]}..., want {want[:8]}...")
            if reference[i] is None:
                reference[i] = got
        return True, "ok"

    def run(self, canary: Optional[str] = None) -> dict:
        """Execute the deploy. Returns the report dict; ``ok`` is True
        only when EVERY replica reloaded and passed its gate."""
        t0 = time.monotonic()
        with self.router._lock:
            replicas = [r for r in self.router.replicas.values()
                        if r.alive]
        if not replicas:
            return {"ok": False, "reason": "no live replicas",
                    "rolled": [], "seconds": 0.0}
        if canary is not None:
            replicas.sort(key=lambda r: (r.name != canary, r.name))
        report = {"ok": False, "canary": replicas[0].name,
                  "rolled": [], "probes": len(self.probes)}
        reference: List[Optional[List[int]]] = [
            exp for _, _, exp in self.probes]
        for replica in replicas:
            if not replica.quiesce(timeout_s=self.drain_timeout_s):
                replica.resume()
                report.update(failed_at=replica.name,
                              reason=f"drain timed out after "
                                     f"{self.drain_timeout_s:g}s")
                break
            snapshot = replica.params_snapshot()
            try:
                replica.reload_from()
                ok, why = self._gate(replica, reference)
            except Exception as e:      # noqa: BLE001 — reload itself failed
                ok, why = False, f"reload raised {type(e).__name__}: {e}"
            if not ok:
                replica.restore_params(snapshot)
                replica.resume()
                self.metrics.inc("deploy_rollbacks")
                report.update(failed_at=replica.name, reason=why,
                              rolled_back=True)
                break
            replica.resume()
            report["rolled"].append(replica.name)
        else:
            report["ok"] = True
            self.metrics.inc("deploys")
        report["seconds"] = round(time.monotonic() - t0, 3)
        return report


def rolling_deploy(router: FleetRouter, probes: Sequence[Tuple] = (),
                   canary: Optional[str] = None, **kw) -> dict:
    """Functional shorthand for ``RollingDeploy(router, probes,
    **kw).run(canary)``."""
    return RollingDeploy(router, probes=probes, **kw).run(canary=canary)


__all__ = ["RollingDeploy", "rolling_deploy"]
