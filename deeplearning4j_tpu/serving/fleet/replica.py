"""Replica handle: one generative server + its telemetry, fleet-shaped.

A :class:`FleetReplica` wraps one ``GenerativeServer`` /
``PagedGenerativeServer`` (built lazily by a factory, or adopted
pre-built) and gives the fleet tier the four things it needs:

- **scrapeable load** — :meth:`scrape` returns a :class:`ReplicaLoad`
  (ready/healthy + queue depth, occupancy, rolling p99 decode-step ms).
  When the server runs a TelemetryServer the scrape goes over HTTP
  ``GET /readyz`` — the real cross-process path, reading the ``load``
  sub-dict that ``health_snapshot`` merges from the server's health
  provider; without one it calls the provider in-process. Either way
  the router sees the same fields.
- **lifecycle** — :meth:`start` / :meth:`stop` (drain-on-shutdown) /
  :meth:`kill` (the chaos path: abort without drain, state ``dead``).
- **drain-before-reload** — :meth:`quiesce` flags the replica
  ``draining`` (the router stops placing work on it) and waits for the
  server to go idle; :meth:`resume` re-admits it. The rolling deploy
  drives reloads exclusively through this window, so zero in-flight
  requests ever observe a parameter swap mid-drain.
- **reload** — :meth:`reload_from` re-pulls the spec's parameters
  (``update_model`` — prefix-cache fencing included on the paged
  server), bumping ``model_version``; ``params_snapshot()`` /
  ``restore_params()`` pass through for the deploy gate's rollback.
"""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional

from deeplearning4j_tpu.serving.queue import ServerClosedError

#: replica lifecycle states (``draining`` still counts as alive — it
#: finishes in-flight work; ``dead`` vs ``stopped`` distinguishes a
#: chaos kill/crash from an orderly drain-and-stop)
REPLICA_STATES = ("new", "ready", "draining", "stopped", "dead")


@dataclass
class ReplicaLoad:
    """One scrape of a replica's routing signal (the ``/readyz``
    ``load`` sub-dict plus the readiness verdict). ``t`` is the
    scraper's monotonic clock — the router's staleness cutoff compares
    against it, so a replica whose telemetry stops answering ages out
    of the ready set without any extra liveness machinery."""

    t: float
    ready: bool
    healthy: bool
    queue_depth: int = 0
    occupancy: float = 0.0              # max(slot, pool) occupancy
    p99_decode_step_ms: float = 0.0

    def stale(self, now: float, cutoff_s: float) -> bool:
        return (now - self.t) > cutoff_s

    def score(self) -> tuple:
        """Least-loaded ordering key: queue depth dominates (queued
        work is guaranteed wait), occupancy breaks ties."""
        return (self.queue_depth, self.occupancy)


class FleetReplica:
    """Handle on one serving replica for the fleet router/deployer/
    autoscaler. Construct with a live ``server`` or a zero-arg
    ``factory`` (built at :meth:`start` — the autoscaler's scale-up
    path)."""

    def __init__(self, name: str, server=None,
                 factory: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        if server is None and factory is None:
            raise ValueError("FleetReplica needs a server or a factory")
        self.name = str(name)
        self.server = server
        self._factory = factory
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "ready" if server is not None else "new"
        self.model_version = 0
        self.last_load: Optional[ReplicaLoad] = None

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "FleetReplica":
        """Build the server (factory mode) and mark the replica ready.
        Idempotent for an already-ready replica; restarting a stopped/
        dead replica requires a factory (the old server is gone)."""
        with self._lock:
            if self.state == "ready":
                return self
            if self.state in ("stopped", "dead") and self._factory is None:
                raise ServerClosedError(
                    f"replica {self.name} is {self.state} and has no "
                    f"factory to rebuild it")
            if self.server is None or self.state in ("stopped", "dead"):
                self.server = self._factory()
            self.state = "ready"
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Orderly shutdown through the server's drain path."""
        with self._lock:
            if self.state in ("stopped", "dead", "new"):
                self.state = "stopped" if self.state == "new" else self.state
                return
            self.state = "stopped"
        self.server.shutdown(drain=drain, timeout=timeout)

    def kill(self) -> None:
        """Chaos: die without draining — queued AND in-flight
        generations fail typed (``abort``: a killed process completes
        nothing; the old ``shutdown(drain=False)`` let active slots
        finish, which no SIGKILL ever would), and the replica leaves
        the ready set. What a killed process looks like from the
        router's side — the router's continuation failover resumes the
        aborted streams from their emitted prefixes."""
        with self._lock:
            if self.state in ("stopped", "dead"):
                self.state = "dead"
                return
            self.state = "dead"
        abort = getattr(self.server, "abort", None)
        if abort is not None:
            abort()
        else:
            self.server.shutdown(drain=False)

    def mark_dead(self) -> None:
        """Router-side verdict (a submit raised ``ServerClosedError``):
        stop routing here without touching the server."""
        with self._lock:
            if self.state not in ("stopped",):
                self.state = "dead"

    @property
    def alive(self) -> bool:
        return self.state in ("ready", "draining")

    @property
    def routable(self) -> bool:
        return self.state == "ready"

    # -- drain-before-reload -------------------------------------------
    @property
    def idle(self) -> bool:
        s = self.server
        return s is None or (s._queue.pending() == 0
                             and s._n_active() == 0)

    def quiesce(self, timeout_s: float = 30.0,
                poll_s: float = 0.005) -> bool:
        """Stop receiving fleet traffic (state ``draining``) and wait
        until every queued + in-flight generation finished. Returns
        False on timeout — the replica STAYS draining so the caller
        decides (the deploy aborts and resumes it)."""
        with self._lock:
            if self.state != "ready":
                return self.state == "draining" and self.idle
            self.state = "draining"
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            if self.idle:
                return True
            time.sleep(poll_s)
        return self.idle

    def resume(self) -> None:
        with self._lock:
            if self.state == "draining":
                self.state = "ready"

    # -- reload ---------------------------------------------------------
    def reload_from(self, version: Optional[int] = None) -> int:
        """Hot-reload serving parameters from the spec's source graph
        (``update_model`` — the paged server also fences its prefix
        cache). Returns the new ``model_version``."""
        self.server.update_model()
        self.model_version = (self.model_version + 1
                              if version is None else int(version))
        return self.model_version

    def params_snapshot(self):
        return self.server.params_snapshot()

    def restore_params(self, params) -> None:
        self.server.restore_params(params)

    # -- traffic --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 16, **kw):
        """Delegate to the server; a dead/stopped replica raises
        ``ServerClosedError`` exactly like a vanished process would.
        ``**kw`` flows through verbatim — in particular the router's
        ``trace=`` TraceContext (monitor/reqtrace.py), so the server's
        spans carry the fleet-wide trace_id/segment of this hop."""
        if not self.alive or self.server is None:
            raise ServerClosedError(
                f"replica {self.name} is {self.state}")
        return self.server.submit(prompt, max_new_tokens=max_new_tokens,
                                  **kw)

    def submit_continuation(self, prompt, emitted,
                            max_new_tokens: int = 16, **kw):
        """Delegate a resume-from-emitted-prefix continuation (see
        ``GenerativeServer.submit_continuation``) — the router's
        failover/replay path; a dead/stopped replica raises typed."""
        if not self.alive or self.server is None:
            raise ServerClosedError(
                f"replica {self.name} is {self.state}")
        return self.server.submit_continuation(
            prompt, emitted, max_new_tokens=max_new_tokens, **kw)

    def prefix_hits(self) -> int:
        """The replica's prefix-cache hit counter (0 on servers without
        a prefix cache) — what affinity routing is trying to maximize,
        and what the tests assert on."""
        try:
            return int(self.server.metrics.counters.get(
                "prefix_hits", 0))
        except Exception:
            return 0

    # -- load scrape ----------------------------------------------------
    def scrape(self, timeout_s: float = 1.0) -> ReplicaLoad:
        """Read ready/healthy + load. Over HTTP ``/readyz`` when the
        server has a TelemetryServer (the cross-process path), else
        straight from the health provider. Any scrape failure — dead
        process, refused connection, bad JSON — is itself the answer:
        not ready, not healthy."""
        now = self._clock()
        if not self.alive or self.server is None:
            load = ReplicaLoad(t=now, ready=False, healthy=False)
            self.last_load = load
            return load
        try:
            tel = getattr(self.server, "telemetry", None)
            if tel is not None:
                try:
                    with urllib.request.urlopen(tel.url + "/readyz",
                                                timeout=timeout_s) as resp:
                        snap = json.loads(resp.read().decode())
                except urllib.error.HTTPError as e:
                    # /readyz answers 503 WITH the snapshot body when
                    # unready — that is load data, not a scrape failure
                    snap = json.loads(e.read().decode())
            else:
                h = self.server._telemetry_health()
                snap = {"ready": bool(h.get("ready")),
                        "healthy": bool(h.get("healthy")),
                        "load": h.get("load") or {}}
            ld = snap.get("load") or {}
            occ = max(float(ld.get("slot_occupancy", 0.0)),
                      float(ld.get("pool_occupancy", 0.0)))
            load = ReplicaLoad(
                t=now,
                ready=bool(snap.get("ready")) and self.routable,
                healthy=bool(snap.get("healthy")),
                queue_depth=int(ld.get("queue_depth", 0)),
                occupancy=occ,
                p99_decode_step_ms=float(
                    ld.get("p99_decode_step_ms", 0.0)))
        except Exception:   # noqa: BLE001 — unreachable replica = unready
            load = ReplicaLoad(t=now, ready=False, healthy=False)
        self.last_load = load
        return load


__all__ = ["FleetReplica", "ReplicaLoad", "REPLICA_STATES"]
