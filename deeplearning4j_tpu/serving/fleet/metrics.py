"""Fleet-tier metrics: routing, retry, deploy and autoscale accounting.

One :class:`FleetMetrics` instance is shared by the router, the deploy
driver and the autoscaler, so a single ``{"type": "fleet"}`` record (or
one ``registry.fold_fleet`` scrape → ``dl4j_fleet_*`` gauges) tells the
whole cluster story: how traffic was placed (affinity home vs spill vs
least-loaded), how often sheds/deaths forced retries, what each replica
looked like at the last scrape, and every deploy/scale event.

The affinity hit rate is defined over affinity-ELIGIBLE requests only
(prompts with at least one full hashed block): ``home / (home +
spill)``. Requests with no affinity key route least-loaded and do not
dilute the rate — they could never have hit a prefix cache anyway.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.serving.metrics import safe_ratio

#: every counter FleetMetrics tracks (zero-initialized so records and
#: gauge folds are shape-stable from the first scrape)
FLEET_COUNTERS = (
    "requests_routed",          # submits that reached a replica
    "requests_ok",              # front-door generations that returned
    "requests_failed",          # permanent/exhausted failures surfaced
    "requests_timed_out",       # deadline misses (never retried)
    "routed_affinity",          # placed on the rendezvous home replica
    "routed_spill",             # had an affinity key, home overloaded
    "routed_least_loaded",      # no affinity key: pure load balancing
    "retries",                  # re-attempts after a shed or a death
    "sheds_seen",               # typed RetryableServingError observed
    "replica_deaths_seen",      # replicas marked dead mid-request
    "retry_giveups",            # budgets exhausted, shed re-raised typed
    "deploys",                  # completed rolling deploys
    "deploy_rollbacks",         # canary/roll gates that restored params
    "scale_up_events",
    "scale_down_events",
)


class FleetMetrics:
    """Thread-safe counters + per-replica last-scrape snapshots for the
    fleet front door (mirrors ``ServingMetrics``/``PagedMetrics``:
    plain ints under one lock, exported via :meth:`to_record`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in FLEET_COUNTERS}
        # name -> {"ready", "queue_depth", "occupancy",
        #          "p99_decode_step_ms", "routed"} from the last scrape
        self.replicas: Dict[str, dict] = {}
        # attached by a FleetRouter (its DurabilityMetrics): when set,
        # to_record extends the fleet record with a "durability" sub-
        # dict — same record type, no new registry/report plumbing
        self.durability = None
        # attached by a FleetRouter (its SLOTracker): same pattern, the
        # fleet record grows an "slo" sub-dict (attainment, burn rate,
        # percentiles, worst sampled waterfalls) — see monitor/reqtrace
        self.slo = None

    def inc(self, name: str, v: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + int(v)

    def on_routed(self, kind: str, replica: str) -> None:
        """One placement decision: ``kind`` is ``affinity`` (rendezvous
        home), ``spill`` (home overloaded → least-loaded) or
        ``least_loaded`` (no affinity key)."""
        key = {"affinity": "routed_affinity", "spill": "routed_spill",
               "least_loaded": "routed_least_loaded"}[kind]
        with self._lock:
            self.counters["requests_routed"] += 1
            self.counters[key] += 1
            rep = self.replicas.setdefault(replica, {})
            rep["routed"] = rep.get("routed", 0) + 1

    def observe_replica(self, name: str, load) -> None:
        """Record a replica's last scraped load (a ``ReplicaLoad``)."""
        with self._lock:
            rep = self.replicas.setdefault(name, {})
            rep.update(ready=bool(load.ready),
                       queue_depth=int(load.queue_depth),
                       occupancy=round(float(load.occupancy), 4),
                       p99_decode_step_ms=round(
                           float(load.p99_decode_step_ms), 3))

    def forget_replica(self, name: str) -> None:
        with self._lock:
            self.replicas.pop(name, None)

    def affinity_hit_rate(self) -> float:
        with self._lock:
            home = self.counters["routed_affinity"]
            spill = self.counters["routed_spill"]
        return safe_ratio(home, home + spill)

    def to_record(self, now: Optional[float] = None) -> dict:
        """One ``{"type": "fleet"}`` record for ``StatsStorage`` (the
        shape ``ui.report`` renders and ``registry.fold_fleet``
        exports)."""
        with self._lock:
            counters = dict(self.counters)
            replicas = {n: dict(r) for n, r in self.replicas.items()}
        ready = sum(1 for r in replicas.values() if r.get("ready"))
        durability = (self.durability.to_dict()
                      if self.durability is not None else None)
        rec = {
            "type": "fleet",
            "t": time.time() if now is None else now,
            "counters": counters,
            "fleet": {
                "n_replicas": len(replicas),
                "n_ready": ready,
                "affinity_hit_rate": round(safe_ratio(
                    counters["routed_affinity"],
                    counters["routed_affinity"]
                    + counters["routed_spill"]), 4),
                "retries_per_request": round(safe_ratio(
                    counters["retries"],
                    counters["requests_routed"]), 4),
            },
            "replicas": replicas,
        }
        if durability is not None:
            rec["durability"] = durability
        if self.slo is not None:
            rec["slo"] = self.slo.to_dict()
        return rec


__all__ = ["FLEET_COUNTERS", "FleetMetrics"]
