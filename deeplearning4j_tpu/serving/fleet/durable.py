"""Durable generative requests: the fleet's write-ahead journal rail.

PR 17's router already *retries* a mid-generation replica death — but by
resubmitting from scratch: every token the dead replica decoded is
discarded, a streaming consumer has no exactly-once contract across the
restart, and a router crash loses all in-flight requests with no
record. This module closes that last unguarded failure domain with
three pieces the router composes (see ``router.py``):

- :class:`RequestJournal` — an append-only fsync'd JSONL write-ahead
  log. Every record carries its own sha256 (over the canonical
  sorted-key JSON), so a recovery scan verifies integrity line by line
  and truncates a torn tail (a crash mid-append) at the last good
  record. Segment rotation reuses the checkpoint staging/commit
  discipline (``checkpoint/atomic.py``): live entries are *compacted*
  into the next segment via temp-file + fsync + atomic rename + dir
  fsync, and only then are the older segments deleted — at every
  instant a crash leaves a readable journal.
- :class:`StreamCursor` — the exactly-once delivery gate. Caller
  ``on_token`` callbacks route through it, deduplicated by generated
  ordinal, so a failover (which resumes from the emitted prefix) is
  invisible to a streaming consumer: zero duplicated, zero lost tokens.
- :class:`DurabilityMetrics` — ``resumes``, ``tokens_salvaged``,
  ``dedup_drops``, ``journal_fsync_ms`` et al., folded into the
  ``{"type": "fleet"}`` record's ``durability`` sub-dict
  (``registry.fold_fleet`` → ``dl4j_fleet_durability_*`` gauges).

Why journaling *tokens* is enough for bit-identity: PR 18 keys sampling
on ``(seed, absolute token index)`` where the index is ``prompt length
+ generated ordinal`` — a continuation prefilled with ``prompt +
emitted`` lands every remaining draw on exactly the indices the
uninterrupted run would have used, so the journal only needs the
submitted record (prompt, sampling kwargs, pinned seed) and the emitted
prefix; regeneration of anything not yet durable is bit-exact. Token
records are therefore batched (``flush_every``) without risking
correctness — an unflushed tail is simply re-decoded identically.

See docs/serving.md ("Durability") for the record format and the
recovery procedure.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.checkpoint.atomic import (atomic_output_file,
                                                  fsync_dir)
from deeplearning4j_tpu.serving.metrics import LatencyHistogram
from deeplearning4j_tpu.serving.resilience import ServingError

#: every counter DurabilityMetrics tracks (zero-initialized so records
#: and gauge folds are shape-stable from the first scrape)
DURABILITY_COUNTERS = (
    "resumes",                  # failovers resumed from an emitted prefix
    "tokens_salvaged",          # emitted tokens carried across a resume
                                # (per resume: the whole prefix a
                                # restart-from-scratch would regenerate)
    "dedup_drops",              # duplicate deliveries the cursor absorbed
    "journal_records",          # records appended (rotation snapshots too)
    "journal_truncated_bytes",  # torn-tail bytes dropped by recovery scans
    "recovered_requests",       # incomplete entries replayed by recover()
)

_SEGMENT_PREFIX = "journal-"
_SEGMENT_SUFFIX = ".jsonl"


class JournalCorruptError(ServingError):
    """A *sealed* journal segment failed its per-record sha256 scan.
    Unlike a torn tail on the active segment (a crash mid-append —
    expected, truncated, survivable), a bad record inside a segment
    that was committed through the atomic rotation path means the
    storage itself lied; recovery must not guess, so this is permanent
    (not retryable)."""


class DurabilityMetrics:
    """Thread-safe counters + fsync latency histogram for the durable
    request rail (mirrors ``FleetMetrics``: plain ints under one lock,
    exported as the fleet record's ``durability`` sub-dict)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {k: 0 for k in DURABILITY_COUNTERS}
        self.journal_fsync_ms = LatencyHistogram()

    def inc(self, name: str, v: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + int(v)

    def observe_fsync(self, ms: float) -> None:
        with self._lock:
            self.journal_fsync_ms.record(ms)

    def to_dict(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            h = self.journal_fsync_ms
            out["journal_fsync_ms"] = {
                "count": int(h.count),
                "mean": round(h.mean(), 4),
                "p50": round(h.percentile(50), 4),
                "p99": round(h.percentile(99), 4),
                "max": round(h.max_ms, 4),
            }
        return out


class StreamCursor:
    """Exactly-once delivery gate for one request's token stream.

    The router routes the caller's ``on_token`` through
    :meth:`deliver`, keyed by generated ordinal: a token already
    delivered is dropped (counted ``dedup_drops``) so retries and
    failovers can never double-stream, and a skipped ordinal raises —
    a gap would mean the continuation machinery lost a token, which
    must surface as a loud bug, never as silent stream corruption.
    ``delivered`` doubles as the resume prefix the next attempt
    prefills with.

    Every attempt the cursor survives shares ONE request trace: the
    router's TraceContext (monitor/reqtrace.py) keeps its trace_id
    across the resume, so the assembled waterfall shows the death and
    the continuation as consecutive segments of the same request."""

    def __init__(self, on_token: Optional[Callable[[int], None]] = None,
                 *, metrics: Optional[DurabilityMetrics] = None,
                 preload=()):
        self._on_token = on_token
        self._metrics = metrics
        # journal-replayed tokens arrive pre-delivered: the crashed
        # router's consumer already saw them, so they seed the resume
        # prefix without re-invoking the callback
        self.delivered: List[int] = [int(t) for t in preload]

    def deliver(self, index: int, token: int) -> bool:
        """Deliver the token at generated ordinal ``index`` exactly
        once. Returns True when this call was the delivery (the caller
        journals it), False for an absorbed duplicate."""
        index = int(index)
        if index < len(self.delivered):
            if self._metrics is not None:
                self._metrics.inc("dedup_drops")
            return False
        if index > len(self.delivered):
            raise RuntimeError(
                f"stream gap: token ordinal {index} delivered with only "
                f"{len(self.delivered)} tokens streamed — the exactly-"
                f"once contract is broken upstream")
        self.delivered.append(int(token))
        if self._on_token is not None:
            self._on_token(int(token))
        return True


def _record_sha(rec: dict) -> str:
    """sha256 over the record's canonical (sorted-key, tight-separator)
    JSON, excluding the ``sha`` field itself."""
    body = {k: v for k, v in rec.items() if k != "sha"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class RequestJournal:
    """Append-only fsync'd JSONL write-ahead log of fleet generations.

    One directory holds numbered segments (``journal-000001.jsonl``,
    ...); the highest-numbered segment is active, the rest are sealed.
    Record types (each line also carries ``rid`` and ``sha``):

    - ``submitted`` — prompt, ``max_new_tokens``, ``timeout_ms`` and
      the sampling kwargs with the *pinned* seed (the router pins it
      before journaling: a server-local default would not survive a
      cross-replica failover).
    - ``tokens`` — a batch of emitted tokens, ``at`` = the absolute
      index of the first (prompt length + generated ordinal). Batched
      ``flush_every`` deep; an unflushed tail is regenerated bit-
      identically on replay (see the module docstring), so batching
      trades recovery *work*, never correctness.
    - ``completed`` / ``failed`` — terminal. A retryable give-up is
      deliberately NOT terminal: the entry stays open so a restarted
      router's ``recover()`` replays it.

    Recovery scan (at open): sealed segments must verify clean
    (:class:`JournalCorruptError` otherwise — they were committed
    atomically); the active segment truncates at its first torn/corrupt
    line (a crash mid-append). Rotation compacts live entries into the
    next segment with the checkpoint staging/commit discipline, then
    deletes the older segments — terminal entries are how the journal
    reclaims space."""

    def __init__(self, directory: str, *, fsync: bool = True,
                 segment_max_bytes: int = 4 << 20, flush_every: int = 8,
                 metrics: Optional[DurabilityMetrics] = None):
        self.directory = str(directory)
        self.fsync = bool(fsync)
        self.segment_max_bytes = int(segment_max_bytes)
        self.flush_every = max(1, int(flush_every))
        self.metrics = metrics
        self._lock = threading.RLock()
        self._entries: Dict[int, dict] = {}
        self._pending: Dict[int, List[int]] = {}    # rid -> unflushed toks
        self._pending_at: Dict[int, int] = {}       # rid -> batch start idx
        self._next_rid = 1
        self._fh = None
        os.makedirs(self.directory, exist_ok=True)
        self._seg_index = self._recover()
        self._open_active()

    # -- segment bookkeeping --------------------------------------------
    def _seg_path(self, index: int) -> str:
        return os.path.join(
            self.directory,
            f"{_SEGMENT_PREFIX}{index:06d}{_SEGMENT_SUFFIX}")

    def _segments(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if (name.startswith(_SEGMENT_PREFIX)
                    and name.endswith(_SEGMENT_SUFFIX)):
                try:
                    out.append(int(name[len(_SEGMENT_PREFIX):
                                        -len(_SEGMENT_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(out)

    def _open_active(self) -> None:
        self._fh = open(self._seg_path(self._seg_index), "ab")

    # -- recovery scan ---------------------------------------------------
    def _recover(self) -> int:
        """Replay every segment into the in-memory entry table,
        truncating the active segment's torn tail. Returns the active
        segment index (1 for a fresh directory)."""
        segs = self._segments()
        if not segs:
            return 1
        for i, seg in enumerate(segs):
            sealed = i < len(segs) - 1
            self._scan_segment(self._seg_path(seg), sealed=sealed)
        return segs[-1]

    def _scan_segment(self, path: str, sealed: bool) -> None:
        good_end = 0
        with open(path, "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw.decode("utf-8"))
                    if not isinstance(rec, dict) or \
                            rec.get("sha") != _record_sha(rec):
                        raise ValueError("sha mismatch")
                except (ValueError, UnicodeDecodeError) as e:
                    if sealed:
                        raise JournalCorruptError(
                            f"sealed journal segment {path} fails its "
                            f"integrity scan at byte {good_end}: {e} — "
                            f"it was committed atomically, so this is "
                            f"storage corruption, not a torn tail"
                        ) from e
                    break
                good_end += len(raw)
                self._apply(rec)
        size = os.path.getsize(path)
        if not sealed and good_end < size:
            with open(path, "r+b") as f:
                f.truncate(good_end)
            if self.metrics is not None:
                self.metrics.inc("journal_truncated_bytes",
                                 size - good_end)

    def _apply(self, rec: dict) -> None:
        kind = rec.get("rec")
        rid = int(rec.get("rid", 0))
        self._next_rid = max(self._next_rid, rid + 1)
        if kind == "hwm":
            # compaction drops terminal entries, which would otherwise
            # forget the highest rid ever issued — the snapshot leads
            # with an explicit high-water mark so ids never reuse
            self._next_rid = max(self._next_rid, int(rec["next_rid"]))
        elif kind == "submitted":
            self._entries[rid] = {
                "prompt": [int(t) for t in rec["prompt"]],
                "max_new_tokens": int(rec["max_new_tokens"]),
                "timeout_ms": rec.get("timeout_ms"),
                "sampling": dict(rec.get("sampling") or {}),
                "emitted": [],
                "status": "open",
            }
        elif kind == "tokens":
            entry = self._entries.get(rid)
            if entry is None:
                return
            # idempotent replay: 'at' is absolute, so a batch that
            # overlaps what a compaction snapshot already holds only
            # contributes its fresh suffix
            start = int(rec["at"]) - len(entry["prompt"])
            toks = [int(t) for t in rec["toks"]]
            have = len(entry["emitted"])
            if start <= have:
                entry["emitted"].extend(toks[have - start:])
        elif kind == "completed":
            entry = self._entries.get(rid)
            if entry is not None:
                entry["status"] = "completed"
        elif kind == "failed":
            entry = self._entries.get(rid)
            if entry is not None:
                entry["status"] = "failed"

    # -- append path -----------------------------------------------------
    def _append_locked(self, rec: dict) -> None:
        rec = dict(rec)
        rec["sha"] = _record_sha(rec)
        line = (json.dumps(rec, sort_keys=True, separators=(",", ":"))
                + "\n").encode("utf-8")
        self._fh.write(line)
        self._fh.flush()
        if self.fsync:
            t0 = time.perf_counter()
            os.fsync(self._fh.fileno())
            if self.metrics is not None:
                self.metrics.observe_fsync(
                    (time.perf_counter() - t0) * 1000.0)
        if self.metrics is not None:
            self.metrics.inc("journal_records")
        self._apply(rec)
        if self._fh.tell() >= self.segment_max_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Compact live state into the next segment atomically (temp →
        fsync → rename → dir fsync — the checkpoint commit discipline),
        then drop the older segments. Terminal entries do not survive
        the compaction: that is the reclaim."""
        nxt = self._seg_index + 1
        path = self._seg_path(nxt)
        with atomic_output_file(path) as tmp:
            with open(tmp, "wb") as f:
                hwm = {"rec": "hwm", "next_rid": self._next_rid}
                hwm["sha"] = _record_sha(hwm)
                f.write((json.dumps(hwm, sort_keys=True,
                                    separators=(",", ":"))
                         + "\n").encode("utf-8"))
                for rid in sorted(self._entries):
                    entry = self._entries[rid]
                    if entry["status"] != "open":
                        continue
                    for rec in self._snapshot_records(rid, entry):
                        rec["sha"] = _record_sha(rec)
                        f.write((json.dumps(rec, sort_keys=True,
                                            separators=(",", ":"))
                                 + "\n").encode("utf-8"))
        fsync_dir(self.directory)
        old_fh, self._fh = self._fh, None
        old_fh.close()
        dropped = [s for s in self._segments() if s < nxt]
        # terminal entries are gone from disk now — forget them in
        # memory too, or the table grows forever on a long-lived router
        self._entries = {r: e for r, e in self._entries.items()
                         if e["status"] == "open"}
        self._seg_index = nxt
        self._open_active()
        for s in dropped:
            try:
                os.unlink(self._seg_path(s))
            except OSError:
                pass
        fsync_dir(self.directory)

    @staticmethod
    def _snapshot_records(rid: int, entry: dict) -> List[dict]:
        recs = [{"rec": "submitted", "rid": rid,
                 "prompt": list(entry["prompt"]),
                 "max_new_tokens": entry["max_new_tokens"],
                 "timeout_ms": entry["timeout_ms"],
                 "sampling": dict(entry["sampling"])}]
        if entry["emitted"]:
            recs.append({"rec": "tokens", "rid": rid,
                         "at": len(entry["prompt"]),
                         "toks": list(entry["emitted"])})
        return recs

    # -- the router-facing API -------------------------------------------
    def next_request_id(self) -> int:
        """Monotonic across restarts: the recovery scan advances past
        every rid the journal has ever seen."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            return rid

    def log_submitted(self, rid: int, prompt, max_new_tokens: int,
                      timeout_ms: Optional[float],
                      sampling: Optional[dict] = None) -> None:
        with self._lock:
            self._append_locked({
                "rec": "submitted", "rid": int(rid),
                "prompt": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens),
                "timeout_ms": (None if timeout_ms is None
                               else float(timeout_ms)),
                "sampling": dict(sampling or {})})

    def append_token(self, rid: int, at: int, token: int) -> None:
        """Buffer one emitted token (``at`` = absolute index); a batch
        record is written every ``flush_every`` tokens. Call
        :meth:`flush` at a durability point (failover, terminal)."""
        with self._lock:
            buf = self._pending.setdefault(int(rid), [])
            if not buf:
                self._pending_at[int(rid)] = int(at)
            buf.append(int(token))
            if len(buf) >= self.flush_every:
                self._flush_locked(int(rid))

    def _flush_locked(self, rid: int) -> None:
        buf = self._pending.pop(rid, None)
        if not buf:
            return
        at = self._pending_at.pop(rid)
        self._append_locked({"rec": "tokens", "rid": rid,
                             "at": at, "toks": buf})

    def flush(self, rid: int) -> None:
        with self._lock:
            self._flush_locked(int(rid))

    def log_completed(self, rid: int, n_tokens: int) -> None:
        with self._lock:
            self._flush_locked(int(rid))
            self._append_locked({"rec": "completed", "rid": int(rid),
                                 "n_tokens": int(n_tokens)})

    def log_failed(self, rid: int, error) -> None:
        with self._lock:
            self._flush_locked(int(rid))
            self._append_locked({"rec": "failed", "rid": int(rid),
                                 "error": str(error)})

    # -- the recovery-facing API -----------------------------------------
    def incomplete(self) -> Dict[int, dict]:
        """Every open entry, as ``{rid: {"prompt", "max_new_tokens",
        "timeout_ms", "sampling", "emitted"}}`` — what
        ``FleetRouter.recover`` replays as continuations. Completed and
        failed entries are skipped by construction."""
        with self._lock:
            return {rid: {"prompt": list(e["prompt"]),
                          "max_new_tokens": e["max_new_tokens"],
                          "timeout_ms": e["timeout_ms"],
                          "sampling": dict(e["sampling"]),
                          "emitted": list(e["emitted"])}
                    for rid, e in self._entries.items()
                    if e["status"] == "open"}

    def entry(self, rid: int) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(int(rid))
            return None if e is None else dict(e, emitted=list(e["emitted"]))

    def close(self) -> None:
        with self._lock:
            for rid in list(self._pending):
                self._flush_locked(rid)
            if self._fh is not None:
                self._fh.close()
                self._fh = None


__all__ = ["DURABILITY_COUNTERS", "DurabilityMetrics",
           "JournalCorruptError", "RequestJournal", "StreamCursor"]
